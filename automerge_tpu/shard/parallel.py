"""Parallel mesh execution: persistent per-lane worker threads
(INTERNALS §24).

Every structural win since the stacked executor is dispatch-count
accounting; this module converts them into wall-clock on a real mesh.
A :class:`LaneExecutor` owns ONE persistent daemon worker thread per
shard lane (the `PipelinedIngestor` thread/queue discipline, lifted
from per-doc to per-lane): the router fans a serving round out on the
caller thread, each touched lane's worker runs its stacked ingest
concurrently under ``jax.default_device(lane.device)``, and a round
barrier precedes every piece of commit-boundary work (quarantine drain
to fixpoint, rebalancer policy, residency ``after_round`` + the
reservation-ledger clear) — so the budget invariant and the migration
pen semantics are untouched by parallelism.

Safety argument (PAM's partition-parallel shape, PAPERS.md): placement
gives every doc exactly ONE owning lane, so concurrent lane ingests
never share doc state; the zero-collective audit proves no lane program
ever names another lane's device. Shared sinks on the worker path are
all already concurrency-safe (telemetry: lock-striped; lineage ledger:
locked; byte/dispatch accounting: locked + `thread_snapshot`;
device-truth registry: process-global lock). Everything else — the
``ShardedDocSet.stats`` dict, residency, rebalance, placement — stays
caller-thread-only, and per-lane ``ShardLane.stats`` increments ride a
per-task delta dict folded at the barrier (no lost updates, and budget
tests read race-free numbers).

Flags (read per call, like ``stacked_rounds_enabled``):

- ``AMTPU_PARALLEL_LANES`` — ``0`` forces the sequential loop (the
  parity comparator, kept verbatim in ``ShardedDocSet``), ``1`` forces
  workers on; unset defaults to ON when the mesh has more than one
  lane.
- ``AMTPU_TICK_PIPELINE`` — the service-tick fan-out + frame pre-decode
  seam (service/server.py); defaults to the lane-worker setting.

Acceptance is byte-identity: the parallel and sequential paths commit
through the SAME `ShardLane.ingest` / `apply_stacked` code, differing
only in which thread runs it, so capture bundles and texts cannot
diverge; the flag-matrix parity suite (tests/test_parallel_mesh.py)
asserts exactly that on randomized chaotic streams.
"""

from __future__ import annotations

import os
import queue
import threading
import time

from .. import obs


def parallel_lanes_enabled(n_lanes: int) -> bool:
    """Whether lane ingest rounds fan out to the worker pool.
    ``AMTPU_PARALLEL_LANES``: ``0`` off, ``1`` on, unset → on iff the
    mesh has more than one lane (a 1-lane mesh has nothing to overlap;
    forcing ``1`` there stays correct and exercises the worker path)."""
    raw = os.environ.get("AMTPU_PARALLEL_LANES", "").strip()
    if raw == "0":
        return False
    if raw == "1":
        return True
    return n_lanes > 1


def tick_pipeline_enabled(n_lanes: int) -> bool:
    """Whether ``SyncService.tick()`` fans grouped gate deliveries out
    per lane and pre-decodes the next tick's frames while device work
    drains. Defaults to the lane-worker setting so one flag drives the
    whole parallel tier; ``AMTPU_TICK_PIPELINE=0/1`` overrides."""
    raw = os.environ.get("AMTPU_TICK_PIPELINE", "").strip()
    if raw == "0":
        return False
    if raw == "1":
        return True
    return parallel_lanes_enabled(n_lanes)


class _Task:
    """One unit of lane work: a future the round barrier waits on."""

    __slots__ = ("fn", "args", "kwargs", "lane_index", "result", "error",
                 "_done", "queued_while_busy")

    def __init__(self, lane_index, fn, args, kwargs):
        self.lane_index = lane_index
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.result = None
        self.error = None
        self._done = threading.Event()
        self.queued_while_busy = False

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self):
        self._done.wait()


_STOP = object()


class _LaneWorker(threading.Thread):
    """The persistent thread bound to one shard lane. Tasks run in
    submission order (a lane's rounds are causally ordered — the queue
    IS the per-lane pipeline); every task executes inside the lane's
    device context so staged arrays and kernel launches land on the
    lane's device, exactly like the caller-thread path."""

    def __init__(self, lane, executor):
        super().__init__(name=f"amtpu-lane{lane.index}", daemon=True)
        self.lane = lane
        self.executor = executor
        self.tasks: "queue.SimpleQueue" = queue.SimpleQueue()
        self.busy = False          # caller-observed (GIL-atomic flag)
        self.rounds = 0
        # resolved ONCE (engine/pipeline.py, shared with the per-doc
        # ring): the hot loop never re-imports jax per round
        from ..engine.pipeline import device_ctx_factory
        self._device_ctx = device_ctx_factory(lane.device)
        self.start()

    def run(self):
        while True:
            task = self.tasks.get()
            if task is _STOP:
                return
            self.busy = True
            _t0 = obs.now() if obs.ENABLED else 0
            try:
                with self._device_ctx():
                    task.result = task.fn(*task.args, **task.kwargs)
            except BaseException as exc:   # surfaced at the barrier
                task.error = exc
            finally:
                self.rounds += 1
                if obs.ENABLED:
                    obs.span("lane", "round", _t0, args={
                        "lane": self.lane.index,
                        "worker": self.name,
                        "error": task.error is not None})
                self.busy = False
                task._done.set()


class LaneExecutor:
    """The per-mesh worker pool: one persistent worker per lane,
    ``submit`` + ``barrier``, per-round overlap counters, and the
    ``amtpu_mesh_*`` exposition families."""

    def __init__(self, lanes, telemetry=None):
        self.telemetry = telemetry
        self.stats = {"submitted": 0, "completed": 0, "barriers": 0,
                      "rounds_overlapped": 0, "predecoded_batches": 0,
                      "errors": 0}
        self._closed = False
        self._workers = {lane.index: _LaneWorker(lane, self)
                         for lane in lanes}

    # -- dispatch -------------------------------------------------------

    def submit(self, lane_index: int, fn, *args, **kwargs) -> _Task:
        """Queue one unit of work on `lane_index`'s worker. Returns the
        task future the round barrier waits on. Tasks for one lane run
        in submission order; tasks for different lanes run
        concurrently."""
        if self._closed:
            raise RuntimeError("LaneExecutor is closed")
        w = self._workers[lane_index]
        task = _Task(lane_index, fn, args, kwargs)
        task.queued_while_busy = w.busy
        self.stats["submitted"] += 1
        w.tasks.put(task)
        return task

    def barrier(self, tasks, while_waiting=None) -> list:
        """The round barrier: wait for EVERY task (commit-boundary work
        must never observe a half-ingested round), then re-raise the
        first worker error on the caller thread — after all workers
        quiesced, so an assert in one lane cannot leave another lane's
        ingest racing the caller's unwind. `while_waiting` is the
        host/device overlap seam: pure host work (next-round decode)
        the caller runs before blocking."""
        if while_waiting is not None:
            while_waiting()
        t0 = time.perf_counter_ns()
        for task in tasks:
            task.wait()
        wait_ns = time.perf_counter_ns() - t0
        self.stats["barriers"] += 1
        self.stats["completed"] += len(tasks)
        if self.telemetry is not None:
            # the barrier-wait histogram the amtpu_mesh_* families export:
            # how long the caller thread stalls on the slowest lane
            # (overlap work excluded — it ran before the block above)
            self.telemetry.observe_span("mesh", "barrier_wait", wait_ns)
        if obs.ENABLED:
            obs.span("mesh", "barrier_wait", t0, args={
                "tasks": len(tasks)}, t1_ns=t0 + wait_ns)
        for task in tasks:
            if task.error is not None:
                self.stats["errors"] += 1
                raise task.error
        return [task.result for task in tasks]

    # -- lifecycle ------------------------------------------------------

    @property
    def n_workers(self) -> int:
        return len(self._workers)

    def close(self):
        """Stop every worker (idempotent). Pending tasks drain first —
        the stop sentinel queues BEHIND them, so close at a commit
        boundary never abandons an in-flight round."""
        if self._closed:
            return
        self._closed = True
        for w in self._workers.values():
            w.tasks.put(_STOP)
        for w in self._workers.values():
            w.join(timeout=10.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- exposition -----------------------------------------------------

    def describe(self) -> dict:
        return {
            "schema": "amtpu-mesh-exec-v1",
            "workers": {i: {"alive": w.is_alive(), "rounds": w.rounds}
                        for i, w in sorted(self._workers.items())},
            "stats": dict(self.stats),
        }

    def families(self, prefix: str = "amtpu_mesh") -> list:
        """Prometheus exposition families (SyncService.scrape appends
        these next to the service families): worker count, per-worker
        round totals, rounds overlapped (host planning of round t+1
        under round t's device drain), and the barrier-wait
        histogram."""
        fams = [
            (f"{prefix}_workers", "gauge",
             "Persistent lane worker threads (one per shard lane; 0 "
             "when parallel execution is off).",
             [({}, sum(w.is_alive() for w in self._workers.values()))]),
            (f"{prefix}_rounds_total", "counter",
             "Lane ingest rounds executed per worker.",
             [({"lane": str(i)}, w.rounds)
              for i, w in sorted(self._workers.items())]),
            (f"{prefix}_rounds_overlapped_total", "counter",
             "Rounds whose next-round host planning (wire decode / "
             "columnar build) overlapped the in-flight device leg.",
             [({}, self.stats["rounds_overlapped"])]),
            (f"{prefix}_barriers_total", "counter",
             "Round barriers taken (one per fanned-out round).",
             [({}, self.stats["barriers"])]),
        ]
        if self.telemetry is not None:
            from ..obs.telemetry import N_BUCKETS, bucket_le_ns
            hists, aggs = self.telemetry.span_view()
            key = ("mesh", "barrier_wait")
            if key in hists:
                buckets = hists[key]
                agg = aggs.get(key, {"count": 0, "total_ns": 0})
                samples, cum = [], 0
                for i in range(N_BUCKETS + 1):
                    cum += buckets[i]
                    le = bucket_le_ns(i) / 1e9
                    samples.append((("_bucket", {
                        "le": "+Inf" if le == float("inf") else repr(le)}),
                        cum))
                samples.append((("_sum", {}), agg["total_ns"] / 1e9))
                samples.append((("_count", {}), agg["count"]))
                fams.append((
                    f"{prefix}_barrier_wait_seconds", "histogram",
                    "Caller-thread stall at the round barrier (time to "
                    "the slowest lane), log2 buckets fed at emit time.",
                    samples))
        return fams

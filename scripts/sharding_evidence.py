"""Evidence for the elem-axis sharding story: compiled-HLO collective audit
+ 1-vs-N virtual-device scaling of the sharded merge.

Writes docs/SHARDING_r<round>.md (AMTPU_ROUND, default 5). Run with the scrubbed CPU env:
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
      XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python scripts/sharding_evidence.py
"""

import os
import re
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from automerge_tpu.parallel.mesh import (example_doc_tables, make_mesh,  # noqa: E402
                                         merge_step)

COLLECTIVES = ("all-gather", "all-reduce", "all-to-all", "collective-permute",
               "reduce-scatter")


def count_collectives(fn, args) -> dict:
    """Compile and count collective ops in the HLO (zero-count keys dropped)."""
    hlo = fn.lower(*args).compile().as_text()
    counts = {c: len(re.findall(rf"\b{c}\b", hlo)) for c in COLLECTIVES}
    return {c: n for c, n in counts.items() if n}


def audit(mesh, n_docs, cap):
    shard = NamedSharding(mesh, P("doc", "elem"))
    fn = jax.jit(jax.vmap(merge_step), in_shardings=(shard,) * 6,
                 out_shardings=(shard, shard, NamedSharding(mesh, P("doc"))))
    tables = [jax.device_put(np.asarray(t), shard)
              for t in example_doc_tables(n_docs, cap, seed=3)]
    counts = count_collectives(fn, tables)
    hlo = fn.lower(*tables).compile().as_text()
    # largest replicated intermediate: scan for full-shape ops vs sharded
    full_shape = f"s32[{n_docs},{cap}]"
    n_full = hlo.count(full_shape + "{")  # layout-annotated full tensors
    return counts, n_full, tables, fn


def audit_materialize(mesh_elem, cap, S):
    """Collective audit of the codes-only materialization, one document
    sharded along `elem`: self-contained kernel (device sort + pointer
    doubling) vs host-planned kernel (segplan staged, no sort)."""
    from automerge_tpu.ops.ingest import (materialize_codes,
                                          materialize_codes_planned)
    elem = NamedSharding(mesh_elem, P("elem"))
    rep = NamedSharding(mesh_elem, P())
    z32 = jax.device_put(np.zeros(cap, np.int32), elem)
    zb = jax.device_put(np.zeros(cap, bool), elem)
    n = jax.device_put(np.int32(cap - 2), rep)
    segplan = jax.device_put(np.zeros((4, S), np.int32), rep)

    plain = jax.jit(
        lambda p, c, a, v, h, ch, n: materialize_codes(
            p, c, a, v, h, ch, n, S=S),
        in_shardings=(elem,) * 6 + (rep,), out_shardings=(elem, rep))
    planned = jax.jit(
        lambda p, c, a, v, h, ch, n, sp: materialize_codes_planned(
            p, c, a, v, h, ch, n, sp, S=S),
        in_shardings=(elem,) * 6 + (rep, rep),
        out_shardings=(elem, rep))
    return (count_collectives(plain, (z32, z32, z32, z32, zb, zb, n)),
            count_collectives(planned,
                              (z32, z32, z32, z32, zb, zb, n, segplan)))


def scaling(cap_per_dev=2048, n_docs=8):
    """Wall time of the sharded merge at 1 vs N virtual devices, same total
    work (CPU devices: indicative of work distribution, not TPU rates)."""
    rows = []
    n = len(jax.devices())
    for doc_axis, elem_axis in ((1, 1), (n, 1), (1, n)):
        devs = jax.devices()[: doc_axis * elem_axis]
        grid = np.asarray(devs).reshape(doc_axis, elem_axis)
        from jax.sharding import Mesh
        mesh = Mesh(grid, ("doc", "elem"))
        shard = NamedSharding(mesh, P("doc", "elem"))
        fn = jax.jit(jax.vmap(merge_step), in_shardings=(shard,) * 6,
                     out_shardings=(shard, shard,
                                    NamedSharding(mesh, P("doc"))))
        tables = [jax.device_put(np.asarray(t), shard)
                  for t in example_doc_tables(n_docs, cap_per_dev, seed=5)]
        jax.block_until_ready(fn(*tables))  # compile
        t0 = time.perf_counter()
        for _ in range(3):
            out = fn(*tables)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / 3
        rows.append((f"({doc_axis} doc, {elem_axis} elem)", dt * 1e3))
    return rows


def main():
    n = len(jax.devices())
    mesh = make_mesh()
    counts_mixed, full_mixed, _, _ = audit(mesh, n_docs=8, cap=2048)
    mesh_elem = make_mesh(doc_axis=1)
    counts_elem, full_elem, _, _ = audit(mesh_elem, n_docs=1, cap=8192)
    mesh_doc = make_mesh(doc_axis=n)
    counts_doc, _, _, _ = audit(mesh_doc, n_docs=n * 2, cap=1024)
    counts_plain_mat, counts_planned_mat = audit_materialize(
        mesh_elem, cap=8192, S=256)
    mesh_elem_shape = tuple(mesh_elem.shape.items())
    rows = scaling()

    rnd = int(os.environ.get("AMTPU_ROUND", "5"))
    doc = f"""# Sharding evidence — round {rnd} ({n} virtual CPU devices)

Claim under test (parallel/mesh.py): documents shard over the `doc` axis
with no cross-device traffic; one huge document shards along `elem`, with
XLA inserting collectives for the linearization's sort and pointer-doubling
gathers. The round-2 verdict asked for proof the compiled program does not
simply all-gather the whole table.

## Compiled-HLO collective audit

`sharded_merge_step` lowered + compiled with explicit in/out shardings,
then grepped for collective ops:

| mesh | shapes | collectives in compiled module |
|---|---|---|
| {tuple(mesh_doc.shape.items())} | {n * 2} docs x 1024 (doc-only) | {counts_doc or "NONE"} |
| {tuple(mesh.shape.items())} | 8 docs x 2048 | {counts_mixed or "none"} |
| {tuple(mesh_elem.shape.items())} | 1 doc x 8192 (elem-only) | {counts_elem or "none"} |

Reading: the doc-only mesh compiles with **{counts_doc and "collectives" or "ZERO collectives"}**
— the vmap dimension is embarrassingly parallel, as claimed. On the `elem` axis
the sort and pointer-doubling gathers are NOT locally partitionable, and
the partitioner inserts the gathers/permutes above — i.e. the element axis
pays real communication, it is not silently replicated-per-device; output
buffers stay sharded (asserted in tests/test_parallel.py, incl. a single
document spanning every shard many times over).

## Honest finding

XLA's SPMD partitioner resolves the linearization's `sort` by gathering
the sort operand across the elem axis (visible as all-gather/all-to-all
above) — the standard behavior for unpartitionable ops. So elem-axis
sharding of the self-contained kernel buys **memory capacity** (a document
larger than one device's HBM) and parallel elementwise/scan phases, while
the sort phase serializes through collectives.

## Host-planned materialization removes the sort from the sharded program

The planned kernel (engine/segments.py + ops/ingest.py:
_materialize_core_planned) receives the segment structure from the host,
so the elem-sharded compiled program has **no sort to partition at all**
— what remains is prefix-sum carries and the codes scatter. Collective
audit of the codes-only materialization, 1 doc x 8192 elements sharded
over {mesh_elem_shape} (S=256):

| kernel | collectives in compiled module |
|---|---|
| self-contained (`materialize_codes`) | {counts_plain_mat} |
| host-planned (`materialize_codes_planned`) | {counts_planned_mat} |

Parity of the sharded planned path against the single-device engine —
including a document spanning every shard — is pinned by
tests/test_parallel.py::test_sharded_planned_materialize_matches_engine.
The Pallas fused-segment-scan building block (ops/scan_pallas.py:
block-local scans with explicit carries) remains the alternative for the
self-contained path and the sharded-carry design.

## 1-vs-{n} virtual-device scaling (same per-device work, CPU: indicative
of distribution, not TPU rates)

| mesh (doc, elem) | wall/step |
|---|---|
""" + "".join(f"| {name} | {ms:.1f} ms |\n" for name, ms in rows) + f"""
Generated by scripts/sharding_evidence.py on {n} virtual CPU devices.

## Decision (round 4): the elem axis is a CAPACITY feature

Recorded design decision, closing the round-3 deferral. On every
measurable configuration the elem axis does not beat 1-way on wall time,
and this environment cannot produce the measurement that could justify
more: the virtual mesh runs {n} devices on ONE physical CPU core (any
parallel win is structurally unmeasurable), and the real deployment has a
single TPU chip behind the tunnel (no ICI). What the evidence does
establish: (a) the doc axis is communication-free (the scaling axis that
matters for DocSet workloads); (b) the elem-sharded PLANNED program
contains no sort — its collectives are prefix-sum carries and scatter
permutes, the cheap shape; (c) sharded-vs-engine parity holds on
documents spanning every shard.

Capacity math for the headline config: 1M elements x 9 int32/int64
columns is ~50 MB — one v5e chip (16 GB HBM) holds documents TWO ORDERS
larger before elem sharding is needed (~300M elements with workspace).
The elem axis therefore exists for documents beyond single-chip HBM, and
for that regime the planned kernel is the one to shard (evidence above).
Revisit only with real multi-chip ICI hardware; until then the production
materialize stays 1-way on the elem axis.
"""
    out = os.path.join(os.path.dirname(__file__), "..", "docs",
                       f"SHARDING_r{rnd}.md")
    with open(out, "w") as fh:
        fh.write(doc)
    print(doc)


if __name__ == "__main__":
    main()

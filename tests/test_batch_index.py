"""Batch-update range index vs the sorted-insert legacy (INTERNALS §16.2).

The tiered :class:`BatchRangeIndex` (AMTPU_BATCH_INDEX default) must be
indistinguishable from the legacy :class:`SortedInsertIndex` on every
read — lookups, reverse lookups, the flattened checkpoint rows — over
randomized interleaved merge histories, and must additionally deliver
the persistence contract the legacy array never promised: a snapshot
taken with ZERO coordination while another thread bulk-merges can never
observe a torn state. Both are pinned here."""

import threading

import numpy as np
import pytest

from automerge_tpu.engine import host_index as H


# ---------------------------------------------------------------------------
# randomized merge-history generation
# ---------------------------------------------------------------------------


def rand_merge_history(seed, n_merges=60, n_actors=5, max_ranges=16):
    """A sequence of non-overlapping bulk merges (ranges keyed like the
    engine's: packed (actor_rank << 32 | ctr)), plus the key->slot truth
    table."""
    rng = np.random.default_rng(seed)
    taken = {}
    slot = 1
    merges = []
    for _ in range(n_merges):
        starts, lens, slots = [], [], []
        for _ in range(int(rng.integers(1, max_ranges))):
            a = int(rng.integers(0, n_actors))
            c = int(rng.integers(0, 10 ** 6))
            length = int(rng.integers(1, 40))
            key = (a << 32) | c
            if any(key < k + l and k < key + length
                   for k, l in taken.items()):
                continue
            if any(s < key + length and key < s + l
                   for s, l in zip(starts, lens)):
                continue
            starts.append(key)
            lens.append(length)
            slots.append(slot)
            slot += length
            taken[key] = length
        if starts:
            merges.append((np.asarray(starts, np.int64),
                           np.asarray(lens, np.int64),
                           np.asarray(slots, np.int64)))
    return merges, taken


def replay(cls, merges):
    idx = cls()
    for s, l, sl in merges:
        idx = idx.merge(s, l, sl)
    return idx


# ---------------------------------------------------------------------------
# read parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(6))
def test_read_parity_random_histories(seed):
    merges, taken = rand_merge_history(seed)
    legacy = replay(H.SortedInsertIndex, merges)
    batch = replay(H.BatchRangeIndex, merges)

    # flattened rows byte-identical (the checkpoint bundle contract)
    for a, b in zip(legacy.rows(), batch.rows()):
        assert np.array_equal(a, b)

    # every inserted key (range starts, interiors, ends) resolves equally
    keys = []
    for k, l in taken.items():
        keys += [k, k + l - 1, k + l // 2]
    keys = np.asarray(sorted(set(keys)), np.int64)
    sa, fa = legacy.lookup(keys)
    sb, fb = batch.lookup(keys)
    assert fa.all() and np.array_equal(sa, sb) and np.array_equal(fa, fb)

    # misses resolve equally (just-outside probes)
    misses = np.asarray([k + l for k, l in taken.items()
                         if (k + l) not in taken], np.int64)
    _, fa = legacy.lookup(misses)
    _, fb = batch.lookup(misses)
    assert np.array_equal(fa, fb)

    # reverse lookup parity over every live slot
    slots = np.concatenate([np.arange(s, s + l) for (k, l), s in
                            zip(taken.items(), _slots_of(legacy, taken))])
    ra = np.stack(legacy.slot_to_key(slots))
    rb = np.stack(batch.slot_to_key(slots))
    assert np.array_equal(ra, rb)


def _slots_of(idx, taken):
    keys = np.asarray(list(taken), np.int64)
    s, f = idx.lookup(keys)
    assert f.all()
    return s.tolist()


@pytest.mark.parametrize("seed", range(4))
def test_remap_parity(seed):
    merges, taken = rand_merge_history(seed, n_merges=30)
    legacy = replay(H.SortedInsertIndex, merges)
    batch = replay(H.BatchRangeIndex, merges)
    rng = np.random.default_rng(seed + 99)
    remap = rng.permutation(8).astype(np.int64)
    l2 = legacy.remap_actors(remap)
    b2 = batch.remap_actors(remap)
    # pure: the originals (and any snapshot of them) are untouched
    for a, b in zip(legacy.rows(), batch.rows()):
        assert np.array_equal(a, b)
    keys = np.asarray(sorted(taken), np.int64)
    keys2 = (remap[keys >> 32] << np.int64(32)) | (keys & 0xFFFFFFFF)
    sa, fa = l2.lookup(keys2)
    sb, fb = b2.lookup(keys2)
    assert fa.all() and fb.all() and np.array_equal(sa, sb)


def test_duplicate_raises_same_key_both_structures():
    merges, taken = rand_merge_history(3, n_merges=10)
    legacy = replay(H.SortedInsertIndex, merges)
    batch = replay(H.BatchRangeIndex, merges)
    key = sorted(taken)[len(taken) // 2]
    for idx in (legacy, batch):
        with pytest.raises(H.DuplicateElemId) as ei:
            idx.merge(np.asarray([key], np.int64),
                      np.asarray([1], np.int64),
                      np.asarray([10 ** 6], np.int64))
        assert ei.value.key == key
    # overlap WITHIN one merge call raises too
    for idx in (H.SortedInsertIndex(), H.BatchRangeIndex()):
        with pytest.raises(H.DuplicateElemId):
            idx.merge(np.asarray([10, 12], np.int64),
                      np.asarray([5, 5], np.int64),
                      np.asarray([1, 6], np.int64))


def test_flag_selects_structure(monkeypatch):
    monkeypatch.setenv("AMTPU_BATCH_INDEX", "0")
    assert isinstance(H.new_index(), H.SortedInsertIndex)
    monkeypatch.setenv("AMTPU_BATCH_INDEX", "1")
    assert isinstance(H.new_index(), H.BatchRangeIndex)
    idx = H.index_from_rows(np.asarray([8], np.int64),
                            np.asarray([2], np.int64),
                            np.asarray([1], np.int64))
    s, f = idx.lookup(np.asarray([8, 9, 10], np.int64))
    assert f.tolist() == [True, True, False]
    assert s[:2].tolist() == [1, 2]


def test_merge_accounting_one_bulk_update_per_round():
    before = H.merge_stats_snapshot()
    idx = H.new_index()
    for r in range(5):
        base = r * 100
        idx = idx.merge(
            np.asarray([base + i * 10 for i in range(4)], np.int64),
            np.full(4, 3, np.int64),
            np.asarray([1 + r * 12 + i * 3 for i in range(4)], np.int64))
    after = H.merge_stats_snapshot()
    assert after["bulk_merges"] - before["bulk_merges"] == 5
    assert after["ranges_inserted"] - before["ranges_inserted"] == 20


# ---------------------------------------------------------------------------
# zero-coordination snapshots under concurrent bulk merges (8 threads)
# ---------------------------------------------------------------------------


def _validate_snapshot(idx):
    """A snapshot must be internally consistent: sorted disjoint rows,
    every row resolvable at its start/end, reverse lookup closing the
    loop."""
    starts, lens, slots = idx.rows()
    if not len(starts):
        return 0
    assert (np.diff(starts) > 0).all()
    assert ((starts + lens)[:-1] <= starts[1:]).all()
    probes = np.concatenate([starts, starts + lens - 1])
    got, found = idx.lookup(probes)
    assert found.all()
    n = len(starts)
    assert np.array_equal(got[:n], slots)
    assert np.array_equal(got[n:], slots + lens - 1)
    a, c = idx.slot_to_key(slots)
    assert np.array_equal((a << np.int64(32)) | c, starts)
    return int(lens.sum())


@pytest.mark.parametrize("structure", ["batch", "legacy"])
def test_snapshot_never_observes_torn_merge_8_threads(structure):
    """One writer bulk-merging (single ranges and multi-range splits
    interleaved), seven readers snapshotting with zero coordination:
    every observed snapshot is a fully consistent prior version, and the
    observed element count never goes backwards for any single reader
    (persistence = monotone publication)."""
    cls = (H.BatchRangeIndex if structure == "batch"
           else H.SortedInsertIndex)
    holder = {"idx": cls()}
    stop = threading.Event()
    failures = []
    merges, _ = rand_merge_history(11, n_merges=300, max_ranges=8)

    def writer():
        try:
            idx = holder["idx"]
            for s, l, sl in merges:
                idx = idx.merge(s, l, sl)
                holder["idx"] = idx       # atomic publish (rebind)
        except Exception as exc:          # pragma: no cover
            failures.append(exc)
        finally:
            stop.set()

    def reader():
        last = 0
        try:
            while not stop.is_set() or last == 0:
                snap = holder["idx"].snapshot()
                total = _validate_snapshot(snap)
                assert total >= last, "snapshot went backwards"
                last = total
                if stop.is_set():
                    break
        except Exception as exc:          # pragma: no cover
            failures.append(exc)

    threads = [threading.Thread(target=writer)] + \
        [threading.Thread(target=reader) for _ in range(7)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not failures, failures
    final = _validate_snapshot(holder["idx"])
    assert final == sum(int(l.sum()) for _, l, _ in merges)


def test_compaction_bounds_tier_count():
    idx = H.BatchRangeIndex()
    key = 1
    slot = 1
    for r in range(500):
        idx = idx.merge(np.asarray([key], np.int64),
                        np.asarray([2], np.int64),
                        np.asarray([slot], np.int64))
        key += 3                          # never coalescible
        slot += 2
        assert len(idx._runs) <= idx._COMPACT_TIERS
    assert idx.n_ranges == 500
    _validate_snapshot(idx)

"""Two-phase ingestion (prepare_batch / commit_prepared): the pipelining
seam the headline bench times. Equivalence with apply_batch is the contract:
same changes, same final document, regardless of phase split."""

import numpy as np
import pytest

from automerge_tpu.engine import DeviceTextDoc, TextChangeBatch


def typing_change(actor, seq, deps, text, start_ctr, parent):
    """A change typing `text` as one run after `parent` ('_head' or elemId)."""
    ops = []
    for i, ch in enumerate(text):
        ctr = start_ctr + i
        key = "_head" if (i == 0 and parent == "_head") else (
            parent if i == 0 else f"{actor}:{ctr - 1}")
        ops.append({"action": "ins", "obj": "t", "key": key, "elem": ctr})
        ops.append({"action": "set", "obj": "t", "key": f"{actor}:{ctr}",
                    "value": ch})
    return {"actor": actor, "seq": seq, "deps": deps, "ops": ops}


def build_batch(changes):
    return TextChangeBatch.from_changes(changes, "t")


def seed_doc():
    doc = DeviceTextDoc("t")
    doc.apply_changes([typing_change("base", 1, {}, "hello world", 1, "_head")])
    return doc


CONCURRENT = [
    typing_change("alice", 1, {"base": 1}, "AAA", 100, "base:5"),
    typing_change("bob", 1, {"base": 1}, "BB", 100, "base:5"),
    # a residual-heavy change: delete + overwrite (no runs)
    {"actor": "carol", "seq": 1, "deps": {"base": 1}, "ops": [
        {"action": "del", "obj": "t", "key": "base:1"},
        {"action": "set", "obj": "t", "key": "base:2", "value": "X"},
    ]},
]


def test_prepare_commit_matches_apply():
    direct = seed_doc().apply_batch(build_batch(CONCURRENT))
    two_phase = seed_doc()
    prepared = two_phase.prepare_batch(build_batch(CONCURRENT))
    assert prepared.n_staged_bytes > 0
    two_phase.commit_prepared(prepared)
    assert two_phase.text() == direct.text()
    assert two_phase.elem_ids() == direct.elem_ids()
    assert two_phase.clock == direct.clock


def test_prepare_commit_multi_round():
    """seq-2 changes depending on seq-1 changes in the same batch force
    multiple causal rounds; planning threads shadow state through them."""
    changes = [
        typing_change("alice", 1, {"base": 1}, "AA", 100, "base:5"),
        typing_change("alice", 2, {}, "CC", 200, "alice:101"),
        typing_change("bob", 1, {"alice": 1, "base": 1}, "B", 300, "alice:100"),
    ]
    direct = seed_doc().apply_batch(build_batch(changes))
    two_phase = seed_doc()
    prepared = two_phase.prepare_batch(build_batch(changes))
    assert len(prepared.rounds) >= 2
    two_phase.commit_prepared(prepared)
    assert two_phase.text() == direct.text()
    assert two_phase.elem_ids() == direct.elem_ids()


def test_prepare_commit_with_queued_unready():
    """Changes whose deps are missing stay queued across the phases."""
    doc = seed_doc()
    future = typing_change("dave", 2, {}, "Z", 400, "dave:399")
    doc.apply_batch(build_batch([future]))  # unready: queued
    assert doc.queue
    prepared = doc.prepare_batch(build_batch(CONCURRENT))
    doc.commit_prepared(prepared)
    assert doc.queue  # still waiting on dave seq 1
    direct = seed_doc()
    direct.apply_batch(build_batch([future]))
    direct.apply_batch(build_batch(CONCURRENT))
    assert doc.text() == direct.text()


def test_commit_rejects_stale_plan():
    doc = seed_doc()
    prepared = doc.prepare_batch(build_batch(CONCURRENT))
    doc.apply_changes([typing_change("eve", 1, {"base": 1}, "!", 500,
                                     "base:11")])
    with pytest.raises(ValueError, match="re-prepare"):
        doc.commit_prepared(prepared)


def test_prepare_does_not_mutate_content():
    doc = seed_doc()
    before = doc.text()
    n_elems = doc.n_elems
    clock = dict(doc.clock)
    doc.prepare_batch(build_batch(CONCURRENT))
    assert doc.text() == before
    assert doc.n_elems == n_elems
    assert doc.clock == clock


def test_prepare_rejects_invalid_batch_without_damage():
    doc = seed_doc()
    bad = build_batch([
        typing_change("alice", 1, {"base": 1}, "A", 100, "base:999")])
    with pytest.raises(ValueError, match="unknown parent"):
        doc.prepare_batch(bad)
    # document unharmed, further ingestion fine
    doc.apply_batch(build_batch(CONCURRENT))


def test_eager_materialize_matches_lazy():
    """The fused merge+materialize program (eager_materialize) must produce
    the same text, elem ids, and subsequent-edit behavior as the lazy
    two-program path."""
    lazy = seed_doc()
    eager = seed_doc()
    eager.eager_materialize = True
    batch_a = [typing_change("alice", 1, {"base": 1}, "AAAA", 100, "base:5")]
    batch_b = [typing_change("bob", 1, {"base": 1, "alice": 1}, "BB", 200,
                             "alice:101")]
    for b in (batch_a, batch_b):
        lazy.apply_changes(list(b))
        eager.apply_changes(list(b))
        assert eager.text() == lazy.text()
    assert eager.elem_ids() == lazy.elem_ids()
    # the two-phase path takes the fused branch too, AND the fused cache
    # must survive the batch driver's trailing invalidation so text()
    # dispatches no second materialization (the point of the feature)
    lazy2 = seed_doc()
    eager2 = seed_doc()
    eager2.eager_materialize = True
    for doc in (lazy2, eager2):
        prepared = doc.prepare_batch(build_batch(batch_a))
        doc.commit_prepared(prepared)
    assert eager2._mat is not None, "fused cache wiped by batch driver"
    assert eager2.text() == lazy2.text()
    # ...but a later mutating round must stale it
    eager2.apply_changes(
        [typing_change("carol", 1, {"base": 1}, "C", 300, "base:1")])
    lazy2.apply_changes(
        [typing_change("carol", 1, {"base": 1}, "C", 300, "base:1")])
    assert eager2.text() == lazy2.text()


def test_duplicate_delivery_through_prepare():
    """Re-preparing an already-applied batch admits nothing (idempotent)."""
    doc = seed_doc()
    doc.apply_batch(build_batch(CONCURRENT))
    text = doc.text()
    prepared = doc.prepare_batch(build_batch(CONCURRENT))
    assert all(p is None for _, _, _, p in prepared.rounds)
    doc.commit_prepared(prepared)
    assert doc.text() == text


def test_run_plan_cache_reuses_and_rebases_across_docs():
    """Run detection is memoized on the batch object (text_doc._plan_round):
    DocSet broadcasts ONE delivery to every doc, so the second doc must
    reuse the first doc's detection — including when its element count
    differs (slot fields rebase) — and produce exactly what a fresh,
    uncached batch produces."""
    import bench as B
    from automerge_tpu.engine import DeviceTextDoc

    def fresh_doc(extra_round: bool):
        d = DeviceTextDoc("t")
        d.apply_batch(B.base_batch("t", 120))
        if extra_round:                     # shifts base_elems for doc B
            d.apply_batch(B.merge_batch("t", 3, 10, 120, seed=9,
                                        actor_prefix="pre"))
        d.text()
        return d

    batch = B.merge_batch("t", 20, 12, 120, seed=4)
    doc_a = fresh_doc(False)
    doc_a.apply_batch(batch)
    assert getattr(batch, "_run_plan_cache", None) is not None

    # doc B: different base_elems -> the cached plan must rebase
    doc_b = fresh_doc(True)
    doc_b.apply_batch(batch)                # cache HIT (rebased)
    control = fresh_doc(True)
    control.apply_batch(B.merge_batch("t", 20, 12, 120, seed=4))  # no cache
    assert doc_b.text() == control.text()
    assert doc_b.elem_ids() == control.elem_ids()

    # doc C: same base_elems as A (delta 0, shared-array fast path)
    doc_c = fresh_doc(False)
    doc_c.apply_batch(batch)
    assert doc_c.text() == doc_a.text()
    assert doc_c.elem_ids() == doc_a.elem_ids()


def test_run_plan_cache_does_not_leak_across_batches():
    """The memo must never leak between DIFFERENT batches: a doc preparing
    its own distinct batch after another batch was cached must detect
    fresh (the cache lives on the batch object, not the doc)."""
    import bench as B
    from automerge_tpu.engine import DeviceTextDoc

    b1 = B.merge_batch("t", 10, 8, 100, seed=1)
    b2 = B.merge_batch("t", 10, 8, 100, seed=2, actor_prefix="other")
    d = DeviceTextDoc("t")
    d.apply_batch(B.base_batch("t", 100))
    d.apply_batch(b1)
    d.apply_batch(b2)               # b2 must not see b1's cached plan
    control = DeviceTextDoc("t")
    control.apply_batch(B.base_batch("t", 100))
    control.apply_batch(B.merge_batch("t", 10, 8, 100, seed=1))
    control.apply_batch(B.merge_batch("t", 10, 8, 100, seed=2,
                                      actor_prefix="other"))
    assert d.text() == control.text()
    assert d.elem_ids() == control.elem_ids()

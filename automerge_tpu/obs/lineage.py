"""Distributed change-lineage tracing (INTERNALS §18).

PR 6 records *spans* (where did this process spend its time) and PR 9
records *aggregates* (how far behind is this tenant).  Neither can answer
the question a federated deployment asks constantly: *where did this
specific change spend its time, and on which hop did it get stuck?*
This module makes per-change, cross-replica visibility a first-class
measured quantity: a bounded, deterministically-sampled provenance
ledger records every hop a change takes —

    origin -> chan/send (/retransmit) -> hub/flush -> svc/admit
    (/defer /shed) -> quar/park (/release /pen) -> plan/stacked
    -> commit (per replica) / ckpt/adopt (snapshot bootstrap)

keyed by ``(actor, seq)``, the change's globally-unique identity.

**Zero-coordination sampling.**  Whether a change is traced is a pure
function of its identity: ``sha1(actor:seq) % AMTPU_LINEAGE_RATE == 0``.
Every replica — with no handshake, no shared state, no sampling header —
independently selects the *identical* subset of changes, so the hops one
replica records stitch onto the hops every other replica records for the
same change.  (Okapi's cheap-causal-metadata discipline, PAPERS.md: the
metadata that makes geo-replication debuggable must not itself require
coordination.)

**Trace context on the wire.**  The origin timestamp travels as trace
context: an optional ``trace`` manifest entry on ``AMTPUWIRE1`` frames
and an optional ``trace`` field on dict sync messages — both
version-tolerant (old decoders ignore them) and typed-validated (a
malformed context is a ``ProtocolError``, never a crash).  Hop
timestamps are WALL-CLOCK nanoseconds (:func:`now_ns`), not the obs
tier's process-local ``perf_counter``: an adopted origin must be
comparable on the receiving replica, so cross-replica visibility is
accurate to clock sync (NTP) — the standard distributed-tracing
tradeoff.  ``adopt()`` re-verifies sampling on every adopted entry, so
hostile context can never grow the ledger beyond the sampled subset.

**Hot-path discipline** (the PR-6 contract): every hop site is guarded
by ONE module-flag check::

    from ..obs import lineage
    ...
    if lineage.ENABLED:
        lineage.hop(actor, seq, "quar/park", site=..., doc=doc_id)

Disabled, the whole emit path is a module-dict lookup and a falsy
branch — no call, no hash, no lock (bounded and asserted in
tests/test_lineage.py).  Sampled-mode overhead carries its own
committed bench row (cfg14) enforced by ``benchmarks/slo_gate.py``.

**Bounds.**  The ledger retains at most ``AMTPU_LINEAGE_CAPACITY``
chains (default 4096); at the cap the OLDEST chain is evicted while the
exact counters (``chains_started``/``chains_evicted``/``hops_recorded``)
survive eviction — the PR-6 wraparound discipline.  Each chain holds at
most ``AMTPU_LINEAGE_MAX_HOPS`` hops; duplicates dedup by
``(stage, site, extra)`` so dup/reorder/retransmit chaos never grows a
chain (a retransmission adds a distinct ``chan/retransmit`` hop — its
``extra`` carries the attempt — never a duplicate chain).

**Read side.**  Per-stage dwell histograms and end-to-end
``visibility`` spans feed the ledger's own always-on
:class:`~.telemetry.Telemetry` store at record time (exact across
eviction); :func:`families` exports them in Prometheus exposition form;
:func:`postmortem` ranks the K most-stuck sampled changes with their
full hop chains (what ``SyncService.describe()`` embeds); hops also
emit ``lineage``-category obs events when tracing is live, which
``obs/export.py`` stitches into Perfetto flow events — one change's
journey across actors as a single loadable timeline.

Enable via ``AMTPU_LINEAGE_RATE=N`` in the environment (sample 1/N;
``1`` samples everything; unset/0 disables) or :func:`enable`.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from collections import OrderedDict
from typing import Optional

from .telemetry import Telemetry

#: THE fast-path gate: hop sites read this module attribute directly
#: (`if lineage.ENABLED:`) so a disabled process pays one dict lookup
#: per site and nothing else.  Mutated only by enable()/disable().
ENABLED = False

_ledger: Optional["LineageLedger"] = None

#: Hop stages that make a change VISIBLE on a replica: a normal gate
#: commit, or adoption via a checkpoint-bundle bootstrap (the change's
#: effect arrived inside the bundle; it never re-crossed the wire).
VISIBILITY_STAGES = ("commit", "ckpt/adopt")

DEFAULT_CAPACITY = 4096
DEFAULT_MAX_HOPS = 128

#: Longest trace-context list either wire accepts (typed rejection
#: beyond it — enforced by ``wire_format.validate_trace_context``):
#: context is bounded by the sender's sampled subset, so an oversized
#: list is malformed or hostile, never legitimate.
MAX_CONTEXT_ENTRIES = 8192


def now_ns() -> int:
    """THE lineage hop clock: wall-clock nanoseconds (``time.time_ns``),
    NOT the obs tier's ``perf_counter_ns`` — hop timestamps cross
    process boundaries inside trace context, and perf_counter epochs
    are process-local (an adopted origin would make every visibility/
    dwell number meaningless on a real wire).  Cross-replica accuracy
    is therefore bounded by clock sync (NTP), the standard distributed-
    tracing tradeoff; dwell computations clamp at 0 against small clock
    steps."""
    return time.time_ns()


def _env_int(name: str, default: int) -> int:
    try:
        v = int(os.environ.get(name, "") or 0)
    except ValueError:
        return default
    return v if v > 0 else default


def sample_key(actor: str, seq: int) -> int:
    """The content hash sampling keys on: the first 8 bytes of
    ``sha1(actor:seq)`` as an unsigned int.  A pure function of the
    change identity — every replica computes the same value with zero
    coordination."""
    digest = hashlib.sha1(f"{actor}:{seq}".encode("utf-8")).digest()
    return int.from_bytes(digest[:8], "big")


class LineageLedger:
    """Bounded, deterministic-sampled per-change provenance store.

    One instance lives module-level (`lineage.enable()`); tests
    instantiate their own to prove the zero-coordination sampling
    property across independent "processes"."""

    def __init__(self, rate: int, capacity: Optional[int] = None,
                 max_hops: Optional[int] = None):
        if rate < 1:
            raise ValueError("sampling rate must be >= 1 (1 = sample "
                             "everything)")
        self.rate = rate
        self.capacity = capacity if capacity is not None \
            else _env_int("AMTPU_LINEAGE_CAPACITY", DEFAULT_CAPACITY)
        self.max_hops = max_hops if max_hops is not None \
            else _env_int("AMTPU_LINEAGE_MAX_HOPS", DEFAULT_MAX_HOPS)
        #: always-on dwell/visibility store: per-stage ``dwell:<stage>``
        #: histograms + end-to-end ``visibility`` spans, fed at record
        #: time so accuracy is independent of chain eviction
        self.telemetry = Telemetry()
        self._lock = threading.Lock()
        # memoized sampling decisions: hop sites evaluate the same
        # (actor, seq) dozens of times along one change's journey, and
        # the sha1 is pure — bounded (wholesale-cleared at the cap, a
        # cache, never a record; GIL-atomic get/set, a racing clear just
        # recomputes)
        self._sample_cache: dict = {}
        # (actor, seq) -> chain dict; insertion-ordered so capacity
        # eviction drops the OLDEST chain deterministically
        self._chains: OrderedDict = OrderedDict()
        self.stats = {"chains_started": 0, "chains_evicted": 0,
                      "hops_recorded": 0, "hops_deduped": 0,
                      "hops_dropped_cap": 0, "context_adopted": 0,
                      "context_ignored": 0}

    # -- sampling -------------------------------------------------------

    def sampled(self, actor: str, seq: int) -> bool:
        key = (actor, seq)
        hit = self._sample_cache.get(key)
        if hit is None:
            if len(self._sample_cache) >= 65536:
                self._sample_cache.clear()
            hit = self._sample_cache[key] = \
                sample_key(actor, seq) % self.rate == 0
        return hit

    # -- write side -----------------------------------------------------

    #: Stage pairs whose dwell is measured between the MATCHING hops at
    #: the SAME site, not to whatever hop lands next on the shared
    #: chain: an interleaved hop from another replica (a retransmit, a
    #: commit elsewhere) must not truncate the reported parked/deferred
    #: period — these are the headline dwell numbers the cfg14 row and
    #: the soak summary report.
    PAIRED_DWELL = {"quar/release": "quar/park", "svc/admit": "svc/defer",
                    # residency page-in dwell: bundle pop + h2d staging,
                    # opened by res/page_wait at the adopting lane site
                    "res/page_in": "res/page_wait"}

    def record(self, actor: str, seq: int, stage: str, site=None,
               doc=None, extra=0, t_ns: Optional[int] = None) -> bool:
        """Append one hop to the change's chain (creating the chain on
        first sight).  Returns False when the change is not in the
        sampled subset or the hop deduped.  Dedup key: ``(stage, site,
        extra)`` — dup delivery of the same hop never grows the chain;
        distinguishable repeats (retransmit attempts) pass a distinct
        ``extra``.  An ``origin`` hop adopted AFTER later hops (late
        wire context for a chain another path already committed)
        prepends — it carries the oldest timestamp and must never make
        a finished chain look mid-flight."""
        if not self.sampled(actor, seq):
            return False
        if t_ns is None:
            t_ns = now_ns()
        site = site or ""
        key = (actor, seq)
        hop_key = (stage, site, extra)
        dwells = []
        visibility = []
        with self._lock:
            chain = self._chains.get(key)
            if chain is None:
                while len(self._chains) >= self.capacity:
                    self._chains.popitem(last=False)
                    self.stats["chains_evicted"] += 1
                chain = self._chains[key] = {
                    "actor": actor, "seq": seq, "origin_ns": None,
                    "origin_site": None, "hops": [], "keys": set(),
                    "docs": set()}
                self.stats["chains_started"] += 1
            if hop_key in chain["keys"] \
                    or (stage == "origin"
                        and chain["origin_ns"] is not None):
                self.stats["hops_deduped"] += 1
                return False
            if len(chain["hops"]) >= self.max_hops:
                self.stats["hops_dropped_cap"] += 1
                return False
            opener = self.PAIRED_DWELL.get(stage)
            if opener is not None:
                # paired dwell: latest matching opener at THIS site
                for h_stage, h_site, h_ts, _x in reversed(chain["hops"]):
                    if h_stage == opener and h_site == site:
                        dwells.append((opener, max(0, t_ns - h_ts)))
                        break
            elif chain["hops"] and stage != "origin":
                prev_stage, _ps, prev_ts, _pe = chain["hops"][-1]
                if prev_stage not in self.PAIRED_DWELL.values():
                    dwells.append((prev_stage, max(0, t_ns - prev_ts)))
            chain["keys"].add(hop_key)
            if stage == "origin" and chain["hops"]:
                # late-adopted origin: prepend (oldest timestamp), and
                # retroactively emit the visibility samples the earlier
                # commit hops could not compute without an origin
                chain["hops"].insert(0, (stage, site, t_ns, extra))
            else:
                chain["hops"].append((stage, site, t_ns, extra))
            self.stats["hops_recorded"] += 1
            if stage == "origin":
                chain["origin_ns"] = t_ns
                chain["origin_site"] = site
                for h_stage, h_site, h_ts, _x in chain["hops"][1:]:
                    if h_stage in VISIBILITY_STAGES and h_site != site:
                        visibility.append((max(0, h_ts - t_ns), h_ts))
            if stage in VISIBILITY_STAGES:
                if doc is not None:
                    chain["docs"].add(doc)
                if chain["origin_ns"] is not None \
                        and site != chain["origin_site"]:
                    visibility.append(
                        (max(0, t_ns - chain["origin_ns"]), t_ns))
        # telemetry + obs emission OUTSIDE the chain lock (the store has
        # its own striped locks; the obs ring likewise)
        for d_stage, d_ns in dwells:
            self.telemetry.observe_span("lineage", f"dwell:{d_stage}",
                                        d_ns, ts_ns=t_ns)
        for v_ns, v_ts in visibility:
            self.telemetry.observe_span("lineage", "visibility",
                                        v_ns, ts_ns=v_ts)
        import automerge_tpu.obs as _obs
        if _obs.ENABLED:
            args = {"actor": actor, "seq": seq, "site": site}
            if doc is not None:
                args["doc"] = doc
            if extra:
                args["extra"] = str(extra)
            _obs.event("lineage", stage, args=args)
        return True

    def adopt(self, entries) -> int:
        """Merge wire trace context — ``[[actor, seq, origin_ns,
        origin_site], ...]`` — into the ledger: each SAMPLED entry
        ensures a chain exists with its origin hop pinned at the
        sender's origin timestamp/site.  Unsampled entries are counted
        and ignored (hostile or stale context cannot grow the ledger
        beyond the deterministic subset).  Returns adopted count."""
        n = 0
        for ent in entries:
            actor, seq, t0, site = ent
            if not self.sampled(actor, seq):
                self.stats["context_ignored"] += 1
                continue
            if self.record(actor, seq, "origin", site=site, t_ns=t0):
                n += 1
                self.stats["context_adopted"] += 1
        return n

    def adopt_clock(self, clock: dict, site=None, doc=None,
                    t_ns: Optional[int] = None) -> int:
        """Snapshot-bootstrap visibility: every retained chain whose
        ``(actor, seq)`` the adopted checkpoint clock covers gains a
        ``ckpt/adopt`` hop at `site` — the change became visible on
        this replica inside the bundle, without re-crossing the wire.
        Bounded by the ledger's own chain count, never the clock."""
        with self._lock:
            keys = list(self._chains.keys())
        n = 0
        for actor, seq in keys:
            if clock.get(actor, 0) >= seq:
                if self.record(actor, seq, "ckpt/adopt", site=site,
                               doc=doc, t_ns=t_ns):
                    n += 1
        return n

    # -- read side ------------------------------------------------------

    @property
    def n_chains(self) -> int:
        return len(self._chains)

    def chain(self, actor: str, seq: int) -> Optional[dict]:
        """One chain's snapshot: {"actor", "seq", "origin_ns",
        "origin_site", "docs", "hops": [(stage, site, ts_ns, extra)]}
        or None."""
        with self._lock:
            c = self._chains.get((actor, seq))
            if c is None:
                return None
            return {"actor": c["actor"], "seq": c["seq"],
                    "origin_ns": c["origin_ns"],
                    "origin_site": c["origin_site"],
                    "docs": set(c["docs"]), "hops": list(c["hops"])}

    def chains(self) -> list:
        """Snapshots of every retained chain (insertion order)."""
        with self._lock:
            keys = list(self._chains.keys())
        out = []
        for actor, seq in keys:
            c = self.chain(actor, seq)
            if c is not None:
                out.append(c)
        return out

    @staticmethod
    def visible_sites(chain: dict) -> set:
        """Sites where the chain's change is committed/visible."""
        return {site for stage, site, _ts, _x in chain["hops"]
                if stage in VISIBILITY_STAGES}

    def context_for(self, keys) -> list:
        """Wire trace-context entries for the sampled changes among
        `keys` (``(actor, seq)`` pairs) whose origin this ledger knows:
        ``[[actor, seq, origin_ns, origin_site], ...]``, deduped."""
        out = []
        seen = set()
        for actor, seq in keys:
            k = (actor, seq)
            if k in seen or not self.sampled(actor, seq):
                continue
            seen.add(k)
            with self._lock:
                c = self._chains.get(k)
                if c is None or c["origin_ns"] is None:
                    continue
                out.append([actor, seq, c["origin_ns"],
                            c["origin_site"] or ""])
        return out

    def visibility_ms(self, p: float) -> float:
        """Conservative end-to-end visibility-latency quantile bound in
        milliseconds (log-bucket histogram; 0.0 with no samples)."""
        return round(
            self.telemetry.quantile_ns("lineage", "visibility", p) / 1e6,
            3)

    def max_dwell_ms(self, stage: str) -> float:
        """Exact maximum dwell observed in `stage` (time from the
        stage's hop to the chain's next hop), ms."""
        agg = self.telemetry.span_aggregates().get(
            ("lineage", f"dwell:{stage}"))
        return round(agg["max_ns"] / 1e6, 3) if agg else 0.0

    def stuck(self, k: int = 8, at_ns: Optional[int] = None) -> list:
        """The K most-stuck sampled changes: chains with NO visibility
        hop anywhere yet (mid-flight), ranked by dwell since their last
        hop — the postmortem's "which hop is it stuck on" answer.
        (Visibility-anywhere, not last-hop-shape: a late retransmit or
        adopted hop landing after a commit must not resurrect a
        finished chain onto this list.)  Falls back to the slowest
        completed chains when nothing is mid-flight."""
        if at_ns is None:
            at_ns = now_ns()
        scored = []
        for c in self.chains():
            if not c["hops"]:
                continue
            last_stage, last_site, last_ts, _x = c["hops"][-1]
            mid_flight = not self.visible_sites(c)
            scored.append((mid_flight, at_ns - last_ts, c))
        scored.sort(key=lambda t: (not t[0], -t[1]))
        out = []
        for mid_flight, dwell_ns, c in scored[:k]:
            t0 = c["origin_ns"] if c["origin_ns"] is not None \
                else c["hops"][0][2]
            out.append({
                "actor": c["actor"], "seq": c["seq"],
                "origin_site": c["origin_site"],
                "docs": sorted(c["docs"]),
                "mid_flight": mid_flight,
                "stuck_at": c["hops"][-1][0],
                "stuck_site": c["hops"][-1][1],
                "dwell_ms": round(dwell_ns / 1e6, 3),
                "hops": [[stage, site, round((ts - t0) / 1e6, 3)]
                         + ([str(extra)] if extra else [])
                         for stage, site, ts, extra in c["hops"]],
            })
        return out

    def postmortem(self, k: int = 8) -> dict:
        """The JSON-serializable lineage block ``SyncService.describe()``
        embeds: config, exact counters, and the K most-stuck chains
        with their full hop chains (INTERNALS §18.4)."""
        agg = self.telemetry.span_aggregates()
        dwell_max = {key[1][len("dwell:"):]: round(v["max_ns"] / 1e6, 3)
                     for key, v in agg.items()
                     if key[0] == "lineage" and key[1].startswith("dwell:")}
        return {
            "schema": "amtpu-lineage-v1",
            "rate": self.rate,
            "capacity": self.capacity,
            "chains": self.n_chains,
            "stats": dict(self.stats),
            "visibility_p50_ms": self.visibility_ms(0.50),
            "visibility_p99_ms": self.visibility_ms(0.99),
            "max_dwell_ms": dwell_max,
            "stuck": self.stuck(k),
        }

    def families(self, prefix: str = "amtpu_lineage") -> list:
        """Prometheus exposition families: per-stage dwell + visibility
        histograms (from the ledger's telemetry store), ledger counters,
        and visibility quantile gauges — what ``SyncService.scrape()``
        appends when lineage is enabled."""
        from . import prom
        fams = prom.telemetry_families(self.telemetry, prefix)
        fams.append((
            f"{prefix}_ledger_total", "counter",
            "Exact lineage ledger counters (survive chain eviction).",
            [({"name": k}, v) for k, v in sorted(self.stats.items())]))
        fams.append((
            f"{prefix}_chains", "gauge",
            "Sampled chains currently retained (bounded by "
            "AMTPU_LINEAGE_CAPACITY).",
            [({}, self.n_chains)]))
        fams.append((
            f"{prefix}_visibility_ms", "gauge",
            "End-to-end origin->remote-visibility latency quantile "
            "bounds (log-bucket conservative).",
            [({"q": "p50"}, self.visibility_ms(0.50)),
             ({"q": "p99"}, self.visibility_ms(0.99))]))
        return fams

    def clear(self):
        with self._lock:
            self._chains = OrderedDict()
            for k in self.stats:
                self.stats[k] = 0
        self.telemetry.clear()


# ---------------------------------------------------------------------------
# module-level singleton + the hop-site emit surface
# ---------------------------------------------------------------------------


def ledger() -> Optional[LineageLedger]:
    """The live ledger (None when lineage never enabled)."""
    return _ledger


def enable(rate: Optional[int] = None,
           capacity: Optional[int] = None) -> LineageLedger:
    """Turn lineage tracing on (idempotent).  A ledger is created on
    first enable and retained across disable() so late readers can
    still export; pass `rate`/`capacity` to size a fresh one."""
    global ENABLED, _ledger
    if _ledger is None or rate is not None or capacity is not None:
        r = rate if rate is not None else _env_int(
            "AMTPU_LINEAGE_RATE", 64)
        _ledger = LineageLedger(r, capacity=capacity)
    ENABLED = True
    return _ledger


def disable():
    global ENABLED
    ENABLED = False


def clear():
    if _ledger is not None:
        _ledger.clear()


def sampled(actor: str, seq: int) -> bool:
    led = _ledger
    return led is not None and led.sampled(actor, seq)


def hop(actor: str, seq: int, stage: str, site=None, doc=None, extra=0,
        t_ns: Optional[int] = None):
    """Record one hop for one change — call ONLY behind an
    ``if lineage.ENABLED:`` check (the one-flag-per-site contract)."""
    led = _ledger
    if led is not None:
        led.record(actor, seq, stage, site=site, doc=doc, extra=extra,
                   t_ns=t_ns)


def change_keys(delivery):
    """``(actor, seq)`` pairs of one delivery: a list of wire change
    dicts, a decoded columnar batch, or a WireFrame-shaped object.
    Never forces a frame decode (an undecoded frame yields nothing —
    the receive side decodes before its hops run)."""
    if delivery is None:
        return []
    if hasattr(delivery, "data") and callable(
            getattr(delivery, "batch", None)):  # WireFrame-shaped: read
        # ONLY the caches (hasattr on its n_changes PROPERTY would
        # decode; the send path must never pay that)
        chs = getattr(delivery, "_changes", None)
        if chs is not None:
            return [(c["actor"], c["seq"]) for c in chs]
        batch = getattr(delivery, "_batch", None)
        if batch is None:
            return []
        return list(zip(batch.actors, batch.seqs.tolist()))
    if hasattr(delivery, "n_changes"):          # decoded columnar batch
        return list(zip(delivery.actors, delivery.seqs.tolist()))
    return [(c["actor"], c["seq"]) for c in delivery
            if isinstance(c, dict) and "actor" in c and "seq" in c]


def hop_delivery(delivery, stage: str, site=None, doc=None, extra=0,
                 t_ns: Optional[int] = None):
    """Record `stage` for every sampled change in a delivery (change
    dicts / decoded batch / frame)."""
    led = _ledger
    if led is None:
        return
    for actor, seq in change_keys(delivery):
        led.record(actor, seq, stage, site=site, doc=doc, extra=extra,
                   t_ns=t_ns)


def payload_keys(payload):
    """``(actor, seq)`` pairs of one channel payload (a sync message
    dict, possibly carrying both a dict-change prefix and a binary
    frame).  Undecoded frames contribute their cached change list (set
    at mint time by ``split_outgoing``) — the send path never pays a
    decode."""
    if not isinstance(payload, dict):
        return []
    out = change_keys(payload.get("changes") or ())
    wire = payload.get("wire")
    if wire is not None:
        out.extend(change_keys(wire))
    return out


def context_for(delivery) -> Optional[list]:
    """Wire trace-context for a delivery's sampled changes (None when
    empty or lineage is off) — what the hub attaches to outbound
    messages/frames."""
    led = _ledger
    if led is None:
        return None
    ctx = led.context_for(change_keys(delivery))
    return ctx or None


def adopt(entries):
    """Merge received wire trace context (already schema-validated by
    the wire layer) into the ledger."""
    led = _ledger
    if led is not None and entries:
        led.adopt(entries)


def adopt_clock(clock: dict, site=None, doc=None):
    led = _ledger
    if led is not None:
        led.adopt_clock(clock, site=site, doc=doc)


def site_of(doc_set) -> str:
    """The replica-site label for a DocSet: its explicit
    ``_lineage_site`` when the owner named one (the service names
    rooms ``svc:<room>``, soak clients their tenant id), else a
    process-local fallback that at least separates doc sets."""
    site = getattr(doc_set, "_lineage_site", None)
    return site if site else f"ds-{id(doc_set) & 0xffff:04x}"


def postmortem(k: int = 8) -> Optional[dict]:
    led = _ledger
    return led.postmortem(k) if led is not None else None


def families(prefix: str = "amtpu_lineage") -> list:
    led = _ledger
    return led.families(prefix) if led is not None else []


# honor AMTPU_LINEAGE_RATE at import (mirrors AMTPU_TRACE): a soak or CI
# step enables sampling with an env var, no code path needed
if os.environ.get("AMTPU_LINEAGE_RATE", "0") not in ("", "0"):
    try:
        enable(int(os.environ["AMTPU_LINEAGE_RATE"]))
    except ValueError:
        pass

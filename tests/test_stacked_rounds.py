"""Stacked multi-object rounds vs the per-object path (INTERNALS §12).

The stacked executor (engine/stacked.py, the AMTPU_STACKED_ROUNDS
default) must produce EXACTLY the per-object path's committed state on
every nested-document delivery: same materialized document, same
serialized change log, same per-object engine registers/conflicts/
clocks — across out-of-order chunked deliveries, duplicates, mixed
map+text objects, multi-round causal chains, and BOTH host planners
(AMTPU_COLUMNAR_PLAN 0/1). Plus the tentpole's accounting contract:
a cfg4-shaped commit dispatches a constant number of device programs
per causal round, independent of object count."""

import json
import os
import random

import pytest

import automerge_tpu as am
from automerge_tpu import frontend as Frontend
from automerge_tpu._common import ROOT_ID
from automerge_tpu.backend import device as device_backend
from automerge_tpu.backend import facade as oracle_backend
from automerge_tpu.engine import stacked
from automerge_tpu.engine.map_doc import DeviceMapDoc


@pytest.fixture(autouse=True)
def _small_gate(monkeypatch):
    """Engage the stacked path at test scale (the production gate skips
    tiny interactive rounds)."""
    monkeypatch.setenv("AMTPU_STACKED_MIN_OPS", "1")


# ---------------------------------------------------------------------------
# randomized nested-board generation (oracle-minted, so every delivery
# is valid; parity shares ONE change set across both paths)
# ---------------------------------------------------------------------------


def make_board(n_cards=4):
    return am.change(am.init("base"), lambda d: d.update(
        {"cards": [{"title": f"card{i}", "meta": {"prio": i},
                    "tasks": [f"t{j}" for j in range(3)]}
                   for i in range(n_cards)],
         "name": "board"}))


def rand_peer_changes(rng, base, n_actors=10, n_cards=4, chained=False):
    """Concurrent peer edits over the shared board: task appends/inserts/
    deletes (text-tier lists), title/meta register writes and deletes
    (map tier), root-key writes — the cfg4 mixed shape. `chained` makes
    every peer emit TWO causally chained changes, forcing multi-round
    stacked schedules."""
    base_changes = am.get_all_changes(base)
    out = []
    for a in range(n_actors):
        peer = am.apply_changes(
            am.init({"actorId": f"actor-{a:05d}",
                     "backend": oracle_backend.Backend}), base_changes)
        k = rng.randrange(n_cards)
        r = rng.random()
        if r < 0.3:
            p2 = am.change(peer, lambda d, k=k, a=a:
                           d["cards"][k]["tasks"].append(f"new-{a}"))
        elif r < 0.45:
            p2 = am.change(peer, lambda d, k=k, a=a:
                           d["cards"][k]["tasks"].insert(0, f"front-{a}"))
        elif r < 0.6:
            p2 = am.change(peer, lambda d, k=k:
                           d["cards"][k]["tasks"].__delitem__(0))
        elif r < 0.75:
            p2 = am.change(peer, lambda d, k=k, a=a:
                           d["cards"][k].__setitem__("title", f"re-{a}"))
        elif r < 0.85:
            p2 = am.change(peer, lambda d, k=k, a=a:
                           d["cards"][k]["meta"].__setitem__("prio", a))
        else:
            p2 = am.change(peer, lambda d, a=a:
                           d.__setitem__("name", f"board-{a}"))
        if chained:
            p2 = am.change(p2, lambda d, k=k, a=a:
                           d["cards"][k]["tasks"].append(f"second-{a}"))
        out.append(am.get_changes(base, p2))
    return out


def engine_state(doc):
    """Everything the committed per-object device state consists of."""
    state = Frontend.get_backend_state(doc)
    assert isinstance(state, device_backend.DeviceBackendState), \
        "document unexpectedly graduated off the device tier"
    core = state._core
    core.flush_pending()
    out = {"clock": dict(core.clock), "deps": dict(core.deps),
           "order": list(core.obj_order)}
    wrappers = {ROOT_ID: core.root}
    wrappers.update(core.objects)
    for oid, w in wrappers.items():
        d = w.doc
        if isinstance(d, DeviceMapDoc):
            out[oid] = {
                "kind": w.kind,
                "items": d.to_dict(),
                "conflicts": {k: d.conflicts_for(k) for k in d._key_slot
                              if d.conflicts_for(k)},
                "clock": dict(d.clock),
            }
        else:
            out[oid] = {
                "kind": w.kind,
                "values": d.values(),
                "elem_ids": d.elem_ids(),
                "conflicts": {i: d.conflicts_at(i)
                              for i in range(len(d))
                              if d.conflicts_at(i)},
                "clock": dict(d.clock),
            }
    return out


def apply_with(flag, base, deliveries, monkeypatch):
    monkeypatch.setenv("AMTPU_STACKED_ROUNDS", flag)
    doc = base
    for chunk in deliveries:
        doc = am.apply_changes(doc, chunk)
    return doc


def canon(doc):
    return json.dumps(am.to_json(doc), sort_keys=True, default=str)


# ---------------------------------------------------------------------------
# parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("columnar", ["1", "0"])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_randomized_board_parity(seed, columnar, monkeypatch):
    """Randomized mixed map+text board merges: stacked and per-object
    paths commit byte-identical state under both host planners."""
    monkeypatch.setenv("AMTPU_COLUMNAR_PLAN", columnar)
    rng = random.Random(seed)
    base = make_board()
    changes = [c for cs in rand_peer_changes(rng, base, n_actors=12)
               for c in cs]
    deliveries = [list(changes)]
    stacked.LAST_STATS.clear()
    d1 = apply_with("1", base, deliveries, monkeypatch)
    assert stacked.LAST_STATS, "stacked path did not engage"
    d0 = apply_with("0", base, deliveries, monkeypatch)
    assert canon(d1) == canon(d0)
    assert am.save(d1) == am.save(d0)
    assert engine_state(d1) == engine_state(d0)
    stacked.assert_round_budget()


@pytest.mark.parametrize("seed", [3, 4])
def test_out_of_order_dup_chunked_parity(seed, monkeypatch):
    """Shuffled chunked deliveries with duplicates: core admission queues
    premature changes and skips dups; the stacked engine must commit the
    same state as the per-object path through every partial apply."""
    rng = random.Random(seed)
    base = make_board()
    per_peer = rand_peer_changes(rng, base, n_actors=10, chained=True)
    changes = [c for cs in per_peer for c in cs]
    rng.shuffle(changes)                       # out-of-order delivery
    for _ in range(3):                         # duplicated deliveries
        changes.insert(rng.randrange(len(changes) + 1),
                       dict(rng.choice(changes)))
    chunks = []
    i = 0
    while i < len(changes):
        n = rng.randrange(1, 8)
        chunks.append(changes[i: i + n])
        i += n
    d1 = apply_with("1", base, chunks, monkeypatch)
    d0 = apply_with("0", base, chunks, monkeypatch)
    assert canon(d1) == canon(d0)
    assert am.save(d1) == am.save(d0)
    assert engine_state(d1) == engine_state(d0)


def test_multi_round_causal_chains_parity(monkeypatch):
    """Every peer emits two causally chained changes in one delivery:
    per-object admission schedules >= 2 rounds and the stacked engine
    must execute them as ordered stacked passes."""
    rng = random.Random(7)
    base = make_board()
    changes = [c for cs in rand_peer_changes(rng, base, n_actors=8,
                                             chained=True)
               for c in cs]
    stacked.LAST_STATS.clear()
    d1 = apply_with("1", base, [changes], monkeypatch)
    assert stacked.LAST_STATS.get("rounds", 0) >= 2
    d0 = apply_with("0", base, [changes], monkeypatch)
    assert canon(d1) == canon(d0)
    assert engine_state(d1) == engine_state(d0)
    stacked.assert_round_budget()


def test_interactive_then_flush_parity(monkeypatch):
    """Write-behind fast-path rounds (cached routing triples) followed by
    a remote delivery that flushes them: the flush replays through
    `_distribute(routed=...)` without re-walking ops, on both paths."""
    def run(flag):
        monkeypatch.setenv("AMTPU_STACKED_ROUNDS", flag)
        base = make_board()
        doc = am.change(base, lambda d: d["cards"][0]
                        .__setitem__("title", "local-edit"))
        doc = am.change(doc, lambda d: d["cards"][1]["meta"]
                        .__setitem__("prio", 99))
        core = Frontend.get_backend_state(doc)._core
        assert core.pending, "fast path did not engage"
        assert len(core._pending_routed) == len(core.pending)
        peer = am.apply_changes(
            am.init({"actorId": "remote-peer",
                     "backend": oracle_backend.Backend}),
            am.get_all_changes(base))
        p2 = am.change(peer, lambda d: d["cards"][2]["tasks"]
                       .append("remote-task"))
        doc = am.apply_changes(doc, am.get_changes(base, p2))
        core = Frontend.get_backend_state(doc)._core
        assert not core._pending_routed
        return doc
    d1, d0 = run("1"), run("0")
    assert canon(d1) == canon(d0)


# ---------------------------------------------------------------------------
# the accounting contract (the tentpole's acceptance criterion)
# ---------------------------------------------------------------------------


def _board_merge_stats(n_cards, n_actors, monkeypatch):
    monkeypatch.setenv("AMTPU_STACKED_ROUNDS", "1")
    base = make_board(n_cards=n_cards)
    base_changes = am.get_all_changes(base)
    changes = []
    for a in range(n_actors):
        peer = am.apply_changes(
            am.init({"actorId": f"actor-{a:05d}",
                     "backend": oracle_backend.Backend}), base_changes)
        k = a % n_cards
        if a % 2:
            p2 = am.change(peer, lambda d, k=k, a=a:
                           d["cards"][k]["tasks"].append(f"n-{a}"))
        else:
            p2 = am.change(peer, lambda d, k=k, a=a:
                           d["cards"][k].__setitem__("title", f"r-{a}"))
        changes.extend(am.get_changes(base, p2))
    stacked.LAST_STATS.clear()
    am.apply_changes(base, changes)
    assert stacked.LAST_STATS, "stacked path did not engage"
    return dict(stacked.LAST_STATS)


def test_dispatch_budget_object_count_independent(monkeypatch):
    """THE acceptance criterion: a cfg4-shaped commit executes <= a
    constant number of device dispatches per causal round, independent
    of object count — tripling the board's object population must not
    change the dispatch count at all (same round/shape structure)."""
    small = _board_merge_stats(n_cards=4, n_actors=8, monkeypatch=monkeypatch)
    large = _board_merge_stats(n_cards=12, n_actors=24,
                               monkeypatch=monkeypatch)
    assert large["docs"] > 2 * small["docs"]
    assert small["passes"] == large["passes"] == 1
    assert large["dispatches"] == small["dispatches"], (
        f"dispatches scaled with object count: "
        f"{small['docs']} objs -> {small['dispatches']}, "
        f"{large['docs']} objs -> {large['dispatches']}")
    for s in (small, large):
        limit = (stacked.APPLY_DISPATCH_BASE
                 + stacked.PASS_DISPATCH_BUDGET * s["passes"])
        assert s["dispatches"] <= limit
        assert s["syncs"] <= 2 + 2 * s["passes"]


def test_stacked_spans_recorded(monkeypatch):
    """The new path is observable: plan/stack + commit/stacked_round
    spans and the stacked kernel dispatch counters reach the flight
    recorder (PR-6 tier)."""
    from automerge_tpu import obs
    monkeypatch.setenv("AMTPU_STACKED_ROUNDS", "1")
    rng = random.Random(11)
    base = make_board()
    changes = [c for cs in rand_peer_changes(rng, base, n_actors=8)
               for c in cs]
    with obs.tracing():
        am.apply_changes(base, changes)
        rec = obs.recorder()
        names = {(r[obs.CAT], r[obs.NAME]) for r in rec.snapshot()}
        counters = obs.metrics_snapshot()["counters"]
    assert ("plan", "stack") in names
    assert ("commit", "stacked_round") in names
    assert any(k.startswith("device.dispatch:stacked_")
               for k in counters), counters


def test_per_object_comparator_unchanged(monkeypatch):
    """AMTPU_STACKED_ROUNDS=0 never enters the stacked engine."""
    monkeypatch.setenv("AMTPU_STACKED_ROUNDS", "0")
    rng = random.Random(13)
    base = make_board()
    changes = [c for cs in rand_peer_changes(rng, base, n_actors=6)
               for c in cs]
    stacked.LAST_STATS.clear()
    am.apply_changes(base, changes)
    assert not stacked.LAST_STATS


# ---------------------------------------------------------------------------
# cross-doc planning through the stacked executor (INTERNALS §16)
# ---------------------------------------------------------------------------


def test_stacked_stats_carry_index_merge_budget(monkeypatch):
    """Every stacked apply's stats carry the ISSUE-12 bulk-update
    accounting (index_merges <= planned text rounds), and the budget
    assert rejects a violated count."""
    import pytest

    from automerge_tpu.engine.text_doc import DeviceTextDoc

    monkeypatch.setenv("AMTPU_CROSS_DOC_PLAN", "1")
    docs = {f"b{i}": DeviceTextDoc(f"b{i}") for i in range(4)}
    items = []
    for k, doc in docs.items():
        ops = []
        key = "_head"
        for j in range(1, 9):
            ops.append({"action": "ins", "obj": k, "key": key, "elem": j})
            ops.append({"action": "set", "obj": k, "key": f"a:{j}",
                        "value": chr(97 + j)})
            key = f"a:{j}"
        items.append((doc, [{"actor": "a", "seq": 1, "deps": {},
                             "ops": ops}]))
    st = stacked.apply_stacked(items)
    assert st
    assert st["index_merges"] == st["text_plans"] == 4
    assert st["cross_doc"]["sched_shared"] == 3
    stacked.assert_round_budget(st)
    bad = {**st, "index_merges": st["text_plans"] + 1}
    with pytest.raises(AssertionError, match="bulk merge per doc"):
        stacked.assert_round_budget(bad)


def test_cross_doc_disabled_keeps_per_doc_path(monkeypatch):
    """AMTPU_CROSS_DOC_PLAN=0: the stacked apply carries no cross_doc
    stats and still commits the identical state (the comparator
    contract the randomized suites pin at population scale)."""
    from automerge_tpu.engine.text_doc import DeviceTextDoc

    def build(flag):
        monkeypatch.setenv("AMTPU_CROSS_DOC_PLAN", flag)
        docs = {f"c{i}": DeviceTextDoc(f"c{i}") for i in range(3)}
        items = []
        for k, doc in docs.items():
            ops = []
            key = "_head"
            for j in range(1, 7):
                ops.append({"action": "ins", "obj": k, "key": key,
                            "elem": j})
                ops.append({"action": "set", "obj": k, "key": f"a:{j}",
                            "value": chr(110 + j)})
                key = f"a:{j}"
            items.append((doc, [{"actor": "a", "seq": 1, "deps": {},
                                 "ops": ops}]))
        st = stacked.apply_stacked(items)
        assert st
        return docs, st

    docs_on, st_on = build("1")
    docs_off, st_off = build("0")
    assert "cross_doc" in st_on and "cross_doc" not in st_off
    for k in docs_on:
        assert docs_on[k].text() == docs_off[k].text()

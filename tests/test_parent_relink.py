"""Targeted parent relinking (InboundIndex.key_of, apply_patch.py).

A nested change used to relink its parent by scanning EVERY key of the
parent (~70 ms per one-key change under a 100k-key root); map parents now
relink the updated children directly at their recorded keys. These tests
pin the semantics the targeted path must preserve, including the
fallback cases (lists, plain-dict inbound callers).
"""

import automerge_tpu as am
from automerge_tpu.frontend.apply_patch import InboundIndex, copy_inbound


def test_nested_map_change_propagates_to_root():
    doc = am.change(am.init({"actorId": "u"}),
                    lambda d: d.__setitem__("sub", {"a": 1, "obj": {"x": 0}}))
    doc2 = am.change(doc, lambda d: d["sub"].__setitem__("a", 2))
    assert am.to_json(doc2)["sub"]["a"] == 2
    assert am.to_json(doc)["sub"]["a"] == 1       # old snapshot intact
    doc3 = am.change(doc2, lambda d: d["sub"]["obj"].__setitem__("x", 9))
    assert am.to_json(doc3)["sub"]["obj"]["x"] == 9
    assert am.to_json(doc2)["sub"]["obj"]["x"] == 0


def test_sibling_children_both_relinked_in_one_change():
    doc = am.change(am.init({"actorId": "u"}), lambda d: d.update(
        {"a": {"n": 1}, "b": {"n": 2}}))
    doc2 = am.change(doc, lambda d: (d["a"].__setitem__("n", 10),
                                     d["b"].__setitem__("n", 20)))
    j = am.to_json(doc2)
    assert j["a"]["n"] == 10 and j["b"]["n"] == 20


def test_child_moved_by_overwrite_in_same_patch():
    """Overwriting a key whose old value was an object must not leave the
    stale child resurrected by the relink pass."""
    doc = am.change(am.init({"actorId": "u"}),
                    lambda d: d.__setitem__("k", {"old": True}))
    doc2 = am.change(doc, lambda d: d.__setitem__("k", "plain"))
    assert am.to_json(doc2)["k"] == "plain"


def test_remote_merge_relinks_nested_children():
    base = am.change(am.init({"actorId": "base"}),
                     lambda d: d.__setitem__("sub", {"a": 0}))
    peer = am.merge(am.init({"actorId": "peer"}), base)
    peer = am.change(peer, lambda d: d["sub"].__setitem__("a", 7))
    merged = am.merge(base, peer)
    assert am.to_json(merged)["sub"]["a"] == 7


def test_objects_inside_lists_still_relink():
    """List children record no key (indices shift) — the scan fallback
    must still propagate their updates."""
    doc = am.change(am.init({"actorId": "u"}),
                    lambda d: d.__setitem__("xs", [{"n": 1}, {"n": 2}]))
    doc2 = am.change(doc, lambda d: d["xs"][1].__setitem__("n", 22))
    assert am.to_json(doc2)["xs"][1]["n"] == 22
    # and after a shifting splice, updates still land at the right object
    doc3 = am.change(doc2, lambda d: d["xs"].insert(0, "pad"))
    doc4 = am.change(doc3, lambda d: d["xs"][2].__setitem__("n", 33))
    assert am.to_json(doc4)["xs"] == ["pad", {"n": 1}, {"n": 33}]


def test_inbound_index_copy_isolated():
    idx = InboundIndex({"c1": "p1"})
    idx.key_of["c1"] = "k1"
    cp = copy_inbound(idx)
    cp["c2"] = "p1"
    cp.key_of["c2"] = "k2"
    assert "c2" not in idx and "c2" not in idx.key_of
    assert cp.key_of["c1"] == "k1"
    # plain dicts keep working (older callers, tests)
    assert copy_inbound({"a": "b"}) == {"a": "b"}

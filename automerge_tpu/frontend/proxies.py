"""Mutable-feeling views over document objects inside change blocks.

Counterpart of /root/reference/frontend/proxies.js, re-idiomized: instead of ES
Proxy traps, Python mapping/sequence protocols plus attribute access. Reads
come from the context's updated/cache overlay; writes are recorded as ops and
optimistic diffs.
"""

from __future__ import annotations

from .types import ListDoc, MapDoc


class MapProxy:
    """dict-like view of a map object: `d['key']`, `d.key`, `in`, iteration."""

    __slots__ = ("_context", "_object_id")

    def __init__(self, context, object_id):
        object.__setattr__(self, "_context", context)
        object.__setattr__(self, "_object_id", object_id)

    def _target(self) -> MapDoc:
        return self._context.get_object(self._object_id)

    # -- mapping protocol --

    def __getitem__(self, key):
        if not dict.__contains__(self._target(), key):
            raise KeyError(key)
        return self._context.get_object_field(self._object_id, key)

    def __setitem__(self, key, value):
        self._context.set_map_key(self._object_id, self._type_tag(), key, value)

    def __delitem__(self, key):
        self._context.delete_map_key(self._object_id, key)

    def __contains__(self, key):
        return dict.__contains__(self._target(), key)

    def __iter__(self):
        return iter(self._target().keys())

    def __len__(self):
        return len(self._target())

    def keys(self):
        return self._target().keys()

    def values(self):
        return [self._context.get_object_field(self._object_id, k) for k in self._target()]

    def items(self):
        return [(k, self._context.get_object_field(self._object_id, k))
                for k in self._target()]

    def get(self, key, default=None):
        if dict.__contains__(self._target(), key):
            return self._context.get_object_field(self._object_id, key)
        return default

    def update(self, other=(), **kwargs):
        pairs = other.items() if isinstance(other, dict) else other
        for key, value in pairs:
            self[key] = value
        for key, value in kwargs.items():
            self[key] = value

    def _type_tag(self) -> str:
        return "map"

    # -- attribute-style access (doc.key = value) --

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        try:
            return self[name]
        except KeyError:
            raise AttributeError(name) from None

    def __setattr__(self, name, value):
        if name.startswith("_"):
            object.__setattr__(self, name, value)
        else:
            self[name] = value

    def __delattr__(self, name):
        if name.startswith("_"):
            object.__delattr__(self, name)
        else:
            del self[name]

    def __eq__(self, other):
        if isinstance(other, MapProxy):
            return self._object_id == other._object_id
        if isinstance(other, dict):
            return dict(self.items()) == other
        return NotImplemented

    def __repr__(self):
        return f"MapProxy({dict(self._target())!r})"

    def to_dict(self) -> dict:
        """Deep plain-Python snapshot of the current (in-block) state."""
        return {k: _plain(v) for k, v in self.items()}


class ListProxy:
    """list-like view of a list object, with the reference's list methods
    (insert_at/delete_at) plus Python sequence idioms."""

    __slots__ = ("_context", "_object_id")

    def __init__(self, context, object_id):
        object.__setattr__(self, "_context", context)
        object.__setattr__(self, "_object_id", object_id)

    def _target(self) -> ListDoc:
        return self._context.get_object(self._object_id)

    def _norm_index(self, index, for_insert=False):
        n = len(self._target())
        if index < 0:
            index += n
        if for_insert:
            return max(0, min(index, n))
        return index

    def __len__(self):
        return len(self._target())

    def __getitem__(self, index):
        if isinstance(index, slice):
            return [self[i] for i in range(*index.indices(len(self)))]
        index = self._norm_index(index)
        if not (0 <= index < len(self)):
            raise IndexError("list index out of range")
        return self._context.get_object_field(self._object_id, index)

    def __setitem__(self, index, value):
        if isinstance(index, slice):
            raise TypeError("slice assignment is not supported in change blocks; "
                            "use splice()")
        self._context.set_list_index(self._object_id, self._norm_index(index), value)

    def __delitem__(self, index):
        if isinstance(index, slice):
            indices = range(*index.indices(len(self)))
            if indices.step != 1:
                raise TypeError("stepped slice deletion is not supported")
            self._context.splice(self._object_id, indices.start, len(indices), [])
        else:
            self._context.splice(self._object_id, self._norm_index(index), 1, [])

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __contains__(self, value):
        return any(v == value for v in self)

    def append(self, value):
        self._context.insert_list_item(self._object_id, len(self), value)

    def extend(self, values):
        self._context.splice(self._object_id, len(self), 0, list(values))

    def insert(self, index, value):
        self._context.insert_list_item(
            self._object_id, self._norm_index(index, for_insert=True), value)

    def insert_at(self, index, *values):
        self._context.splice(self._object_id, index, 0, list(values))
        return self

    def delete_at(self, index, num_delete=1):
        self._context.splice(self._object_id, index, num_delete, [])
        return self

    def splice(self, start, deletions=0, insertions=()):
        self._context.splice(self._object_id, start, deletions, list(insertions))

    def pop(self, index=-1):
        index = self._norm_index(index)
        value = self[index]
        self._context.splice(self._object_id, index, 1, [])
        return value

    def remove(self, value):
        for i, v in enumerate(self):
            if v == value:
                self._context.splice(self._object_id, i, 1, [])
                return
        raise ValueError(f"{value!r} not in list")

    def index(self, value):
        for i, v in enumerate(self):
            if v == value:
                return i
        raise ValueError(f"{value!r} not in list")

    def count(self, value):
        return sum(1 for v in self if v == value)

    def __eq__(self, other):
        if isinstance(other, ListProxy):
            return self._object_id == other._object_id
        if isinstance(other, (list, tuple)):
            return list(self) == list(other)
        return NotImplemented

    def __repr__(self):
        return f"ListProxy({list(self._target())!r})"

    def to_list(self) -> list:
        return [_plain(v) for v in self]


class TextProxy:
    """Live view of a Text object inside a change block: reads always come
    from the context's current overlay, so captured references never go stale."""

    __slots__ = ("_context", "_object_id")

    def __init__(self, context, object_id):
        object.__setattr__(self, "_context", context)
        object.__setattr__(self, "_object_id", object_id)

    def _target(self):
        return self._context.get_object(self._object_id)

    def __len__(self):
        return len(self._target())

    def __getitem__(self, index):
        return self._target()[index]

    def get(self, index):
        return self._target().get(index)

    def get_elem_id(self, index):
        return self._target().get_elem_id(index)

    def __iter__(self):
        return iter(self._target())

    def __str__(self):
        return str(self._target())

    def __eq__(self, other):
        return self._target() == other

    def __repr__(self):
        return f"TextProxy({str(self._target())!r})"

    def to_spans(self):
        return self._target().to_spans()

    def to_json(self):
        return str(self._target())

    def set(self, index, value):
        self._context.set_list_index(self._object_id, index, value)
        return self

    def insert_at(self, index, *values):
        self._context.splice(self._object_id, index, 0, list(values))
        return self

    def delete_at(self, index, num_delete=1):
        self._context.splice(self._object_id, index, num_delete, [])
        return self


def _plain(value):
    if isinstance(value, MapProxy):
        return value.to_dict()
    if isinstance(value, ListProxy):
        return value.to_list()
    if isinstance(value, TextProxy):
        return value._target()
    return value


def root_object_proxy(context) -> MapProxy:
    from .._common import ROOT_ID
    return MapProxy(context, ROOT_ID)

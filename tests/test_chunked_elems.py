"""ChunkedElems: the COW chunked store backing Text.elems.

The frontend's immutable-snapshot contract (every change produces a new
document while old ones stay valid — the reference gets this from
Immutable.js persistent vectors, frontend/apply_patch.js) is carried here
by chunk-level copy-on-write. These tests pin (a) sequence semantics
against a plain-list mirror under random mutation, and (b) snapshot
isolation: post-copy mutations on either side never leak to the other.
"""

import numpy as np
import pytest

from automerge_tpu.frontend.types import ChunkedElems, Text


def test_sequence_ops_mirror_plain_list():
    rng = np.random.default_rng(7)
    ce = ChunkedElems(range(100))
    ref = list(range(100))
    for step in range(400):
        op = rng.integers(0, 5)
        n = len(ref)
        if op == 0:                                  # insert run at point
            i = int(rng.integers(0, n + 1))
            run = [int(x) for x in rng.integers(0, 999, rng.integers(1, 7))]
            ce[i:i] = run
            ref[i:i] = run
        elif op == 1 and n:                          # point write
            i = int(rng.integers(0, n))
            ce[i] = ref[i] = int(rng.integers(0, 999))
        elif op == 2 and n:                          # point delete
            i = int(rng.integers(0, n))
            del ce[i]
            del ref[i]
        elif op == 3 and n:                          # range delete
            i = int(rng.integers(0, n))
            j = int(rng.integers(i, min(n, i + 9) + 1))
            del ce[i:j]
            del ref[i:j]
        else:                                        # insert single
            i = int(rng.integers(0, n + 1))
            v = int(rng.integers(0, 999))
            ce.insert(i, v)
            ref.insert(i, v)
        assert len(ce) == len(ref), f"step {step}"
        if step % 25 == 0:
            assert list(ce) == ref, f"step {step}"
            if ref:
                k = int(rng.integers(0, len(ref)))
                assert ce[k] == ref[k]
                assert ce[k : k + 5] == ref[k : k + 5]
    assert list(ce) == ref


def test_bulk_run_insert_crosses_chunks():
    C = ChunkedElems.CHUNK
    ce = ChunkedElems(range(3 * C))
    ref = list(range(3 * C))
    run = list(range(10_000, 10_000 + 5 * C + 3))    # > CHUNK: bulk path
    ce[C + 17 : C + 17] = run
    ref[C + 17 : C + 17] = run
    assert len(ce) == len(ref)
    assert list(ce) == ref
    # appends also take the bulk path
    ce[len(ce):len(ce)] = run
    ref[len(ref):len(ref)] = run
    assert list(ce) == ref


def test_copy_is_isolated_both_directions():
    ce = ChunkedElems(range(5000))
    snap = ce.copy()
    before = list(snap)
    ce[123] = -1
    ce[4000:4000] = [7, 8, 9]
    del ce[0]
    assert list(snap) == before            # snapshot unaffected by source
    snap[200] = -2
    del snap[300:350]
    assert ce[0] == 1 and ce[122] == -1    # source unaffected by snapshot
    assert len(ce) == 5002
    assert len(snap) == 4950


def test_copy_cost_is_chunk_count_not_elements():
    """The interactive-latency win (cfg7): snapshots must not scale with
    document size. A 200k-element copy touches ~n/CHUNK chunk refs."""
    import time
    ce = ChunkedElems({"value": "x"} for _ in range(200_000))
    t0 = time.perf_counter()
    for _ in range(50):
        ce.copy()
    per_copy = (time.perf_counter() - t0) / 50
    flat = list(ce)
    t0 = time.perf_counter()
    for _ in range(5):
        list(flat)
    per_list = (time.perf_counter() - t0) / 5
    assert per_copy < per_list / 10, (per_copy, per_list)


def test_text_snapshot_chain_stays_valid():
    """am.change chains: every intermediate doc keeps its own content."""
    import automerge_tpu as am

    doc = am.change(am.init({"actorId": "u"}),
                    lambda d: d.__setitem__("t", Text("abcdef")))
    snaps = [doc]
    for i in range(8):
        doc = am.change(doc, lambda d, i=i: d["t"].insert_at(3, str(i)))
        snaps.append(doc)
    texts = [str(am.to_json(s)["t"]) for s in snaps]
    assert texts[0] == "abcdef"
    for i in range(1, 9):
        assert len(texts[i]) == 6 + i
        assert texts[i][3] == str(i - 1)


def test_no_empty_chunks_invariant():
    """Bulk insert into an empty store must replace the [[]] sentinel,
    and whole-chunk deletes must drop references without privatizing."""
    C = ChunkedElems.CHUNK
    ce = ChunkedElems()
    ce[0:0] = list(range(3 * C))
    assert all(len(c) > 0 for c in ce._chunks), [len(c) for c in ce._chunks]
    assert list(ce) == list(range(3 * C))
    snap = ce.copy()
    del ce[0 : 2 * C]                      # spans two whole shared chunks
    assert list(ce) == list(range(2 * C, 3 * C))
    assert len(snap) == 3 * C              # snapshot untouched
    del ce[0 : len(ce)]                    # delete everything
    assert len(ce) == 0 and list(ce) == []
    ce.insert(0, 42)                       # still usable afterwards
    assert list(ce) == [42]


def test_extended_step_slices_rejected():
    ce = ChunkedElems(range(10))
    with pytest.raises(TypeError):
        ce[::2] = [1, 2, 3]
    with pytest.raises(TypeError):
        del ce[::2]
    assert ce[::2] == [0, 2, 4, 6, 8]      # stepped READS still work

"""Vmapped multi-document text engine: one device program for a whole DocSet.

The reference merges a DocSet one document at a time
(/root/reference/src/doc_set.js:29-37 — a JS loop calling the backend per
doc). On TPU the per-call dispatch dominates for small docs, so this engine
stacks every document's element tables into (docs, capacity) arrays and runs
ingestion/materialization as ONE vmapped program over the doc axis — the
data-parallel "doc" dimension of the mesh design (parallel/mesh.py shards
the same stacked tables over devices).

Scope: the vmapped fast path covers rounds that are *runs-only* and fully
causally ready (the overwhelming bulk-sync shape). A document whose batch
needs the general machinery (residual ops, queueing, conflicts) permanently
*graduates* to its own `DeviceTextDoc` built from its table slices —
correctness never depends on the fast path applying.

The GENERAL multi-doc execution engine this tier pioneered now lives in
`engine/stacked.py` (INTERNALS §12): it runs the full mixed map/text
round machinery — residuals, slow registers, conflicts, multi-round
causal chains — as vmapped stacked programs with no graduation cliff,
and backs the nested-document backend path. This homogeneous tier
remains the sync DocSet's bulk fast path; unifying the two is the
recorded follow-up (ROADMAP item 1).
"""

from __future__ import annotations

import os

import numpy as np

from .._common import HEAD_PARENT, make_elem_id
from .base import transitive_closure
from .columnar import TextChangeBatch
from .host_index import DuplicateElemId, new_index, pack_keys, unpack_key
from .runs import detect_runs
from .segments import SegmentMirror
from .text_doc import DeviceTextDoc, logger


class _DocMeta:
    __slots__ = ("clock", "actor_table", "actor_rank", "index", "n_elems",
                 "seg_bound", "all_ascii", "all_deps", "mirror")

    def __init__(self):
        self.clock: dict = {}
        self.actor_table: list = []
        self.actor_rank: dict = {}
        self.index = new_index()
        self.n_elems = 0
        self.seg_bound = 2
        self.all_ascii = True
        self.all_deps: dict = {}   # (actor, seq) -> transitive deps clock
        self.mirror = SegmentMirror.empty()  # host segment structure


class DeviceTextDocSet:
    """A set of text documents merged as one stacked device program.

    With a `jax.sharding.Mesh` (axes "doc", "elem"), the stacked tables
    shard over the devices — documents data-parallel along "doc", elements
    of each document sequence-parallel along "elem" — and the same vmapped
    programs run SPMD with XLA inserting the collectives (the condensed
    linearization's small sort rides all-to-all; the prefix scans exchange
    carries over ICI). This is the framework's multi-chip execution path
    (parallel/mesh.py builds meshes; __graft_entry__.dryrun_multichip
    drives it on a virtual device mesh)."""

    def __init__(self, obj_ids, capacity: int = 1024, mesh=None):
        from ..ops.ingest import bucket
        self.obj_ids = list(obj_ids)
        self._idx = {o: i for i, o in enumerate(self.obj_ids)}
        self._meta = [_DocMeta() for _ in self.obj_ids]
        self._cap = bucket(max(capacity, 16))
        self.mesh = mesh
        self._dev = None                      # stacked (D, cap) tables
        self._overlay: dict = {}              # doc idx -> DeviceTextDoc
        self._codes_cache = None
        if mesh is not None:
            if self.n_docs % mesh.shape["doc"]:
                raise ValueError(
                    f"the mesh's doc axis ({mesh.shape['doc']}) must divide "
                    f"n_docs ({self.n_docs})")
            if self._cap % mesh.shape["elem"]:
                raise ValueError(
                    f"the mesh's elem axis ({mesh.shape['elem']}) must "
                    f"divide the bucketed capacity ({self._cap}); pick a "
                    f"power-of-two elem axis")

    @property
    def n_docs(self) -> int:
        return len(self.obj_ids)

    _TABLE_KEYS = DeviceTextDoc._TABLE_KEYS

    def _sharding(self, *axes):
        if self.mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec
        return NamedSharding(self.mesh, PartitionSpec(*axes))

    def _put(self, arr, *axes):
        """Host array -> device, sharded over the mesh when one is set."""
        import jax
        import jax.numpy as jnp
        if self.mesh is None:
            return jnp.asarray(arr)
        return jax.device_put(arr, self._sharding(*axes))

    def _ensure_dev(self):
        if self._dev is None:
            import numpy as onp
            D, cap = self.n_docs, self._cap
            self._dev = {
                "parent": self._put(onp.zeros((D, cap), onp.int32),
                                    "doc", "elem"),
                "ctr": self._put(onp.zeros((D, cap), onp.int32),
                                 "doc", "elem"),
                "actor": self._put(onp.zeros((D, cap), onp.int32),
                                   "doc", "elem"),
                "value": self._put(onp.zeros((D, cap), onp.int32),
                                   "doc", "elem"),
                "has_value": self._put(onp.zeros((D, cap), bool),
                                       "doc", "elem"),
                "win_actor": self._put(onp.full((D, cap), -1, onp.int32),
                                       "doc", "elem"),
                "win_seq": self._put(onp.zeros((D, cap), onp.int32),
                                     "doc", "elem"),
                "win_counter": self._put(onp.zeros((D, cap), bool),
                                         "doc", "elem"),
                "chain": self._put(onp.zeros((D, cap), bool),
                                   "doc", "elem"),
            }
        return self._dev

    # ------------------------------------------------------------------

    def _graduate(self, d: int) -> DeviceTextDoc:
        """Extract doc d into its own DeviceTextDoc (general path)."""
        if d in self._overlay:
            return self._overlay[d]
        meta = self._meta[d]
        doc = DeviceTextDoc(self.obj_ids[d], capacity=self._cap)
        dev = self._ensure_dev()
        doc._dev = {k: dev[k][d] for k in self._TABLE_KEYS}
        doc._cap = self._cap
        doc.n_elems = meta.n_elems
        doc.index = meta.index
        doc.clock = dict(meta.clock)
        doc.actor_table = list(meta.actor_table)
        doc._actor_rank = dict(meta.actor_rank)
        doc._all_deps = dict(meta.all_deps)
        doc._seg_bound = meta.seg_bound
        doc.all_ascii = meta.all_ascii
        doc.seg_mirror = meta.mirror   # None degrades to the self-contained
        # kernels; otherwise the mirror carries over with the table slices
        self._overlay[d] = doc
        return doc

    def doc(self, obj_id: str) -> DeviceTextDoc:
        """The general-path engine for one document (graduates it)."""
        return self._graduate(self._idx[obj_id])

    def apply_batches(self, batches: dict):
        """Merge {obj_id: TextChangeBatch}: vmapped fast path for runs-only
        ready batches; the GENERAL stacked executor (engine/stacked.py)
        otherwise — every batch the fast tier can't serve graduates its
        doc and the whole graduated group executes as ONE stacked
        multi-object apply per call (the same admission/planning/round
        machinery as the single-device path, so the sync-tier DocSet and
        the backend path cannot drift; ROADMAP 1b). The pre-unification
        per-object loop is kept verbatim as the parity comparator behind
        ``AMTPU_DOCSET_STACKED=0`` (mesh-backed sets also keep it: their
        graduated rows slice mesh-sharded tables, and the SPMD fast tier
        IS the sharded execution path)."""
        from ..ops.ingest import bucket
        from ..ops.ingest import expand_runs_dense

        self._codes_cache = None
        fast: list = []
        general: list = []            # (graduated doc, batch)
        for obj_id, batch in batches.items():
            d = self._idx[obj_id]
            if d in self._overlay:
                general.append((self._overlay[d], batch))
                continue
            plan_pack = self._plan_fast(d, batch)
            if plan_pack == "skip":
                continue
            if plan_pack is None:
                general.append((self._graduate(d), batch))
            else:
                fast.append(plan_pack)
        if general:
            self._apply_general(general)
        if not fast:
            return self

        # --- commit staged per-doc state now that every plan succeeded ---
        for p in fast:
            meta = self._meta[p["d"]]
            meta.index = p["staged_index"]
            meta.mirror = p["staged_mirror"]
            meta.clock.update(p["staged_clock"])
            meta.all_deps.update(p["staged_all_deps"])
            meta.all_ascii = meta.all_ascii and p["staged_ascii"]
            if p["staged_actors"] is not None:
                meta.actor_table, meta.actor_rank = p["staged_actors"]

        # --- stack run descriptors over the doc axis and expand once ---
        R = bucket(max(p["n_runs"] for p in fast), 64)
        N = bucket(max(p["n_pairs"] for p in fast), 256)
        # every doc's write window [n_elems+1, n_elems+1+N) must fit: the
        # dense expansion writes the whole padded window for ALL rows
        # (inactive docs write only past their live region)
        need = max(m.n_elems for m in self._meta) + 1 + N
        out_cap = max(bucket(need), self._cap)
        if self.mesh is not None:
            # bucket() can yield 3*2^(k-1) sizes that a power-of-two elem
            # axis doesn't divide; keep the constructor's sharding invariant
            # by rounding up to a multiple of the elem axis
            e = self.mesh.shape["elem"]
            out_cap = -(-out_cap // e) * e
        D = self.n_docs

        cols = {k: np.zeros((D, R), np.int32) for k in
                ("head_slot", "parent_slot", "ctr0", "actor", "win_actor",
                 "win_seq")}
        elem_base = np.full((D, R), N, np.int32)
        has_val = np.zeros((D, R), bool)
        blob = np.zeros((D, N), np.int32)
        n_pairs_v = np.zeros(D, np.int32)
        # inactive rows write garbage past their live region (harmless)
        base_slot_v = np.asarray([m.n_elems + 1 for m in self._meta],
                                 np.int32)
        for p in fast:
            d, nr = p["d"], p["n_runs"]
            for k in cols:
                cols[k][d, :nr] = p[k]
            elem_base[d, :nr] = p["elem_base"]
            has_val[d, :nr] = True
            blob[d, : p["n_pairs"]] = p["blob"]
            n_pairs_v[d] = p["n_pairs"]

        dev = self._ensure_dev()
        tables = tuple(dev[k] for k in self._TABLE_KEYS)
        import jax
        expanded = jax.vmap(
            lambda *a: expand_runs_dense(*a, out_cap=out_cap))(
            *tables,
            self._put(cols["head_slot"], "doc"),
            self._put(cols["parent_slot"], "doc"),
            self._put(cols["ctr0"], "doc"), self._put(cols["actor"], "doc"),
            self._put(cols["win_actor"], "doc"),
            self._put(cols["win_seq"], "doc"),
            self._put(elem_base, "doc"), self._put(has_val, "doc"),
            self._put(blob, "doc"), self._put(n_pairs_v, "doc"),
            self._put(base_slot_v, "doc"))
        self._dev = dict(zip(self._TABLE_KEYS, expanded))
        self._cap = out_cap

        # chain breaks for touched parents (stacked, one scatter)
        touches = [(p["d"], p["parent_slot"], p["ctr0"], p["actor"])
                   for p in fast if p["n_breaks"]]
        if touches:
            from ..ops.ingest import break_chains
            T = bucket(max(len(t[1]) for t in touches), 64)
            tp = np.zeros((D, T), np.int32)
            tc_ = np.full((D, T), -1, np.int32)
            ta_ = np.full((D, T), -1, np.int32)
            for d, ps, cs, as_ in touches:
                tp[d, : len(ps)] = ps
                tc_[d, : len(ps)] = cs
                ta_[d, : len(ps)] = as_
            chain_n = jax.vmap(break_chains)(
                self._dev["chain"], self._dev["parent"], self._dev["ctr"],
                self._dev["actor"], self._put(tp, "doc"),
                self._put(tc_, "doc"), self._put(ta_, "doc"))
            self._dev["chain"] = chain_n

        for p in fast:
            meta = self._meta[p["d"]]
            meta.n_elems += p["n_pairs"]
            if meta.mirror is not None:
                meta.seg_bound = max(meta.mirror.n_segs, 1)
            else:
                meta.seg_bound += 3 * p["n_runs"] + 2
        return self

    def _apply_general(self, general: list):
        """Apply the graduated group: one stacked multi-object program
        set per call by default (engine/stacked.apply_stacked consumes
        the already-decoded batches), per-doc `apply_batch` when the
        stacked tier declines the population (single doc / tiny payload
        / skewed caps) or the comparator flag selects the old path."""
        stacked_route = (self.mesh is None and
                         os.environ.get("AMTPU_DOCSET_STACKED", "1")
                         != "0")
        if stacked_route and len(general) >= 2:
            from . import stacked as _stacked
            if _stacked.apply_stacked(general):
                return
        for doc, batch in general:
            doc.apply_batch(batch)

    def _plan_fast(self, d: int, b: TextChangeBatch):
        """Host planning for the vmapped path; None -> general engine.

        Pure: all state updates are staged in the returned pack and
        committed by apply_batches only after every doc's plan succeeds."""
        meta = self._meta[d]
        # fully-ready batch? the clock advances through the loop, so
        # sequential same-actor changes stay fast and any duplicate —
        # pre-applied or repeated within the batch — is detected
        clock = dict(meta.clock)
        dups = 0
        for row in range(b.n_changes):
            actor, seq = b.actors[row], int(b.seqs[row])
            deps = dict(b.deps[row])
            deps[actor] = seq - 1
            if seq <= clock.get(actor, 0):
                dups += 1
                continue
            if not all(clock.get(a, 0) >= s for a, s in deps.items()
                       if a != actor):
                return None
            if clock.get(actor, 0) != seq - 1:
                return None
            clock[actor] = seq
        if dups == b.n_changes:
            return "skip"         # redelivery of an applied batch: no-op
        if dups:
            return None           # partial duplicate: general path filters
        plan = detect_runs(b.op_kind, b.op_target_actor, b.op_target_ctr,
                           b.op_parent_actor, b.op_parent_ctr, b.op_value,
                           b.op_change, meta.n_elems)
        if len(plan.rpos) or plan.n_runs == 0:
            return None

        # intern actors; order change would need a remap -> general path
        staged_actors = None
        actor_rank = meta.actor_rank
        missing = sorted(set(a for a in b.actor_table
                             if a not in meta.actor_rank))
        if missing:
            merged = sorted(set(meta.actor_table) | set(missing))
            if meta.actor_table and \
                    merged[: len(meta.actor_table)] != meta.actor_table:
                return None
            actor_rank = {a: i for i, a in enumerate(merged)}
            staged_actors = (merged, actor_rank)

        batch_rank = np.asarray(
            [actor_rank[a] for a in b.actor_table], np.int64)
        row_rank = np.asarray([actor_rank[a] for a in b.actors], np.int32)
        row_seq = np.asarray(b.seqs, np.int32)
        hpos = plan.hpos
        ta, tc = b.op_target_actor, b.op_target_ctr
        pa, pc = b.op_parent_actor, b.op_parent_ctr

        try:
            staged_index = meta.index.merge(
                pack_keys(batch_rank[ta[hpos]], tc[hpos].astype(np.int64)),
                plan.run_len, plan.head_slot)
        except DuplicateElemId as e:
            rank, k_ctr = unpack_key(e.key)
            table = staged_actors[0] if staged_actors else meta.actor_table
            raise ValueError(
                f"Duplicate list element ID "
                f"{make_elem_id(table[rank], k_ctr)} "
                f"in {self.obj_ids[d]}") from None
        is_head = pa[hpos] == HEAD_PARENT
        keys = pack_keys(batch_rank[np.where(is_head, 0, pa[hpos])],
                         pc[hpos].astype(np.int64))
        slots, found = staged_index.lookup(keys)
        if not (found | is_head).all():
            raise ValueError(
                f"ins references unknown parent element in {self.obj_ids[d]}")
        parent_slot = np.where(is_head, 0, slots)

        # transitive dependency closure per change (the graduated doc's slow
        # path needs it to judge causal coverage); a dep may reference an
        # earlier in-batch change, so close over staged entries as well
        staged_all_deps: dict = {}
        combined = dict(meta.all_deps)
        for row in range(b.n_changes):
            actor, seq = b.actors[row], int(b.seqs[row])
            closure = transitive_closure(combined, actor, seq, b.deps[row])
            staged_all_deps[(actor, seq)] = closure
            combined[(actor, seq)] = closure

        # host segment mirror (same round inputs as the vmapped chain
        # breaks below); failure degrades THIS doc to the self-contained
        # materialize kernel, never the round itself
        staged_mirror = None
        if meta.mirror is not None:
            try:
                staged_mirror = meta.mirror.apply_round(
                    plan.head_slot, parent_slot,
                    tc[hpos].astype(np.int64), batch_rank[ta[hpos]],
                    meta.n_elems + plan.n_pairs, staged_index.slot_to_key)
            except Exception:
                logger.warning(
                    "segment-mirror planning failed for %s (doc-set row %d)",
                    self.obj_ids[d], d, exc_info=True)

        return {
            "d": d, "n_runs": plan.n_runs, "n_pairs": plan.n_pairs,
            "staged_mirror": staged_mirror,
            "head_slot": plan.head_slot, "parent_slot": parent_slot,
            "ctr0": tc[hpos], "actor": batch_rank[ta[hpos]],
            "win_actor": row_rank[b.op_change[hpos]],
            "win_seq": row_seq[b.op_change[hpos]],
            "elem_base": np.cumsum(plan.run_len) - plan.run_len,
            "blob": plan.blob,
            "n_breaks": int((~is_head).sum()),
            "staged_index": staged_index,
            "staged_clock": {b.actors[r]: int(b.seqs[r])
                             for r in range(b.n_changes)},
            "staged_all_deps": staged_all_deps,
            "staged_ascii": plan.blob_lt_128,
            "staged_actors": staged_actors,
        }

    # ------------------------------------------------------------------

    def _rebuild_row_mirror(self, d: int):
        """Heal path: reconstruct row d's segment mirror from its fetched
        chain/parent rows (None if that fails too)."""
        dev = self._ensure_dev()
        meta = self._meta[d]
        try:
            meta.mirror = SegmentMirror.rebuild(
                np.asarray(dev["chain"][d]), np.asarray(dev["parent"][d]),
                meta.n_elems, meta.index.slot_to_key)
        except Exception:
            logger.warning("mirror rebuild failed for doc-set row %d", d,
                           exc_info=True)
            meta.mirror = None

    def texts(self) -> dict:
        """Materialize every document: one vmapped program + one fetch.

        When every stacked document has a live segment mirror, the vmapped
        HOST-PLANNED kernel runs (no per-doc sort or pointer doubling on
        device); per-doc plan consistency is verified against the chain
        bits. A divergent or missing mirror is REBUILT from the real chain
        bits (the affected call serves through the self-contained kernel;
        the next call is planned again) and only drops to None if the
        rebuild itself fails.

        Deliberately NOT gated on text_doc.prefer_planned (the single-doc
        planned/self-contained switch): under vmap every lane must run one
        uniform program, and the plan's sort-free structure is what keeps
        the stacked program uniform across docs of different shapes — the
        choice here is vmappability, not single-doc kernel speed."""
        import jax
        from ..ops.ingest import (bucket, materialize_codes,
                                  materialize_codes_planned)

        out = {}
        stacked_idx = [d for d in range(self.n_docs)
                       if d not in self._overlay]
        if stacked_idx:
            if self._codes_cache is None:
                dev = self._ensure_dev()
                all_ascii = all(self._meta[d].all_ascii for d in stacked_idx)
                n_el = np.asarray([m.n_elems for m in self._meta], np.int32)
                for d in stacked_idx:
                    # a row whose plan-time mirror update failed rebuilds
                    # here from its chain bits, so one bad round degrades
                    # one call, not the doc-set forever
                    if self._meta[d].mirror is None:
                        self._rebuild_row_mirror(d)
                planned = all(self._meta[d].mirror is not None
                              for d in stacked_idx)

                def run_planned(S):
                    # overlay (graduated) rows ride along with an empty plan;
                    # their stacked tables are stale and their output ignored
                    stacked = set(stacked_idx)
                    empty = SegmentMirror.empty()
                    plans = np.stack([
                        self._meta[d].mirror.plan(S, self._meta[d].n_elems)
                        if d in stacked else empty.plan(S, 0)
                        for d in range(self.n_docs)])
                    return jax.vmap(
                        lambda p, t, a, v, h, c, n, sp:
                        materialize_codes_planned(
                            p, t, a, v, h, c, n, sp, S=S, as_u8=all_ascii))(
                        dev["parent"], dev["ctr"], dev["actor"],
                        dev["value"], dev["has_value"], dev["chain"],
                        self._put(n_el, "doc"), self._put(plans, "doc"))

                def run(S):
                    return jax.vmap(
                        lambda *a: materialize_codes(*a, S=S,
                                                     as_u8=all_ascii))(
                        dev["parent"], dev["ctr"], dev["actor"],
                        dev["value"], dev["has_value"], dev["chain"],
                        self._put(n_el, "doc"))

                if planned:
                    S = bucket(max(self._meta[d].mirror.n_segs
                                   for d in stacked_idx) + 2, 64)
                    codes, scalars = run_planned(S)
                    scalars_np = np.asarray(scalars)  # (D, 5)
                    bad = [d for d in stacked_idx
                           if int(scalars_np[d, 1]) != int(scalars_np[d, 2])
                           or int(scalars_np[d, 3])
                           != self._meta[d].mirror.head_checksum()
                           or int(scalars_np[d, 4])
                           != self._meta[d].mirror.aux_checksum()]
                    if bad:
                        # rebuild diverged mirrors from the real chain bits
                        # (a small per-row fetch; None only if that fails),
                        # then serve THIS call via the self-contained kernel
                        logger.warning(
                            "segment mirror diverged for doc-set rows %s; "
                            "rebuilding and re-materializing", bad)
                        for d in bad:
                            self._rebuild_row_mirror(d)
                            self._meta[d].seg_bound = max(
                                int(scalars_np[d, 2]), 1)
                        planned = False
                if not planned:
                    S = bucket(max(self._meta[d].seg_bound
                                   for d in stacked_idx) + 2, 64)
                    codes, scalars = run(S)
                    scalars_np = np.asarray(scalars)  # (D, 2): n_vis, n_segs
                    if (scalars_np[:, 1] + 2 > S).any():
                        S = bucket(int(scalars_np[:, 1].max()) + 2, 64)
                        codes, scalars = run(S)
                        scalars_np = np.asarray(scalars)
                for d in stacked_idx:
                    self._meta[d].seg_bound = int(scalars_np[d, 1])
                self._codes_cache = (np.asarray(codes), scalars_np[:, 0],
                                     all_ascii)
            fetched, n_vis, all_ascii = self._codes_cache
            for d in stacked_idx:
                row = fetched[d][: n_vis[d]]
                if all_ascii:
                    out[self.obj_ids[d]] = row.tobytes().decode("ascii")
                else:
                    out[self.obj_ids[d]] = "".join(
                        chr(v) for v in row.astype(np.uint32))
        for d, doc in self._overlay.items():
            out[self.obj_ids[d]] = doc.text()
        return out

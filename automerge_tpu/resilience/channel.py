"""Reliable, ordered, exactly-once message delivery over a lossy link.

``ResilientChannel`` is one endpoint of a full-duplex reliability layer
between a sync peer and its transport. It restores exactly the guarantees
the ``{docId, clock, changes?}`` protocol was written against — lossless,
ordered, duplicate-free delivery — without changing a byte of that protocol:
payloads ride inside ``{"kind": "data", "seq": n, "ack": m, "payload": …}``
envelopes, and the peer protocol never sees the envelope.

Mechanics (time is modeled as explicit ``tick()`` rounds, so everything is
deterministic and thread-free):

- **send**: each payload gets the next sequence number and is retained until
  cumulatively acked. Retransmit timers back off exponentially
  (``base_rto * 2^attempts``, capped at ``max_rto``) with deterministic
  seeded jitter so two channels sharing a link don't retransmit in lockstep.
- **receive** (``on_wire``): envelopes are validated (malformed ones raise
  :class:`~.errors.ProtocolError`), deduped against everything already
  delivered or buffered, reassembled into sequence order, and released to
  the ``deliver`` callback strictly in-order. Every data envelope triggers a
  cumulative ack; acks also piggyback on outgoing data.
- **exactly-once**: a payload is handed to ``deliver`` exactly once no
  matter how often the link duplicates or the sender retransmits it.
"""

from __future__ import annotations

import operator

import numpy as np

from .. import obs
from ..obs import lineage
from .errors import PeerDeadError, ProtocolError

ENVELOPE_KINDS = ("data", "ack")


def validate_envelope(env) -> dict:
    if not isinstance(env, dict):
        raise ProtocolError(f"channel envelope must be an object, got "
                            f"{type(env).__name__}")
    kind = env.get("kind")
    if kind not in ENVELOPE_KINDS:
        raise ProtocolError(f"channel envelope kind must be one of "
                            f"{ENVELOPE_KINDS}, got {kind!r}")
    for field in ("seq", "ack"):
        try:
            if operator.index(env.get(field)) < 0:
                raise ProtocolError(
                    f"channel envelope `{field}` must be >= 0")
        except TypeError:
            raise ProtocolError(
                f"channel envelope `{field}` must be an integer, got "
                f"{env.get(field)!r}") from None
    for field in ("epoch", "aepoch"):
        # optional reconnect-epoch fields (revive()): absent == 0, so a
        # pre-epoch peer's envelopes stay byte-identical and valid
        if field in env:
            try:
                if operator.index(env[field]) < 0:
                    raise ProtocolError(
                        f"channel envelope `{field}` must be >= 0")
            except TypeError:
                raise ProtocolError(
                    f"channel envelope `{field}` must be an integer, got "
                    f"{env[field]!r}") from None
    if kind == "data" and "payload" not in env:
        raise ProtocolError("truncated data envelope: missing `payload`")
    return env


#: Receive-window size: out-of-order payloads buffer only within
#: ``recv_high + 1 .. recv_high + RECV_WINDOW``. A peer streaming frames
#: with an unfilled gap (hostile, or just a huge seq jump) cannot grow the
#: reorder buffer without bound — frames beyond the window drop un-acked,
#: so a legitimate sender's retransmit timer redelivers them once the
#: in-order release drains the window.
RECV_WINDOW = 1024

def payload_wire_bytes(payload) -> int:
    """Wire-byte size of one channel payload: exact for binary frames
    (the ``wire`` field's encoded length IS the wire form), JSON-ish
    estimate for dict-shaped parts (the same accounting
    ``service.budget.approx_msg_bytes`` uses). Computed ONCE at send and
    stored with the un-acked entry, so retransmissions charge the stored
    size — never re-measuring, mirroring the never-re-encode contract."""
    nbytes = getattr(payload, "nbytes", None)
    if isinstance(nbytes, int) and not isinstance(payload, np.ndarray):
        return nbytes
    if isinstance(payload, dict):
        return 2 + sum(len(str(k)) + 4 + payload_wire_bytes(v)
                       for k, v in payload.items())
    if isinstance(payload, (list, tuple)):
        return 2 + sum(2 + payload_wire_bytes(v) for v in payload)
    if isinstance(payload, str):
        return 2 + len(payload)
    return 8


#: Default retransmit budget PER ENVELOPE. With exponential backoff this
#: spans hundreds of rounds of sustained silence — far beyond any fault
#: the chaos profiles inject against a live peer — so a legitimate slow
#: or partitioned peer never trips it, while a vanished peer stops
#: costing timer work and send-window memory in bounded time. The
#: service tier configures a tighter cap (its heartbeat path usually
#: declares death first; this is the backstop).
MAX_RETRIES = 64


class ResilientChannel:
    def __init__(self, send_raw, deliver, *, seed: int = 0,
                 base_rto: int = 2, max_rto: int = 16,
                 recv_window: int = RECV_WINDOW,
                 max_retries: int = MAX_RETRIES,
                 on_dead=None, admit=None, label: str = None):
        self._send_raw = send_raw
        self._deliver = deliver
        #: lineage site label for chan/* hops (the service names tenant
        #: channels after the tenant); None -> anonymous hops
        self.label = label
        self._rng = np.random.default_rng(seed)
        self._base_rto = base_rto
        self._max_rto = max_rto
        self._recv_window = recv_window
        self._max_retries = max_retries
        self._on_dead = on_dead
        self._admit = admit           # credit gate: un-acked drop when falsy
        self._round = 0
        self._next_seq = 1
        self._unacked: dict = {}      # seq -> {"payload","due","rto","tries"}
        self._recv_high = 0           # highest contiguously delivered seq
        self._recv_buf: dict = {}     # out-of-order seq -> payload
        self.dead = False
        #: reconnect epochs (revive(), INTERNALS §20.2): `epoch` scopes
        #: OUR seq numbering, `_peer_epoch` the highest sender epoch we
        #: accept data under. Both start at 0 and the fields are omitted
        #: from envelopes while 0, so a never-revived channel is
        #: wire-identical to the pre-epoch protocol.
        self.epoch = 0
        self._peer_epoch = 0
        self.stats = {"sent": 0, "retransmits": 0, "acks_sent": 0,
                      "dup_dropped": 0, "held_out_of_order": 0,
                      "window_dropped": 0, "delivered": 0,
                      "deliver_errors": 0, "backpressured": 0,
                      "bytes_sent": 0, "bytes_resent": 0,
                      "dead": False, "revives": 0,
                      "stale_epoch_dropped": 0, "stale_acks": 0}

    def _stamp(self, env: dict) -> dict:
        """Attach the reconnect-epoch fields when nonzero: `epoch` scopes
        this envelope's seq numbering, `aepoch` names the peer epoch its
        cumulative ack refers to. Omitted at 0 (the common case), so a
        never-revived channel's wire bytes are unchanged."""
        if self.epoch:
            env["epoch"] = self.epoch
        if self._peer_epoch:
            env["aepoch"] = self._peer_epoch
        return env

    def revive(self):
        """Re-establish a channel declared dead by retransmit-cap
        exhaustion (the partition-heal reconnect path, INTERNALS §20.2):
        a FRESH seq/ack epoch — seq numbering restarts at 1, the send
        window and reorder buffer reset, and both epoch counters bump so
        (a) stale acks from the old epoch cannot delete new-epoch window
        entries and (b) stale pre-epoch data frames still floating in
        the network drop instead of replaying into the reset receive
        window. Correctness does NOT depend on resending the cleared
        window: the sync layer above re-advertises on reconnect (hub
        peer remove/re-add), and the clock exchange re-extracts anything
        the partition ate — the proven lossy-link recovery contract.
        Both endpoints must revive for a reconnect cycle (the federation
        hello handshake coordinates this); `revive()` on a live channel
        is allowed and simply starts the next epoch."""
        self.epoch += 1
        self._peer_epoch += 1
        self._next_seq = 1
        self._unacked.clear()
        self._recv_high = 0
        self._recv_buf.clear()
        self.dead = False
        self.stats["dead"] = False
        self.stats["revives"] += 1
        if obs.ENABLED:
            obs.event("chan", "revive", args={"epoch": self.epoch})

    # -- outbound -------------------------------------------------------

    def send(self, payload):
        """Queue + transmit one payload. The payload object (its binary
        frames included) is CACHED in the send window as-is: a
        retransmission resends the stored object/bytes verbatim — frames
        are never re-encoded on retry, and the per-payload wire size is
        measured once here (``bytes_sent``/``bytes_resent`` let the
        bench report wire bytes per op for the dict-vs-binary A/B)."""
        if self.dead:
            raise PeerDeadError(
                "channel is dead (retransmit cap exhausted); revive() "
                "it after the partition heals, or reconnect with a "
                "fresh channel")
        seq = self._next_seq
        self._next_seq += 1
        nbytes = payload_wire_bytes(payload)
        self._unacked[seq] = {"payload": payload, "nbytes": nbytes,
                              "due": self._round + self._base_rto,
                              "rto": self._base_rto, "tries": 0}
        self.stats["sent"] += 1
        self.stats["bytes_sent"] += nbytes
        if lineage.ENABLED:
            # extra=seq: one send hop per envelope carrying the change —
            # a dup-delivered envelope dedups, a distinct envelope
            # (e.g. a re-extracted resend on a fresh channel) records
            for a, s in lineage.payload_keys(payload):
                lineage.hop(a, s, "chan/send", site=self.label, extra=seq)
        self._send_raw(self._stamp({"kind": "data", "seq": seq,
                                    "ack": self._recv_high,
                                    "payload": payload}))

    def tick(self):
        """Advance one time round; retransmit overdue unacked envelopes
        with exponential backoff + deterministic jitter. An envelope that
        exhausts ``max_retries`` declares the PEER dead: retransmission
        stops, the send window is dropped (bounded-memory reclaim), and
        the death surfaces through ``on_dead`` when installed, else as a
        typed :class:`PeerDeadError` — never a silent retry-forever."""
        if self.dead:
            return
        self._round += 1
        for seq in sorted(self._unacked):
            # a synchronous transport can ack DURING this loop (the
            # retransmit below fills the receiver's gap, whose inline
            # cumulative ack re-enters on_wire and deletes later seqs) —
            # re-check membership instead of indexing the snapshot
            entry = self._unacked.get(seq)
            if entry is None or entry["due"] > self._round:
                continue
            if entry["tries"] >= self._max_retries:
                self._declare_dead(seq, entry["tries"])
                return
            entry["tries"] += 1
            entry["rto"] = min(entry["rto"] * 2, self._max_rto)
            jitter = int(self._rng.integers(0, max(2, entry["rto"] // 2)))
            entry["due"] = self._round + entry["rto"] + jitter
            self.stats["retransmits"] += 1
            # stored bytes: the size measured at send time, the payload
            # object cached at send time — no re-encode, no re-measure
            self.stats["bytes_resent"] += entry["nbytes"]
            if obs.ENABLED:
                obs.event("chan", "retransmit",
                          args={"seq": seq, "rto": entry["rto"]})
            if lineage.ENABLED:
                # a retransmission adds a DISTINCT chan/retransmit hop
                # per attempt (extra carries the attempt number) — never
                # a duplicate chain, never a deduped-away repeat
                for a, s in lineage.payload_keys(entry["payload"]):
                    lineage.hop(a, s, "chan/retransmit", site=self.label,
                                extra=(seq, entry["tries"]))
            self._send_raw(self._stamp({"kind": "data", "seq": seq,
                                        "ack": self._recv_high,
                                        "payload": entry["payload"]}))

    def _declare_dead(self, seq: int, tries: int):
        self.dead = True
        self.stats["dead"] = True
        self._unacked.clear()         # no resurrection: reclaim the window
        if obs.ENABLED:
            obs.event("chan", "dead", args={"seq": seq, "tries": tries})
        if self._on_dead is not None:
            self._on_dead(self)
        else:
            raise PeerDeadError(
                f"peer unresponsive: envelope seq={seq} retransmitted "
                f"{tries} times without an ack")

    # -- inbound --------------------------------------------------------

    def on_wire(self, env):
        env = validate_envelope(env)
        # cumulative ack (piggybacked on data, or a pure ack frame) —
        # applied only when it refers to OUR current send epoch: a stale
        # ack from before a revive() must not delete new-epoch window
        # entries that happen to share seq numbers
        ack = env["ack"]
        if ack and env.get("aepoch", 0) != self.epoch:
            self.stats["stale_acks"] += 1
            ack = 0
        if ack:
            for seq in [s for s in self._unacked if s <= ack]:
                del self._unacked[seq]
        if env["kind"] == "ack":
            return
        epoch = env.get("epoch", 0)
        if epoch < self._peer_epoch:
            # pre-epoch data still floating in the network after a
            # reconnect: its seq numbering belongs to the dead epoch's
            # space — deliverable-looking against the reset receive
            # window, so it MUST drop (un-acked; nobody retransmits a
            # dead epoch) rather than dedup by seq
            self.stats["stale_epoch_dropped"] += 1
            if obs.ENABLED:
                obs.event("chan", "stale_epoch_drop",
                          args={"seq": env["seq"], "epoch": epoch})
            return
        if epoch > self._peer_epoch:
            # the peer revived ahead of us (its hello raced this data
            # frame): adopt its new epoch — the old epoch's receive
            # state is dead bookkeeping now
            self._peer_epoch = epoch
            self._recv_high = 0
            self._recv_buf.clear()
        seq = env["seq"]
        if seq <= self._recv_high or seq in self._recv_buf:
            self.stats["dup_dropped"] += 1
            if obs.ENABLED:
                obs.event("chan", "dup_drop", args={"seq": seq})
        elif seq > self._recv_high + self._recv_window:
            # beyond the reorder window: drop UN-acked (the bounded-memory
            # guarantee; a real sender retransmits once the window opens)
            self.stats["window_dropped"] += 1
            if obs.ENABLED:
                obs.event("chan", "window_drop", args={"seq": seq})
            return
        elif self._admit is not None and not self._admit(env):
            # credit-based flow control (the service tier's backpressure
            # path): no credit -> the frame drops UN-acked, so the
            # sender's own retransmit timer redelivers it once credit
            # frees — the over-budget peer slows down instead of growing
            # an unbounded server-side queue
            self.stats["backpressured"] += 1
            if obs.ENABLED:
                obs.event("chan", "backpressure", args={"seq": seq})
            return
        else:
            self._recv_buf[seq] = env["payload"]
            if seq != self._recv_high + 1:
                self.stats["held_out_of_order"] += 1
        # release everything now contiguous, strictly in order. A RAISING
        # deliver callback still consumes its payload (the attempt is the
        # exactly-once event; redelivering identical bytes to a consumer
        # that rejected them would fail identically forever) — but it must
        # not corrupt channel state: later payloads still release, the
        # cumulative ack still goes out, and the first error re-raises to
        # the caller only after the channel is consistent.
        deliver_err = None
        while self._recv_high + 1 in self._recv_buf:
            self._recv_high += 1
            payload = self._recv_buf.pop(self._recv_high)
            self.stats["delivered"] += 1
            try:
                self._deliver(payload)
            except Exception as exc:
                if deliver_err is None:
                    deliver_err = exc
                self.stats["deliver_errors"] += 1
                if obs.ENABLED:
                    obs.event("chan", "deliver_error",
                              args={"seq": self._recv_high})
        self.stats["acks_sent"] += 1
        self._send_raw(self._stamp({"kind": "ack", "seq": 0,
                                    "ack": self._recv_high}))
        if deliver_err is not None:
            raise deliver_err

    # -- introspection --------------------------------------------------

    @property
    def idle(self) -> bool:
        """Nothing awaiting ack and nothing buffered out-of-order."""
        return not self._unacked and not self._recv_buf

    @property
    def in_flight(self) -> int:
        return len(self._unacked)

    @property
    def buffered(self) -> int:
        """Frames held in the out-of-order reorder buffer (bounded by
        the receive window) — credit-occupancy introspection."""
        return len(self._recv_buf)

    def pending_payloads(self) -> list:
        """The payloads of every un-acked outbound frame, send order —
        what the peer has NOT durably received yet. The service tier's
        lag probe counts the change batches in here as the wire
        component of replication lag (the hub's believed clocks advance
        optimistically at send time, so the matrix alone can't see
        in-flight loss)."""
        return [self._unacked[s]["payload"] for s in sorted(self._unacked)]

"""Parallel mesh execution (automerge_tpu/shard/parallel, INTERNALS §24).

The tier's contract is FLAG parity: the same seeded chaotic session must
converge to byte-identical state (checkpoint-bundle bytes AND rendered
texts, lane counters included) with the per-lane workers on or off, at
every shard count — the sequential loop is kept verbatim as the parity
comparator. Plus: the executor lifecycle (persistent workers, drain-
before-stop close, submit-after-close refusal), worker-error surfacing
at the round barrier AFTER every lane quiesced, the deliver_rounds /
service-tick host-overlap seams (pre-decoded batches actually engage and
never change results), the barrier-wait telemetry + `amtpu_mesh_*`
exposition families, and the residency tier under parallelism (budget
holds after every round; the reservation ledger survives a
barrier-released page-in thundering herd).
"""

import json
import random
import threading

import pytest

from automerge_tpu.engine import stacked
from automerge_tpu.obs import device_truth as dt
from automerge_tpu.obs.telemetry import Telemetry
from automerge_tpu.shard import ShardLane, ShardedDocSet
from automerge_tpu.shard.parallel import (LaneExecutor,
                                          parallel_lanes_enabled,
                                          tick_pipeline_enabled)
from test_shard import chaotic_stream, map_change, text_change


@pytest.fixture(autouse=True)
def _small_gate(monkeypatch):
    """Engage the stacked path at test scale."""
    monkeypatch.setenv("AMTPU_STACKED_MIN_OPS", "1")


# ---------------------------------------------------------------------------
# the flags
# ---------------------------------------------------------------------------


class TestFlags:
    def test_parallel_default_is_multi_lane_only(self, monkeypatch):
        monkeypatch.delenv("AMTPU_PARALLEL_LANES", raising=False)
        assert not parallel_lanes_enabled(1)
        assert parallel_lanes_enabled(2)
        assert parallel_lanes_enabled(8)

    def test_parallel_overrides(self, monkeypatch):
        monkeypatch.setenv("AMTPU_PARALLEL_LANES", "0")
        assert not parallel_lanes_enabled(8)
        monkeypatch.setenv("AMTPU_PARALLEL_LANES", "1")
        assert parallel_lanes_enabled(1)

    def test_tick_pipeline_follows_parallel_by_default(self, monkeypatch):
        monkeypatch.delenv("AMTPU_TICK_PIPELINE", raising=False)
        monkeypatch.delenv("AMTPU_PARALLEL_LANES", raising=False)
        assert tick_pipeline_enabled(2) and not tick_pipeline_enabled(1)
        monkeypatch.setenv("AMTPU_PARALLEL_LANES", "0")
        assert not tick_pipeline_enabled(2)

    def test_tick_pipeline_overrides_independently(self, monkeypatch):
        monkeypatch.setenv("AMTPU_PARALLEL_LANES", "1")
        monkeypatch.setenv("AMTPU_TICK_PIPELINE", "0")
        assert not tick_pipeline_enabled(8)
        monkeypatch.setenv("AMTPU_PARALLEL_LANES", "0")
        monkeypatch.setenv("AMTPU_TICK_PIPELINE", "1")
        assert tick_pipeline_enabled(1)


# ---------------------------------------------------------------------------
# flag parity: the tier's headline contract
# ---------------------------------------------------------------------------


def _run_mesh(seed, n_shards, flag, monkeypatch, rounds_api=False):
    monkeypatch.setenv("AMTPU_PARALLEL_LANES", flag)
    docs, rounds = chaotic_stream(seed)
    mesh = ShardedDocSet(n_shards=n_shards, capacity=64)
    try:
        if rounds_api:
            mesh.deliver_rounds(rounds)
        else:
            for chunk in rounds:
                mesh.deliver_round(chunk)
        for d in docs:
            assert mesh.quarantined(d) == 0
        bundles = {d: mesh.capture(d) for d in docs}
        texts = mesh.texts()
        lane_stats = [dict(lane.stats) for lane in mesh.lanes]
        ex_stats = dict(mesh._executor.stats) \
            if mesh._executor is not None else None
    finally:
        mesh.close()
    return bundles, texts, lane_stats, ex_stats


class TestFlagParity:
    @pytest.mark.parametrize("seed", [0, 1])
    @pytest.mark.parametrize("n_shards", [1, 2, 8])
    def test_parallel_matches_sequential_byte_identical(
            self, seed, n_shards, monkeypatch):
        """parallel vs sequential on the same seeded chaotic stream:
        byte-identical bundles, texts, AND per-lane counters (the
        fold-at-the-barrier stats discipline is exact)."""
        seq = _run_mesh(seed, n_shards, "0", monkeypatch)
        par = _run_mesh(seed, n_shards, "1", monkeypatch)
        assert par[0] == seq[0], "bundle bytes diverged"
        assert par[1] == seq[1], "texts diverged"
        assert par[2] == seq[2], "lane stats diverged"
        assert seq[3] is None                 # comparator never fanned out
        assert par[3] is not None and par[3]["errors"] == 0
        assert par[3]["submitted"] == par[3]["completed"] > 0
        assert par[3]["barriers"] > 0

    def test_deliver_rounds_overlap_engages_and_stays_identical(
            self, monkeypatch):
        """The lane-level round-pipelining seam: deliver_rounds
        pre-decodes round t+1 while round t's lane work drains — the
        overlap counters move and the result is still byte-identical to
        the sequential per-round loop."""
        seq = _run_mesh(3, 8, "0", monkeypatch)
        par = _run_mesh(3, 8, "1", monkeypatch, rounds_api=True)
        assert par[0] == seq[0] and par[1] == seq[1] and par[2] == seq[2]
        assert par[3]["rounds_overlapped"] > 0
        assert par[3]["predecoded_batches"] > 0

    def test_forced_parallel_on_one_lane(self, monkeypatch):
        """AMTPU_PARALLEL_LANES=1 on a 1-lane mesh runs the worker path
        (nothing to overlap, still correct)."""
        seq = _run_mesh(2, 1, "0", monkeypatch)
        par = _run_mesh(2, 1, "1", monkeypatch)
        assert par[0] == seq[0] and par[1] == seq[1] and par[2] == seq[2]
        assert par[3]["submitted"] > 0

    def test_migration_mid_stream_under_parallelism(self, monkeypatch):
        """Migration pens + the commit-boundary barrier: an 8-shard
        parallel run that migrates docs between rounds still lands
        byte-identical with the sequential 1-shard reference."""
        docs, rounds = chaotic_stream(9, n_chunks=4)
        monkeypatch.setenv("AMTPU_PARALLEL_LANES", "0")
        ref = ShardedDocSet(n_shards=1, capacity=64)
        for chunk in rounds:
            ref.deliver_round(chunk)
        monkeypatch.setenv("AMTPU_PARALLEL_LANES", "1")
        mesh = ShardedDocSet(n_shards=8, capacity=64)
        try:
            moved = 0
            for i, chunk in enumerate(rounds):
                mesh.deliver_round(chunk)
                victim = docs[i % len(docs)]
                if mesh.doc(victim) is not None:
                    dst = (mesh.placement.shard_of(victim) + 3) % 8
                    moved += mesh.migrate(victim, dst)
            assert moved >= 2, "migrations never engaged"
            assert mesh.texts() == ref.texts()
            for d in docs:
                assert mesh.capture(d) == ref.capture(d)
        finally:
            mesh.close()


# ---------------------------------------------------------------------------
# the executor: lifecycle, ordering, errors, telemetry
# ---------------------------------------------------------------------------


def _lanes(n):
    return [ShardLane(i) for i in range(n)]


class TestExecutor:
    def test_results_in_submission_order(self):
        with LaneExecutor(_lanes(3)) as ex:
            tasks = [ex.submit(i, lambda v=i: v * 10) for i in range(3)]
            assert ex.barrier(tasks) == [0, 10, 20]
            assert ex.stats["completed"] == 3
            assert ex.stats["barriers"] == 1

    def test_per_lane_tasks_run_in_order(self):
        seen = []
        with LaneExecutor(_lanes(1)) as ex:
            tasks = [ex.submit(0, seen.append, k) for k in range(20)]
            ex.barrier(tasks)
        assert seen == list(range(20))

    def test_close_is_idempotent_and_drains_pending(self):
        done = []
        ex = LaneExecutor(_lanes(2))
        for k in range(6):
            ex.submit(k % 2, done.append, k)
        ex.close()
        ex.close()
        assert sorted(done) == list(range(6)), \
            "close abandoned in-flight work"
        assert all(not w.is_alive() for w in ex._workers.values())
        with pytest.raises(RuntimeError):
            ex.submit(0, lambda: None)

    def test_error_reraises_after_all_lanes_quiesce(self):
        """A worker error (the budget-assert shape) surfaces on the
        caller at the barrier — but only after every OTHER lane's task
        finished, so no lane races the caller's unwind."""
        other_done = threading.Event()

        def boom():
            raise AssertionError("round budget exceeded")

        def slow_ok():
            other_done.wait(timeout=5)
            return "ok"

        with LaneExecutor(_lanes(2)) as ex:
            t0 = ex.submit(0, boom)
            t1 = ex.submit(1, slow_ok)
            other_done.set()
            with pytest.raises(AssertionError, match="round budget"):
                ex.barrier([t0, t1])
            assert t1.done() and t1.result == "ok"
            assert ex.stats["errors"] == 1

    def test_while_waiting_runs_before_the_block(self):
        order = []
        with LaneExecutor(_lanes(1)) as ex:
            task = ex.submit(0, lambda: order.append("work"))
            ex.barrier([task], while_waiting=lambda: order.append("over"))
        assert "over" in order

    def test_barrier_wait_telemetry_and_families(self):
        tel = Telemetry()
        with LaneExecutor(_lanes(2), telemetry=tel) as ex:
            tasks = [ex.submit(i, lambda: None) for i in range(2)]
            ex.barrier(tasks)
            hists, aggs = tel.span_view()
            assert ("mesh", "barrier_wait") in hists
            assert aggs[("mesh", "barrier_wait")]["count"] == 1
            fams = ex.families()
            names = [f[0] for f in fams]
            assert "amtpu_mesh_workers" in names
            assert "amtpu_mesh_rounds_total" in names
            assert "amtpu_mesh_rounds_overlapped_total" in names
            assert "amtpu_mesh_barriers_total" in names
            assert "amtpu_mesh_barrier_wait_seconds" in names
            workers = dict(zip(names, fams))["amtpu_mesh_workers"]
            assert workers[3] == [({}, 2)]
            d = ex.describe()
            assert d["schema"] == "amtpu-mesh-exec-v1"
            assert len(d["workers"]) == 2

    def test_budget_assert_surfaces_through_the_mesh(self, monkeypatch):
        """The per-lane round-budget assert — evaluated on the worker
        against the stats dict ITS apply returned — propagates to the
        deliver_round caller; the mesh stays usable afterwards."""
        monkeypatch.setenv("AMTPU_PARALLEL_LANES", "1")
        mesh = ShardedDocSet(n_shards=2, capacity=64, doc_kind="map")
        try:
            def boom(st):
                raise AssertionError("dispatch budget exceeded")
            monkeypatch.setattr(stacked, "assert_round_budget", boom)
            round_ = {f"bud-{i}": [map_change("a", 1, f"bud-{i}",
                                              [("k", i)])]
                      for i in range(8)}
            with pytest.raises(AssertionError, match="dispatch budget"):
                mesh.deliver_round(round_)
            monkeypatch.undo()
            monkeypatch.setenv("AMTPU_PARALLEL_LANES", "1")
            monkeypatch.setenv("AMTPU_STACKED_MIN_OPS", "1")
            round2 = {f"ok-{i}": [map_change("a", 1, f"ok-{i}",
                                             [("k", i)])]
                      for i in range(8)}
            assert mesh.deliver_round(round2) == 8
        finally:
            mesh.close()

    def test_mesh_describe_carries_executor(self, monkeypatch):
        monkeypatch.setenv("AMTPU_PARALLEL_LANES", "1")
        mesh = ShardedDocSet(n_shards=2, capacity=64)
        try:
            mesh.deliver_round({
                "da": [text_change("a", 1, "x", obj="da")],
                "db": [text_change("a", 1, "y", obj="db")]})
            d = mesh.describe()
            assert d["mesh_exec"]["schema"] == "amtpu-mesh-exec-v1"
        finally:
            mesh.close()


# ---------------------------------------------------------------------------
# service tick pipelining
# ---------------------------------------------------------------------------


def _service_session(monkeypatch, flag, n_rooms=4, steps=24, **cfg_kw):
    from test_service import _Client, _seed, am
    from automerge_tpu.service import ServiceConfig, SyncService
    monkeypatch.setenv("AMTPU_PARALLEL_LANES", flag)
    monkeypatch.setenv("AMTPU_TICK_PIPELINE", flag)
    svc = SyncService(ServiceConfig(shard_lanes=4, **cfg_kw))
    rng = random.Random(31)
    rooms = [f"pr-{i}" for i in range(n_rooms)]
    clients = []
    for room_id in rooms:
        base = _seed(svc, room_id)
        clients.append(_Client(svc, f"{room_id}-t0", room_id, base=base))
    for step in range(steps):
        c = rng.choice(clients)
        c.edit(f"k{rng.randrange(6)}", f"v{step}")
        if step % 3 == 0:
            for cl in clients:
                cl.pump()
            svc.tick()
    for _ in range(300):
        for cl in clients:
            cl.pump()
        svc.tick()
        if svc.idle() and all(cl.chan.idle and not cl.to_server
                              and not cl.to_client for cl in clients):
            break
    state = {r: json.dumps(am.to_json(svc.room(r).doc_set.get_doc(r)),
                           sort_keys=True) for r in rooms}
    lane_stats = [dict(lane.stats) for lane in svc._shard_lanes]
    ex = svc._mesh_executor()
    ex_stats = dict(ex.stats) if ex is not None else None
    svc.close()
    return state, lane_stats, ex_stats, svc


class TestServiceTickPipeline:
    def test_tick_parity_pipelined_vs_sequential(self, monkeypatch):
        """The same multi-room client session through the pipelined and
        the sequential tick: identical final room docs, identical lane
        counters; the executor actually fanned out in the ON leg."""
        seq = _service_session(monkeypatch, "0")
        par = _service_session(monkeypatch, "1")
        assert par[0] == seq[0], "room docs diverged"
        assert par[1] == seq[1], "lane stats diverged"
        assert seq[2] is None
        assert par[2] is not None and par[2]["errors"] == 0
        assert par[2]["barriers"] > 0 and par[2]["completed"] > 0

    def test_executor_shared_with_residency_mesh(self, monkeypatch,
                                                 tmp_path):
        """When the bulk doc mesh rides the service's own lanes
        (sharded + residency) the tick fan-out reuses the mesh's worker
        pool — ONE set of persistent threads."""
        from automerge_tpu.service import ServiceConfig, SyncService
        monkeypatch.setenv("AMTPU_PARALLEL_LANES", "1")
        svc = SyncService(ServiceConfig(
            shard_lanes=4, residency_budget_bytes=1 << 30,
            residency_spill_dir=str(tmp_path)))
        try:
            assert svc.doc_mesh is not None
            assert svc._mesh_executor() is svc.doc_mesh.executor()
            assert svc._tick_executor is None
        finally:
            svc.close()

    def test_tick_overlap_predecodes_mesh_backlog(self, monkeypatch,
                                                  tmp_path):
        """The tick-pipelining host-overlap seam: while tick t's
        grouped gate deliveries drain on the workers, the queued
        bulk-mesh rounds pre-decode on the caller — counters move, and
        the backlog still converges."""
        from test_service import _Client, _seed
        from automerge_tpu.service import ServiceConfig, SyncService
        monkeypatch.setenv("AMTPU_PARALLEL_LANES", "1")
        monkeypatch.setenv("AMTPU_TICK_PIPELINE", "1")
        svc = SyncService(ServiceConfig(
            shard_lanes=4, residency_budget_bytes=1 << 30,
            residency_spill_dir=str(tmp_path)))
        try:
            clients = []
            for i in range(4):
                base = _seed(svc, f"ov-{i}")
                clients.append(_Client(svc, f"ov-{i}-t0", f"ov-{i}",
                                       base=base))
            # materialize the bulk-mesh doc (predecode only touches
            # already-resident docs), then keep the backlog fed while
            # multi-lane grouped deliveries force the fan-out whose
            # barrier runs the overlap
            svc.mesh_deliver({"bulk": [text_change("ba", 1, "xx",
                                                   obj="bulk")]})
            svc.tick()
            seq = 1
            for step in range(8):
                for j, c in enumerate(clients):
                    c.edit("k", f"v{step}-{j}")
                seq += 1
                svc.mesh_deliver({"bulk": [text_change(
                    "ba", seq, "yy", start_ctr=(seq - 1) * 2 + 1,
                    after=f"ba:{(seq - 1) * 2}", obj="bulk")]})
                for c in clients:
                    c.pump()
                svc.tick()
            ex = svc._mesh_executor()
            assert ex is not None
            assert ex.stats["predecoded_batches"] > 0
            assert ex.stats["rounds_overlapped"] > 0
            lane = svc.doc_mesh.lane_of("bulk")
            with lane.device_ctx():
                assert lane.docs["bulk"].text() == "xx" + "yy" * (seq - 1)
        finally:
            svc.close()

    def test_scrape_exposes_mesh_families(self, monkeypatch):
        from test_service import _Client, _seed
        from automerge_tpu.service import ServiceConfig, SyncService
        monkeypatch.setenv("AMTPU_PARALLEL_LANES", "1")
        monkeypatch.setenv("AMTPU_TICK_PIPELINE", "1")
        svc = SyncService(ServiceConfig(shard_lanes=4))
        try:
            clients = []
            for i in range(4):
                base = _seed(svc, f"sc-{i}")
                clients.append(_Client(svc, f"sc-{i}-t0", f"sc-{i}",
                                       base=base))
            for step in range(6):
                for j, c in enumerate(clients):
                    c.edit("k", f"v{step}-{j}")
                for c in clients:
                    c.pump()
                svc.tick()
            assert svc._tick_executor is not None, \
                "the tick fan-out never engaged"
            page = svc.scrape()
            assert "amtpu_mesh_workers" in page
            assert "amtpu_mesh_barriers_total" in page
        finally:
            svc.close()


# ---------------------------------------------------------------------------
# residency under parallelism (ISSUE 20 satellite)
# ---------------------------------------------------------------------------


@pytest.fixture
def _fresh_gauges():
    dt.REGISTRY.clear_session()
    yield
    dt.REGISTRY.clear_session()


class TestResidencyUnderParallelism:
    def test_population_10x_budget_peak_bounded_with_workers_on(
            self, monkeypatch, tmp_path, _fresh_gauges):
        """ISSUE 18's acceptance shape with the lane workers ON: a
        population 10x the device budget, the doc-kind peak footprint
        gauge never exceeds the budget after ANY round — the residency
        hooks stay caller-thread at the commit boundary, so the budget
        invariant is untouched by parallelism."""
        from test_residency import build_mesh, prime
        monkeypatch.setenv("AMTPU_PARALLEL_LANES", "1")
        mesh, res = build_mesh(n_shards=2, spill_dir=str(tmp_path),
                               budget=0, cold_after=3)
        try:
            prime(mesh, res)
            per_doc = res._est_bytes
            assert per_doc > 0
            budget = 3 * per_doc
            res.config.budget_bytes = budget
            n_docs, seqs = 30, {i: 0 for i in range(30)}
            rng = random.Random(20)
            for rnd in range(40):
                deliveries = {}
                for i in rng.sample(range(n_docs), 2):
                    seqs[i] += 1
                    a = f"a-doc{i}"
                    deliveries[f"doc{i}"] = [text_change(
                        a, seqs[i], "x", start_ctr=seqs[i], obj=f"doc{i}",
                        after=(None if seqs[i] == 1
                               else f"{a}:{seqs[i] - 1}"))]
                mesh.deliver_round(deliveries)
                fp = dt.REGISTRY.footprint()
                assert fp["peak_device_bytes"] <= budget, (
                    f"round {rnd}: peak {fp['peak_device_bytes']} > "
                    f"budget {budget}")
            m = res.metrics()
            assert m["budget_overruns"] == 0
            assert m["page_outs"] > 0 and m["page_ins"] > 0
            acct = res.accounting()
            population = sorted(acct["hot"] + acct["warm"] + acct["cold"])
            assert population == sorted(
                f"doc{i}" for i in range(n_docs) if seqs[i])
            assert mesh._executor is not None \
                and mesh._executor.stats["barriers"] > 0
        finally:
            mesh.close()

    def test_reservation_ledger_survives_page_in_thundering_herd(
            self, monkeypatch, tmp_path, _fresh_gauges):
        """The ledger-banking lock: a barrier-released herd of threads
        paging distinct demoted docs in concurrently must keep the
        make-room/adopt pairs atomic — the budget holds at the herd's
        peak, and every doc lands in exactly one tier with its content
        intact."""
        from test_residency import build_mesh, prime
        monkeypatch.setenv("AMTPU_PARALLEL_LANES", "0")
        mesh, res = build_mesh(n_shards=2, spill_dir=str(tmp_path),
                               budget=0)
        try:
            prime(mesh, res)
            per_doc = res._est_bytes
            budget = 3 * per_doc
            res.config.budget_bytes = budget
            n_docs = 8
            for i in range(n_docs):
                mesh.deliver_round({f"h{i}": [text_change(
                    f"a{i}", 1, "z", obj=f"h{i}")]})
            for i in range(n_docs):
                if res.tier_of(f"h{i}") == "hot":
                    res.demote(f"h{i}")
            start = threading.Barrier(n_docs)
            errors = []

            def herd(i):
                try:
                    start.wait(timeout=10)
                    res.ensure_resident(f"h{i}")
                except Exception as exc:   # noqa: BLE001
                    errors.append(exc)
            threads = [threading.Thread(target=herd, args=(i,))
                       for i in range(n_docs)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert not errors, errors
            fp = dt.REGISTRY.footprint()
            assert fp["peak_device_bytes"] <= budget, (
                f"herd peak {fp['peak_device_bytes']} > budget {budget}")
            acct = res.accounting()
            tiers = acct["hot"] + acct["warm"] + acct["cold"]
            herd_docs = [d for d in tiers if d.startswith("h")]
            assert sorted(herd_docs) == [f"h{i}" for i in range(n_docs)]
            assert res.metrics()["budget_overruns"] == 0
            for i in range(n_docs):
                res.ensure_resident(f"h{i}")
                lane = mesh.lane_of(f"h{i}")
                with lane.device_ctx():
                    assert lane.docs[f"h{i}"].text() == "z"
        finally:
            mesh.close()

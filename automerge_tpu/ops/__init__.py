import jax

# Packed elemId keys are (actor_rank << 32 | ctr) int64 (ops/ingest.py): the
# device engine needs real 64-bit integers. Set before any kernel traces.
jax.config.update("jax_enable_x64", True)

from .linearize import rga_linearize  # noqa: E402,F401
from .scan import segment_starts, visible_index  # noqa: E402,F401

"""Device RGA linearization vs the oracle's tree walk.

Property test: build random concurrent-insert histories through the oracle
backend, extract the element table, and check that `rga_linearize` produces
exactly the oracle's RGA order (including tombstones).
"""

import random

import numpy as np
import pytest

import automerge_tpu as _am
from automerge_tpu import backend as oracle_backend
from automerge_tpu import frontend as Frontend


class am:
    """Thin view of the public API with init pinned to the ORACLE backend:
    these tests introspect the oracle's OpSetIndex (read_index), so docs must
    be built on it regardless of the default device-backend binding."""

    change = staticmethod(_am.change)
    apply_changes = staticmethod(_am.apply_changes)
    get_all_changes = staticmethod(_am.get_all_changes)
    merge = staticmethod(_am.merge)

    @staticmethod
    def init(options=None):
        if isinstance(options, str):
            options = {"actorId": options}
        return Frontend.init(
            {"backend": oracle_backend.Backend, **(options or {})})


def oracle_order(doc, list_key):
    """All elemIds of doc[list_key] in oracle RGA order (tombstones included)."""
    state = Frontend.get_backend_state(doc)
    index = state.read_index()
    obj_id = doc[list_key]._object_id
    order = []
    elem = "_head"
    while True:
        elem = index.get_next(obj_id, elem)
        if elem is None:
            return order, index, obj_id
        order.append(elem)


def element_table(index, obj_id, pad_to=None):
    """Extract (parent, ctr, actor_rank, valid, elem_ids) arrays, head at 0."""
    from automerge_tpu._common import parse_elem_id
    rec = index.by_object[obj_id]
    elem_ids = list(rec.insertion.keys())
    actors = sorted({parse_elem_id(e)[0] for e in elem_ids})
    actor_rank = {a: i for i, a in enumerate(actors)}
    slot = {e: i + 1 for i, e in enumerate(elem_ids)}
    n = 1 + len(elem_ids)
    cap = pad_to or n
    parent = np.zeros(cap, dtype=np.int32)
    ctr = np.zeros(cap, dtype=np.int32)
    actor = np.zeros(cap, dtype=np.int32)
    valid = np.zeros(cap, dtype=bool)
    valid[0] = True
    for e, i in slot.items():
        op = rec.insertion[e]
        a, c = parse_elem_id(e)
        parent[i] = 0 if op["key"] == "_head" else slot[op["key"]]
        ctr[i] = c
        actor[i] = actor_rank[a]
        valid[i] = True
    return parent, ctr, actor, valid, elem_ids


def device_order(index, obj_id, pad_to=None):
    from automerge_tpu.ops import rga_linearize
    from automerge_tpu.ops.linearize import pad_capacity
    import jax.numpy as jnp
    if pad_to is None:
        pad_to = pad_capacity(1 + len(index.by_object[obj_id].insertion))
    parent, ctr, actor, valid, elem_ids = element_table(index, obj_id, pad_to)
    pos = np.asarray(rga_linearize(jnp.asarray(parent), jnp.asarray(ctr),
                                   jnp.asarray(actor), jnp.asarray(valid)))
    n_live = len(elem_ids)
    order = [None] * n_live
    for i, e in enumerate(elem_ids):
        p = pos[i + 1]
        assert 0 <= p < n_live, f"element {e} got position {p}"
        order[p] = e
    return order


def random_history(seed, n_actors=3, n_rounds=5, edits_per_round=4):
    rng = random.Random(seed)
    base = am.change(am.init("base"), lambda d: d.__setitem__("xs", ["s0", "s1"]))
    base_changes = am.get_all_changes(base)
    docs = [am.apply_changes(am.init(f"actor-{i}"), base_changes)
            for i in range(n_actors)]
    for _ in range(n_rounds):
        for i, doc in enumerate(docs):
            def edit(d):
                for _ in range(rng.randrange(1, edits_per_round + 1)):
                    xs = d["xs"]
                    if len(xs) and rng.random() < 0.25:
                        xs.delete_at(rng.randrange(len(xs)))
                    else:
                        xs.insert(rng.randint(0, len(xs)), f"a{i}-{rng.randrange(1000)}")
            docs[i] = am.change(doc, edit)
        i, j = rng.sample(range(n_actors), 2)
        docs[i] = am.merge(docs[i], docs[j])
    merged = docs[0]
    for d in docs[1:]:
        merged = am.merge(merged, d)
    return merged


@pytest.mark.parametrize("seed", range(4))
def test_linearize_matches_oracle(seed):
    doc = random_history(seed)
    expected, index, obj_id = oracle_order(doc, "xs")
    got = device_order(index, obj_id)
    assert got == expected


def test_linearize_with_padding():
    doc = random_history(99)
    expected, index, obj_id = oracle_order(doc, "xs")
    got = device_order(index, obj_id, pad_to=128)
    assert got == expected


def test_linearize_sequential_typing_chain():
    # worst case for tree depth: each insert's parent is the previous element
    doc = am.init("typist")
    doc = am.change(doc, lambda d: d.__setitem__("xs", []))
    for i in range(40):
        doc = am.change(doc, lambda d, i=i: d["xs"].append(i))
    expected, index, obj_id = oracle_order(doc, "xs")
    got = device_order(index, obj_id)
    assert got == expected


def test_linearize_empty_list():
    import jax.numpy as jnp
    from automerge_tpu.ops import rga_linearize
    pos = rga_linearize(jnp.zeros(4, jnp.int32), jnp.zeros(4, jnp.int32),
                        jnp.zeros(4, jnp.int32),
                        jnp.array([True, False, False, False]))
    assert int(pos[0]) == -1


def test_visible_index_matches_numpy():
    import jax.numpy as jnp
    from automerge_tpu.ops import visible_index
    rng = np.random.default_rng(3)
    n = 64
    pos = rng.permutation(n).astype(np.int32)
    visible = rng.random(n) < 0.6
    vis_rank, n_visible = visible_index(jnp.asarray(pos), jnp.asarray(visible))
    # shadow model: rank among visible elements ordered by position
    order = np.argsort(pos)
    expected = np.zeros(n, np.int32)
    r = 0
    for i in order:
        expected[i] = r
        if visible[i]:
            r += 1
    assert int(n_visible) == int(visible.sum())
    assert np.array_equal(np.asarray(vis_rank)[visible], expected[visible])

"""Wire-message and change-schema validation.

One shared schema, two strictness levels:

- **strict** (the sync tier: ``SyncHub._receive``, ``Connection.receive_msg``,
  ``DocSet.deliver``): everything a peer can put on the wire is checked —
  message envelope (``docId``/``clock``/``changes``), change fields
  (``actor``/``seq``/``deps``/``ops``), and every op, including that the op
  action is one the wire grammar defines. Anything off-schema raises
  :class:`~.errors.ProtocolError` before any state is touched.

- **lenient** (backend change application: ``facade.apply_changes``,
  ``device.apply_changes``): identical structural checks, except unknown op
  *action strings* pass through. The device backend's scope gate routes those
  to the oracle via graduation, and the oracle rejects them authoritatively
  with the reference's ``Unknown operation type`` error — a pinned contract
  (tests/test_graduation.py). That is the ONLY divergence: everything the
  lenient mode admits gets stored in history and later shipped over the
  wire, so admitting anything strict peers would reject (a deps-less
  change, a container-valued set op) would mint locally-valid state that
  silently diverges the moment it syncs.

Validation never mutates or copies its input; it returns the validated value
so call sites can write ``changes = validate_changes(changes)`` (which also
materializes iterator inputs exactly once). One deliberate exception: a
bytes-typed ``wire`` field is replaced in place by its validated
``WireFrame`` so the decode is paid once (see ``validate_msg``).
"""

from __future__ import annotations

import operator
from contextlib import contextmanager

from .errors import ProtocolError

#: Op actions the wire grammar defines (the reference's full set:
#: backend/op_set.js applyOps + applyMake).
MAKE_ACTIONS = ("makeMap", "makeTable", "makeList", "makeText")
ASSIGN_ACTIONS = ("set", "del", "link", "inc")
OP_ACTIONS = frozenset(MAKE_ACTIONS) | frozenset(ASSIGN_ACTIONS) | {"ins"}

#: Assign actions that must carry a ``value`` field (a "truncated" op — an
#: assign missing its payload — is malformed, not a None assignment).
_VALUE_ACTIONS = frozenset(("set", "link", "inc"))


def _as_seq(value, what: str) -> int:
    """An integer-like value (int or numpy integer), else ProtocolError."""
    try:
        return operator.index(value)
    except TypeError:
        raise ProtocolError(f"{what} must be an integer, got "
                            f"{type(value).__name__}") from None


def validate_clock(clock, what: str = "clock") -> dict:
    if not isinstance(clock, dict):
        raise ProtocolError(f"{what} must be an object of actor -> seq, got "
                            f"{type(clock).__name__}")
    for actor, seq in clock.items():
        if not isinstance(actor, str) or not actor:
            raise ProtocolError(f"{what} keys must be non-empty actor id "
                                f"strings, got {actor!r}")
        if _as_seq(seq, f"{what}[{actor!r}]") < 0:
            raise ProtocolError(f"{what}[{actor!r}] must be >= 0, got {seq!r}")
    return clock


def validate_op(op, strict: bool = True) -> dict:
    if not isinstance(op, dict):
        raise ProtocolError(f"op must be an object, got "
                            f"{type(op).__name__}")
    action = op.get("action")
    if not isinstance(action, str):
        raise ProtocolError(f"op action must be a string, got {action!r}")
    if not isinstance(op.get("obj"), str) or not op["obj"]:
        raise ProtocolError(f"op {action!r} requires a string `obj`, got "
                            f"{op.get('obj')!r}")
    if action not in OP_ACTIONS:
        if strict:
            raise ProtocolError(f"unknown op action {action!r}")
        return op  # lenient: the backend scope gate / oracle judges it
    if action == "ins":
        if not isinstance(op.get("key"), str) or not op["key"]:
            raise ProtocolError("ins op requires a string `key` "
                               "(parent element id or _head)")
        if "elem" not in op or _as_seq(op["elem"], "ins op `elem`") < 1:
            raise ProtocolError(f"ins op requires an integer `elem` >= 1, "
                               f"got {op.get('elem')!r}")
    elif action in ASSIGN_ACTIONS:
        if not isinstance(op.get("key"), str) or not op["key"]:
            raise ProtocolError(f"{action} op requires a string `key`, got "
                               f"{op.get('key')!r}")
        if action in _VALUE_ACTIONS and "value" not in op:
            raise ProtocolError(f"truncated {action} op: missing `value`")
        if action == "link" and not isinstance(op.get("value"), str):
            raise ProtocolError(f"link op `value` must be an object id "
                               f"string, got {op.get('value')!r}")
        if action == "inc":
            v = op["value"]
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                raise ProtocolError(f"inc op `value` must be a number, "
                                   f"got {v!r}")
        elif action == "set" and isinstance(op.get("value"), (dict, list)):
            # nested containers arrive as make+link, never as raw set
            # payloads (the reference's wire grammar); accepting them here
            # would let one peer smuggle unmergeable state past the CRDT
            raise ProtocolError("set op `value` must be a primitive "
                               "(objects arrive as make+link)")
    return op


def validate_change(change, strict: bool = True) -> dict:
    if not isinstance(change, dict):
        raise ProtocolError(f"change must be an object, got "
                            f"{type(change).__name__}")
    actor = change.get("actor")
    if not isinstance(actor, str) or not actor:
        raise ProtocolError(f"change requires a non-empty string `actor`, "
                            f"got {actor!r}")
    if "seq" not in change or _as_seq(change["seq"], "change `seq`") < 1:
        raise ProtocolError(f"change requires an integer `seq` >= 1, got "
                            f"{change.get('seq')!r}")
    deps = change.get("deps")
    if deps is None:
        raise ProtocolError("change requires a `deps` clock object")
    validate_clock(deps, "change `deps`")
    ops = change.get("ops")
    if not isinstance(ops, (list, tuple)):
        raise ProtocolError(f"change requires an `ops` array, got "
                            f"{ops!r}")
    for op in ops:
        validate_op(op, strict)
    return change


#: Depth of `prevalidated()` extents on the stack. While non-zero, LENIENT
#: validation short-circuits to materialization: the inbound gate already
#: ran the (strictly stronger) wire checks over the same changes, so the
#: backend layer re-walking every op would be pure duplicated work on the
#: hot catch-up path. Strict validation never short-circuits. A plain
#: module counter suffices — the sync tier is single-threaded by design
#: (in-process callbacks; see docs/INTERNALS.md §7).
_prevalidated_depth = 0


@contextmanager
def prevalidated():
    """Mark the dynamic extent as carrying changes that need no lenient
    re-validation: either the inbound gate already ran the strict wire
    checks over them, or they were extracted from an admitted local
    lineage (merge) and are schema-valid by construction."""
    global _prevalidated_depth
    _prevalidated_depth += 1
    try:
        yield
    finally:
        _prevalidated_depth -= 1


def validate_changes(changes, strict: bool = True) -> list:
    """Validate a delivery; returns it materialized as a list."""
    if isinstance(changes, (str, bytes, dict)):
        raise ProtocolError(f"changes must be an array of change objects, "
                            f"got {type(changes).__name__}")
    try:
        changes = list(changes)
    except TypeError:
        raise ProtocolError(f"changes must be an array of change objects, "
                            f"got {type(changes).__name__}") from None
    if not strict and _prevalidated_depth:
        return changes   # already passed the stricter wire checks
    for change in changes:
        validate_change(change, strict)
    return changes


def validate_msg(msg) -> dict:
    """Validate one ``{docId, clock, changes?, wire?, checkpoint?,
    noSnapshot?}`` sync message (strict). ``checkpoint`` (a base64
    checkpoint bundle, the snapshot-bootstrap path) and ``noSnapshot``
    (the receiver's typed fallback request after a corrupt bundle) are
    optional extensions; the bundle's own integrity is verified by the
    checkpoint codec at restore time, not here. ``wire`` carries an
    ``AMTPUWIRE1`` binary change frame (engine/wire_format.py) — it is
    fully decoded (integrity hash + column envelope/bounds checks) HERE,
    so a truncated, bit-flipped, wrong-version, or out-of-envelope frame
    raises the typed ``WireFormatError`` (a ``ProtocolError``) before
    any state is touched, exactly like dict-wire malformation. A message
    may carry both ``changes`` (the dict prefix, e.g. a creation change)
    and ``wire`` (the frame-scoped tail); they apply in that order."""
    if not isinstance(msg, dict):
        raise ProtocolError(f"sync message must be an object, got "
                            f"{type(msg).__name__}")
    doc_id = msg.get("docId")
    if not isinstance(doc_id, str) or not doc_id:
        raise ProtocolError(f"sync message requires a non-empty string "
                            f"`docId`, got {doc_id!r}")
    clock = msg.get("clock")
    if clock is not None:
        validate_clock(clock, "message `clock`")
    changes = msg.get("changes")
    if changes is not None:
        if not isinstance(changes, (list, tuple)):
            raise ProtocolError(f"message `changes` must be an array, got "
                                f"{type(changes).__name__}")
        for change in changes:
            validate_change(change, strict=True)
    wire = msg.get("wire")
    if wire is not None:
        from ..engine.wire_format import WireFormatError, as_frame
        try:
            frame = as_frame(wire).validate()
        except WireFormatError:
            raise
        except (ValueError, TypeError, OverflowError) as exc:
            raise WireFormatError(
                f"malformed wire frame: {exc}") from exc
        if frame is not wire:
            # the ONE exception to the never-mutate rule: a bytes-typed
            # frame is replaced in place by its validated WireFrame, so
            # the decode just paid (body hash + bounds checks) is cached
            # for every downstream consumer instead of re-run per access
            # (in-process senders already pass WireFrame objects and are
            # untouched)
            msg["wire"] = frame
    trace = msg.get("trace")
    if trace is not None:
        # optional lineage trace context (INTERNALS §18.2): peers that
        # predate it never send or read it; a PRESENT value must be
        # schema-clean — WireFormatError is a ProtocolError, so a
        # malformed context degrades per-tenant like any other
        # malformed message, never crashes the tick
        from ..engine.wire_format import validate_trace_context
        validate_trace_context(trace)
    ckpt = msg.get("checkpoint")
    if ckpt is not None and not isinstance(ckpt, str):
        raise ProtocolError(f"message `checkpoint` must be a base64 string, "
                            f"got {type(ckpt).__name__}")
    if "noSnapshot" in msg and not isinstance(msg["noSnapshot"], bool):
        raise ProtocolError("message `noSnapshot` must be a boolean, got "
                            f"{msg['noSnapshot']!r}")
    return msg


def validate_save_payload(payload, require_changes: bool = True) -> dict:
    """Validate a deserialized ``api.save`` payload envelope.

    ``api.load`` historically leaked raw ``AttributeError`` on non-dict
    JSON (``load("[1]")``) and ``KeyError`` on a missing ``changes`` key;
    everything off-schema now raises :class:`ProtocolError` (a
    ``ValueError``) instead. Per-change validation stays with the backend
    apply path (lenient mode) — this checks the envelope only."""
    if not isinstance(payload, dict):
        raise ProtocolError(f"save payload must be an object, got "
                            f"{type(payload).__name__}")
    if not isinstance(payload.get("format"), str):
        raise ProtocolError(f"save payload requires a string `format`, got "
                            f"{payload.get('format')!r}")
    if require_changes and not isinstance(payload.get("changes"),
                                          (list, tuple)):
        raise ProtocolError(f"save payload requires a `changes` array, got "
                            f"{type(payload.get('changes')).__name__}")
    return payload

"""int32-envelope capacity guards (ISSUE 4 satellite / VERDICT r5 #3).

The device tier packs elemId keys as (actor_rank << 32 | ctr) int64 and
stores every column int32; actor ranks stand in for the reference's
string ordering (op_set.js:432-436). A counter, seq, or rank past
2^31-1 — or negative — would therefore WRAP into wrong ordering
silently. These tests pin that every packing/encoding site fails loudly
(OverflowError) instead.
"""

import numpy as np
import pytest

from automerge_tpu._common import INT32_MAX, check_int32_envelope
from automerge_tpu.engine import TextChangeBatch
from automerge_tpu.engine.columnar import MapChangeBatch
from automerge_tpu.engine.host_index import pack_keys


def test_check_int32_envelope_bounds():
    check_int32_envelope("x", np.asarray([0, 1, INT32_MAX]))
    with pytest.raises(OverflowError, match="envelope"):
        check_int32_envelope("x", np.asarray([INT32_MAX + 1]))
    with pytest.raises(OverflowError, match="envelope"):
        check_int32_envelope("x", np.asarray([-1]))
    check_int32_envelope("x", np.empty(0, np.int64))     # empty: no-op


def test_pack_keys_rejects_overflowing_ctr():
    ok = pack_keys(np.asarray([1, 2]), np.asarray([5, INT32_MAX]))
    assert ok.dtype == np.int64
    with pytest.raises(OverflowError, match="elemId counter"):
        pack_keys(np.asarray([1]), np.asarray([INT32_MAX + 1]))
    with pytest.raises(OverflowError, match="elemId counter"):
        pack_keys(np.asarray([1]), np.asarray([-7]))
    with pytest.raises(OverflowError, match="actor rank"):
        pack_keys(np.asarray([-2]), np.asarray([1]))


def test_pack_keys_boundary_does_not_collide():
    """Adjacent in-envelope keys stay distinct and ordered — the property
    a silent wrap would destroy."""
    keys = pack_keys(np.asarray([0, 0, 1]),
                     np.asarray([INT32_MAX - 1, INT32_MAX, 0]))
    assert len(set(keys.tolist())) == 3
    assert (np.diff(keys) > 0).all()


def test_text_batch_rejects_overflowing_elem_counter():
    """Wire changes minting an elemId counter past the envelope fail at
    batch construction — before anything reaches a device column."""
    big = INT32_MAX + 1
    changes = [{"actor": "a", "seq": 1, "deps": {}, "ops": [
        {"action": "ins", "obj": "t", "key": "_head", "elem": big}]}]
    with pytest.raises(OverflowError, match="elemId counter"):
        TextChangeBatch.from_changes(changes, "t")
    # a parent reference overflowing is caught by the same gate
    changes = [{"actor": "a", "seq": 1, "deps": {}, "ops": [
        {"action": "ins", "obj": "t", "key": f"b:{big}", "elem": 1}]}]
    with pytest.raises(OverflowError, match="counter"):
        TextChangeBatch.from_changes(changes, "t")


def test_batches_reject_overflowing_seq():
    changes = [{"actor": "a", "seq": INT32_MAX + 1, "deps": {}, "ops": [
        {"action": "ins", "obj": "t", "key": "_head", "elem": 1}]}]
    with pytest.raises(OverflowError, match="seq"):
        TextChangeBatch.from_changes(changes, "t")
    mchanges = [{"actor": "a", "seq": INT32_MAX + 1, "deps": {}, "ops": [
        {"action": "set", "obj": "m", "key": "k", "value": 1}]}]
    with pytest.raises(OverflowError, match="seq"):
        MapChangeBatch.from_changes(mchanges, "m")
    # seq 0 / negative is equally outside the envelope (lo=1)
    zchanges = [{"actor": "a", "seq": 0, "deps": {}, "ops": [
        {"action": "ins", "obj": "t", "key": "_head", "elem": 1}]}]
    with pytest.raises(OverflowError, match="seq"):
        TextChangeBatch.from_changes(zchanges, "t")


def test_in_envelope_batch_still_round_trips():
    """The guard must not reject legitimate large-but-legal counters."""
    from automerge_tpu.engine import DeviceTextDoc

    changes = [{"actor": "a", "seq": 1, "deps": {}, "ops": [
        {"action": "ins", "obj": "t", "key": "_head",
         "elem": INT32_MAX},
        {"action": "set", "obj": "t", "key": f"a:{INT32_MAX}",
         "value": "z"}]}]
    doc = DeviceTextDoc("t")
    doc.apply_batch(TextChangeBatch.from_changes(changes, "t"))
    assert doc.text() == "z"
    assert doc.elem_ids() == [f"a:{INT32_MAX}"]

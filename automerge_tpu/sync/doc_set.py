"""Keyed collection of documents with change handlers.

Counterpart of /root/reference/src/doc_set.js. A DocSet is the unit the sync
protocol multiplexes over one connection, and the unit the device engine
batches over (many documents merged in one call).
"""

from __future__ import annotations

from ..backend import default as Backend
from .. import frontend as Frontend


class DocSet:
    def __init__(self):
        self._docs: dict = {}
        self._handlers: list = []

    @property
    def doc_ids(self):
        return list(self._docs.keys())

    def get_doc(self, doc_id: str):
        return self._docs.get(doc_id)

    def remove_doc(self, doc_id: str):
        self._docs.pop(doc_id, None)

    def set_doc(self, doc_id: str, doc):
        self._docs[doc_id] = doc
        for handler in list(self._handlers):
            handler(doc_id, doc)

    def apply_changes(self, doc_id: str, changes):
        """Raw application — trusted (in-process) callers only. Network
        deliveries go through :meth:`deliver`, which validates and
        quarantines first; this method is what the inbound gate itself
        calls once a batch is admitted."""
        doc = self._applied_doc(doc_id, changes)
        self.set_doc(doc_id, doc)
        return doc

    def _applied_doc(self, doc_id: str, changes):
        """The doc with `changes` applied, WITHOUT committing it — the
        inbound gate uses this to separate backend rejection (state
        untouched, wrapped as ProtocolError) from exceptions raised by
        change handlers after the commit (which must propagate as-is:
        the document did change)."""
        doc = self._docs.get(doc_id)
        if doc is None:
            doc = Frontend.init({"backend": Backend.Backend})
        old_state = Frontend.get_backend_state(doc)
        new_state, patch = Backend.apply_changes(old_state, changes)
        patch["state"] = new_state
        return Frontend.apply_patch(doc, patch)

    def deliver(self, doc_id: str, changes):
        """Validated + quarantined inbound application (the network path).

        Malformed changes raise ``ProtocolError`` leaving document state
        and clock untouched; causally-premature changes park in the
        bounded per-doc quarantine and apply automatically once their
        deps arrive. Returns the (possibly unchanged) document."""
        from ..resilience.inbound import inbound_gate
        return inbound_gate(self).deliver(doc_id, changes)

    def checkpoint_doc(self, doc_id: str):
        """An integrity-checked columnar snapshot bundle of one document
        (``automerge_tpu.checkpoint.Checkpoint``) — what the snapshot
        bootstrap hands a joining peer instead of full history."""
        from ..checkpoint import checkpoint_doc
        doc = self._docs.get(doc_id)
        if doc is None:
            raise KeyError(f"no document {doc_id!r} in this doc set")
        return checkpoint_doc(doc)

    def bootstrap_doc(self, doc_id: str, checkpoint, changes=None,
                      fallback_changes=None, validated: bool = False,
                      wire=None):
        """Install a document from a checkpoint + op-log tail (snapshot
        bootstrap). The bundle is integrity-verified before any state is
        installed; a corrupt bundle raises ``CheckpointError`` — or,
        when ``fallback_changes`` carries the full log, degrades to full
        log replay instead. The tail then applies through the validated
        + quarantined inbound gate like any network delivery; ``wire``
        carries the tail's binary frame when the peer served it on the
        binary wire (the dict ``changes`` are then the prefix)."""
        from ..checkpoint import restore_doc_or_replay
        from ..resilience.inbound import inbound_gate
        doc = restore_doc_or_replay(checkpoint, fallback_changes)
        self.set_doc(doc_id, doc)
        from ..obs import lineage
        if lineage.ENABLED:
            # snapshot-bootstrap visibility: every sampled chain the
            # restored clock covers became visible on this replica
            # INSIDE the bundle (it never re-crossed the wire) — the
            # ckpt/adopt hop keeps those chains complete here
            state = Frontend.get_backend_state(doc)
            if state is not None:
                lineage.adopt_clock(dict(state.clock),
                                    site=lineage.site_of(self),
                                    doc=doc_id)
        gate = inbound_gate(self)
        if wire is not None:
            gate.deliver_wire(doc_id, [(wire, None)],
                              changes=changes or (), validated=validated)
        elif changes:
            gate.deliver(doc_id, changes, validated=validated)
        else:
            gate.release(doc_id)   # parked changes the snapshot satisfied
        return self.get_doc(doc_id)

    def register_handler(self, handler):
        if handler not in self._handlers:
            self._handlers.append(handler)

    def unregister_handler(self, handler):
        if handler in self._handlers:
            self._handlers.remove(handler)

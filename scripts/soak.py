"""Seeded randomized soak harness — the round-4 campaign, committed.

Round 4 ran ~800 ad-hoc soak sessions that found a real convergence bug
(net-zero remote histories silently dropped by merge — fixed,
tests/test_integration.py::TestNetZeroMerge); the runner itself was never
committed (VERDICT r4 Next #7). This is that harness as a reproducible,
seeded tool, exceeding the reference's fixed-scenario suite
(/root/reference/test/connection_test.js:17-65) by fuzzing at scale.

Profiles (each session is deterministic in its seed):
  general   nested histories with undo/redo and merge interleavings
  conflict  same-key / same-element races with partial pairwise sync
  lossy     Connection-protocol sync over a dropping network with churn
  table     concurrent Table row add/update/remove with partial sync
  chaos     Connection sync over ChaosLink+ResilientChannel (drop/dup/
            reorder/delay plus one partition/heal cycle) — byte-identical
            convergence after heal, no reconnects needed
  checkpoint chaos sync with periodic async snapshots of one peer and a
            mid-run RESTART of that peer from its latest checkpoint
            bundle (automerge_tpu.checkpoint) — byte-identical
            convergence after catch-up
  service   the multi-tenant service tier (automerge_tpu.service,
            INTERNALS §13) at scale: N client sessions over chaotic
            links into one tick-scheduled SyncService (room-sharded
            hubs, budgeted admission, credit backpressure), with
            partitions, slow-peer injection, and kill/rejoin churn.
            Asserts byte-identical convergence of every SURVIVOR with
            its room's server replica, bounded memory (inbox / channel
            reorder window / quarantine peaks never exceed the
            configured caps), no tenant starvation, and full dead-peer
            state reclamation (hub + ClockMatrix + quarantine) after
            eviction.

Usage:
  python scripts/soak.py [--profile all] [--sessions 30] [--seed-base 0]
  python scripts/soak.py --chaos [--sessions 50]     # chaos campaign
  python scripts/soak.py --checkpoint [--sessions 10]
  python scripts/soak.py --service [--clients 1000]  # service-scale soak
  python scripts/soak.py --service --quick           # CI smoke (100)
  python scripts/soak.py --chaos --trace             # + Perfetto trace

Exit 0 iff every session converged; failures print their profile+seed so
`--profile P --sessions 1 --seed-base SEED` reproduces one exactly.

The final line is ONE JSON summary (the machine-readable artifact):
profile, seed_base, per-seed failures, and the aggregated obs event
counters (INTERNALS §11) — chaos injections (drops/dups/reorders/delays/
partition drops), channel retransmits/dedups/window drops, and
quarantine parks/evictions/releases — so a failing soak is diagnosable
from the artifact alone: the seed reproduces it, the event mix says what
the transport actually did. ``--trace`` additionally dumps the retained
flight-recorder records as Chrome trace JSON (``soak_trace.json``;
AMTPU_TRACE_OUT overrides).
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _am():
    import automerge_tpu as am
    return am


KEYS = ["alpha", "beta", "gamma", "delta", "eps"]


def _rand_value(rng):
    kind = rng.integers(0, 4)
    if kind == 0:
        return int(rng.integers(-1000, 1000))
    if kind == 1:
        return "".join(chr(97 + int(c)) for c in rng.integers(0, 26, 5))
    if kind == 2:
        return {"n": int(rng.integers(0, 99))}
    return [int(x) for x in rng.integers(0, 9, 3)]


def _text_edit(am, doc, rng):
    def cb(d):
        t = d["t"]
        n = len(t)
        if n and rng.integers(0, 3) == 0:
            t.delete_at(int(rng.integers(0, n)))
        else:
            t.insert_at(int(rng.integers(0, n + 1)),
                        chr(97 + int(rng.integers(0, 26))))
    return am.change(doc, cb)


def _converged(am, docs):
    jsons = [am.to_json(d) for d in docs]
    ref = {k: (str(v) if hasattr(v, "elems") else v)
           for k, v in jsons[0].items()}
    for j in jsons[1:]:
        got = {k: (str(v) if hasattr(v, "elems") else v)
               for k, v in j.items()}
        if got != ref:
            return False, (ref, got)
    return True, None


def session_general(seed: int) -> None:
    """Nested histories + undo/redo + merge interleavings."""
    am = _am()
    from automerge_tpu import Text
    rng = np.random.default_rng(seed)
    base = am.change(am.init("base"), lambda d: (
        d.__setitem__("t", Text("seed")), d.__setitem__("m", {"k": 0})))
    changes = am.get_all_changes(base)
    peers = [am.apply_changes(am.init(f"actor-{i}"), changes)
             for i in range(3)]
    for _ in range(int(rng.integers(15, 30))):
        i = int(rng.integers(0, len(peers)))
        act = int(rng.integers(0, 6))
        if act == 0:
            k = KEYS[int(rng.integers(0, len(KEYS)))]
            v = _rand_value(rng)
            peers[i] = am.change(peers[i],
                                 lambda d, k=k, v=v: d.__setitem__(k, v))
        elif act == 1:
            peers[i] = _text_edit(am, peers[i], rng)
        elif act == 2:
            n = int(rng.integers(0, 50))
            peers[i] = am.change(
                peers[i], lambda d, n=n: d["m"].__setitem__("k", n))
        elif act == 3 and am.can_undo(peers[i]):
            peers[i] = am.undo(peers[i])
        elif act == 4 and am.can_redo(peers[i]):
            peers[i] = am.redo(peers[i])
        else:
            j = int(rng.integers(0, len(peers)))
            if j != i:
                peers[i] = am.merge(peers[i], peers[j])
    # full cross-merge in seed-random order until stable, then converge
    order = rng.permutation(len(peers))
    for _ in range(2):
        for i in order:
            for j in order:
                if i != j:
                    peers[i] = am.merge(peers[i], peers[j])
    ok, diff = _converged(am, peers)
    assert ok, f"general seed {seed} diverged: {diff}"
    # save/load must preserve the converged state
    back = am.load(am.save(peers[0]))
    ok, diff = _converged(am, [peers[0], back])
    assert ok, f"general seed {seed} save/load mismatch: {diff}"


def session_conflict(seed: int) -> None:
    """Same-key and same-element races with partial pairwise sync."""
    am = _am()
    from automerge_tpu import Text
    rng = np.random.default_rng(seed)
    base = am.change(am.init("base"), lambda d: (
        d.__setitem__("t", Text("abcdef")),
        *[d.__setitem__(k, 0) for k in KEYS]))
    changes = am.get_all_changes(base)
    peers = [am.apply_changes(am.init(f"w{i}"), changes) for i in range(4)]
    for step in range(int(rng.integers(10, 20))):
        for i in range(len(peers)):          # every peer races every step
            act = int(rng.integers(0, 3))
            if act == 0:
                k = KEYS[int(rng.integers(0, len(KEYS)))]
                peers[i] = am.change(
                    peers[i], lambda d, k=k, i=i, s=step:
                    d.__setitem__(k, f"w{i}s{s}"))
            elif act == 1 and len(peers[i]["t"]):
                idx = int(rng.integers(0, len(peers[i]["t"])))
                peers[i] = am.change(
                    peers[i], lambda d, idx=idx, i=i:
                    d["t"].set(min(idx, len(d["t"]) - 1), str(i)))
            else:
                peers[i] = _text_edit(am, peers[i], rng)
        if rng.integers(0, 2):               # partial sync: one random pair
            i, j = rng.choice(len(peers), 2, replace=False)
            peers[int(i)] = am.merge(peers[int(i)], peers[int(j)])
    for _ in range(2):
        for i in range(len(peers)):
            for j in range(len(peers)):
                if i != j:
                    peers[i] = am.merge(peers[i], peers[j])
    ok, diff = _converged(am, peers)
    assert ok, f"conflict seed {seed} diverged: {diff}"
    # conflict METADATA must converge too, not just winners
    for k in KEYS:
        refc = am.get_conflicts(peers[0], k)
        for p in peers[1:]:
            assert am.get_conflicts(p, k) == refc, \
                f"conflict seed {seed}: conflicts diverged at {k}"


def session_lossy(seed: int) -> None:
    """Connection sync over a dropping in-memory network with churn."""
    am = _am()
    from automerge_tpu import Connection, DocSet, Text
    rng = np.random.default_rng(seed)
    n = int(rng.integers(2, 4))
    sets = [DocSet() for _ in range(n)]
    doc0 = am.change(am.init("origin"),
                     lambda d: d.__setitem__("t", Text("start")))
    base_changes = am.get_all_changes(doc0)
    for i, ds in enumerate(sets):
        ds.set_doc("doc", am.apply_changes(am.init(f"peer-{i}"),
                                           base_changes))

    queues: dict = {}
    conns: dict = {}

    def wire(a: int, b: int):
        ca = Connection(sets[a], lambda m, a=a, b=b:
                        queues.setdefault((a, b), []).append(m))
        cb = Connection(sets[b], lambda m, a=a, b=b:
                        queues.setdefault((b, a), []).append(m))
        conns[(a, b)], conns[(b, a)] = ca, cb
        ca.open()
        cb.open()

    def deliver(edge, drop_p: float):
        q = queues.get(edge, [])
        while q:
            msg = q.pop(0)
            if rng.random() < drop_p:
                continue                      # lost on the wire
            conns[(edge[1], edge[0])].receive_msg(msg)

    for a in range(n):
        for b in range(a + 1, n):
            wire(a, b)
    edges = list(conns.keys())

    for step in range(int(rng.integers(10, 25))):
        i = int(rng.integers(0, n))
        doc = sets[i].get_doc("doc")
        sets[i].set_doc("doc", _text_edit(am, doc, rng))
        for edge in edges:
            deliver(edge, drop_p=0.3)
        if rng.integers(0, 5) == 0:           # churn: bounce one pair
            a, b = edges[int(rng.integers(0, len(edges)))]
            if a < b:                         # close both directions once
                conns[(a, b)].close()
                conns[(b, a)].close()
                queues.pop((a, b), None)      # in-flight frames die too
                queues.pop((b, a), None)
                wire(a, b)
    # recovery contract (pinned by tests/test_connection_traces.py):
    # dropped frames are recovered on the next STATE CHANGE or peer
    # RECONNECT — a bare re-delivery of what's still queued is not enough,
    # because the receiver never learns a dropped frame existed. Bounce
    # every connection (reconnect re-advertises clocks, prompting
    # re-sends), then drain losslessly until quiescent.
    for a in range(n):
        for b in range(a + 1, n):
            conns[(a, b)].close()
            conns[(b, a)].close()
            queues.pop((a, b), None)
            queues.pop((b, a), None)
            wire(a, b)
    for _ in range(4):                        # let re-requests settle
        for edge in edges:
            deliver(edge, drop_p=0.0)
    docs = [ds.get_doc("doc") for ds in sets]
    ok, diff = _converged(am, docs)
    assert ok, f"lossy seed {seed} diverged: {diff}"


def session_table(seed: int) -> None:
    """Concurrent Table row add/update/remove with partial sync — the
    row-oriented surface the other profiles never touch."""
    am = _am()
    from automerge_tpu import Table
    rng = np.random.default_rng(seed)
    base = am.change(am.init("base"), lambda d: d.__setitem__("t", Table()))
    changes = am.get_all_changes(base)
    peers = [am.apply_changes(am.init(f"tw{i}"), changes) for i in range(3)]
    known_rows: list = []           # row ids any peer has minted
    for step in range(int(rng.integers(12, 24))):
        i = int(rng.integers(0, len(peers)))
        act = int(rng.integers(0, 4))
        if act == 0 or not known_rows:       # add a row
            holder = {}
            def add(d, i=i, s=step, holder=holder):
                holder["id"] = d["t"].add(
                    {"by": f"tw{i}", "step": s,
                     "v": int(rng.integers(0, 99))})
            peers[i] = am.change(peers[i], add)
            known_rows.append(holder["id"])
        elif act == 1:                       # update a row if visible here
            rid = known_rows[int(rng.integers(0, len(known_rows)))]
            if peers[i]["t"].by_id(rid) is not None:
                peers[i] = am.change(
                    peers[i], lambda d, rid=rid, s=step:
                    d["t"].by_id(rid).__setitem__("v", 1000 + s))
        elif act == 2:                       # remove a row if visible here
            rid = known_rows[int(rng.integers(0, len(known_rows)))]
            if peers[i]["t"].by_id(rid) is not None:
                peers[i] = am.change(
                    peers[i], lambda d, rid=rid: d["t"].remove(rid))
        else:                                # partial sync
            j = int(rng.integers(0, len(peers)))
            if j != i:
                peers[i] = am.merge(peers[i], peers[j])
    for _ in range(2):
        for i in range(len(peers)):
            for j in range(len(peers)):
                if i != j:
                    peers[i] = am.merge(peers[i], peers[j])
    ok, diff = _converged(am, peers)   # to_json renders tables as dicts
    assert ok, f"table seed {seed} diverged: {diff}"


def session_chaos(seed: int) -> None:
    """3-peer Connection sync over a chaotic transport — drop, duplication,
    reordering, delay, and ONE partition/heal cycle — made survivable by
    the resilience layer (ResilientChannel seq/ack/retry over ChaosLink).

    Unlike the `lossy` profile, nothing is ever reconnected and no state
    change is needed for recovery: the channel's retransmit + dedup +
    in-order release restores the lossless transport the wire protocol
    assumes, and causally-premature cross-edge arrivals park in the
    bounded quarantine until their deps land. Convergence is asserted
    byte-identically: same rendered document AND same serialized change
    history on every peer."""
    import json as _json

    am = _am()
    from automerge_tpu import Connection, DocSet, Text
    from automerge_tpu.resilience import ChaosLink, ResilientChannel

    rng = np.random.default_rng(seed)
    n = 3
    sets = [DocSet() for _ in range(n)]
    doc0 = am.change(am.init("origin"),
                     lambda d: d.__setitem__("t", Text("start")))
    base = am.get_all_changes(doc0)
    for i, ds in enumerate(sets):
        ds.set_doc("doc", am.apply_changes(am.init(f"peer-{i}"), base))

    drop = float(rng.uniform(0.05, 0.30))        # ≤ 30% loss
    dup = float(rng.uniform(0.0, 0.20))          # ≤ 20% duplication
    reorder = float(rng.uniform(0.05, 0.30))
    delay = float(rng.uniform(0.0, 0.30))
    edges = [(a, b) for a in range(n) for b in range(n) if a != b]
    links, channels, conns = {}, {}, {}
    for a, b in edges:                            # directed chaos edges
        links[(a, b)] = ChaosLink(
            lambda env, a=a, b=b: channels[(b, a)].on_wire(env),
            rng=rng, drop=drop, dup=dup, reorder=reorder, delay=delay)
    for a, b in edges:                            # reliability endpoints
        channels[(a, b)] = ResilientChannel(
            links[(a, b)].send,
            lambda msg, a=a, b=b: conns[(a, b)].receive_msg(msg),
            seed=seed * 7919 + a * 97 + b)
    for a, b in edges:                            # the UNCHANGED protocol
        conns[(a, b)] = Connection(sets[a], channels[(a, b)].send)
        conns[(a, b)].open()

    def pump(rounds: int = 1):
        for _ in range(rounds):
            for e in edges:
                links[e].pump()
            for e in edges:
                channels[e].tick()

    n_steps = int(rng.integers(12, 22))
    part_at = int(rng.integers(2, n_steps - 6))   # one partition/heal cycle
    part_len = int(rng.integers(2, 6))
    pa, pb = (int(x) for x in rng.choice(n, 2, replace=False))
    for step in range(n_steps):
        if step == part_at:
            links[(pa, pb)].partition()
            links[(pb, pa)].partition()
        if step == part_at + part_len:
            links[(pa, pb)].heal()
            links[(pb, pa)].heal()
        i = int(rng.integers(0, n))
        sets[i].set_doc("doc", _text_edit(am, sets[i].get_doc("doc"), rng))
        pump(1)
    # heal, switch the links lossless, and let retransmission finish the
    # job — no reconnects, no fresh state changes
    for e in edges:
        links[e].heal()
        links[e].drop = links[e].dup = 0.0
        links[e].reorder = links[e].delay = 0.0
    for _ in range(400):
        pump(1)
        if all(ch.idle for ch in channels.values()) \
                and all(ln.idle for ln in links.values()):
            break
    else:
        raise AssertionError(f"chaos seed {seed}: channels never quiesced")

    docs = [ds.get_doc("doc") for ds in sets]
    ok, diff = _converged(am, docs)
    assert ok, f"chaos seed {seed} diverged: {diff}"
    hists = [sorted(_json.dumps(c, sort_keys=True)
                    for c in am.get_all_changes(d)) for d in docs]
    assert hists.count(hists[0]) == len(hists), \
        f"chaos seed {seed}: change histories diverged after heal"
    for ds in sets:                               # nothing left parked
        gate = getattr(ds, "_inbound_gate", None)
        assert not gate or gate.quarantined("doc") == 0, \
            f"chaos seed {seed}: quarantine not drained"


def session_checkpoint(seed: int) -> None:
    """Chaos sync with mid-run checkpointing and a peer RESTART: one peer
    periodically captures its document through the async checkpoint
    writer (automerge_tpu.checkpoint.AsyncCheckpointer), then mid-chaos
    its whole DocSet is torn down and rebuilt from the LAST completed
    checkpoint bundle — in-flight frames die, edits made after the
    capture are forgotten locally — and the sync protocol must pull the
    restarted peer back to byte-identical convergence over the still-
    chaotic links. Exercises capture-under-ingestion, bundle integrity
    verification, snapshot-bootstrapped rejoin, and tail catch-up in one
    scenario."""
    import json as _json

    am = _am()
    from automerge_tpu import Connection, DocSet, Text
    from automerge_tpu.checkpoint import AsyncCheckpointer
    from automerge_tpu.resilience import ChaosLink, ResilientChannel

    rng = np.random.default_rng(seed)
    n = 3
    sets = [DocSet() for _ in range(n)]
    doc0 = am.change(am.init("origin"),
                     lambda d: d.__setitem__("t", Text("start")))
    base = am.get_all_changes(doc0)
    for i, ds in enumerate(sets):
        ds.set_doc("doc", am.apply_changes(am.init(f"peer-{i}"), base))

    drop = float(rng.uniform(0.05, 0.25))
    reorder = float(rng.uniform(0.05, 0.25))
    links, channels, conns = {}, {}, {}

    def wire_edge(a, b):
        links[(a, b)] = ChaosLink(
            lambda env, a=a, b=b: channels[(b, a)].on_wire(env),
            rng=rng, drop=drop, dup=0.05, reorder=reorder, delay=0.1)
        channels[(a, b)] = ResilientChannel(
            links[(a, b)].send,
            lambda msg, a=a, b=b: conns[(a, b)].receive_msg(msg),
            seed=seed * 7919 + a * 97 + b)
        conns[(a, b)] = Connection(sets[a], channels[(a, b)].send)

    edges = [(a, b) for a in range(n) for b in range(n) if a != b]
    for a, b in edges:
        wire_edge(a, b)
    for e in edges:
        conns[e].open()

    def pump(rounds: int = 1):
        for _ in range(rounds):
            for e in edges:
                links[e].pump()
            for e in edges:
                channels[e].tick()

    victim = int(rng.integers(0, n))
    writer = AsyncCheckpointer()
    handles: list = []
    bundle = None
    n_steps = int(rng.integers(14, 22))
    restart_at = int(rng.integers(6, n_steps - 4))
    restarted = False
    try:
        for step in range(n_steps):
            i = int(rng.integers(0, n))
            sets[i].set_doc("doc",
                            _text_edit(am, sets[i].get_doc("doc"), rng))
            if step % 3 == 0:        # periodic async snapshot of the victim
                from automerge_tpu import Frontend
                state = Frontend.get_backend_state(
                    sets[victim].get_doc("doc"))
                handles.append(writer.capture_async(state))
            if step == restart_at:
                for h in handles:    # latest completed capture wins
                    bundle = h.result(30)
                assert bundle is not None, "no checkpoint completed"
                # RESTART: the victim loses everything since its last
                # checkpoint; a fresh DocSet bootstraps from the bundle
                # and fresh links/channels/conns rejoin the mesh
                for a, b in edges:
                    if victim in (a, b):
                        conns[(a, b)].close()
                sets[victim] = DocSet()
                sets[victim].bootstrap_doc("doc", bundle)
                for a, b in edges:
                    if victim in (a, b):
                        wire_edge(a, b)
                        conns[(a, b)].open()
                restarted = True
            pump(1)
    finally:
        writer.close()
    assert restarted
    for e in edges:                  # heal: lossless from here on
        links[e].heal()
        links[e].drop = links[e].dup = 0.0
        links[e].reorder = links[e].delay = 0.0
    for _ in range(400):
        pump(1)
        if all(ch.idle for ch in channels.values()) \
                and all(ln.idle for ln in links.values()):
            break
    else:
        raise AssertionError(f"checkpoint seed {seed}: never quiesced")
    docs = [ds.get_doc("doc") for ds in sets]
    ok, diff = _converged(am, docs)
    assert ok, f"checkpoint seed {seed} diverged after restart: {diff}"
    hists = [sorted(_json.dumps(c, sort_keys=True)
                    for c in am.get_all_changes(d)) for d in docs]
    assert hists.count(hists[0]) == len(hists), \
        f"checkpoint seed {seed}: change histories diverged after restart"


#: Per-profile metrics registry: a profile that wants its numbers in the
#: campaign summary UPDATES ITS ENTRY IN PLACE (never prints its own
#: JSON — the one-line artifact contract lives in emit_summary alone,
#: so a new profile cannot regress it by copy-pasting emission logic).
#: Non-empty entries fold into the summary as "<profile>_metrics".
PROFILE_METRICS: dict = {"service": {}, "sharded": {}, "federation": {},
                         "residency": {}}

#: back-compat alias: the service profile's registry entry
LAST_SERVICE_METRICS = PROFILE_METRICS["service"]

#: --scrape: serve the live Prometheus endpoint during the service soak
#: and validate the exposition + /describe dump from an actual HTTP
#: fetch before the acceptance asserts run
SCRAPE = False


def _validate_scrape(url: str):
    """Fetch the LIVE scrape endpoint: the exposition page must pass the
    format validator (INTERNALS §14.3) and /describe must parse as the
    postmortem schema. Results fold into the summary line."""
    import json as _json
    import urllib.request

    from automerge_tpu.obs.prom import validate_prom

    page = urllib.request.urlopen(url + "/metrics", timeout=10) \
        .read().decode()
    counts = validate_prom(page)
    dump = _json.loads(
        urllib.request.urlopen(url + "/describe", timeout=10).read())
    assert dump.get("schema") == "amtpu-postmortem-v1", dump.get("schema")
    LAST_SERVICE_METRICS.update(scrape_ok=True,
                                scrape_families=counts["families"],
                                scrape_samples=counts["samples"])


class _SvcClient:
    """One tenant-side endpoint: DocSet + Connection + ResilientChannel
    over a pair of directed ChaosLinks into the service."""

    __slots__ = ("tid", "room_id", "ds", "chan", "conn", "c2s", "s2c",
                 "slow", "alive")

    def __init__(self, am, svc, tid, room_id, base_changes, actor,
                 link_seed, chaos, empty=False):
        from automerge_tpu import Connection, DocSet
        from automerge_tpu.resilience import ChaosLink, ResilientChannel
        self.tid = tid
        self.room_id = room_id
        self.slow = 1          # pump every `slow` ticks
        self.alive = True
        self.ds = DocSet()
        # lineage replica-site label: commit hops on this client's gate
        # name the tenant, so the per-replica completeness bar below can
        # ask "did THIS surviving replica see the change" (§18)
        self.ds._lineage_site = tid
        if not empty:
            # a rejoiner starts EMPTY instead: it must bootstrap from the
            # server (snapshot bundle when the history is long enough)
            self.ds.set_doc(room_id,
                            am.apply_changes(am.init(actor), base_changes))
        # frames for an evicted tenant (no live session) die on the
        # floor — exactly what a real listener does for a closed socket
        self.c2s = ChaosLink(
            lambda env: (svc.session(tid) is not None
                         and svc.session(tid).on_wire(env)),
            seed=link_seed, **chaos)
        self.s2c = ChaosLink(lambda env: self.chan.on_wire(env),
                             seed=link_seed + 1, **chaos)
        sess = svc.connect(tid, room_id, self.s2c.send,
                           seed=link_seed + 2)
        assert sess is not None
        self.chan = ResilientChannel(self.c2s.send, None,
                                     seed=link_seed + 3)
        self.conn = Connection(self.ds, self.chan.send)
        self.chan._deliver = self.conn.receive_msg
        self.conn.open()

    def pump(self):
        self.c2s.pump()
        self.s2c.pump()
        self.chan.tick()

    def heal(self):
        for ln in (self.c2s, self.s2c):
            ln.heal()
            ln.drop = ln.dup = ln.reorder = ln.delay = 0.0
        self.slow = 1

    def idle(self):
        return self.chan.idle and self.c2s.idle and self.s2c.idle


def session_service(seed: int, n_clients: int = 24, n_ticks: int = 30,
                    room_size: int = 4, quiesce_ticks: int = 400) -> None:
    """N concurrent tenant sessions against one SyncService under churn,
    partitions, slow peers, and kill/rejoin — the service tier's honest
    load test (ISSUE 8 acceptance run: ``--service --clients 1000``).

    Fault schedule (all seeded): every client link carries drop/dup/
    reorder/delay chaos; ~8% of clients get partitioned for a window;
    ~8% run slow (pump every 4th tick); ~6% are KILLED mid-run (vanish
    without a goodbye — the heartbeat/retransmit-cap ladder must declare
    them dead and reclaim everything), and half the killed REJOIN later
    as fresh sessions bootstrapped by the server (snapshot cache when
    history is long enough, plain changes otherwise).

    Asserted at the end (the acceptance bars):
      1. every room's surviving clients render AND serialize
         byte-identically to the server replica (change histories too);
      2. bounded memory: peak inbox <= inbox_cap + recv_window, peak
         channel reorder buffer <= recv_window, peak quarantine <= the
         aggregate cap — and zero parked changes remain;
      3. no tenant starved: max consecutive backlogged-but-unadmitted
         ticks <= 2x the starvation boost threshold;
      4. every killed-and-not-rejoined tenant was EVICTED and its hub /
         ClockMatrix / quarantine state fully reclaimed;
      5. the telemetry tier agrees: zero replication lag (ClockMatrix
         deficit + un-acked wire frames) for every live tenant at
         quiescence.

    Any failure — never-quiesced, divergence, a violated bound — writes
    the black-box postmortem dump (``SyncService.describe()``) to
    ``AMTPU_POSTMORTEM_OUT`` (default ``service_postmortem.json``)
    before re-raising, so a failed soak leaves flight data, not just a
    seed. With ``--scrape`` the Prometheus endpoint is served live for
    the whole session and validated over real HTTP at the end."""
    am = _am()
    from automerge_tpu.obs import lineage as _lin
    from automerge_tpu.service import ServiceConfig, SyncService, \
        TenantBudget

    # sample-EVERYTHING lineage (the acceptance/debug mode, rate=1)
    # adds measurable per-message work to the admission loop (~40% on
    # tick p50 at 100 clients on this box), which the population-scaled
    # deadline below doesn't know about — scale the budget so the
    # no-starvation bar keeps measuring scheduling fairness, not
    # tracing overhead. Production-rate sampling (1/64) is bounded at
    # <= 5% by the committed cfg14 row and gets no allowance.
    lineage_full = (_lin.ENABLED and _lin.ledger() is not None
                    and _lin.ledger().rate == 1)

    cfg = ServiceConfig(
        heartbeat_ticks=12, suspect_grace_ticks=12, max_retries=24,
        recv_window=256,
        # a real admission deadline so deadline shedding and the
        # starvation accounting are actually EXERCISED at scale — with
        # the default 0.0 the _starve path is unreachable (the first
        # message of a visit always admits) and the no-starvation
        # acceptance bar would be vacuously true. Scaled with the
        # population: the deadline bounds the admission LOOP, whose cost
        # is O(tenants), so a flat sub-ms budget that sheds honestly at
        # 100 clients starves everything at 1000 (measured: 972k sheds,
        # zero drain progress) while a flat generous one never fires
        tick_budget_ms=max(0.5, n_clients / 200.0)
        * (1.5 if lineage_full else 1.0),
        default_budget=TenantBudget(ops_per_tick=64,
                                    bytes_per_tick=32 * 1024,
                                    inbox_cap=32))
    svc = SyncService(cfg)
    # each seeded session is an independent deployment: a fresh ledger,
    # or seed N's acceptance would evaluate seed N-1's chains against
    # rooms that share names across sessions
    if _lin.ENABLED:
        _lin.clear()
    scrape_srv = svc.serve_metrics() if SCRAPE else None
    try:
        _service_scenario(am, svc, cfg, seed, n_clients, n_ticks,
                          room_size, quiesce_ticks)
        if scrape_srv is not None:
            _validate_scrape(scrape_srv.url)
    except Exception:
        # the black-box contract: a failing soak leaves a parseable
        # flight-data dump, not just an assertion message
        path = os.environ.get("AMTPU_POSTMORTEM_OUT",
                              "service_postmortem.json")
        try:
            svc.write_postmortem(path)
            print(f"soak: service postmortem written to {path}",
                  file=sys.stderr, flush=True)
        except Exception as dump_exc:   # noqa: BLE001 — never mask the
            print(f"soak: postmortem dump failed: {dump_exc!r}",  # cause
                  file=sys.stderr, flush=True)
        raise
    finally:
        if scrape_srv is not None:
            scrape_srv.close()


def _service_scenario(am, svc, cfg, seed, n_clients, n_ticks, room_size,
                      quiesce_ticks):
    import json as _json
    import math

    from automerge_tpu import Text

    rng = np.random.default_rng(seed)
    n_rooms = max(1, math.ceil(n_clients / room_size))
    base_changes: dict = {}
    for g in range(n_rooms):
        room_id = f"room-{g}"
        doc0 = am.change(am.init(f"{room_id}-origin"), lambda d: (
            d.__setitem__("t", Text("start")), d.__setitem__("m", {})))
        base_changes[room_id] = am.get_all_changes(doc0)
        svc.seed_doc(room_id,
                     am.apply_changes(am.init(f"server-{g}"),
                                      base_changes[room_id]))
        # small rooms have short histories; a lowered snapshot threshold
        # keeps the rejoin path exercising the cached-bundle bootstrap
        svc.room(room_id).hub.snapshot_min_changes = 8

    chaos = {"drop": float(rng.uniform(0.02, 0.10)),
             "dup": float(rng.uniform(0.0, 0.05)),
             "reorder": float(rng.uniform(0.02, 0.10)),
             "delay": float(rng.uniform(0.0, 0.10))}
    clients: dict = {}
    epoch: dict = {}          # tid -> rejoin epoch (fresh actor ids)

    def wire(tid: str, room_id: str, empty: bool = False):
        e = epoch.get(tid, 0)
        clients[tid] = _SvcClient(
            am, svc, tid, room_id, base_changes[room_id],
            actor=f"c-{tid}-e{e}",
            link_seed=seed * 104729 + int(tid.split("-")[-1]) * 13 + e * 7,
            chaos=chaos, empty=empty)

    for i in range(n_clients):
        wire(f"{seed}-{i}", f"room-{i % n_rooms}")

    ids = list(clients)
    n_slow = max(1, n_clients // 12)
    for tid in rng.choice(ids, n_slow, replace=False):
        clients[str(tid)].slow = 4
    # partitions: a window per victim inside the main loop
    n_part = max(1, n_clients // 12)
    part_victims = [str(t) for t in rng.choice(ids, n_part, replace=False)]
    part_at = {t: int(rng.integers(3, max(4, n_ticks - 10)))
               for t in part_victims}
    part_len = {t: int(rng.integers(3, 9)) for t in part_victims}
    # kills (never the last live member of a room) + later rejoins
    n_kill = max(1, n_clients // 16)
    kill_order = [str(t) for t in rng.choice(ids, n_kill, replace=False)]
    kill_at = {t: int(rng.integers(6, max(7, n_ticks - 4)))
               for t in kill_order}
    rejoiners = set(kill_order[: len(kill_order) // 2])
    rejoin_at = {t: kill_at[t] + int(rng.integers(4, 10))
                 for t in rejoiners}
    killed: set = set()
    n_kills_done = 0
    n_rejoins_done = 0

    def live_room_members(room_id):
        return [c for c in clients.values()
                if c.room_id == room_id and c.alive]

    def pump_all(tick_no: int):
        for c in clients.values():
            if c.alive and tick_no % c.slow == 0:
                c.pump()
        svc.tick()

    for t in range(n_ticks):
        for tid in part_victims:
            c = clients[tid]
            if t == part_at[tid] and c.alive:
                c.c2s.partition()
                c.s2c.partition()
            if t == part_at[tid] + part_len[tid]:
                c.c2s.heal()
                c.s2c.heal()
        for tid, at in kill_at.items():
            c = clients[tid]
            if t == at and c.alive and len(live_room_members(c.room_id)) > 1:
                c.alive = False          # vanishes; no goodbye
                killed.add(tid)
                n_kills_done += 1
        for tid, at in rejoin_at.items():
            if t == at and tid in killed:
                killed.discard(tid)
                epoch[tid] = epoch.get(tid, 0) + 1
                n_rejoins_done += 1
                # fresh everything, EMPTY doc-set: the server must
                # bootstrap the rejoiner (snapshot cache / plain changes)
                wire(tid, clients[tid].room_id, empty=True)
        # edits: a random slice of live clients each tick
        n_edit = max(1, n_clients // 20)
        for tid in rng.choice(ids, n_edit, replace=False):
            c = clients[str(tid)]
            if not c.alive:
                continue
            doc = c.ds.get_doc(c.room_id)
            if doc is None:
                continue    # a rejoiner still waiting on its bootstrap
            if int(rng.integers(0, 3)) == 0:
                doc = _text_edit(am, doc, rng)
            else:
                k = KEYS[int(rng.integers(0, len(KEYS)))]
                v = int(rng.integers(0, 999))
                doc = am.change(doc, lambda d, k=k, v=v:
                                d["m"].__setitem__(k, v))
            c.ds.set_doc(c.room_id, doc)
        pump_all(t)

    # ---- drain: heal everything, then run lossless until quiescent ----
    for c in clients.values():
        c.heal()
    # rooms holding killed-but-unowed tenants get one server-side edit so
    # the hub OWES the dead peer frames — the heartbeat ladder needs an
    # outstanding debt to escalate on (an idle peer is not a dead peer)
    for tid in killed:
        room_id = clients[tid].room_id
        room = svc.room(room_id)
        doc = room.doc_set.get_doc(room_id)
        if doc is not None:
            room.doc_set.set_doc(room_id, am.change(
                doc, lambda d: d["m"].__setitem__("_drain", 1)))
    n_orphan_rejoins = 0
    for q in range(quiesce_ticks):
        # a slow/partitioned-but-live client is server-side
        # indistinguishable from a vanished one, so the health ladder may
        # evict it (a legitimate per-tenant degradation). Its recovery
        # path is the client keepalive noticing the dead session and
        # REJOINING fresh — eviction is degradation, never loss
        for tid, c in list(clients.items()):
            if c.alive and svc.session(tid) is None:
                epoch[tid] = epoch.get(tid, 0) + 1
                n_orphan_rejoins += 1
                wire(tid, c.room_id, empty=True)
        pump_all(q)
        if svc.idle() \
                and all(c.idle() for c in clients.values() if c.alive) \
                and all(svc.session(tid) is None for tid in killed):
            break
    else:
        raise AssertionError(
            f"service seed {seed}: never quiesced "
            f"(unevicted={[t for t in killed if svc.session(t)]}, "
            f"metrics={svc.metrics()})")

    # ---- the acceptance asserts ----
    svc.probe_lag()                 # a fresh lag table for m + assert 5
    m = svc.metrics()
    LAST_SERVICE_METRICS.clear()
    LAST_SERVICE_METRICS.update(m, n_clients=n_clients, n_rooms=n_rooms,
                                killed=n_kills_done,
                                rejoined=n_rejoins_done,
                                orphan_rejoins=n_orphan_rejoins,
                                # the rolling-telemetry view of the tick
                                # tail (log-bucket conservative bound)
                                tick_p99_ms_telemetry=(
                                    svc.tick_p99_ms_telemetry()))
    # 1. byte-identical convergence of every survivor with its room
    for g in range(n_rooms):
        room_id = f"room-{g}"
        server_doc = svc.room(room_id).doc_set.get_doc(room_id)
        members = live_room_members(room_id)
        if server_doc is None:
            assert not members, f"room {room_id} lost its server replica"
            continue
        docs = [server_doc] + [c.ds.get_doc(room_id) for c in members]
        ok, diff = _converged(am, docs)
        assert ok, f"service seed {seed} room {room_id} diverged: {diff}"
        hists = [sorted(_json.dumps(ch, sort_keys=True)
                        for ch in am.get_all_changes(d)) for d in docs]
        assert hists.count(hists[0]) == len(hists), \
            f"service seed {seed} room {room_id}: histories diverged"
    # 2. bounded memory, and nothing left parked
    assert m["peak_inbox"] <= cfg.default_budget.inbox_cap \
        + cfg.recv_window, m
    assert m["peak_recv_buf"] <= cfg.recv_window, m
    assert m["peak_parked"] <= cfg.quarantine_global_capacity, m
    for g in range(n_rooms):
        gate = svc.room(f"room-{g}").gate
        assert gate._n_parked == 0, \
            f"service seed {seed}: room-{g} quarantine not drained"
    for c in clients.values():
        if c.alive:
            assert len(c.chan._recv_buf) <= 1024   # client RECV_WINDOW
    # 3. no tenant starves
    assert m["max_starved_streak"] <= 2 * cfg.starvation_boost_ticks, m
    # 4. dead-peer state fully reclaimed
    for tid in killed:
        assert svc.reclaimed(tid), \
            f"service seed {seed}: tenant {tid} not reclaimed after " \
            f"eviction"
    # every kill ends in exactly one eviction (health-ladder eviction for
    # the vanished, or the rejoin path evicting the stale session first)
    assert m["evictions"] >= n_kills_done, m
    # 5. the telemetry tier agrees convergence is done: zero replication
    #    lag — matrix deficit AND un-acked wire frames — for every live
    #    tenant (a quiesced mesh with nonzero lag would mean the probes
    #    measure something other than what convergence asserts)
    lag = svc.replication_lag()
    laggards = {t: v for t, v in lag.items() if v["ops"]}
    assert not laggards, \
        f"service seed {seed}: replication lag nonzero at quiescence: " \
        f"{dict(list(laggards.items())[:5])}"
    assert m["max_lag_ops"] == 0 and m["max_lag_ticks"] == 0, m
    # 6. lineage acceptance (ISSUE 14, when AMTPU_LINEAGE_RATE enabled
    #    sampling): >= 99% of sampled changes the server committed show
    #    a COMPLETE origin->visibility hop chain on every surviving
    #    replica of their room at quiescence, and the worst quarantine/
    #    defer dwell folds into the summary line
    _lineage_acceptance(svc, clients, seed)


def _lineage_acceptance(svc, clients, seed):
    from automerge_tpu.obs import lineage as lin
    led = lin.ledger()
    if led is None or not lin.ENABLED:
        return
    live_by_room: dict = {}
    for tid, c in clients.items():
        if c.alive and svc.session(tid) is not None:
            live_by_room.setdefault(c.room_id, set()).add(tid)
    total = complete = 0
    incomplete_sample = []
    for ch in led.chains():
        vis = led.visible_sites(ch)
        for room_id in {d for d in ch["docs"]
                        if isinstance(d, str) and d in svc._rooms}:
            server_site = f"svc:{room_id}"
            if server_site not in vis:
                # never committed at the authority over the wire: either
                # pre-seeded history (every replica was born with it) or
                # a dead client's change no survivor holds — out of the
                # per-replica completeness population either way
                continue
            origin = ch["origin_site"] or ""
            # map the origin actor back to its replica: soak client
            # actors are f"c-{tid}-e{epoch}"; everything else (seed
            # docs, server drain edits) originates at the server
            if origin.startswith("c-") and "-e" in origin:
                origin_replica = origin[2:].rsplit("-e", 1)[0]
            else:
                origin_replica = server_site
            expected = {server_site} | live_by_room.get(room_id, set())
            expected.discard(origin_replica)
            total += 1
            if ch["origin_ns"] is not None and expected <= vis:
                complete += 1
            elif len(incomplete_sample) < 5:
                incomplete_sample.append(
                    (ch["actor"], ch["seq"], sorted(expected - vis),
                     [h[0] for h in ch["hops"]]))
    ratio = complete / total if total else 1.0
    LAST_SERVICE_METRICS.update(
        lineage_rate=led.rate,
        lineage_sampled_chains=led.n_chains,
        lineage_commit_population=total,
        lineage_complete_ratio=round(ratio, 4),
        lineage_hops_per_chain=round(
            led.stats["hops_recorded"] / max(1, led.stats[
                "chains_started"]), 2),
        lineage_max_quarantine_dwell_ms=led.max_dwell_ms("quar/park"),
        lineage_max_defer_dwell_ms=led.max_dwell_ms("svc/defer"),
        lineage_visibility_p99_ms=led.visibility_ms(0.99))
    assert total > 0, \
        f"service seed {seed}: lineage sampling enabled but no sampled " \
        f"chain committed at any server replica (rate {led.rate} too " \
        f"selective for this population?)"
    assert ratio >= 0.99, (
        f"service seed {seed}: only {ratio:.2%} of sampled changes have "
        f"a complete origin->visibility chain on every surviving "
        f"replica; first incomplete: {incomplete_sample}")


def _sharded_stream(seed: int, n_docs: int, n_actors: int, n_seqs: int,
                    hot_doc: str, hot_factor: int, n_chunks: int):
    """Deterministic chaotic delivery schedule for one sharded session:
    per-doc causally-chained change lists (every seq depends on every
    actor's previous seq), fully shuffled across docs and seqs (so
    causally-premature arrivals are guaranteed and park in the router
    quarantine), with ~10% duplicated deliveries, chunked into
    `n_chunks` serving rounds. Same seed -> byte-identical schedule,
    whatever the shard count."""
    rng = np.random.default_rng(seed * 7919 + 17)
    docs = [f"sdoc-{seed}-{i}" for i in range(n_docs)]
    flat = []
    for di, doc in enumerate(docs):
        seqs = n_seqs * (hot_factor if doc == hot_doc else 1)
        for s in range(1, seqs + 1):
            for a in range(n_actors):
                actor, run = f"w{a}", 4
                base = (s - 1) * run + 1
                key = "_head" if s == 1 else f"{actor}:{base - 1}"
                ops = []
                for k in range(run):
                    ctr = base + k
                    ops.append({"action": "ins", "obj": doc, "key": key,
                                "elem": ctr})
                    ops.append({"action": "set", "obj": doc,
                                "key": f"{actor}:{ctr}",
                                "value": chr(97 + (ctr + a + di) % 26)})
                    key = f"{actor}:{ctr}"
                deps = {} if s == 1 else \
                    {f"w{b}": s - 1 for b in range(n_actors) if b != a}
                flat.append((doc, {"actor": actor, "seq": s,
                                   "deps": deps, "ops": ops}))
    rng.shuffle(flat)
    for i in rng.choice(len(flat), max(1, len(flat) // 10),
                        replace=False):
        flat.insert(int(rng.integers(0, len(flat))), flat[int(i)])
    per = max(1, -(-len(flat) // n_chunks))
    rounds = []
    for c in range(0, len(flat), per):
        chunk: dict = {}
        for doc, ch in flat[c: c + per]:
            chunk.setdefault(doc, []).append(ch)
        rounds.append(chunk)
    return docs, rounds


def session_sharded(seed: int, n_docs: int = 8, n_actors: int = 2,
                    n_seqs: int = 4, shard_counts=(1, 8)) -> None:
    """Shard-count invariance under chaotic delivery (ISSUE 10): the
    SAME seeded change stream — full cross-doc shuffle (premature
    arrivals park in the router quarantine), duplicated deliveries, and
    a telemetry-triggered hot-doc migration mid-stream on the
    multi-shard mesh — served at every shard count in `shard_counts`
    must converge to byte-identical state: per-doc checkpoint-bundle
    bytes (automerge_tpu.checkpoint.capture_engine — tables, clocks,
    dep closures, conflicts) AND rendered texts equal across meshes,
    with every quarantine drained. On meshes with >= 2 shards the
    rebalance policy must have actually moved the hot doc (the
    acceptance bar's "at least one telemetry-triggered migration
    mid-stream"); single-shard runs prove the same stream without any
    migration, so the comparison also pins migration neutrality."""
    from automerge_tpu.shard import ShardedDocSet
    from automerge_tpu.shard.parallel import parallel_lanes_enabled
    from automerge_tpu.shard.placement import hash_shard

    # hot doc: hammered `hot_factor` harder than the rest, chosen (from
    # ids alone, so every mesh sees the same stream) to share its
    # max-shard-count lane with another doc — migrating it away must
    # actually relieve a co-tenant
    max_shards = max(shard_counts)
    ids = [f"sdoc-{seed}-{i}" for i in range(n_docs)]
    homes = [hash_shard(d, max_shards) for d in ids]
    hot_doc = ids[0]
    for i, d in enumerate(ids):
        if homes.count(homes[i]) >= 2:
            hot_doc = d
            break
    results = {}
    exec_stats = {}
    for n_shards in shard_counts:
        docs, rounds = _sharded_stream(seed, n_docs, n_actors, n_seqs,
                                       hot_doc, hot_factor=4,
                                       n_chunks=6)
        mesh = ShardedDocSet(n_shards=n_shards, capacity=64)
        if n_shards >= 2:
            mesh.attach_rebalancer(ratio=2.0, min_ops=64, cooldown=2)
        # deliver_rounds (not a deliver_round loop): the multi-shard leg
        # runs the INTERNALS §24 parallel tier — per-lane workers + the
        # round-pipelining pre-decode seam — so the byte-identity
        # comparison below also pins parallel-vs-sequential parity (the
        # 1-shard leg stays the sequential comparator by default)
        mesh.deliver_rounds(rounds)
        ex = mesh._executor
        if parallel_lanes_enabled(n_shards):
            assert ex is not None, \
                f"sharded seed {seed} ({n_shards} shards): parallel " \
                "lanes enabled but no executor engaged"
        if ex is not None:
            assert ex.stats["barriers"] > 0 and ex.stats["errors"] == 0 \
                and ex.stats["submitted"] == ex.stats["completed"], \
                f"sharded seed {seed} ({n_shards} shards): lane workers " \
                f"attached but never engaged cleanly ({ex.stats})"
            exec_stats[n_shards] = dict(ex.stats)
        mesh.close()
        for doc in docs:
            assert mesh.quarantined(doc) == 0, \
                f"sharded seed {seed} ({n_shards} shards): quarantine " \
                f"not drained for {doc}"
        if n_shards >= 2:
            assert mesh.stats["migrations"] >= 1, \
                f"sharded seed {seed}: no telemetry-triggered migration " \
                f"on the {n_shards}-shard mesh ({mesh.stats}, loads " \
                f"{mesh.rebalancer.window_loads()})"
        results[n_shards] = (
            {doc: mesh.capture(doc) for doc in docs}, mesh.texts(),
            dict(mesh.stats))
    ref_shards = shard_counts[0]
    bundles0, texts0, _ = results[ref_shards]
    for n_shards, (bundles, texts, _stats) in results.items():
        assert texts == texts0, \
            f"sharded seed {seed}: texts diverged at {n_shards} vs " \
            f"{ref_shards} shards"
        for doc in bundles0:
            assert bundles[doc] == bundles0[doc], \
                f"sharded seed {seed}: checkpoint bytes of {doc} " \
                f"diverged at {n_shards} vs {ref_shards} shards"
    multi = max(shard_counts)
    PROFILE_METRICS["sharded"].clear()
    PROFILE_METRICS["sharded"].update(
        shard_counts=list(shard_counts), n_docs=n_docs,
        hot_doc=hot_doc, **{f"stats_{n}_shards": results[n][2]
                            for n in shard_counts},
        migrations=results[multi][2]["migrations"],
        parked=results[multi][2]["parked"],
        released=results[multi][2]["released"],
        lane_executor={str(n): st for n, st in exec_stats.items()})


def session_residency(seed: int, n_docs: int = 40, n_seqs: int = 4,
                      budget_docs: int = 4) -> None:
    """Bounded-HBM serving (ISSUE 18 acceptance run: ``--residency``):
    a doc population >= 10x the device byte budget served through the
    residency tier. Two legs, same seeded stream — interleaved per-doc
    touches with occasional one-seq-early arrivals (premature parks
    exercise the admission-aware prefetch) and ~10% dup redeliveries:

    1. a REFERENCE mesh with no residency manager (everything stays
       device-resident) establishes the expected captures/texts and the
       measured per-doc footprint the budget derives from;
    2. a budgeted mesh with a disk spill dir serves the identical
       stream; after EVERY round the doc-kind peak footprint gauge must
       be <= the budget (the reservation discipline's absolute bar).

    Convergence is compared doc-at-a-time — the reads themselves demand
    page under the same budget — and the final accounting must name
    every doc in exactly one tier with nothing lost."""
    import tempfile

    from automerge_tpu.obs import device_truth as dtruth
    from automerge_tpu.shard import ShardedDocSet

    rng = np.random.default_rng(seed * 6133 + 11)
    docs = [f"rdoc-{seed}-{i}" for i in range(n_docs)]
    streams = {}
    for di, doc in enumerate(docs):
        actor, run_len = f"r{di}", 3
        chs = []
        for s in range(1, n_seqs + 1):
            base = (s - 1) * run_len + 1
            key = "_head" if s == 1 else f"{actor}:{base - 1}"
            ops = []
            for k in range(run_len):
                ctr = base + k
                ops.append({"action": "ins", "obj": doc, "key": key,
                            "elem": ctr})
                ops.append({"action": "set", "obj": doc,
                            "key": f"{actor}:{ctr}",
                            "value": chr(97 + (ctr + di) % 26)})
                key = f"{actor}:{ctr}"
            chs.append({"actor": actor, "seq": s, "deps": {}, "ops": ops})
        streams[doc] = chs
    # the round schedule: two docs per round (the budget must hold one
    # round's working set — that is the invariant's own precondition),
    # each touch advancing its doc one seq; ~20% of touches send the
    # NEXT seq one touch early (premature -> router park -> prefetch
    # hint), the held-back seq follows on the doc's next touch
    pos = {d: 0 for d in docs}
    skipped: dict = {}
    rounds = []
    while True:
        pool = [d for d in docs if pos[d] < n_seqs or d in skipped]
        if not pool:
            break
        chunk = {}
        for i in rng.choice(len(pool), size=min(2, len(pool)),
                            replace=False):
            d = pool[int(i)]
            if d in skipped:
                out = [streams[d][skipped.pop(d)]]
            elif pos[d] + 1 < n_seqs and rng.random() < 0.2:
                skipped[d] = pos[d]
                out = [streams[d][pos[d] + 1]]
                pos[d] += 2
            else:
                out = [streams[d][pos[d]]]
                pos[d] += 1
            if rng.random() < 0.1:
                out = out + [out[0]]            # dup redelivery
            chunk[d] = out
        rounds.append(chunk)

    # leg 1: the unbounded reference (no residency manager attached)
    ref = ShardedDocSet(n_shards=2, capacity=64)
    for chunk in rounds:
        ref.deliver_round(chunk)
    ref_caps = {d: ref.capture(d) for d in docs}
    ref_texts = ref.texts()
    per_doc = max(doc.device_footprint()["device_bytes"]
                  for lane in ref.lanes for doc in lane.docs.values())
    budget = budget_docs * per_doc
    assert n_docs * per_doc >= 10 * budget, \
        f"residency seed {seed}: population only " \
        f"{n_docs * per_doc / budget:.1f}x the budget"

    # leg 2: the budgeted mesh — fresh gauge session, disk spill tier
    dtruth.REGISTRY.clear_session()
    with tempfile.TemporaryDirectory() as spill:
        mesh = ShardedDocSet(n_shards=2, capacity=64)
        res = mesh.attach_residency(budget_bytes=budget, spill_dir=spill,
                                    cold_after=4)
        for n, chunk in enumerate(rounds):
            mesh.deliver_round(chunk)
            peak = dtruth.REGISTRY.footprint()["peak_device_bytes"]
            assert peak <= budget, \
                f"residency seed {seed}: round {n} peak {peak} > " \
                f"budget {budget}"
        for d in docs:
            assert mesh.quarantined(d) == 0, \
                f"residency seed {seed}: quarantine not drained for {d}"
        acct = res.accounting()
        population = sorted(acct["hot"] + acct["warm"] + acct["cold"])
        assert population == sorted(docs), \
            f"residency seed {seed}: tier accounting lost docs"
        m = res.metrics()
        assert m["budget_overruns"] == 0, \
            f"residency seed {seed}: {m['budget_overruns']} budget " \
            f"overruns (working set exceeded the budget)"
        assert m["page_outs"] > 0 and m["page_ins"] > 0
        assert m["prefetches"] > 0, \
            f"residency seed {seed}: premature arrivals never " \
            f"prefetched a demoted doc ({m})"
        assert m["cold_ages"] > 0, \
            f"residency seed {seed}: the disk tier never engaged ({m})"
        # doc-at-a-time convergence: the reads page under the budget
        texts = {}
        for d in docs:
            assert mesh.capture(d) == ref_caps[d], \
                f"residency seed {seed}: capture of {d} diverged " \
                f"after paging churn"
            res.ensure_resident(d)
            lane = mesh.lane_of(d)
            with lane.device_ctx():
                texts[d] = lane.docs[d].text()
        assert texts == ref_texts, \
            f"residency seed {seed}: texts diverged after paging churn"
        peak = dtruth.REGISTRY.footprint()["peak_device_bytes"]
        assert peak <= budget, \
            f"residency seed {seed}: paged reads breached the budget " \
            f"({peak} > {budget})"
        final = res.metrics()
    PROFILE_METRICS["residency"].clear()
    PROFILE_METRICS["residency"].update(
        n_docs=n_docs, budget_bytes=budget, per_doc_bytes=per_doc,
        population_over_budget=round(n_docs * per_doc / budget, 1),
        peak_resident_bytes=final["peak_resident_bytes"],
        gauge_peak_bytes=peak, hit_rate=final["hit_rate"],
        page_in_p99_ms=final["page_in_p99_ms"],
        page_ins=final["page_ins"], page_outs=final["page_outs"],
        prefetches=final["prefetches"], cold_ages=final["cold_ages"],
        cold_loads=final["cold_loads"],
        budget_overruns=final["budget_overruns"])


def session_federation(seed: int, n_rooms: int = 6,
                       n_sessions: int = 1000, n_ticks: int = 80,
                       quiesce_rounds: int = 6000) -> None:
    """Three federated regions over WAN chaos (ISSUE 16 acceptance run:
    ``--federation``): `n_sessions` write sessions land across the
    fabric while region pairs partition and heal and one whole region
    is KILLED mid-run and REJOINS empty (snapshot-bootstrapped by the
    survivors through the probe/hello reconnect handshake).

    Asserted at the end:
      1. every room converges byte-identically on all three regions —
         canonical saves (history replayed in deterministic order under
         one probe actor) AND sorted change histories;
      2. zero residual cross-region lag (pending group-token envelopes
         + partition-buffered payloads) on every link, every link back
         on the ``ok`` rung;
      3. full reclamation: no parked quarantine changes, no partition
         buffers, no channel reorder state anywhere in the fabric.

    Any failure writes a federation postmortem (every region's
    ``describe()``, federation block included) before re-raising."""
    am = _am()
    import json as _json

    from automerge_tpu import Text
    from automerge_tpu.federation import (
        FederatedRegion, RegionPlacement, connect_regions,
    )
    from automerge_tpu.service import ServiceConfig, SyncService

    rng = np.random.default_rng(seed)
    names = ["us", "eu", "ap"]
    placement = RegionPlacement(names)

    def mk_region(name):
        return FederatedRegion(
            SyncService(ServiceConfig(region=name)), name,
            placement=placement, probe_every=2, max_buffer=256,
            max_retries=4)

    regions = {n: mk_region(n) for n in names}
    chaos = {}
    s = seed * 7919 + 1
    for i in range(len(names)):
        for j in range(i + 1, len(names)):
            a, b = names[i], names[j]
            _, _, fwd, rev = connect_regions(
                regions[a], regions[b], profile="cross_region", seed=s)
            chaos[(a, b)] = (fwd, rev)      # fwd: a -> b, rev: b -> a
            s += 10

    room_ids = [f"room-{g}" for g in range(n_rooms)]
    for room_id in room_ids:
        doc0 = am.change(am.init(f"{room_id}-origin"), lambda d: (
            d.__setitem__("t", Text("start")), d.__setitem__("m", {})))
        base = am.get_all_changes(doc0)
        for r in regions.values():
            r.svc.seed_doc(room_id, am.apply_changes(
                am.init(f"srv-{r.name}-{room_id}"), base))
            # short histories at soak scale: a lowered threshold keeps
            # the rejoined region exercising the snapshot bootstrap
            r.svc.room(room_id).hub.snapshot_min_changes = 8

    def pump_all(rounds=1):
        for _ in range(rounds):
            for r in regions.values():
                r.pump()
                r.svc.tick()

    def edit(region_name, room_id):
        ds = regions[region_name].svc.room(room_id).doc_set
        doc = ds.get_doc(room_id)
        if doc is None:
            return False     # a rejoined region still bootstrapping
        if int(rng.integers(0, 3)) == 0:
            doc = _text_edit(am, doc, rng)
        else:
            k = KEYS[int(rng.integers(0, len(KEYS)))]
            doc = am.change(doc, lambda d, k=k,
                            v=int(rng.integers(0, 999)):
                            d["m"].__setitem__(k, v))
        ds.set_doc(room_id, doc)
        return True

    # fault schedule: two pair-partition windows + one region kill
    cut_a = ("us", "eu")
    cut_a_at, cut_a_len = n_ticks // 5, max(4, n_ticks // 6)
    cut_b = ("eu", "ap")
    cut_b_at, cut_b_len = (2 * n_ticks) // 3, max(4, n_ticks // 8)
    kill_name = "ap"
    kill_at = n_ticks // 2
    rejoin_at = kill_at + max(4, n_ticks // 8)
    killed = False
    n_writes = 0
    n_skipped = 0
    per_tick = max(1, n_sessions // n_ticks)

    def kill_edges(name):
        """A vanished region: its WAN edges go dark in BOTH directions
        (frames die in flight; survivors' channels hit the retransmit
        cap and walk the ladder to `partitioned`)."""
        for (a, b), (f, r) in chaos.items():
            if name in (a, b):
                f.partition()
                r.partition()

    def rejoin_region(name):
        """A fresh, EMPTY region under the old name: new service, new
        links, the same chaos edges rewired and healed — the survivors'
        probe loop finds it and the hello handshake bootstraps it."""
        fresh = mk_region(name)
        ls = seed * 104729 + 17
        for (a, b), (f, r) in chaos.items():
            if b == name:     # fwd a->b delivers to name's link
                ln = fresh.link_to(a, seed=ls)
                f._deliver = ln.on_raw
                ln.attach_transport(r)
            elif a == name:   # rev b->a delivers to name's link
                ln = fresh.link_to(b, seed=ls)
                r._deliver = ln.on_raw
                ln.attach_transport(f)
            else:
                continue
            ls += 3
            f.heal()
            r.heal()
        regions[name] = fresh

    try:
        for t in range(n_ticks):
            if t == cut_a_at:
                f, r = chaos[cut_a]
                f.partition()
                r.partition()
            if t == cut_a_at + cut_a_len and not killed:
                f, r = chaos[cut_a]
                f.heal()
                r.heal()
            if t == cut_b_at:
                f, r = chaos[cut_b]
                f.partition()
                r.partition()
            if t == cut_b_at + cut_b_len:
                f, r = chaos[cut_b]
                f.heal()
                r.heal()
            if t == kill_at:
                killed = True
                regions.pop(kill_name)
                kill_edges(kill_name)
            if t == rejoin_at:
                killed = False
                rejoin_region(kill_name)
            for _ in range(per_tick):
                room_id = room_ids[int(rng.integers(0, n_rooms))]
                # placement decides the normal write home; any region
                # accepts writes (rung one: local-writes-always-accepted)
                if int(rng.integers(0, 5)) == 0:
                    target = list(regions)[int(rng.integers(0,
                                                            len(regions)))]
                else:
                    target = placement.home(room_id)
                    if target not in regions:   # its home is the corpse
                        target = next(iter(regions))
                if edit(target, room_id):
                    n_writes += 1
                else:
                    n_skipped += 1
            pump_all()

        # ---- heal everything, then drain until the fabric is idle ----
        if killed:
            rejoin_region(kill_name)
        for f, r in chaos.values():
            f.heal()
            r.heal()
        for q in range(quiesce_rounds):
            pump_all()
            if q > 5 and all(r.idle() for r in regions.values()):
                break
        else:
            raise AssertionError(
                f"federation seed {seed}: never quiesced: "
                f"{ {n: r.lag_table() for n, r in regions.items()} }")

        # 1. byte-identical convergence: canonical saves AND histories
        for room_id in room_ids:
            docs = {n: r.svc.room(room_id).doc_set.get_doc(room_id)
                    for n, r in regions.items()}
            assert all(d is not None for d in docs.values()), \
                f"federation seed {seed} {room_id}: missing replica in " \
                f"{ {n: d is None for n, d in docs.items()} }"
            saves = {}
            hists = {}
            for n, d in docs.items():
                chs = sorted(am.get_all_changes(d),
                             key=lambda c: (c["actor"], c["seq"]))
                saves[n] = am.save(am.apply_changes(
                    am.init("canon-probe"), chs))
                hists[n] = sorted(_json.dumps(c, sort_keys=True)
                                  for c in chs)
            assert len(set(saves.values())) == 1, \
                f"federation seed {seed} {room_id}: saves diverged " \
                f"{ {n: len(sv) for n, sv in saves.items()} }"
            ref = next(iter(hists.values()))
            assert all(h == ref for h in hists.values()), \
                f"federation seed {seed} {room_id}: histories diverged"
        # 2. zero residual cross-region lag, every link healthy
        residual = {(n, peer): entry
                    for n, r in regions.items()
                    for peer, entry in r.lag_table().items()
                    if entry["lag_tokens"] or entry["state"] != "ok"}
        assert not residual, \
            f"federation seed {seed}: residual lag at quiescence: " \
            f"{residual}"
        # 3. full reclamation: no parked changes, no partition buffers,
        #    no channel reorder state anywhere
        for n, r in regions.items():
            for room_id in room_ids:
                gate = r.svc.room(room_id).gate
                assert gate._n_parked == 0, \
                    f"federation seed {seed}: {n}/{room_id} quarantine " \
                    f"not drained"
            for peer, link in r.links.items():
                assert not link._buf_adverts and not link._buf_data, \
                    f"federation seed {seed}: {n}->{peer} partition " \
                    f"buffer not drained"
                assert not link.chan._recv_buf, \
                    f"federation seed {seed}: {n}->{peer} reorder " \
                    f"buffer not drained"
    except Exception:
        path = os.environ.get("AMTPU_POSTMORTEM_OUT",
                              "federation_postmortem.json")
        try:
            with open(path, "w") as fh:
                _json.dump({n: r.svc.describe()
                            for n, r in regions.items()}, fh, indent=1)
            print(f"soak: federation postmortem written to {path}",
                  file=sys.stderr, flush=True)
        except Exception as dump_exc:   # noqa: BLE001 — never mask
            print(f"soak: postmortem dump failed: {dump_exc!r}",
                  file=sys.stderr, flush=True)
        raise

    links = [(n, peer, link) for n, r in regions.items()
             for peer, link in r.links.items()]
    PROFILE_METRICS["federation"].clear()
    PROFILE_METRICS["federation"].update(
        regions=len(regions), rooms=n_rooms, writes=n_writes,
        writes_skipped_bootstrapping=n_skipped,
        region_kills=1, residual_lag_tokens=0,
        reconnects=sum(ln.stats["reconnects"] for _, _, ln in links),
        channel_revives=sum(ln.chan.stats["revives"]
                            for _, _, ln in links),
        buffer_dropped=sum(ln.stats["buffer_dropped"]
                           for _, _, ln in links),
        shipped=sum(ln.stats["shipped"] for _, _, ln in links),
        delivered=sum(ln.stats["delivered"] for _, _, ln in links),
        group_tokens_minted=sum(r.clock.stats["minted"]
                                for r in regions.values()),
        group_tokens_observed=sum(r.clock.stats["observed"]
                                  for r in regions.values()),
        ladder_transitions={
            k: sum(ln.transitions.get(k, 0) for _, _, ln in links)
            for k in sorted({t for _, _, ln in links
                             for t in ln.transitions})})


PROFILES = {"general": session_general, "conflict": session_conflict,
            "lossy": session_lossy, "table": session_table,
            "chaos": session_chaos, "checkpoint": session_checkpoint,
            "service": session_service, "sharded": session_sharded,
            "residency": session_residency,
            "federation": session_federation}


def run(profile: str, sessions: int, seed_base: int,
        trace: bool = False, clients: int = None,
        scrape: bool = False, quick: bool = False) -> int:
    import json

    from automerge_tpu import obs

    global SCRAPE
    SCRAPE = scrape
    failures = []
    t0 = time.perf_counter()
    names = list(PROFILES) if profile == "all" else [profile]
    profiles = dict(PROFILES)
    if clients is not None:
        # the service profile at an explicit scale (--service --clients N):
        # tick count grows mildly with scale so churn/partition windows
        # stay proportionate
        profiles["service"] = lambda seed: session_service(
            seed, n_clients=clients, n_ticks=40 if clients >= 500 else 30)
    if quick:
        # the CI smoke scale: same scenario shape (partitions + region
        # kill/rejoin), an order of magnitude fewer write sessions
        profiles["federation"] = lambda seed: session_federation(
            seed, n_rooms=3, n_sessions=150, n_ticks=40)
        # same tier ladder + 10x-over-budget ratio, half the population
        profiles["residency"] = lambda seed: session_residency(
            seed, n_docs=20, n_seqs=3, budget_docs=2)
    # the soak ALWAYS records (counters are exact across ring
    # wraparound, so the summary is right even for long campaigns); the
    # --trace flag only controls whether the ring is also exported
    with obs.tracing():
        # the summary reports THIS campaign's event delta: the recorder
        # may outlive run() (a second campaign in-process, earlier traced
        # tests), and counters are lifetime totals by design
        ev0 = obs.metrics_snapshot()["counters"]
        n0 = obs.metrics_snapshot()["emitted"]
        for name in names:
            fn = profiles[name]
            for s in range(sessions):
                seed = seed_base + s
                try:
                    fn(seed)
                except Exception as exc:  # noqa: BLE001 — record + continue
                    failures.append((name, seed, repr(exc)))
                    print(f"FAIL {name} seed {seed}: {exc!r}", flush=True)
        dt = time.perf_counter() - t0
        total = len(names) * sessions
        print(f"soak: {total - len(failures)}/{total} sessions converged "
              f"({dt:.1f}s)", flush=True)
        for name, seed, exc in failures:
            print(f"  reproduce: python scripts/soak.py --profile {name} "
                  f"--sessions 1 --seed-base {seed}")
        snap = obs.metrics_snapshot()
        events = {k: v - ev0.get(k, 0) for k, v in snap["counters"].items()
                  if v - ev0.get(k, 0) > 0}
        if trace:
            path = os.environ.get("AMTPU_TRACE_OUT", "soak_trace.json")
            obs.write_trace(path)
            print(f"soak: trace written to {path} "
                  "(load at https://ui.perfetto.dev)", file=sys.stderr)
    emit_summary(
        names, sessions, seed_base, total, failures, dt, events,
        obs_records={"emitted": snap["emitted"] - n0,
                     "retained": snap["retained"]},
        trace_path=path if trace else None)
    return 1 if failures else 0


def emit_summary(names, sessions, seed_base, total, failures, dt,
                 events, obs_records, trace_path=None):
    """THE one summary emitter: every campaign — whatever mix of
    profiles ran — ends with exactly ONE machine-readable JSON line
    (profile + SEEDS + event mix: the diagnosable-soak contract, ISSUE
    6; last line of stdout, pinned by tests/test_soak_smoke.py).
    Profiles contribute numbers by updating their PROFILE_METRICS entry
    in place — never by printing JSON themselves, so a new profile
    cannot regress the one-line artifact by copy-pasting emission
    logic."""
    import json

    summary = {
        "soak_profiles": names,
        "sessions_per_profile": sessions,
        "seed_base": seed_base,
        "converged": total - len(failures),
        "total": total,
        "elapsed_s": round(dt, 1),
        "failures": [{"profile": n, "seed": sd, "error": e}
                     for n, sd, e in failures],
        "events": events,
        "obs_records": obs_records,
        **{f"{name}_metrics": dict(PROFILE_METRICS[name])
           for name in names
           if PROFILE_METRICS.get(name)},
        **({"trace_path": trace_path} if trace_path else {}),
    }
    print(json.dumps(summary, sort_keys=True), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--profile", default="all",
                    choices=["all"] + list(PROFILES))
    ap.add_argument("--chaos", action="store_true",
                    help="shorthand for --profile chaos")
    ap.add_argument("--checkpoint", action="store_true",
                    help="shorthand for --profile checkpoint (snapshot "
                         "mid-chaos + restart one peer from its bundle)")
    ap.add_argument("--service", action="store_true",
                    help="shorthand for --profile service at scale "
                         "(--clients concurrent sessions, default 1000; "
                         "--sessions defaults to 1 seed)")
    ap.add_argument("--federation", action="store_true",
                    help="shorthand for --profile federation (3 regions "
                         "over WAN chaos with pair partitions and a "
                         "killed-and-rejoined region; byte-identical "
                         "survivor convergence + zero residual "
                         "cross-region lag; --sessions defaults to 1 "
                         "seed, --quick runs the CI smoke scale)")
    ap.add_argument("--sharded", action="store_true",
                    help="shorthand for --profile sharded (shard-count "
                         "invariance: the same seeded chaotic stream on "
                         "1 vs 8 shards must converge byte-identically, "
                         "with a telemetry-triggered hot-doc migration "
                         "mid-stream on the mesh; --sessions defaults "
                         "to 8 seeds)")
    ap.add_argument("--residency", action="store_true",
                    help="shorthand for --profile residency (bounded-HBM "
                         "serving: a doc population >= 10x the device "
                         "budget pages through the residency tier; the "
                         "peak footprint gauge must never exceed the "
                         "budget and every doc must converge "
                         "byte-identically with a no-residency "
                         "reference mesh; --sessions defaults to 4 "
                         "seeds, --quick halves the population)")
    ap.add_argument("--clients", type=int, default=None,
                    help="service profile: concurrent client sessions "
                         "(default 1000 with --service)")
    ap.add_argument("--quick", action="store_true",
                    help="service/federation profiles: the CI smoke "
                         "scale (100 clients / 150 write sessions)")
    ap.add_argument("--sessions", type=int, default=None)
    ap.add_argument("--seed-base", type=int, default=0)
    ap.add_argument("--trace", action="store_true",
                    help="dump the obs flight recorder as Chrome trace "
                         "JSON (Perfetto-loadable) after the campaign")
    ap.add_argument("--scrape", action="store_true",
                    help="service profile: serve the live Prometheus "
                         "scrape endpoint during the soak and validate "
                         "the exposition + /describe over real HTTP")
    args = ap.parse_args()
    profile = ("chaos" if args.chaos
               else "checkpoint" if args.checkpoint
               else "service" if args.service
               else "federation" if args.federation
               else "sharded" if args.sharded
               else "residency" if args.residency else args.profile)
    clients = args.clients
    if args.service and clients is None:
        clients = 100 if args.quick else 1000
    sessions = args.sessions
    if sessions is None:
        # one seed at service scale (a 1000-session scenario IS the
        # campaign); 8 for the sharded profile (each seed runs the full
        # stream at EVERY shard count); 30 everywhere else
        sessions = (1 if profile in ("service", "federation")
                    else 8 if profile == "sharded"
                    else 4 if profile == "residency" else 30)
    return run(profile, sessions, args.seed_base, trace=args.trace,
               clients=clients, scrape=args.scrape, quick=args.quick)


if __name__ == "__main__":
    sys.exit(main())

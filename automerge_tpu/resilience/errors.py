"""Typed rejection errors for the resilience layer.

The sync tier historically surfaced malformed wire input as whatever the
first broken dict access happened to raise (``KeyError`` on a missing
``docId``, ``TypeError`` on a non-dict message). Transport and application
layers cannot distinguish those accidents from programming bugs, so they
cannot quarantine a misbehaving peer without pattern-matching on internals.
Every validation failure now raises :class:`ProtocolError` instead.
"""

from __future__ import annotations


class ProtocolError(ValueError):
    """A malformed or schema-violating wire input was rejected.

    Raised by the validation layer (``resilience.validation``) before any
    document state is touched, and by the inbound gate when the backend
    rejects a delivery mid-application (after the backend's failure-atomic
    restore ran, so document state and clock are bit-identical to before
    the delivery).

    Subclasses ``ValueError`` so pre-existing callers that catch
    ``ValueError`` around apply paths keep working unchanged.
    """


class PeerDeadError(ProtocolError):
    """A peer exhausted its retransmit budget and was declared dead.

    Raised by :class:`~.channel.ResilientChannel` when one envelope has
    been retransmitted ``max_retries`` times without an ack (or surfaced
    through the channel's ``on_dead`` callback instead, when one is
    installed — the service tier's peer-health path). A dead channel
    stops retransmitting and drops its send window, so a vanished peer
    cannot pin memory or timer work forever; recovery is a NEW channel
    (peer reconnect / service rejoin), never resurrection of this one.
    """


class CheckpointError(ProtocolError):
    """A checkpoint bundle failed structural or integrity validation.

    Raised by the checkpoint codec (``automerge_tpu.checkpoint``) when a
    bundle is truncated, has a bad magic/format-version, or any per-array
    content hash mismatches — always BEFORE any restored state is handed
    out, so a consumer never sees a partially-restored document. Sync-layer
    consumers treat it like any other protocol violation: the snapshot
    bootstrap path falls back to full log replay
    (``DocSet.bootstrap_doc(fallback_changes=...)``, the hub's
    ``noSnapshot`` re-request).
    """

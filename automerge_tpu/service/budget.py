"""Bounded-everything configuration for the sync service tier.

Every resource the service holds per tenant is named here with an explicit
cap — admission work per tick (ops / bytes), queued-but-unadmitted messages
(the inbox, which the channel's credit gate enforces at the ack path), the
channel's reorder window and retransmit budget, and the per-room quarantine
bounds. There is deliberately no "unbounded" value: a missing bound is how
one hot tenant becomes a global outage (Okapi's fault model — degradation
must stay per-tenant).
"""

from __future__ import annotations

from ..resilience.quarantine import DEFAULT_CAPACITY


class TenantBudget:
    """Per-tenant, per-tick admission budget + queueing caps.

    - ``ops_per_tick`` / ``bytes_per_tick``: how much decoded sync work
      one tick admits for this tenant. The first queued message of a
      visited tenant always admits (an oversized message eats the tick,
      it cannot wedge the tenant forever); past that, over-budget
      messages stay queued — deferral, not loss.
    - ``inbox_cap``: credit for the channel's admit gate. Frames beyond
      it drop UN-acked, so the peer's retransmit backoff is the
      backpressure signal. Structural memory bound per tenant:
      ``inbox_cap`` delivered + ``recv_window`` reorder-buffered frames.
    - ``priority``: higher admits first inside a tick; under deadline
      pressure the LOWEST priorities shed (defer) first. The scheduler's
      aging boost still front-runs any starved tenant, so low priority
      bounds latency, it never means "never".
    """

    __slots__ = ("ops_per_tick", "bytes_per_tick", "inbox_cap", "priority")

    def __init__(self, ops_per_tick: int = 256,
                 bytes_per_tick: int = 64 * 1024,
                 inbox_cap: int = 32, priority: int = 0):
        if ops_per_tick < 1 or bytes_per_tick < 1 or inbox_cap < 1:
            raise ValueError("tenant budget caps must be >= 1 "
                             f"(got ops={ops_per_tick}, "
                             f"bytes={bytes_per_tick}, inbox={inbox_cap})")
        self.ops_per_tick = ops_per_tick
        self.bytes_per_tick = bytes_per_tick
        self.inbox_cap = inbox_cap
        self.priority = priority


class ServiceConfig:
    """Service-wide knobs (every per-tenant default lives in
    :class:`TenantBudget`; ``connect`` accepts per-tenant overrides).

    - ``tick_budget_ms``: soft deadline for one tick's admission phase;
      0 disables. When the deadline passes mid-tick, the unvisited tail
      (lowest priority last) is SHED for this tick: counted, evented
      (``svc/shed``), and retried next tick — overload degrades to
      added latency for the cheapest victims, never to collapse or loss.
    - ``heartbeat_ticks`` / ``suspect_grace_ticks``: the peer-health
      ladder. A tenant we are OWED acks by (frames in flight) that has
      sent nothing for ``heartbeat_ticks`` turns SUSPECT; after
      ``suspect_grace_ticks`` more of silence it is declared dead and
      evicted. Any inbound frame (even a bare ack) resets the clock; an
      idle tenant with nothing owed is never suspected.
    - ``max_retries`` (+ ``base_rto``/``max_rto``/``recv_window``):
      server-side channel knobs. The retransmit cap is the heartbeat's
      backstop — whichever fires first declares the peer dead.
    - ``quarantine_capacity`` / ``quarantine_global_capacity``: per-room
      inbound-gate bounds (per-doc and aggregate).
    - ``starvation_boost_ticks``: a tenant with backlog that admitted
      nothing for this many consecutive ticks jumps the priority order
      on its next visit (the no-tenant-starves guarantee).
    - ``tick_ring``: how many tick durations the p50/p99 metrics window
      retains (the bounded history the percentiles are computed over —
      a long-lived service never accumulates unbounded timings).
    - ``lag_probe_ticks``: replication-lag probe cadence (every N ticks;
      0 disables). Each probe is one vectorized ClockMatrix comparison
      per room plus a bounded un-acked-frame scan per tenant
      (INTERNALS §14.2).
    - ``event_log``: how many degradation events (defer / shed /
      suspect / evict / protocol_error ...) the black-box ring retains
      for ``SyncService.describe()`` — the postmortem dump works with
      tracing OFF, so the service keeps its own bounded ring.
    - ``prom_lag_series``: at most this many per-tenant lag gauge
      series on the scrape page (worst-lagging first); aggregates are
      always exported, so the page stays bounded at any tenant count.
    - ``shard_lanes``: partition the room population across this many
      shard execution lanes over the device mesh (INTERNALS §15.4):
      each room maps onto a lane by the deterministic placement table
      and its grouped gate deliveries run under that lane's device
      context, so room document state lives device-local per shard. 0
      (the default) keeps the unsharded single-device behavior; -1 uses
      one lane per visible device.
    - ``residency_budget_bytes`` (+ ``residency_headroom`` /
      ``residency_cold_after`` / ``residency_spill_dir``): the
      device-residency tier (INTERNALS §22). Non-zero turns on the bulk
      doc mesh with a residency manager over the service's shard lanes:
      hot docs stay device-resident under the byte budget, warm docs
      demote to host checkpoint bundles, cold bundles age to disk after
      ``residency_cold_after`` pager rounds (``residency_spill_dir``
      must be set for the cold tier). ``tick()`` is the pager
      heartbeat; ``mesh_deliver`` feeds the paging gate. Like every
      other knob here, this is a BOUND: the live population may be any
      size, the device bytes may not.
    """

    __slots__ = ("tick_budget_ms", "heartbeat_ticks", "suspect_grace_ticks",
                 "max_retries", "base_rto", "max_rto", "recv_window",
                 "quarantine_capacity", "quarantine_global_capacity",
                 "starvation_boost_ticks", "tick_ring", "default_budget",
                 "lag_probe_ticks", "event_log", "prom_lag_series",
                 "shard_lanes", "region", "residency_budget_bytes",
                 "residency_headroom", "residency_cold_after",
                 "residency_spill_dir")

    def __init__(self, *, tick_budget_ms: float = 0.0,
                 heartbeat_ticks: int = 30, suspect_grace_ticks: int = 30,
                 max_retries: int = 12, base_rto: int = 2, max_rto: int = 8,
                 recv_window: int = 256,
                 quarantine_capacity: int = DEFAULT_CAPACITY,
                 quarantine_global_capacity: int = 4 * DEFAULT_CAPACITY,
                 starvation_boost_ticks: int = 8, tick_ring: int = 4096,
                 default_budget: TenantBudget = None,
                 lag_probe_ticks: int = 1, event_log: int = 256,
                 prom_lag_series: int = 64, shard_lanes: int = 0,
                 region: str = None, residency_budget_bytes: int = 0,
                 residency_headroom: float = 0.85,
                 residency_cold_after: int = 64,
                 residency_spill_dir: str = None):
        self.tick_budget_ms = tick_budget_ms
        self.heartbeat_ticks = heartbeat_ticks
        self.suspect_grace_ticks = suspect_grace_ticks
        self.max_retries = max_retries
        self.base_rto = base_rto
        self.max_rto = max_rto
        self.recv_window = recv_window
        self.quarantine_capacity = quarantine_capacity
        self.quarantine_global_capacity = quarantine_global_capacity
        self.starvation_boost_ticks = starvation_boost_ticks
        self.tick_ring = tick_ring
        self.default_budget = default_budget or TenantBudget()
        self.lag_probe_ticks = lag_probe_ticks
        self.event_log = event_log
        self.prom_lag_series = prom_lag_series
        self.shard_lanes = shard_lanes
        #: federation (INTERNALS §20): the region name this service
        #: instance serves, or None for a single-region deployment.
        #: Region-qualifies the rooms' lineage replica-site labels
        #: (``svc:<region>/<room>``), so a change's hop chain names
        #: WHICH region's replica made it visible.
        self.region = region
        self.residency_budget_bytes = int(residency_budget_bytes)
        self.residency_headroom = float(residency_headroom)
        self.residency_cold_after = int(residency_cold_after)
        self.residency_spill_dir = residency_spill_dir


def approx_msg_bytes(msg) -> int:
    """Cheap JSON-ish size estimate for budget accounting (recursive, no
    encode): close enough to wire bytes to meter tenants fairly, and two
    orders of magnitude cheaper than re-serializing every message. A
    binary wire frame's size is EXACT — its encoded length is the wire
    form. ONE implementation, shared with the channel's
    bytes_sent/bytes_resent accounting (resilience/channel.py
    ``payload_wire_bytes``) so the service's tenant metering and the
    bench's dict-vs-binary byte comparison can never drift apart."""
    from ..resilience.channel import payload_wire_bytes
    return payload_wire_bytes(msg)

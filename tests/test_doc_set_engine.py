"""DeviceTextDocSet: vmapped multi-doc merges match per-doc DeviceTextDoc."""

import numpy as np
import pytest

from automerge_tpu.engine import DeviceTextDoc, DeviceTextDocSet


def typing_change(actor, seq, text, start_ctr=1, after=None, deps=None,
                  obj="t"):
    ops = []
    key = after if after is not None else "_head"
    for i, c in enumerate(text):
        ctr = start_ctr + i
        ops.append({"action": "ins", "obj": obj, "key": key, "elem": ctr})
        ops.append({"action": "set", "obj": obj, "key": f"{actor}:{ctr}",
                    "value": c})
        key = f"{actor}:{ctr}"
    return {"actor": actor, "seq": seq, "deps": deps or {}, "ops": ops}


def test_bulk_build_matches_single_doc():
    ids = [f"d{i}" for i in range(5)]
    ds = DeviceTextDocSet(ids)
    batches = {}
    singles = {}
    from automerge_tpu.engine import TextChangeBatch
    for i, obj in enumerate(ids):
        changes = [typing_change(f"actor-{a}", 1, f"doc{i}text{a}", obj=obj)
                   for a in range(3)]
        batches[obj] = TextChangeBatch.from_changes(changes, obj)
        singles[obj] = DeviceTextDoc(obj).apply_changes(changes)
    ds.apply_batches(batches)
    texts = ds.texts()
    for obj in ids:
        assert texts[obj] == singles[obj].text()


def test_incremental_rounds_and_graduation():
    from automerge_tpu.engine import TextChangeBatch
    ids = ["a", "b"]
    ds = DeviceTextDocSet(ids)
    # round 1: plain typing in both docs (fast path)
    ds.apply_batches({o: TextChangeBatch.from_changes(
        [typing_change("w", 1, "hello", obj=o)], o) for o in ids})
    assert ds.texts() == {"a": "hello", "b": "hello"}
    # round 2: doc "a" gets an irregular batch (delete -> graduates)
    ch = {"actor": "w", "seq": 2, "deps": {}, "ops":
          [{"action": "del", "obj": "a", "key": "w:5"}]}
    ds.apply_batches({"a": TextChangeBatch.from_changes([ch], "a")})
    assert ds.texts() == {"a": "hell", "b": "hello"}
    # round 3: both docs extend; "a" continues on its own engine
    ds.apply_batches({o: TextChangeBatch.from_changes(
        [typing_change("w", 3 if o == "a" else 2, "!!", start_ctr=6,
                       after="w:4" if o == "a" else "w:5", obj=o)], o)
        for o in ids})
    assert ds.texts() == {"a": "hell!!", "b": "hello!!"}


def test_unicode_docset():
    from automerge_tpu.engine import TextChangeBatch
    ds = DeviceTextDocSet(["u"])
    ds.apply_batches({"u": TextChangeBatch.from_changes(
        [typing_change("w", 1, "héllo", obj="u")], "u")})
    assert ds.texts()["u"] == "héllo"


def test_concurrent_actors_same_position():
    from automerge_tpu.engine import TextChangeBatch
    ds = DeviceTextDocSet(["x"])
    changes = [typing_change("aaa", 1, "123", obj="x"),
               typing_change("bbb", 1, "456", start_ctr=1, obj="x")]
    ds.apply_batches({"x": TextChangeBatch.from_changes(changes, "x")})
    single = DeviceTextDoc("x").apply_changes(changes)
    assert ds.texts()["x"] == single.text()


def test_graduation_carries_causal_history():
    """A doc graduating off the fast path must keep the transitive-deps
    closure of fast-path changes: a later writer whose deps transitively
    cover an earlier write must overwrite it, not conflict with it."""
    from automerge_tpu.engine import TextChangeBatch
    ds = DeviceTextDocSet(["g"])
    chA = typing_change("A", 1, "x", obj="g")
    chB = {"actor": "B", "seq": 1, "deps": {"A": 1}, "ops": [
        {"action": "ins", "obj": "g", "key": "A:1", "elem": 2},
        {"action": "set", "obj": "g", "key": "B:2", "value": "y"}]}
    ds.apply_batches({"g": TextChangeBatch.from_changes([chA], "g")})
    ds.apply_batches({"g": TextChangeBatch.from_changes([chB], "g")})
    # actor '0' < 'A' lexicographically; deps {B:1} transitively covers A:1
    ch0 = {"actor": "0", "seq": 1, "deps": {"B": 1}, "ops": [
        {"action": "set", "obj": "g", "key": "A:1", "value": "z"}]}
    ds.apply_batches({"g": TextChangeBatch.from_changes([ch0], "g")})
    single = DeviceTextDoc("g").apply_changes([chA, chB, ch0])
    assert ds.texts()["g"] == single.text() == "zy"
    assert ds.doc("g").conflicts_at(0) is None


def test_duplicate_batch_is_noop_without_graduation():
    from automerge_tpu.engine import TextChangeBatch
    ds = DeviceTextDocSet(["dup"])
    batch = TextChangeBatch.from_changes(
        [typing_change("w", 1, "abc", obj="dup")], "dup")
    ds.apply_batches({"dup": batch})
    ds.apply_batches({"dup": batch})  # redelivery
    assert ds.texts()["dup"] == "abc"
    assert not ds._overlay  # still on the vmapped fast path


def test_in_batch_duplicate_change_is_idempotent():
    """The same change twice within ONE batch must apply once, like the
    general engine, not raise a duplicate-elemId error."""
    from automerge_tpu.engine import TextChangeBatch
    ds = DeviceTextDocSet(["ib"])
    ch = typing_change("w", 1, "a", obj="ib")
    ds.apply_batches({"ib": TextChangeBatch.from_changes([ch, ch], "ib")})
    single = DeviceTextDoc("ib").apply_changes([ch, ch])
    assert ds.texts()["ib"] == single.text() == "a"


def test_sequential_same_actor_batch_stays_fast():
    """seq n and n+1 from one actor in one batch ride the vmapped path."""
    from automerge_tpu.engine import TextChangeBatch
    ds = DeviceTextDocSet(["sq"])
    chs = [typing_change("w", 1, "ab", obj="sq"),
           typing_change("w", 2, "cd", start_ctr=3, after="w:2", obj="sq")]
    ds.apply_batches({"sq": TextChangeBatch.from_changes(chs, "sq")})
    assert ds.texts()["sq"] == "abcd"
    assert not ds._overlay


def test_sharded_docset_matches_unsharded():
    """The same merges on a (doc, elem)-sharded mesh produce identical
    texts — XLA inserts the collectives; semantics don't change."""
    from automerge_tpu.engine import TextChangeBatch
    from automerge_tpu.parallel import make_mesh

    mesh = make_mesh(8)  # virtual CPU devices from conftest XLA_FLAGS
    ids = [f"m{i}" for i in range(mesh.shape["doc"] * 2)]
    plain = DeviceTextDocSet(ids)
    sharded = DeviceTextDocSet(ids, mesh=mesh)
    for rnd in range(2):
        batches = {}
        for i, o in enumerate(ids):
            changes = [
                typing_change(
                    f"w{a}", rnd + 1, f"r{rnd}a{a}d{i % 7}xy",
                    start_ctr=16 * rnd + 1,
                    after="w0:8" if rnd else None,
                    deps={"w0": rnd} if rnd and a != 0 else {},
                    obj=o)
                for a in range(2)]
            batches[o] = TextChangeBatch.from_changes(changes, o)
        plain.apply_batches(batches)
        sharded.apply_batches(batches)
    texts = sharded.texts()
    assert texts == plain.texts()
    assert all(len(t) == 32 for t in texts.values())


@pytest.mark.parametrize("seed", range(3))
def test_random_docsets_match_single(seed):
    from automerge_tpu.engine import TextChangeBatch
    rng = np.random.default_rng(seed)
    ids = [f"r{i}" for i in range(4)]
    ds = DeviceTextDocSet(ids)
    singles = {o: DeviceTextDoc(o) for o in ids}
    ctr = {o: 1 for o in ids}
    for rnd in range(3):
        batches = {}
        for o in ids:
            n_act = int(rng.integers(1, 4))
            changes = []
            for a in range(n_act):
                text = "".join(chr(97 + int(c))
                               for c in rng.integers(0, 26, 8))
                changes.append(typing_change(
                    f"w{a}", rnd + 1, text, start_ctr=ctr[o], obj=o,
                    deps={f"w{i}": rnd for i in range(n_act)} if rnd else {}))
            ctr[o] += 8
            batches[o] = TextChangeBatch.from_changes(changes, o)
            singles[o].apply_changes(changes)
        ds.apply_batches(batches)
    texts = ds.texts()
    for o in ids:
        assert texts[o] == singles[o].text(), o


def test_docset_mirrors_track_chain_bits():
    """Per-doc segment mirrors (planned vmapped materialization) must equal
    the stacked chain-bit structure, and texts() must flag planned runs."""
    from automerge_tpu.engine import TextChangeBatch
    ids = ["m0", "m1"]
    ds = DeviceTextDocSet(ids)
    for rnd, start in ((1, 1), (2, 100)):
        batches = {}
        for o in ids:
            changes = [typing_change(f"w{a}", rnd, "abcd", start_ctr=start,
                                     obj=o, after=(None if rnd == 1
                                                   else "w0:2"),
                                     deps={} if rnd == 1 else
                                     {f"w{i}": 1 for i in range(2)})
                       for a in range(2)]
            batches[o] = TextChangeBatch.from_changes(changes, o)
        ds.apply_batches(batches)
    texts = ds.texts()
    chain = np.asarray(ds._ensure_dev()["chain"])
    for d, o in enumerate(ids):
        meta = ds._meta[d]
        assert meta.mirror is not None
        dev_heads = 1 + np.flatnonzero(~chain[d, 1: meta.n_elems + 1])
        np.testing.assert_array_equal(meta.mirror.heads[1:], dev_heads)
        single = DeviceTextDoc(o)
        for rnd, start in ((1, 1), (2, 100)):
            single.apply_changes([
                typing_change(f"w{a}", rnd, "abcd", start_ctr=start, obj=o,
                              after=(None if rnd == 1 else "w0:2"),
                              deps={} if rnd == 1 else
                              {f"w{i}": 1 for i in range(2)})
                for a in range(2)])
        assert texts[o] == single.text()


def test_docset_corrupted_mirror_self_heals():
    from automerge_tpu.engine import TextChangeBatch
    from automerge_tpu.engine.segments import SegmentMirror
    ds = DeviceTextDocSet(["h0", "h1"])
    batches = {o: TextChangeBatch.from_changes(
        [typing_change("w0", 1, "hello", obj=o)], o) for o in ds.obj_ids}
    ds.apply_batches(batches)
    good = ds.texts()
    # corrupt doc 1's mirror: bogus extra head
    m = ds._meta[1].mirror
    ds._meta[1].mirror = SegmentMirror(
        np.append(m.heads, 3), np.append(m.par, 2),
        np.append(m.hctr, 99), np.append(m.hactor, 0))
    ds._meta[1].mirror.heads.sort()
    ds._codes_cache = None
    assert ds.texts() == good           # healed via self-contained kernel
    # the heal rebuilds row 1's mirror from its chain bits
    chain = np.asarray(ds._ensure_dev()["chain"])
    for d in range(2):
        meta = ds._meta[d]
        assert meta.mirror is not None
        dev_heads = 1 + np.flatnonzero(~chain[d, 1: meta.n_elems + 1])
        np.testing.assert_array_equal(meta.mirror.heads[1:], dev_heads)
    # and the planned path serves the NEXT call again
    ds._codes_cache = None
    assert ds.texts() == good

"""Device-resident text/list CRDT document.

This is the TPU-native replacement for the reference's per-op reconciliation
of sequences (`backend/op_set.js` applyInsert/applyAssign + skip list,
/root/reference/backend/op_set.js:63-283, /root/reference/backend/
skip_list.js): the document lives as padded columnar element tables in device
memory; whole *batches* of changes merge in jitted programs (`ops/ingest.py`),
and materialization (RGA order + visible compaction) is a second device
program — the host orchestrates causal admission, elemId reference
resolution, and the rare slow register cases.

Semantics match the oracle exactly (see tests/test_engine_parity.py):
- causal readiness gating with queueing of unready changes, idempotent dups
- per-element multi-value registers: a set op survives until another op on the
  same element causally overwrites it; winner = highest actor id; concurrent
  survivors are conflicts
- counter `inc` folds into causally-visible counter set ops
- RGA concurrent-insert ordering (descending Lamport at each insertion point)

Division of labor per causally-ready round:
- host (numpy, C-speed): vector clocks, transitive deps, actor interning,
  typing-run detection over the op columns, elemId->slot resolution against
  a compressed range index (engine/host_index.py), and the slow-mask
  register residue (dels, counter incs, genuine concurrent conflicts)
  against the host-held conflict/value-pool state
- device: run expansion + irregular-op scatters + LWW register fast path
  (`expand_runs`/`apply_residual`) and materialization (`materialize_text`)
  — all int32, no sorts over elements, O(ops) at HBM bandwidth

The run condensation is the key throughput lever: a typing run of k
characters costs ~20 bytes of descriptor + 4k bytes of value blob on the
wire to the device, instead of 2k op rows.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

import logging

from .._common import HEAD_PARENT, KIND_SET, make_elem_id
from .. import obs
from .base import CausalDeviceDoc
from .columnar import TextChangeBatch
from .pipeline import stage_h2d
from .runs import detect_runs
from .host_index import (DuplicateElemId, ElemRangeIndex, new_index,
                         pack_keys, unpack_key)
from . import learned_index
from .segments import SegmentMirror

logger = logging.getLogger("automerge_tpu.engine")


def run_head_fields(plan, batch_rank, ta, tc, pa, pc) -> dict:
    """Run-head planning fields that are a pure function of the (immutable)
    op columns + one interning table: head ranks/counters, packed head
    keys, and the parent-ref prehash. ONE implementation shared by
    `_plan_round`'s per-(doc, batch) cache fills and the cross-doc
    planner's rank seeding (engine/cross_doc.py), so the two paths cannot
    drift."""
    hpos = plan.hpos
    head_rank = batch_rank[ta[hpos]]
    head_ctr64 = tc[hpos].astype(np.int64)
    p_actor = pa[hpos]
    is_head_p = p_actor == HEAD_PARENT
    return {
        "head_rank": head_rank,
        "head_ctr64": head_ctr64,
        "head_keys": pack_keys(head_rank, head_ctr64),
        "head_parent": (is_head_p,
                        pack_keys(batch_rank[np.where(is_head_p, 0, p_actor)],
                                  pc[hpos].astype(np.int64))),
    }


def build_desc_template(plan, tc, op_row, head_rank, row_actor_rank,
                        row_seq, R: int, N: int) -> np.ndarray:
    """The (9, R) run-descriptor TEMPLATE of one full round: every row
    that is a pure function of (op columns, interning) — only the
    head/parent SLOT rows and the base-slot meta (document state) are
    filled per application. Shared by `_plan_round` and the cross-doc
    planner's seeding (engine/cross_doc.py)."""
    from ..ops.ingest import (DESC_ACTOR, DESC_CTR0, DESC_ELEM_BASE,
                              DESC_HAS_VALUE, DESC_META, DESC_WIN_ACTOR,
                              DESC_WIN_SEQ, META_N_ELEMS, META_N_RUNS)
    hpos = plan.hpos
    n_runs = plan.n_runs
    run_len = plan.run_len
    tmpl = np.zeros((9, R), np.int32)
    tmpl[DESC_ELEM_BASE] = N          # padding sentinel
    tmpl[DESC_CTR0, :n_runs] = tc[hpos]
    tmpl[DESC_ACTOR, :n_runs] = head_rank
    tmpl[DESC_WIN_ACTOR, :n_runs] = row_actor_rank[op_row[hpos]]
    tmpl[DESC_WIN_SEQ, :n_runs] = row_seq[op_row[hpos]]
    tmpl[DESC_ELEM_BASE, :n_runs] = np.cumsum(run_len) - run_len
    tmpl[DESC_HAS_VALUE, :n_runs] = 1
    tmpl[DESC_META, META_N_ELEMS] = plan.n_pairs
    tmpl[DESC_META, META_N_RUNS] = n_runs
    return tmpl


def _resolve_refs_learned(merged_index, head_parent_pre, n_runs, rpos,
                          res_is_ins, n_res_ins, batch_rank, ta, tc, pa,
                          pc, decode, obj_id):
    """The learned-index resolve-refs fast path (engine/learned_index.py,
    ISSUE 19): every parent and assignment-target reference of the round
    resolves through ONE batched index probe — one model evaluation per
    column instead of up to three separate tier-loop lookups — and the
    residual refs pack with ONE int32-envelope guard pair instead of one
    per section. Results, error messages, and the raise order across
    sections are identical to the exact blocks in `_plan_round` (kept
    verbatim as the parity comparator behind AMTPU_LEARNED_INDEX=0)."""
    n_res = len(rpos)
    k0 = n_runs
    is_head0 = keys0 = None
    if n_runs:
        is_head0, keys0 = head_parent_pre
    ranks = []
    ctrs = []
    is_head1 = res_is_assign = None
    k1 = 0
    k2 = 0
    if n_res:
        if n_res_ins:
            ri = rpos[res_is_ins]
            p_a = pa[ri]
            is_head1 = p_a == HEAD_PARENT
            ranks.append(batch_rank[np.where(is_head1, 0, p_a)])
            ctrs.append(pc[ri].astype(np.int64))
            k1 = n_res_ins
        res_is_assign = ~res_is_ins
        k2 = n_res - n_res_ins
        if k2:
            ai = rpos[res_is_assign]
            ranks.append(batch_rank[ta[ai]])
            ctrs.append(tc[ai].astype(np.int64))
    if ranks:
        packed = pack_keys(
            ranks[0] if len(ranks) == 1 else np.concatenate(ranks),
            ctrs[0] if len(ctrs) == 1 else np.concatenate(ctrs))
        keys_all = packed if keys0 is None \
            else np.concatenate([keys0, packed])
    else:
        keys_all = keys0
    slots_all, found_all = learned_index.index_lookup(
        merged_index, keys_all)
    if n_runs:
        missing = ~(found_all[:k0] | is_head0)
        if missing.any():
            raise ValueError(
                "ins references unknown parent element "
                f"{decode(int(keys0[np.flatnonzero(missing)[0]]))} "
                f"in {obj_id}")
        run_parent_slot = np.where(is_head0, 0, slots_all[:k0])
    else:
        run_parent_slot = np.empty(0, np.int64)
    res_parent_slot = res_target_slot = None
    if n_res:
        res_parent_slot = np.zeros(n_res, np.int64)
        if k1:
            s1 = slots_all[k0:k0 + k1]
            f1 = found_all[k0:k0 + k1]
            missing = ~(f1 | is_head1)
            if missing.any():
                bad = int(keys_all[k0 + np.flatnonzero(missing)[0]])
                raise ValueError(
                    "ins references unknown parent element "
                    f"{decode(bad)} in {obj_id}")
            res_parent_slot[res_is_ins] = np.where(is_head1, 0, s1)
        res_target_slot = np.zeros(n_res, np.int64)
        if k2:
            s2 = slots_all[k0 + k1:]
            f2 = found_all[k0 + k1:]
            if not f2.all():
                bad = int(keys_all[k0 + k1 + np.flatnonzero(~f2)[0]])
                raise ValueError(
                    f"assignment to unknown element {decode(bad)} "
                    f"in {obj_id}")
            res_target_slot[res_is_assign] = s2
    return run_parent_slot, res_parent_slot, res_target_slot


@dataclass
class _RoundExec:
    """A planned causally-ready round: staged device inputs + the host
    state deltas `_execute_plan` commits (see `_plan_round`)."""

    index_after: ElemRangeIndex
    n_elems_after: int
    out_cap: int
    dense: bool
    n_runs: int
    n_res: int
    desc: Any                 # staged (9, R) int32 device matrix (or None)
    blob: Any                 # staged value blob (uint8/int32, or None)
    res: Any                  # staged (8, M) int32 residual matrix (or None)
    touch: Any                # staged (3, T) chain-touch matrix (or None)
    ascii_clear: bool
    res_host: Optional[tuple]  # (kind, val64, actor_rank, seq) per residual
    seg_inc: int
    touched_slots: Optional[np.ndarray] = None  # assign-targeted OLD slots
    # (set/del/inc this round): the incremental text pull's dirty feed
    n_elems_dev: Any = None   # staged device mirror of n_elems_after
    mirror_after: Optional[SegmentMirror] = None  # host segment structure
    seg_plan: Any = None      # staged (4, S) segplan matrix (fused path)
    seg_S: int = 0            # S bucket the segplan was packed for
    n_index_merges: int = 0   # bulk index merges this round performed
    # (0 or 1 by construction — the cfg12t budget the stacked executor
    # sums and asserts: one bulk merge per doc per round, never per range)

    @property
    def staged(self) -> list:
        """The round's device buffers (for transfer-completion barriers)."""
        return [x for x in (self.desc, self.blob, self.res, self.touch,
                            self.n_elems_dev, self.seg_plan)
                if x is not None]


class DeviceTextDoc(CausalDeviceDoc):
    """One text/list object, columnar, merged in batches on device.

    Element table layout: slot 0 is the virtual head; live elements occupy
    1..n_elems in insertion order. All tables live in device memory; host
    numpy mirrors are fetched lazily for accessors and the slow path.
    """

    use_condensed = True  # chain-condensed linearization (set False to force
    # the element-wise kernel; parity tests exercise both)

    eager_materialize = False  # fuse the dense merge round and the codes
    # materialization into ONE device program (merge_and_materialize_dense):
    # halves launch/flush overhead for merge->read cycles (the headline
    # bench's shape); costs a wasted materialization when many rounds land
    # between reads, hence opt-in per instance

    # Kernel choice for materialization: the host-PLANNED variant feeds the
    # device a packed segplan so it skips the structural S-stage. Planned
    # is the default: it wins ~6% on cpu and produced the round's best
    # verified on-chip headline (115.5M ops/s). The on-chip A/B was run
    # TWICE in one night and split — self-contained won the 03:24 run by
    # 13%, planned won the 03:38 run by 43% (scripts/chip_session.log;
    # headline-region readings on unchanged code spanned 65-115M ops/s
    # across that window) — so at WAN-tunnel variance the single-chip
    # question is OPEN, not settled; docs/MEASUREMENTS.md records both
    # runs. AMTPU_PLANNED=0 (or the attribute) selects the self-contained
    # kernels; re-run `profile_bench.py --planned` on a quiet link to
    # settle it. The mirror is maintained either way (it tightens
    # _seg_bound and feeds the elem-sharded path, where the plan's
    # sort-free program is structurally required —
    # parallel/sharded_planned_materialize).
    prefer_planned = os.environ.get("AMTPU_PLANNED", "1") == "1"

    # Incremental text pulls: `text()` keeps the last materialized string
    # on the host plus a per-segment (head slot, visible count, text
    # position) table; a later pull ships only CHANGED spans d2h —
    # O(edits) bytes, not O(doc) — reconciling new/split/touched segments
    # against the cache (see `_text_incremental`). Off: AMTPU_INCR_PULL=0.
    incremental_pull = os.environ.get("AMTPU_INCR_PULL", "1") == "1"
    incremental_pull_min = 4096   # below this, a full pull is cheaper than
    # the extra seg-info fetch the cache costs

    _TABLE_KEYS = ("parent", "ctr", "actor", "value", "has_value",
                   "win_actor", "win_seq", "win_counter", "chain")

    batch_type = TextChangeBatch

    # How `_plan_round` ships its packed device inputs. The default stages
    # each buffer h2d immediately (the solo/pipelined path); the stacked
    # multi-object executor (engine/stacked.py) swaps in an identity
    # stager so plans come back as HOST matrices, which it re-pads and
    # uploads as ONE (D, ...) block per round across every object —
    # per-object device_puts are exactly the cfg4 ceiling being removed.
    _stager = staticmethod(stage_h2d)

    def _decode_wire(self, changes):
        """Wire deliveries decode through the columnar protocol-boundary
        decoder (engine/wire_columns.py): vectorized numpy decode for
        bulk plain-text payloads (native C++ codec for JSON), per-op walk
        for the rest — with the per-change columns attached eagerly, so
        the first prepare already runs columnar (INTERNALS §10.1). This
        is the production ingestion path: the device backend's per-object
        change windows (backend/device.py _distribute) and the sync tier
        land here via apply_changes."""
        from .wire_columns import decode_text_changes_columnar
        return decode_text_changes_columnar(changes, self.obj_id)

    def __init__(self, obj_id: str = "text", capacity: int = 1024):
        from ..ops.ingest import bucket
        super().__init__(obj_id)
        self.all_ascii = True                 # every value ever set is 7-bit
        self.n_elems = 0                      # live element count (excl. head)
        self.index = new_index()              # elemId -> slot (host)
        # host mirror of the chain/segment structure; None = degraded (the
        # self-contained device kernels take over — see _scalars self-heal)
        self.seg_mirror = SegmentMirror.empty()
        self._cap = bucket(max(capacity, 16))
        self._seg_bound = 2                   # upper bound for S sizing
        self._mat = None                      # materialization cache (device)
        self._mat_S = 0                       # S the cached kernel ran with
        self._mat_keep_gen = None             # gen at fused-cache seed time
        self._scal = None                     # fetched [n_vis, n_segs]
        self._n_elems_dev = None              # (count, device scalar) mirror
        self._pos_cache = None
        self._text_cache = None               # host text + per-seg table
        self._touched_old = []                # assign-target slots since cache
        self.pull_stats: Optional[dict] = None  # how the LAST text() pulled

    # ------------------------------------------------------------------
    # device state
    # ------------------------------------------------------------------

    def _ensure_dev(self) -> dict:
        self._check_device_alive()
        if self._dev is None:
            import jax.numpy as jnp
            cap = self._cap
            self._dev = {
                "parent": jnp.zeros(cap, jnp.int32),
                "ctr": jnp.zeros(cap, jnp.int32),
                "actor": jnp.zeros(cap, jnp.int32),
                "value": jnp.zeros(cap, jnp.int32),
                "has_value": jnp.zeros(cap, bool),
                "win_actor": jnp.full(cap, -1, jnp.int32),
                "win_seq": jnp.zeros(cap, jnp.int32),
                "win_counter": jnp.zeros(cap, bool),
                "chain": jnp.zeros(cap, bool),
            }
        return self._dev

    def _device_footprint_extra(self) -> int:
        # device bytes held outside the 9-table dict: the staged n_elems
        # scalar and the cached materialization buffers (codes/pos live
        # on device until a pull fetches them)
        extra = 4 if self._n_elems_dev else 0
        if self._mat is not None:
            for a in self._mat:
                if (not isinstance(a, np.ndarray)
                        and hasattr(a, "dtype") and hasattr(a, "shape")):
                    n = 1
                    for d in a.shape:
                        n *= int(d)
                    extra += n * np.dtype(a.dtype).itemsize
        return extra

    def _host_footprint_extra(self) -> dict:
        return {"index_ranges": int(self.index.n_ranges),
                "segments": (self.seg_mirror.n_segs
                             if self.seg_mirror is not None else 0)}

    def _invalidate(self):
        self._host = None
        self._scal = None
        self._pos_cache = None
        if self._mat_keep_gen == self._gen:
            # a just-seeded fused merge+materialize result survives exactly
            # one invalidation: the batch driver's trailing _invalidate()
            # (engine/base.py apply_batch / commit_prepared) runs AFTER the
            # round that produced it. The seed-generation stamp guarantees
            # NOTHING intervened (any other mutation — including the
            # failure paths' bare _gen bumps — moves _gen first).
            self._mat_keep_gen = None
        else:
            self._mat = None
        self._gen += 1

    def _mirrors(self) -> dict:
        """Host numpy mirrors of the element tables (one packed fetch)."""
        if self._host is None:
            self._host = self._fetch_mirrors(
                ("parent", "ctr", "actor", "value", "has_value"))
        return self._host

    def _remap_device(self, remap: np.ndarray):
        import jax.numpy as jnp
        from ..ops.ingest import remap_actors
        dev = self._ensure_dev()
        self._count_dispatch(label="remap_actors")
        actor_n, wa_n = remap_actors(
            dev["actor"], dev["win_actor"], jnp.asarray(remap),
            np.int32(self.n_elems))
        dev.update(actor=actor_n, win_actor=wa_n)
        # pure remap: the index is persistent, so outstanding snapshots
        # (checkpoint grabs, pulls) keep the pre-remap view
        self.index = self.index.remap_actors(remap.astype(np.int64))
        if self.seg_mirror is not None:
            # safe in place: _apply_remap invalidates, so plans derived from
            # the pre-remap mirror can no longer commit
            self.seg_mirror.remap_actors(remap.astype(np.int64))

    def _plan_shadow(self):
        """Planning shadow state threaded through multi-round preparation."""
        return (self.n_elems, self.index, self._cap, self.seg_mirror)

    def _ingest(self, b: TextChangeBatch, mask):
        """One causally-ready round of one batch: host resolution + at most
        two device programs (run expansion, residual ops)."""
        plan, _ = self._plan_round(b, mask, self._plan_shadow())
        if plan is not None:
            self._execute_plan(b, plan)

    def _plan_round(self, b: TextChangeBatch, mask, shadow):
        """Host planning of one causally-ready round: run detection, elemId
        resolution, validity checks, and h2d staging of the packed device
        inputs. Mutates NOTHING (actor interning must already cover the
        batch); returns (plan, shadow') where shadow' reflects the round as
        if committed — `_execute_plan` later applies it for real."""
        import jax.numpy as jnp
        from ..ops.ingest import (DESC_ACTOR, DESC_CTR0, DESC_ELEM_BASE,
                                  DESC_HAS_VALUE, DESC_HEAD_SLOT,
                                  DESC_PARENT_SLOT, DESC_WIN_ACTOR,
                                  DESC_WIN_SEQ, RES_ACTOR, RES_CTR, RES_KIND,
                                  RES_NEW_SLOT, RES_SLOT, RES_VALUE,
                                  RES_WIN_ACTOR, RES_WIN_SEQ, bucket)

        base_elems, base_index, base_cap, base_mirror = shadow
        st = self._stager          # h2d stager (identity on the stacked
        staged_mode = st is stage_h2d  # path: plans stay host matrices)
        kind = np.ascontiguousarray(b.op_kind[mask])
        n_ops = len(kind)
        if n_ops == 0:
            return None, shadow
        ta = b.op_target_actor[mask]
        tc = b.op_target_ctr[mask]
        pa = b.op_parent_actor[mask]
        pc = b.op_parent_ctr[mask]
        val64 = b.op_value[mask]
        op_row = b.op_change[mask]

        # batch actor ranks against THIS doc's interning: resolved once
        # per (doc, interning generation) and cached on the batch's
        # columnar companion — replica fan-out and bench reps hit the
        # cache on every application after the first (INTERNALS §10)
        cols = getattr(b, "_change_columns", None)
        rc = cols.rank_cache.get(self) if cols is not None else None
        if rc is not None and rc["gen"] == self._intern_gen:
            batch_rank = rc["batch_rank"]
            row_actor_rank = rc["row_rank"]
        else:
            _tr = obs.now() if obs.ENABLED else 0
            # learned actor-rank site: the doc's lex-sorted table means
            # rank == table position, so the packed position model (one
            # evaluation per column) replaces the per-actor dict probes;
            # any not-found query falls through to the exact path whose
            # KeyError is the parity-identical unknown-actor signal.
            batch_rank = row_actor_rank = None
            if learned_index.site_enabled("actor_rank"):
                m = learned_index.doc_actor_model(self)
                if m is not None:
                    gb = learned_index.actor_positions(
                        self.actor_table, np.asarray(b.actor_table, object),
                        "actor_rank", model=m)
                    gr = learned_index.actor_positions(
                        self.actor_table, np.asarray(b.actors, object),
                        "actor_rank", model=m)
                    if (gb is not None and gr is not None
                            and gb[1].all() and gr[1].all()):
                        batch_rank = gb[0].astype(np.int64)
                        row_actor_rank = gr[0].astype(np.int32)
            if batch_rank is None:
                rank = self._actor_rank
                batch_rank = np.asarray(
                    [rank[a] for a in b.actor_table], np.int64)
                row_actor_rank = np.asarray(
                    [rank[a] for a in b.actors], np.int32)
            rc = {"gen": self._intern_gen, "batch_rank": batch_rank,
                  "row_rank": row_actor_rank}
            if cols is not None:
                cols.rank_cache[self] = rc
            if obs.ENABLED:
                obs.span("plan", "rank_resolve", _tr, args={
                    "doc": self.obj_id, "what": "batch_rank",
                    "n_actors": len(b.actor_table)})
        row_seq = np.asarray(b.seqs, np.int32)

        # --- typing-run detection: INS immediately followed by its SET,
        # chained with consecutive counters (the dominant text workload).
        # The partition is a pure function of the op columns (slot fields
        # aside, which rebase() shifts), so a FULL round's detection is
        # memoized on the batch object: a caller applying one decoded
        # batch to several documents (replica fan-out, replay, the
        # headline bench's reps) detects once instead of paying the
        # ~45 ms 10M-op walk per application. Partial rounds (multi-round
        # causal batches) see a masked column view and are not cached.
        full_round = (mask == slice(None) if isinstance(mask, slice)
                      else bool(np.all(mask)))
        cached = getattr(b, "_run_plan_cache", None) if full_round else None
        if cached is not None and cached[1].n_ops == n_ops:
            plan = cached[1].rebase(base_elems - cached[0])
        else:
            plan = detect_runs(kind, ta, tc, pa, pc, val64, op_row,
                               base_elems)
            if full_round:
                # freeze before sharing: rebase() aliases these arrays
                # into every later application's plan, so an in-place
                # write by any future consumer must fail loudly instead
                # of silently corrupting other replicas' rounds
                for arr in (plan.hpos, plan.run_len, plan.head_slot,
                            plan.rpos, plan.res_new_slot, plan.blob):
                    if isinstance(arr, np.ndarray):
                        arr.setflags(write=False)
                b._run_plan_cache = (base_elems, plan)
        hpos, run_len, rpos, res_is_ins = (
            plan.hpos, plan.run_len, plan.rpos, plan.res_is_ins)
        n_ins, n_runs, n_pairs, n_res_ins = (
            plan.n_ins, plan.n_runs, plan.n_pairs, plan.n_res_ins)
        res_kind = kind[rpos]

        # --- elemId index: stage this round's minted ranges (commit later) ---
        head_parent_pre = None
        if n_runs:
            # run-head gathers and packed keys are pure functions of the
            # (immutable) op columns + this doc's interning — cached with
            # the rank entry so repeat applications skip them (the
            # cross-doc planner seeds the same keys across the whole doc
            # population, engine/cross_doc.py)
            if full_round and "head_keys" in rc:
                head_keys = rc["head_keys"]
                head_rank = rc["head_rank"]
                head_ctr64 = rc["head_ctr64"]
                head_parent_pre = rc["head_parent"]
            else:
                _tr = obs.now() if obs.ENABLED else 0
                hf = run_head_fields(plan, batch_rank, ta, tc, pa, pc)
                head_keys = hf["head_keys"]
                head_rank = hf["head_rank"]
                head_ctr64 = hf["head_ctr64"]
                head_parent_pre = hf["head_parent"]
                if full_round:
                    rc.update(hf)
                if obs.ENABLED:
                    obs.span("plan", "rank_resolve", _tr, args={
                        "doc": self.obj_id, "what": "head_fields",
                        "n_runs": n_runs})
            new_starts = [head_keys]
            new_lens = [run_len]
            new_slots = [plan.head_slot]
        else:
            new_starts, new_lens, new_slots = [], [], []
        if n_res_ins:
            ri = rpos[res_is_ins]
            new_starts.append(pack_keys(batch_rank[ta[ri]], tc[ri].astype(np.int64)))
            new_lens.append(np.ones(n_res_ins, np.int64))
            new_slots.append(plan.res_new_slot[res_is_ins])
        def decode(key: int) -> str:
            rank, k_ctr = unpack_key(key)
            return make_elem_id(self.actor_table[rank], k_ctr)

        if new_starts:
            try:
                merged_index = base_index.merge(
                    np.concatenate(new_starts), np.concatenate(new_lens),
                    np.concatenate(new_slots))
            except DuplicateElemId as e:
                raise ValueError(
                    f"Duplicate list element ID {decode(e.key)} "
                    f"in {self.obj_id}") from None
        else:
            merged_index = base_index

        _tq = obs.now() if obs.ENABLED else 0
        if learned_index.learned_index_enabled() \
                and not learned_index.RANGE_SITE.demoted:
            # learned fast path: one batched probe for every reference of
            # the round (exact results; misses fall back and are counted).
            # The dominant serving shape — a pure-runs round with a
            # sub-vector-width parent column against a single-affine-range
            # index — resolves inline in scalars (three int ops per key);
            # everything else goes through the batched model resolver.
            got = None
            if not len(rpos) and 0 < n_runs <= 4:
                sc = getattr(merged_index, "scalar_affine", None)
                got = sc(head_parent_pre[1]) if sc is not None else None
            if got is not None:
                slots_l, found_l = got
                is_head0 = head_parent_pre[0]
                run_parent_slot = np.empty(n_runs, np.int64)
                for i in range(n_runs):
                    if is_head0[i]:
                        run_parent_slot[i] = 0
                    elif found_l[i]:
                        run_parent_slot[i] = slots_l[i]
                    else:
                        raise ValueError(
                            "ins references unknown parent element "
                            f"{decode(int(head_parent_pre[1][i]))} "
                            f"in {self.obj_id}")
                res_parent_slot = res_target_slot = res_is_assign = None
            else:
                run_parent_slot, res_parent_slot, res_target_slot = \
                    _resolve_refs_learned(
                        merged_index, head_parent_pre, n_runs, rpos,
                        res_is_ins, n_res_ins, batch_rank, ta, tc, pa,
                        pc, decode, self.obj_id)
                res_is_assign = ~res_is_ins if len(rpos) else None
        else:
            # exact comparator path (AMTPU_LEARNED_INDEX=0 / demoted),
            # kept verbatim
            def resolve_parent(p_actor, p_ctr, pre=None):
                """Parent refs -> slots (HEAD_PARENT -> slot 0). `pre`
                is a cached (is_head, packed keys) pair — the
                doc-interning-keyed half of the resolution; only the
                index lookup is per-state."""
                if pre is None:
                    is_head = p_actor == HEAD_PARENT
                    keys = pack_keys(
                        batch_rank[np.where(is_head, 0, p_actor)],
                        p_ctr.astype(np.int64))
                else:
                    is_head, keys = pre
                slots, found = merged_index.lookup(keys)
                missing = ~(found | is_head)
                if missing.any():
                    raise ValueError(
                        "ins references unknown parent element "
                        f"{decode(int(keys[np.flatnonzero(missing)[0]]))} "
                        f"in {self.obj_id}")
                return np.where(is_head, 0, slots)

            if n_runs:
                run_parent_slot = resolve_parent(None, None,
                                                 pre=head_parent_pre)
            else:
                run_parent_slot = np.empty(0, np.int64)

            res_parent_slot = res_target_slot = None
            if len(rpos):
                res_parent_slot = np.zeros(len(rpos), np.int64)
                if n_res_ins:
                    res_parent_slot[res_is_ins] = resolve_parent(
                        pa[rpos[res_is_ins]], pc[rpos[res_is_ins]])
                res_is_assign = ~res_is_ins
                res_target_slot = np.zeros(len(rpos), np.int64)
                if res_is_assign.any():
                    ai = rpos[res_is_assign]
                    keys = pack_keys(batch_rank[ta[ai]],
                                     tc[ai].astype(np.int64))
                    slots, found = merged_index.lookup(keys)
                    if not found.all():
                        bad = int(keys[np.flatnonzero(~found)[0]])
                        raise ValueError(
                            f"assignment to unknown element {decode(bad)} "
                            f"in {self.obj_id}")
                    res_target_slot[res_is_assign] = slots
        if obs.ENABLED:
            obs.span("plan", "rank_resolve", _tq, args={
                "doc": self.obj_id, "what": "resolve_refs",
                "n_runs": n_runs, "n_res": len(rpos)})

        # --- all validity checks passed: stage packed device inputs. Each
        # host->device transfer pays per-transfer latency (PCIe round trip;
        # ~10^2 ms through the benchmarking tunnel), so the round ships at
        # most three buffers: one (9,R) descriptor matrix, one value blob,
        # and one (8,M) residual matrix ---
        dense = n_runs > 0 and n_res_ins == 0  # new slots form one window
        N = bucket(n_pairs, 256) if n_runs else 0
        needed = base_elems + 1 + (N if dense else n_ins)
        out_cap = max(bucket(needed), base_cap)
        from .._common import check_int32_envelope
        # slots live in int32 device columns; past this the padding bucket
        # itself wraps — fail loudly (shard the doc) instead
        check_int32_envelope("element slot capacity", out_cap)

        desc_dev = blob_dev = None
        ascii_clear = False
        if n_runs:
            from ..ops.ingest import (DESC_META, META_BASE_SLOT,
                                      META_N_ELEMS, META_N_RUNS)
            R = bucket(n_runs, 64)
            # descriptor template: 7 of the 9 rows plus two meta slots are
            # pure functions of the op columns + this doc's interning —
            # only the head/parent SLOT rows and the base-slot meta encode
            # the document's pre-round element count. Cache the template
            # with the rank entry; each repeat application pays one
            # (9, R) copy + two row fills.
            tmpl = rc.get("desc_tmpl") if full_round else None
            if tmpl is None:
                tmpl = np.zeros((9, R), np.int32)
                tmpl[DESC_ELEM_BASE] = N          # padding sentinel
                tmpl[DESC_CTR0, :n_runs] = tc[hpos]
                tmpl[DESC_ACTOR, :n_runs] = head_rank
                tmpl[DESC_WIN_ACTOR, :n_runs] = row_actor_rank[op_row[hpos]]
                tmpl[DESC_WIN_SEQ, :n_runs] = row_seq[op_row[hpos]]
                tmpl[DESC_ELEM_BASE, :n_runs] = np.cumsum(run_len) - run_len
                tmpl[DESC_HAS_VALUE, :n_runs] = 1
                tmpl[DESC_META, META_N_ELEMS] = n_pairs
                tmpl[DESC_META, META_N_RUNS] = n_runs
                if full_round:
                    tmpl.setflags(write=False)
                    rc["desc_tmpl"] = tmpl
            desc = tmpl.copy() if full_round else tmpl
            desc[DESC_HEAD_SLOT, :n_runs] = plan.head_slot
            desc[DESC_PARENT_SLOT, :n_runs] = run_parent_slot
            desc[DESC_META, META_BASE_SLOT] = base_elems + 1
            if not plan.blob_lt_128:
                ascii_clear = True
            # the padded value blob is base- AND doc-independent: stage it
            # h2d once per batch and reuse the (immutable, never-donated)
            # device buffer across every application — at headline scale
            # it is the plan's largest transfer
            sb = (getattr(b, "_staged_blob", None)
                  if full_round and staged_mode else None)
            if sb is not None and sb[0] == N:
                blob_dev = sb[1]
            else:
                blob = np.zeros(N, np.uint8 if plan.blob_lt_256
                                else np.int32)
                blob[:n_pairs] = plan.blob
                blob_dev = st(blob)
                if full_round and staged_mode:
                    b._staged_blob = (N, blob_dev)
            desc_dev = st(desc)

        res_dev = res_host = None
        n_res = len(rpos)
        if n_res:
            M = bucket(n_res, 128)
            res = np.zeros((8, M), np.int32)
            res[RES_KIND] = -1
            res[RES_SLOT] = out_cap
            res[RES_NEW_SLOT] = out_cap
            res[RES_KIND, :n_res] = res_kind
            res[RES_SLOT, :n_res] = np.where(
                res_is_ins, res_parent_slot, res_target_slot)
            res[RES_NEW_SLOT, :n_res] = np.where(
                res_is_ins, plan.res_new_slot, out_cap)
            res[RES_CTR, :n_res] = tc[rpos]
            res[RES_ACTOR, :n_res] = batch_rank[ta[rpos]]
            res_vals = val64[rpos]
            if not np.logical_or(
                    res_kind != KIND_SET, (res_vals >= 0) & (res_vals < 128)
            ).all():
                ascii_clear = True
            res[RES_VALUE, :n_res] = np.clip(res_vals, -2**31, 2**31 - 1)
            res[RES_WIN_ACTOR, :n_res] = row_actor_rank[op_row[rpos]]
            res[RES_WIN_SEQ, :n_res] = row_seq[op_row[rpos]]
            res_dev = st(res)
            # host columns the slow register path needs at execute time
            res_host = (res_kind, res_vals, row_actor_rank[op_row[rpos]],
                        row_seq[op_row[rpos]])
        elif n_runs == 0:
            return None, shadow

        # inserted chain-heads of the round — run heads + residual inserts,
        # with parent slot and Lamport key. ONE source of truth for both the
        # device chain-break inputs (the touch matrix / fused dense breaks)
        # and the host segment mirror, so the two can never desynchronize.
        ins_slot, ins_par, ins_ctr, ins_act = [], [], [], []
        if n_runs:
            ins_slot.append(plan.head_slot)
            ins_par.append(run_parent_slot)
            ins_ctr.append(head_ctr64)
            ins_act.append(head_rank)
        if n_res_ins:
            ri = rpos[res_is_ins]
            ins_slot.append(plan.res_new_slot[res_is_ins])
            ins_par.append(res_parent_slot[res_is_ins])
            ins_ctr.append(tc[ri].astype(np.int64))
            ins_act.append(batch_rank[ta[ri]])

        # chain bits of elements that lost Lamport-max-child status to this
        # round's inserts (R-sized; keeps materialize census-free). The
        # dense path's breaks are fused into expand_runs_dense_packed, so
        # only mixed rounds stage a touch matrix.
        touch_dev = None
        if not dense and ins_par:
            arr_p = np.concatenate(ins_par)
            T = bucket(len(arr_p), 64)
            touch = np.zeros((3, T), np.int32)
            touch[1:] = -1
            touch[0, : len(arr_p)] = arr_p
            touch[1, : len(arr_p)] = np.concatenate(ins_ctr)
            touch[2, : len(arr_p)] = np.concatenate(ins_act)
            touch_dev = st(touch)

        # --- host segment mirror: the round's structural effect (new heads
        # + chain breaks) is fully known here; thread it through the shadow
        # and, when the fused planned materialization will run, stage the
        # packed segplan so the device skips the structural S-stage
        # entirely (engine/segments.py) ---
        n_elems_after = base_elems + n_ins
        mirror_after = None
        mc_entry = None
        if base_mirror is not None and n_ins == 0:
            mirror_after = base_mirror  # no structural change (del/set/inc)
        elif base_mirror is not None:
            # per-batch mirror cache: the post-round segment structure is
            # a pure function of (base mirror content, resolved parent
            # slots, run-head Lamport keys) — identical across replica
            # fan-out and bench reps. The token digests exactly those
            # inputs; the planned-materialize checksum verify at the
            # scalar sync (engine/segments.py module doc) already guards
            # every planned mirror — a stale hit degrades to a rebuilt
            # mirror, never to corruption. Entries hold COPIES because
            # remap_actors mutates mirrors in place.
            mc_token = None
            if full_round and n_runs and not n_res_ins:
                from ..ops.ingest import mix32_np

                def _digest(arr):
                    return int(np.uint32(
                        mix32_np(arr).sum(dtype=np.uint32)))
                mc_token = (base_elems, base_mirror.n_segs,
                            base_mirror.head_checksum(),
                            base_mirror.aux_checksum(),
                            _digest(run_parent_slot), _digest(head_rank),
                            _digest(head_ctr64))
                # the cache lives on the batch's columnar companion when
                # one exists: the cross-doc planner shares ONE cols
                # object across every batch of a planning group, so the
                # whole doc population pays one mirror apply_round (the
                # token digests every input, so a mismatched doc state
                # degrades to a recompute, never to corruption)
                mc_holder = cols if cols is not None else b
                mc = getattr(mc_holder, "_mirror_cache", None)
                if mc is not None and mc[0] == mc_token:
                    mc_entry = mc
                    mirror_after = mc[1].copy()
            if mirror_after is None:
                try:
                    mirror_after = base_mirror.apply_round(
                        np.concatenate(ins_slot), np.concatenate(ins_par),
                        np.concatenate(ins_ctr), np.concatenate(ins_act),
                        n_elems_after, merged_index.slot_to_key)
                except Exception:
                    logger.warning(
                        "segment-mirror planning failed for %s; falling "
                        "back to the self-contained materialize kernel",
                        self.obj_id, exc_info=True)
                    mirror_after = None
                if mc_token is not None and mirror_after is not None:
                    mc_entry = (mc_token, mirror_after.copy(), {})
                    mc_holder._mirror_cache = mc_entry

        seg_plan_dev = None
        seg_S = 0
        if (self.prefer_planned and mirror_after is not None and dense
                and n_res == 0
                and self.eager_materialize and self.use_condensed):
            # same graceful degradation as apply_round above: a corrupted
            # mirror must not abort the whole prepare — the round can still
            # commit via the self-contained kernel
            try:
                seg_S = bucket(mirror_after.n_segs + 2, 64)
                sp_key = (seg_S, n_elems_after)
                if (mc_entry is not None and staged_mode
                        and sp_key in mc_entry[2]):
                    # the staged (immutable, never-donated) segplan device
                    # buffer is shared across applications outright
                    seg_plan_dev = mc_entry[2][sp_key]
                else:
                    seg_plan_dev = st(
                        mirror_after.plan(seg_S, n_elems_after))
                    if mc_entry is not None and staged_mode:
                        mc_entry[2][sp_key] = seg_plan_dev
            except Exception:
                logger.warning(
                    "segplan packing failed for %s; falling back to the "
                    "self-contained materialize kernel", self.obj_id,
                    exc_info=True)
                mirror_after = None
                seg_plan_dev = None
                seg_S = 0

        touched = None
        if res_target_slot is not None and res_is_assign.any():
            touched = np.unique(res_target_slot[res_is_assign])
        exec_plan = _RoundExec(
            index_after=merged_index, n_elems_after=n_elems_after,
            out_cap=out_cap, dense=dense, n_runs=n_runs,
            n_res=n_res, desc=desc_dev,
            blob=blob_dev, res=res_dev, touch=touch_dev,
            ascii_clear=ascii_clear, res_host=res_host,
            seg_inc=3 * (n_runs + n_res_ins) + 2,
            n_elems_dev=(jnp.asarray(np.int32(n_elems_after))
                         if staged_mode else None),
            mirror_after=mirror_after, seg_plan=seg_plan_dev, seg_S=seg_S,
            touched_slots=touched,
            n_index_merges=1 if new_starts else 0)
        return exec_plan, (n_elems_after, merged_index, out_cap,
                           mirror_after)

    def _begin_round_host(self, plan: "_RoundExec"):
        """Pre-dispatch host bookkeeping of one committed round, shared by
        the solo `_execute_plan` and the stacked multi-object executor
        (engine/stacked.py)."""
        self.index = plan.index_after
        self.seg_mirror = plan.mirror_after
        self._mat_keep_gen = None  # a new round stales any prior fused cache

    def _finish_round_host(self, plan: "_RoundExec"):
        """Post-dispatch host bookkeeping of one committed round (counts,
        ascii/caches, segment bound, dirty-span feed, invalidation) —
        shared by `_execute_plan` and the stacked executor."""
        self.n_elems = plan.n_elems_after
        # staged device mirror of the element count (solo path only; the
        # stacked planner skips the per-doc scalar upload and the next
        # materialize re-stages it)
        self._n_elems_dev = ((plan.n_elems_after, plan.n_elems_dev)
                             if plan.n_elems_dev is not None else None)
        if plan.ascii_clear:
            self.all_ascii = False
            # incremental pulls are ascii-gated for good: drop the cache
            # now or the dead entry would keep the touched-slot feed
            # growing for the rest of the document's life
            self._text_cache = None
            self._touched_old = []
        # every inserted run/element can split at most one existing segment;
        # with a live mirror the exact count is known
        if plan.mirror_after is not None:
            self._seg_bound = max(plan.mirror_after.n_segs, 1)
        else:
            self._seg_bound += plan.seg_inc
        if plan.touched_slots is not None and self._text_cache is not None:
            # assign targets are pre-round slots: the text-cache spans they
            # fall in must re-pull (visibility/content may have changed)
            self._touched_old.append(plan.touched_slots)
        self._invalidate()

    def _execute_plan(self, b: TextChangeBatch, plan: "_RoundExec"):
        """Commit a planned round: index/count bookkeeping + device
        dispatches (+ the host slow-register path when flagged)."""
        import jax.numpy as jnp
        from ..ops import ingest as K
        from ..ops.ingest import bucket, donation_enabled

        out_cap = plan.out_cap
        self._begin_round_host(plan)
        dev = self._ensure_dev()
        tables = tuple(dev[k] for k in self._TABLE_KEYS)

        # streaming-tier donation: once the first donated kernel consumes
        # the live tables, a raising step before `self._dev` is rebound
        # leaves NO valid device state — mark the doc lost so every later
        # access fails loudly (see _ensure_dev) instead of corrupting
        donate = self.donate_buffers and donation_enabled()
        try:
            fused_mat = None
            slow_info_np = None
            if (plan.n_runs and plan.dense and self.eager_materialize
                    and self.use_condensed and plan.n_res == 0):
                # the pipelined ring's steady-state commit: the fused
                # tier routes it through the ISSUE-19 ring-commit
                # megakernels (expansion scan on the mode ladder +
                # materialization in one program); the XLA pair below
                # stays verbatim as the comparator per the PR-5/7 flag
                # discipline
                from ..ops import fused_round as F
                use_fused = self.fused_rounds and F.fused_rounds_enabled()
                if plan.seg_plan is not None:
                    # fused merge + HOST-PLANNED materialization: no
                    # device sort, no pointer doubling (engine/segments)
                    S = plan.seg_S
                    _, L, as_u8 = self._mat_params(
                        seg_bound=S, n_elems=plan.n_elems_after,
                        cap=out_cap,
                        ascii_=self.all_ascii and not plan.ascii_clear)
                    if use_fused:
                        fn = (F.fused_commit_round_planned_donated
                              if donate else F.fused_commit_round_planned)
                        self._count_dispatch(label="fused_commit_planned")
                        out = fn(*tables, plan.desc, plan.blob,
                                 plan.seg_plan, out_cap=out_cap, S=S,
                                 as_u8=as_u8, L=L, mode=F.fused_mode())
                    else:
                        fn = (K.merge_and_materialize_dense_planned_donated
                              if donate
                              else K.merge_and_materialize_dense_planned)
                        self._count_dispatch(
                            label="merge_materialize_planned")
                        out = fn(*tables, plan.desc, plan.blob,
                                 plan.seg_plan, out_cap=out_cap, S=S,
                                 as_u8=as_u8, L=L)
                else:
                    S, L, as_u8 = self._mat_params(
                        seg_bound=self._seg_bound + plan.seg_inc,
                        n_elems=plan.n_elems_after, cap=out_cap,
                        ascii_=self.all_ascii and not plan.ascii_clear)
                    if use_fused:
                        fn = (F.fused_commit_round_donated if donate
                              else F.fused_commit_round)
                        self._count_dispatch(label="fused_commit_round")
                        out = fn(*tables, plan.desc, plan.blob,
                                 out_cap=out_cap, S=S, as_u8=as_u8, L=L,
                                 mode=F.fused_mode())
                    else:
                        fn = (K.merge_and_materialize_dense_donated
                              if donate else K.merge_and_materialize_dense)
                        self._count_dispatch(label="merge_materialize_dense")
                        out = fn(*tables, plan.desc, plan.blob,
                                 out_cap=out_cap, S=S, as_u8=as_u8, L=L)
                tables = out[:9]
                fused_mat = (out[9], out[10], S)
            else:
                # every other round shape — dense/sparse expansion,
                # residual placement + register fast path, chain breaks —
                # is ONE fused device program (apply_mixed_round): one
                # dispatch per committed round, and XLA fuses the phases
                # instead of round-tripping tables between three programs
                from ..ops import fused_round as F
                with_res = bool(plan.n_res)
                use_fused = self.fused_rounds and F.fused_rounds_enabled()
                if with_res:
                    # conflict slots are built at execute time (NOT staged
                    # at plan time): an earlier round of the same prepared
                    # batch may have minted conflicts through the slow path
                    Kc = bucket(max(len(self.conflicts), 1), 64)
                    conflict_slots = np.full(Kc, out_cap, np.int32)
                    if self.conflicts:
                        conflict_slots[: len(self.conflicts)] = \
                            list(self.conflicts)
                    conflict_dev = jnp.asarray(conflict_slots)
                elif use_fused:
                    conflict_dev = F.round_dummies(out_cap)[3]
                else:
                    conflict_dev = K._dummy_i32()
                if use_fused:
                    # ISSUE-17 fused round: the flag-free core — every
                    # phase runs over padding-convention no-ops, so one
                    # trace per capacity bucket replaces the
                    # (expand_kind, with_res, with_touch) trace lattice
                    dd, db, dr, _dc, dt = F.round_dummies(out_cap)
                    fn = (F.fused_mixed_round_donated if donate
                          else F.fused_mixed_round)
                    self._count_dispatch(label="fused_mixed_round")
                    out = fn(*tables,
                             plan.desc if plan.desc is not None else dd,
                             plan.blob if plan.blob is not None else db,
                             plan.res if plan.res is not None else dr,
                             conflict_dev,
                             plan.touch if plan.touch is not None else dt,
                             out_cap=out_cap, mode=F.fused_mode())
                else:
                    expand_kind = (("dense" if plan.dense else "sparse")
                                   if plan.n_runs else "none")
                    with_touch = plan.touch is not None
                    dummy = K._dummy_i32()
                    fn = (K.apply_mixed_round_donated if donate
                          else K.apply_mixed_round)
                    self._count_dispatch(label="apply_mixed_round")
                    out = fn(*tables,
                             plan.desc if plan.desc is not None else dummy,
                             plan.blob if plan.blob is not None else dummy,
                             plan.res if plan.res is not None else dummy,
                             conflict_dev,
                             plan.touch if plan.touch is not None else dummy,
                             out_cap=out_cap, expand_kind=expand_kind,
                             with_res=with_res, with_touch=with_touch)
                tables = out[:9]
                if with_res:
                    # the ONE d2h round trip of the residual path: slow
                    # mask + slots + register state, one packed transfer
                    _ts = obs.now() if obs.ENABLED else 0
                    # full padded buffer bytes: the M-bucketed matrix is
                    # what crosses the link, the n_res slice is a view
                    slow_full = np.asarray(out[9])
                    self._count_sync(label="slow_info_fetch",
                                     dur_ns=(obs.now() - _ts) if _ts
                                     else 0,
                                     d2h_bytes=slow_full.nbytes)
                    slow_info_np = slow_full[:, : plan.n_res]
        except BaseException:
            # poison ONLY when a donated kernel actually consumed the live
            # tables (a trace/compile failure consumes nothing and stays
            # retryable — the tables are still valid)
            if donate and K.buffers_consumed(tables):
                self._device_lost = True
                self._dev = None
            raise

        self._dev = dict(zip(self._TABLE_KEYS, tables))
        self._cap = out_cap
        self._finish_round_host(plan)
        if fused_mat is not None:
            # the fused program already materialized codes for this state;
            # the seed-generation stamp lets it survive the batch driver's
            # trailing invalidation (no mutation happens in between)
            self._mat = (fused_mat[0], fused_mat[1])
            self._mat_S = fused_mat[2]
            self._mat_keep_gen = self._gen

        if slow_info_np is not None and slow_info_np[0].any():
            res_kind, res_vals, res_rank, res_seq = plan.res_host
            idxs = np.nonzero(slow_info_np[0])[0]
            self._apply_slow(
                b, slow_info_np[1][idxs], res_kind[idxs], res_vals[idxs],
                res_rank[idxs], res_seq[idxs], slot_cap=self._cap,
                reg_state=tuple(slow_info_np[r][idxs] for r in range(2, 7)))

    # ------------------------------------------------------------------
    # materialization (device kernels)
    # ------------------------------------------------------------------

    def _materialize(self, with_pos: bool = True):
        """Cached device materialization -> (pos?, codes, scalars) with
        scalars = [n_vis, n_segs] still ON DEVICE (dispatch only — no sync;
        fetch through `_scalars()`). `with_pos=False` runs the cheaper
        codes-only kernel (enough for `text()`); codes are uint8 when the
        doc is all-7-bit. Correct by construction: `_seg_bound` is a proven
        upper bound on n_segs (each insert splits at most one segment), so
        the S bucket always fits — `_scalars()` still verifies and retries
        defensively."""
        if self._mat is not None and (len(self._mat) == 3 or not with_pos):
            return self._mat
        S = self._mat_params()[0]
        self._mat = self._run_materialize(with_pos, S)
        self._mat_S = S
        self._scal = None
        return self._mat

    def _mat_params(self, seg_bound=None, n_elems=None, cap=None,
                    ascii_=None):
        """(S, L, as_u8) kernel sizing, shared by the lazy materialize and
        the fused eager path (which sizes for post-round state)."""
        from ..ops.ingest import bucket
        seg_bound = self._seg_bound if seg_bound is None else seg_bound
        n_elems = self.n_elems if n_elems is None else n_elems
        cap = self._cap if cap is None else cap
        ascii_ = self.all_ascii if ascii_ is None else ascii_
        # the kernel slices the columns to the live-window bucket L:
        # capacity can exceed the live prefix by up to 50% and every pass
        # scales with operand length
        return (bucket(seg_bound + 2, 64), min(bucket(n_elems + 2), cap),
                ascii_)

    def _run_materialize(self, with_pos: bool, S: int):
        import jax.numpy as jnp
        from ..ops.ingest import (materialize_codes,
                                  materialize_codes_planned,
                                  materialize_text,
                                  materialize_text_planned)
        dev = self._ensure_dev()
        _, L, as_u8 = self._mat_params()
        # use the staged device mirror of n_elems when current (avoids a
        # commit-path host->device scalar upload)
        if self._n_elems_dev and self._n_elems_dev[0] == self.n_elems:
            n = self._n_elems_dev[1]
        else:
            n = np.int32(self.n_elems)
        self._count_dispatch(label="materialize")  # one materialize program
        if (self.prefer_planned and self.seg_mirror is not None
                and self.seg_mirror.n_segs + 2 <= S):
            # host-planned structure: device skips the structural S-stage
            # (verified against the chain bits at the _scalars sync)
            segplan = jnp.asarray(self.seg_mirror.plan(S, self.n_elems))
            fn = (materialize_text_planned if with_pos
                  else materialize_codes_planned)
            return fn(dev["parent"], dev["ctr"], dev["actor"],
                      dev["value"], dev["has_value"], dev["chain"], n,
                      segplan, S=S, as_u8=as_u8, L=L)
        fn = materialize_text if with_pos else materialize_codes
        return fn(dev["parent"], dev["ctr"], dev["actor"], dev["value"],
                  dev["has_value"], dev["chain"], n,
                  S=S, as_u8=as_u8, L=L)

    def _scalars(self) -> np.ndarray:
        """Fetch [n_vis, n_segs] of the cached materialization (the one
        device->host sync of the read path); verifies the S bucket actually
        fit and re-runs bigger if the host bound was ever stale."""
        if self._scal is None:
            from ..ops.ingest import bucket
            if self._mat is None:
                self._materialize(with_pos=False)
            heals = 0
            while True:
                scalars = np.asarray(self._mat[-1])
                self._count_sync(label="scalars_fetch",  # the read path's
                                 # one device sync
                                 d2h_bytes=scalars.nbytes)
                n_segs = int(scalars[1])
                if len(scalars) == 5:
                    # planned materialization: verify the host mirror against
                    # the device-derived chain-bit count + head-slot hash +
                    # (parent, ctr, actor) hash — together these pin the
                    # full linearization inputs; on mismatch rebuild the
                    # mirror from the real chain bits (one attempt), else
                    # degrade to the self-contained kernel
                    ok = (int(scalars[2]) == n_segs
                          and self.seg_mirror is not None
                          and int(scalars[3])
                          == self.seg_mirror.head_checksum()
                          and int(scalars[4])
                          == self.seg_mirror.aux_checksum())
                    if not ok:
                        logger.warning(
                            "segment mirror diverged from device chain bits "
                            "for %s (plan n_segs=%d device n_segs=%d); "
                            "rebuilding mirror and re-materializing",
                            self.obj_id, n_segs, int(scalars[2]))
                        heals += 1
                        # one rebuild attempt: a rebuilt mirror matches the
                        # chain bits by construction, so a second mismatch
                        # means something deeper is wrong — degrade
                        self.seg_mirror = (self._rebuild_mirror()
                                           if heals == 1 else None)
                        self._seg_bound = max(int(scalars[2]), 1)
                        S = bucket(int(scalars[2]) + 2, 64)
                        self._mat = self._run_materialize(
                            len(self._mat) == 3, S)
                        self._mat_S = S
                        continue
                if n_segs + 2 <= self._mat_S:
                    break
                # bound was stale (defensive; should be unreachable)
                S = bucket(n_segs + 2, 64)
                self._mat = self._run_materialize(len(self._mat) == 3, S)
                self._mat_S = S
            self._seg_bound = n_segs  # tighten for the next materialize
            self._scal = scalars
        return self._scal

    def _rebuild_mirror(self) -> Optional[SegmentMirror]:
        """Heal path: reconstruct the segment mirror from the real device
        chain/parent columns (one small fetch; None if that fails too)."""
        try:
            dev = self._ensure_dev()
            return SegmentMirror.rebuild(
                np.asarray(dev["chain"]), np.asarray(dev["parent"]),
                self.n_elems, self.index.slot_to_key)
        except Exception:
            logger.warning("segment mirror rebuild failed for %s",
                           self.obj_id, exc_info=True)
            return None

    def _positions(self) -> np.ndarray:
        if self._pos_cache is None:
            if self.n_elems == 0:
                self._pos_cache = np.full(1, -1, np.int32)
            elif self.use_condensed:
                self._materialize(with_pos=True)
                self._scalars()  # verify the S bucket fit (re-runs if not)
                pos_np = np.asarray(self._mat[0])
                self._count_sync(label="positions_fetch",
                                 d2h_bytes=pos_np.nbytes)
                self._pos_cache = pos_np[: self.n_elems + 1]
            else:
                self._pos_cache = self._positions_full()
        return self._pos_cache

    def _positions_full(self) -> np.ndarray:
        import jax.numpy as jnp
        from ..ops.linearize import pad_capacity, rga_linearize
        h = self._mirrors()
        n = self.n_elems + 1
        cap = pad_capacity(n)

        def padded(arr):
            if len(arr) >= cap:
                return arr[:cap]
            out = np.zeros(cap, arr.dtype)
            out[: len(arr)] = arr
            return out

        valid = np.zeros(cap, bool)
        valid[:n] = True
        self._count_dispatch(label="rga_linearize")
        pos = rga_linearize(jnp.asarray(padded(h["parent"])),
                            jnp.asarray(padded(h["ctr"])),
                            jnp.asarray(padded(h["actor"])),
                            jnp.asarray(valid))
        pos_np = np.asarray(pos)
        self._count_sync(label="rga_linearize", d2h_bytes=pos_np.nbytes)
        return pos_np[:n]

    def visible_order(self) -> np.ndarray:
        """Slots of visible elements in list order."""
        n = self.n_elems + 1
        if n <= 1:
            return np.empty(0, np.int64)
        pos = self._positions()
        h = self._mirrors()
        # pos[1:] is a permutation of 0..n-2: invert it (counting sort)
        inv = np.empty(n - 1, np.int64)
        inv[pos[1:]] = np.arange(1, n)
        return inv[h["has_value"][inv]]

    def text(self) -> str:
        if not obs.ENABLED:
            return self._text_pull()
        _t0 = obs.now()
        out = self._text_pull()
        # span args carry the pull mode + byte counts the incremental
        # tier reports (pull_stats) — the d2h story per pull, in-trace
        obs.span("pull", "text", _t0,
                 args={"doc": self.obj_id, **(self.pull_stats or {})})
        return out

    def _text_pull(self) -> str:
        if self.n_elems == 0:
            self.pull_stats = {"mode": "empty", "span_bytes": 0,
                               "n_spans": 0}
            return ""
        if self.use_condensed:
            cache = self._text_cache
            if cache is not None and cache["gen"] == self._gen:
                # nothing mutated since the last pull: zero device work
                self.pull_stats = {"mode": "cached", "span_bytes": 0,
                                   "n_spans": 0}
                return cache["text"]
            if cache is not None and self._can_incremental():
                out = self._text_incremental()
                if out is not None:
                    return out
            self._materialize(with_pos=False)
            n_vis = int(self._scalars()[0])   # may re-run w/ bigger S
            codes_np = np.asarray(self._mat[-2])      # the O(doc) codes pull
            self._count_sync(label="codes_pull",
                             d2h_bytes=codes_np.nbytes)
            values = codes_np[:n_vis]
            self.pull_stats = {"mode": "full",
                               "span_bytes": int(values.nbytes),
                               "n_spans": 1}
            if values.dtype == np.uint8:
                text = values.tobytes().decode("ascii")
                self._seed_text_cache(text)
                return text
        else:
            order = self.visible_order()
            values = self._mirrors()["value"][order]
            self.pull_stats = {"mode": "full",
                               "span_bytes": int(values.nbytes),
                               "n_spans": 1}
        if len(values) == 0:
            return ""
        if (values < 0).any():
            # rich (non-single-char) values spliced in — rare path
            return "".join(
                chr(v) if v >= 0 else str(self.value_pool[-int(v) - 1]["value"])
                for v in values)
        if values.max(initial=0) < 128:
            return values.astype(np.uint8).tobytes().decode("ascii")
        return "".join(map(chr, values.astype(np.uint32)))

    # ------------------------------------------------------------------
    # incremental text pull (host cache + dirty spans)
    # ------------------------------------------------------------------

    def _can_incremental(self) -> bool:
        return (self.incremental_pull and self.use_condensed
                and self.seg_mirror is not None and self.all_ascii)

    def _seg_positions(self, segplan: np.ndarray, vis: np.ndarray,
                       n_segs: int) -> np.ndarray:
        """Visible-text start offset of each segment (slot order), from
        the mirror's position->segment permutation + per-seg vis counts."""
        perm = segplan[1][:n_segs].astype(np.int64)   # position order, 1-based
        vis_p = vis[perm - 1]
        start_p = np.cumsum(vis_p) - vis_p
        start = np.empty(n_segs, np.int64)
        start[perm - 1] = start_p
        return start

    def _fetch_seg_vis(self, segplan_dev, S: int) -> np.ndarray:
        """One S-sized d2h fetch: per-segment visible counts (slot order,
        entries 1..n_segs)."""
        from ..ops.ingest import segment_visible_counts
        dev = self._ensure_dev()
        _, L, _ = self._mat_params()
        if self._n_elems_dev and self._n_elems_dev[0] == self.n_elems:
            n = self._n_elems_dev[1]
        else:
            n = np.int32(self.n_elems)
        self._count_dispatch(label="segment_visible_counts")
        counts = np.asarray(segment_visible_counts(
            dev["has_value"], n, segplan_dev, S=S, L=L))
        self._count_sync(label="segment_visible_counts",
                         d2h_bytes=counts.nbytes)
        return counts

    def _seed_text_cache(self, text: str):
        """Record the per-segment table for the NEXT pull to diff against
        (only worthwhile on docs big enough that pulls dominate)."""
        self._text_cache = None
        self._touched_old = []
        if (not self._can_incremental()
                or self.n_elems < self.incremental_pull_min):
            return
        import jax.numpy as jnp
        from ..ops.ingest import bucket
        mirror = self.seg_mirror
        n_segs = mirror.n_segs
        if n_segs == 0:
            return
        try:
            S = bucket(n_segs + 2, 64)
            segplan = mirror.plan(S, self.n_elems)
            sv = self._fetch_seg_vis(jnp.asarray(segplan), S)
            vis = sv[1: n_segs + 1].astype(np.int64)
            if int(vis.sum()) != len(text):
                return   # stale mirror relative to the pulled text
            self._text_cache = dict(
                text=text, heads=mirror.heads[1:].copy(), vis=vis,
                start=self._seg_positions(segplan, vis, n_segs),
                n_elems=self.n_elems, gen=self._gen)
        except Exception:
            logger.warning("text-cache seeding failed for %s; pulls stay "
                           "full", self.obj_id, exc_info=True)
            self._text_cache = None

    def _text_incremental(self) -> Optional[str]:
        """Pull only the spans that changed since the cached text.

        Reconciliation: segments are slot-contiguous chain runs; inserts
        only ever mint NEW heads (every run head / residual insert starts
        chain-clear), so an old segment never absorbs new slots — it can
        only SPLIT. A new segment is therefore (a) brand-new content
        (head > cached n_elems): pull; (b) a piece of a cached segment
        that a residual assign touched: pull; (c) an untouched piece of a
        cached segment: its content is a substring of the cached text at
        the piece's cumulative visible offset — no bytes move. All dirty
        spans ship d2h as ONE `gather_spans` transfer of O(edits) bytes.
        Returns None to fall back to the full pull (any inconsistency —
        e.g. visibility moved without a recorded touch — degrades, never
        corrupts; parity is pinned against the full path in
        tests/test_incremental_pull.py)."""
        import jax.numpy as jnp
        from ..ops.ingest import bucket
        from ..ops.linearize import gather_spans

        cache = self._text_cache
        self._materialize(with_pos=False)
        n_vis = int(self._scalars()[0])      # verifies/heals the mirror
        mirror = self.seg_mirror
        if mirror is None or not self.all_ascii:
            return None                      # healed into degraded mode
        codes = self._mat[-2]
        if codes.dtype != jnp.uint8:
            return None
        n_segs = mirror.n_segs
        if n_segs == 0 or n_vis == 0:
            return None
        S = bucket(n_segs + 2, 64)
        try:
            segplan = mirror.plan(S, self.n_elems)
        except Exception:
            return None
        sv = self._fetch_seg_vis(jnp.asarray(segplan), S)
        vis = sv[1: n_segs + 1].astype(np.int64)
        if int(vis.sum()) != n_vis:
            return None
        heads = mirror.heads[1:]
        start = self._seg_positions(segplan, vis, n_segs)

        old_heads = cache["heads"]
        old_vis = cache["vis"]
        old_start = cache["start"]
        old_n = cache["n_elems"]
        old_text = cache["text"]

        touched = (np.unique(np.concatenate(self._touched_old))
                   if self._touched_old else np.empty(0, np.int64))
        t_seg = (np.unique(np.searchsorted(old_heads, touched,
                                           side="right") - 1)
                 if len(touched) else np.empty(0, np.int64))

        is_old = heads <= old_n
        old_idx = np.searchsorted(old_heads, heads, side="right") - 1
        dirty = ~is_old
        if len(t_seg):
            dirty = dirty | (is_old & np.isin(old_idx, t_seg))

        # piece offsets: new segments with old heads partition their old
        # segment in slot order; an untouched old segment's total visible
        # count must be conserved across its pieces, or something moved
        # without a recorded touch -> full pull
        off_map = np.zeros(n_segs, np.int64)
        oh = np.flatnonzero(is_old)
        if len(oh):
            og = old_idx[oh]
            pv = vis[oh]
            cs = np.cumsum(pv) - pv
            grp_start = np.concatenate(([True], og[1:] != og[:-1]))
            base = np.repeat(cs[grp_start], np.diff(np.append(
                np.flatnonzero(grp_start), len(og))))
            off_map[oh] = cs - base
            grp_end = np.append(grp_start[1:], True)
            tot = (cs + pv)[grp_end] - cs[grp_start]
            og_u = og[grp_start]
            check = (~np.isin(og_u, t_seg) if len(t_seg)
                     else np.ones(len(og_u), bool))
            if (tot[check] != old_vis[og_u[check]]).any():
                return None

        order = np.argsort(start, kind="stable")   # position order
        d_pos = order[dirty[order] & (vis[order] > 0)]
        span_starts = start[d_pos]
        span_lens = vis[d_pos]
        n_spans = len(d_pos)
        if n_spans:
            total = int(span_lens.sum())
            P = bucket(total, 256)
            Db = bucket(n_spans, 64)
            spans_np = np.zeros((2, Db), np.int32)
            spans_np[0, :n_spans] = span_starts
            spans_np[1, :n_spans] = span_lens
            self._count_dispatch(label="gather_spans")
            buf_full = np.asarray(gather_spans(codes, jnp.asarray(spans_np),
                                               P=P))
            self._count_sync(label="gather_spans",
                             d2h_bytes=buf_full.nbytes)
            buf = buf_full[:total]
            pulled = buf.tobytes().decode("ascii")
            span_bytes = int(buf.nbytes)
        else:
            pulled = ""
            span_bytes = 0
        d_off = np.cumsum(span_lens) - span_lens
        buf_at = dict(zip(d_pos.tolist(), d_off.tolist()))

        pieces = []
        for k in order.tolist():
            v = int(vis[k])
            if v == 0:
                continue
            if dirty[k]:
                o = buf_at[k]
                pieces.append(pulled[o: o + v])
            else:
                s0 = int(old_start[old_idx[k]] + off_map[k])
                pieces.append(old_text[s0: s0 + v])
        new_text = "".join(pieces)
        if len(new_text) != n_vis:
            return None
        self.pull_stats = {"mode": "incremental", "span_bytes": span_bytes,
                           "n_spans": int(n_spans),
                           "info_bytes": int(sv.nbytes)}
        self._text_cache = dict(text=new_text, heads=heads.copy(), vis=vis,
                                start=start, n_elems=self.n_elems,
                                gen=self._gen)
        self._touched_old = []
        return new_text

    def _plan_failed(self):
        # a raising round may have partially mutated device tables; the
        # host text cache can no longer be trusted to diff against
        self._text_cache = None
        self._touched_old = []

    def values(self) -> list:
        h = self._mirrors()
        out = []
        for slot in self.visible_order():
            v = int(h["value"][slot])
            if v >= 0:
                out.append(chr(v))
            else:
                out.append(self.value_pool[-v - 1]["value"])
        return out

    def elem_ids(self) -> list:
        h = self._mirrors()
        return [make_elem_id(self.actor_table[h["actor"][s]], int(h["ctr"][s]))
                for s in self.visible_order()]

    def conflicts_at(self, index: int):
        slot = self.visible_order()[index]
        extras = self.conflicts.get(int(slot))
        if not extras:
            return None
        out = {}
        for op in extras:
            v = op["value"]
            out[self.actor_table[op["actor_rank"]]] = (
                chr(v) if v >= 0 else self.value_pool[-v - 1]["value"])
        return out

    def __len__(self) -> int:
        if self.n_elems == 0:
            return 0
        h = self._mirrors()
        return int(h["has_value"][1: self.n_elems + 1].sum())

"""UUID factory — parity with the reference's swappable-factory hook
(/root/reference/src/uuid.js:1-12, test analogue uuid_test.js): the
determinism seam every fuzz/trace suite relies on."""

import re

import automerge_tpu as am
from automerge_tpu import _uuid


def test_default_factory_is_uuid4():
    v = am.uuid()
    assert re.fullmatch(
        r"[0-9a-f]{8}-[0-9a-f]{4}-4[0-9a-f]{3}-[89ab][0-9a-f]{3}"
        r"-[0-9a-f]{12}", v), v
    assert am.uuid() != v                     # fresh value per call


def test_factory_is_swappable_and_resettable():
    counter = {"n": 0}

    def fixed():
        counter["n"] += 1
        return f"fixed-{counter['n']}"

    _uuid.set_factory(fixed)
    try:
        assert am.uuid() == "fixed-1"
        assert am.uuid() == "fixed-2"
    finally:
        _uuid.reset()
    assert re.fullmatch(r"[0-9a-f-]{36}", am.uuid())


def test_minted_object_ids_use_the_factory():
    ids = iter(f"det-{i}" for i in range(100))
    _uuid.set_factory(lambda: next(ids))
    try:
        doc = am.change(am.init("actor"),
                        lambda d: d.__setitem__("m", {"k": 1}))
        obj_id = am.get_object_id(doc["m"])
        assert obj_id.startswith("det-"), obj_id
    finally:
        _uuid.reset()

"""Async checkpoint writer riding the two-phase ingestion seam.

``PipelinedIngestor`` (engine/pipeline.py) established the pattern: heavy
work overlaps device execution on a background thread, and every commit is
generation-checked so a racing mutation degrades to the safe serial path
instead of corrupting state. The checkpoint writer is the read-side twin:

- **Phase 1 (grab)** is a generation-stamped snapshot of an engine doc's
  mutable host state plus references to its immutable device tables
  (:func:`~.engine_codec.grab` — microseconds, no device traffic). The
  worker retries it a bounded number of times when the doc's generation
  moves mid-grab (ingestion committed underneath it).
- **Phase 2 (encode)** — the d2h fetch, hashing, and bundle encoding —
  runs entirely on the worker thread, overlapping subsequent ingestion:
  the grabbed device arrays are immutable (kernels replace, never donate),
  so the captured state stays frozen no matter how far the doc advances.

If every grab attempt conflicts, the handle degrades to a **synchronous
capture**: ``result()`` performs the grab on the calling thread — the
caller invokes it at a commit boundary (after ``flush()``), where it owns
quiescence — and only the encode half still benefits from having been a
separate phase. ``stats`` counts how often each path ran.

Backend states (``DeviceBackendState`` / oracle ``BackendState``) need no
generation protocol at all: they are immutable views, and a state whose
core advanced restores consistency by forking its command-log prefix —
``capture_async`` just ships the whole capture to the worker.
"""

from __future__ import annotations

import threading

from .. import obs
from . import bundle as _bundle
from .engine_codec import CaptureConflict, encode_grab, grab

_ENGINE_DOC_MANIFEST = {"engine": "engine-doc"}


def encode_engine_grab(g: dict) -> bytes:
    """A grab -> standalone engine-doc bundle bytes (deterministic)."""
    frag, arrays = encode_grab(g)
    return _bundle.encode({**_ENGINE_DOC_MANIFEST, "doc": frag,
                           "clock": frag["clock"]}, arrays)


class CheckpointHandle:
    """Future for one capture. ``result()`` blocks until the bundle is
    encoded; on grab-conflict exhaustion it performs the degraded
    synchronous grab on the calling thread."""

    def __init__(self, doc):
        self._doc = doc
        self._done = threading.Event()
        self._data = None
        self._error = None
        self._needs_sync = False

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: float = None) -> bytes:
        if not self._done.wait(timeout):
            raise TimeoutError("checkpoint capture still in flight")
        if self._needs_sync:
            # degraded path: the caller owns quiescence here (commit
            # boundary), so a synchronous grab cannot conflict — and the
            # grab is encoded before any further (possibly donating)
            # commit can consume its buffers, hence inline=True
            _t0 = obs.now() if obs.ENABLED else 0
            self._data = encode_engine_grab(grab(self._doc, inline=True))
            if obs.ENABLED:
                obs.span("ckpt", "capture", _t0, args={
                    "mode": "sync_degraded", "bytes": len(self._data)})
            self._needs_sync = False
            self._error = None
        if self._error is not None:
            raise self._error
        return self._data


class AsyncCheckpointer:
    """Background checkpoint writer for engine docs and backend states.

    One worker thread, lazily started; captures queue FIFO. Contract for
    engine docs mirrors the ingestion pipeline's: the document is mutated
    by one thread (the pipeline caller), grabs race only against commits
    and are generation-checked with bounded retry, and ``result()`` is
    called at a commit boundary."""

    def __init__(self, max_grab_retries: int = 3):
        self._max_retries = max(1, max_grab_retries)
        self._lock = threading.Lock()
        self._thread = None
        self._queue = []
        self._wake = threading.Condition(self._lock)
        self._closing = False
        self.stats = {"async_captures": 0, "grab_conflicts": 0,
                      "sync_fallbacks": 0, "snapshot_serves": 0}

    # -- lifecycle -------------------------------------------------------

    def _ensure_worker(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._worker, name="amtpu-ckpt", daemon=True)
            self._thread.start()

    def close(self):
        with self._wake:
            self._closing = True
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False

    # -- captures --------------------------------------------------------

    def capture_async(self, target) -> CheckpointHandle:
        """Queue a capture of an engine doc or a backend state."""
        handle = CheckpointHandle(target)
        with self._wake:
            if self._closing:
                raise RuntimeError("AsyncCheckpointer is closed")
            self._queue.append((target, handle))
            self._ensure_worker()
            self._wake.notify_all()
        return handle

    @staticmethod
    def capture(target) -> bytes:
        """Synchronous capture (the identity comparator for the async
        path: same target, same bytes)."""
        if _is_engine_doc(target):
            # synchronous: grabbed refs are encoded before returning, so
            # a donation-enabled doc is safe here (inline contract)
            return encode_engine_grab(grab(target, inline=True))
        from .backend_codec import capture_state
        return capture_state(target)

    # -- worker ----------------------------------------------------------

    def _worker(self):
        while True:
            with self._wake:
                while not self._queue and not self._closing:
                    self._wake.wait()
                if not self._queue and self._closing:
                    return
                target, handle = self._queue.pop(0)
            try:
                if _is_engine_doc(target):
                    self._capture_engine(target, handle)
                else:
                    # worker-side backend capture: never walk a live core
                    # another thread mutates — capture a private fork of
                    # the state's command-log prefix instead
                    from .backend_codec import capture_state
                    handle._data = capture_state(target,
                                                 assume_quiescent=False)
                    self.stats["async_captures"] += 1
            except BaseException as exc:   # surfaced via result()
                handle._error = exc
            finally:
                handle._done.set()

    def _capture_engine(self, doc, handle):
        g = None
        _t0 = obs.now() if obs.ENABLED else 0
        for _ in range(self._max_retries):
            try:
                g = grab(doc)
                if g.get("mode") == "snapshot":
                    # zero-coordination read of the doc's cached
                    # commit-boundary state: a mutation (bulk index
                    # merge, stacked apply) was in flight, and instead
                    # of the old busy-wait/retry ladder the grab served
                    # the last consistent snapshot (INTERNALS §16.4)
                    self.stats["snapshot_serves"] += 1
                break
            except CaptureConflict:
                self.stats["grab_conflicts"] += 1
                if obs.ENABLED:
                    obs.event("ckpt", "grab_conflict",
                              args={"doc": doc.obj_id})
        if g is None:
            # ingestion never paused long enough: degrade to a
            # synchronous grab on the caller's thread at result() time
            self.stats["sync_fallbacks"] += 1
            handle._needs_sync = True
            if obs.ENABLED:
                obs.event("ckpt", "sync_fallback",
                          args={"doc": doc.obj_id})
            return
        handle._data = encode_engine_grab(g)
        self.stats["async_captures"] += 1
        if obs.ENABLED:
            obs.span("ckpt", "capture", _t0, args={
                "mode": "async", "doc": doc.obj_id,
                "bytes": len(handle._data)})


def _is_engine_doc(target) -> bool:
    from ..engine.base import CausalDeviceDoc
    return isinstance(target, CausalDeviceDoc)

#!/bin/bash
# One-shot TPU chip session (v2): runs every measurement this round still
# needs, in priority order, appending to scripts/chip_session.log. Safe to
# re-run; each step has its own timeout so a wedged tunnel can't eat the
# session.
#
# v2 restructures for FLAPPY windows (round 5's first window closed 16 min
# in and the v1 full-pytest smoke gate burned all of it — docs/PROFILE_r5.md):
#   - the smoke is scripts/chip_smoke.py: the same device-vs-oracle parity
#     bar, delivered as bulk apply_changes rounds (dozens of dispatches, not
#     tens of thousands through a 70 ms-RTT tunnel)
#   - a smoke TIMEOUT is retryable tunnel weather (probe.sh --forever relaunches);
#     only a deterministic parity failure writes the stop-probing marker
#   - measurements run highest-value first (headline bench, planned A/B)
#     and are NON-gating: a failed step logs its rc and the session moves on
#   - the config sweep writes its record incrementally (benchmarks/run_all
#     --record), so a mid-sweep drop keeps completed rows
#   - the full pytest suite is a best-effort TAIL step, never a gate
set -u
cd "$(dirname "$0")/.."
LOG=scripts/chip_session.log

# single-flight guard: the chip admits ONE client; a second concurrent
# session would wedge both (the probe loop may auto-launch this script)
exec 9> /tmp/chip_session.lock
flock -n 9 || { echo "chip session already running; exiting" >> "$LOG"; exit 5; }

echo "=== chip session $(date -u +%FT%TZ) ===" >> "$LOG"

run() {
  local name="$1"; shift
  echo "--- $name ($(date -u +%T)) ---" >> "$LOG"
  timeout "$1" "${@:2}" >> "$LOG" 2>&1
  local rc=$?
  echo "--- $name rc=$rc ---" >> "$LOG"
  return $rc
}

# shared strict probe: proves a NON-CPU device actually computes — a
# silent CPU fallback would run the whole measurement queue off-chip.
# AMTPU_SESSION_DRYRUN=1 relaxes the probe to --allow-cpu so the WHOLE
# session pipeline (step sequencing, gates, record writing, log format)
# can be exercised without the chip; every emitted row still carries
# platform:cpu provenance, so a dry run can never masquerade as a chip
# sweep.
PROBE_ARGS=""
if [ "${AMTPU_SESSION_DRYRUN:-0}" = "1" ]; then
  PROBE_ARGS="--allow-cpu"
  echo "DRY RUN (cpu-allowed probe): pipeline validation, not chip data" >> "$LOG"
fi
run "probe" 120 python scripts/probe_device.py $PROBE_ARGS \
  || { echo "tunnel down, aborting" >> "$LOG"; exit 3; }
export AMTPU_SKIP_PREFLIGHT=1   # this session IS the parent probe

# ONE smoke definition for both modes (divergence here is exactly what the
# dry run exists to prevent): chip_smoke.py runs on whatever platform jax
# selected — chip in a session, cpu in a dry run.
run "smoke_batched" 600 python scripts/chip_smoke.py
SMOKE_RC=$?
if [ "$SMOKE_RC" != "0" ] && [ "$SMOKE_RC" != "1" ]; then
  # marker text matters: probe.sh --forever stops permanently at "on-chip
  # smoke FAILED", so rc=1 (chip_smoke's explicit parity-MISMATCH
  # verdict) is the ONLY code allowed to write it. Everything else is
  # weather: 124 = wrapper timeout, 7 = chip_smoke's own caught infra
  # exception, and 128+N = signal deaths that never reach Python's
  # except clause (134 C++ CHECK abort on a dropped RPC, 137 OOM-kill,
  # 139 segfault) — classifying those as deterministic was exactly the
  # v1 window-killing conflation.
  echo "on-chip smoke TIMEOUT/INFRA rc=$SMOKE_RC (retryable tunnel weather), aborting" >> "$LOG"
  exit 6
elif [ "$SMOKE_RC" = "1" ]; then
  if [ "${AMTPU_SESSION_DRYRUN:-0}" = "1" ]; then
    # distinct marker: a cpu dry-run flake must not kill the round's probing
    echo "DRYRUN smoke failed (cpu), not recording benchmarks" >> "$LOG"
  else
    echo "on-chip smoke FAILED, not recording benchmarks" >> "$LOG"
  fi
  exit 4
fi

# Measurements, highest value first, non-gating. configs_record folds the
# bench.py headline in as its FIRST row and rewrites the record after every
# config, so each completed step survives a drop.
#
# --reps 5: the headline VALUE is the median of >=5 back-to-back timed
# reps with the spread recorded (VERDICT r5 items 1a/1c — never a
# best-of-N maximum), and every live chip run appends its full JSON to
# the committed session log (BENCH_SESSIONS.jsonl) BEFORE any last-good
# promotion; maybe_refresh_last_good refuses runs absent from that log.
SESSIONS_LOG=BENCH_SESSIONS.jsonl
LOG_LINES_BEFORE=$(wc -l < "$SESSIONS_LOG" 2>/dev/null || echo 0)
run "bench"          1200 python bench.py --reps 5
run "bench_pipeline" 1200 python bench.py --pipeline --reps 5
LOG_LINES_AFTER=$(wc -l < "$SESSIONS_LOG" 2>/dev/null || echo 0)
if [ "$LOG_LINES_AFTER" -le "$LOG_LINES_BEFORE" ] && [ "${AMTPU_SESSION_DRYRUN:-0}" != "1" ]; then
  # a chip bench run that left no session-log line cannot be promoted or
  # cited later — surface it in the session log NOW, not at review time
  echo "WARNING: headline steps appended nothing to $SESSIONS_LOG (tunnel drop mid-run?); these runs are NOT promotable" >> "$LOG"
fi
run "planned_ab" 900 python profile_bench.py --planned
# cfg4 stacked-rounds A/B (ISSUE 7 re-measure hook): dispatch-count AND
# wall-clock delta of one-dispatch-per-round vs per-(object, round) on a
# real accelerator, appended to BENCH_SESSIONS.jsonl (the cpu rows only
# prove the dispatch cut; the time payoff is per-dispatch link overhead)
run "cfg4_stacked_ab" 600 python -m benchmarks.cfg4_smoke --record-session
# service tier on a real accelerator (ISSUE 8): the 100-session chaos
# smoke (convergence + bounds asserted inside the profile), then the
# cfg11 clean-path capacity row appended to BENCH_SESSIONS.jsonl — the
# cpu rows only prove the scheduler; aggregate ops/s and p99_tick_ms
# are the chip numbers
run "service_soak"  900 python scripts/soak.py --service --quick
run "cfg11_service" 900 python -m benchmarks.run_all --service-session
# sharded serving tier (ISSUE 10): the shard-count invariance soak
# (same seeded chaotic stream on 1 vs 8 shards -> byte-identical
# bundles, incl. a telemetry-triggered hot-doc migration mid-stream),
# then the cfg12 aggregate-mesh row. The cfg12 step runs in its own
# subprocess with the 8-virtual-device env (run_all config12_sharded),
# so ON the chip it still measures the cpu-dryrun distribution
# property; a real multi-chip window should export AMTPU_SHARDS and
# run bench.py --sharded directly against the hardware mesh
run "sharded_soak"  900 python scripts/soak.py --sharded --sessions 4
run "cfg12_sharded" 1800 python -m benchmarks.run_all --sharded-session
# cross-doc cold text planning (ISSUE 12): the cfg12t A/B row — the
# span-derived detect_runs/index_merge/rank_resolve terms on the chip
# host, budget-asserted inside the measurement
run "cfg12t_text_prepare" 1200 python -m benchmarks.run_all --text-prepare-session
# binary columnar wire A/B (ISSUE 13): the cfg13 row on the chip host —
# service-ingest decode term dict vs AMTPUWIRE1 frames on the same
# seeded session, byte-identity + the >=5x decode bar + the <5%
# tick-share bar asserted inside the measurement, wire bytes/op both
# legs; appended to BENCH_SESSIONS.jsonl
run "cfg13_wire" 1200 python -m benchmarks.run_all --wire-session
# change-lineage tracing A/B (ISSUE 14): the cfg14 row on the chip
# host — the cfg11-shaped service session lineage off vs 1/64 sampled,
# byte-identity + clean-path chain completeness + the <=5% sampled
# overhead bar asserted inside the measurement, visibility quantiles
# and per-stage dwell maxima recorded; appended to BENCH_SESSIONS.jsonl
run "cfg14_lineage" 1200 python -m benchmarks.run_all --lineage-session
# device-truth telemetry (ISSUE 15): the cfg15 row on the chip — the
# FIRST run whose compile wall times, persistent-cache hit/miss split,
# per-kernel cost-model flops/bytes, staged bytes/op and peak device
# footprint are measured below the dispatch boundary on real hardware;
# recompiles_at_steady_state == 0 asserted inside the measurement (a
# TPU bucket-churn recompile is exactly what this step exists to
# catch), roofline ratio against the chip's datasheet peaks via
# AMTPU_PEAK_FLOPS / AMTPU_PEAK_BYTES_PER_S; appended to
# BENCH_SESSIONS.jsonl
run "cfg15_device_truth" 1200 python -m benchmarks.run_all --device-truth-session
# geo-federation replication (ISSUE 16): the cfg16 row on the chip —
# three federated regions full-meshed over the seeded cross_region WAN
# chaos profile, replica-commits/s from first write to full fabric
# quiescence, byte-identical canonical saves + residual lag == 0
# asserted inside the measurement, cross-region visibility quantiles
# from rate=1 lineage; appended to BENCH_SESSIONS.jsonl
run "cfg16_federation" 1200 python -m benchmarks.run_all --federation-session
# fused-round megakernel A/B (ISSUE 17): the cfg17 row on the chip —
# the FIRST run where the Pallas rung (not the cpu lax fallback) carries
# the fused leg: one fused_stacked_round megakernel + at most one
# combined scatter per stacked pass vs the verbatim XLA program path on
# the same stream. Identical committed state, byte-identical saves
# across AMTPU_FUSED_ROUNDS, the tightened 4/pass budget, zero
# steady-state recompiles and per-kernel roofline ratios all asserted
# inside the measurement; datasheet peaks exported so the
# measured-vs-roofline columns are chip-real, not the cpu sanity band;
# appended to BENCH_SESSIONS.jsonl
run "cfg17_fused" 1200 env \
  AMTPU_PEAK_FLOPS="${AMTPU_PEAK_FLOPS:-2e14}" \
  AMTPU_PEAK_BYTES_PER_S="${AMTPU_PEAK_BYTES_PER_S:-8e11}" \
  python -m benchmarks.run_all --fused-session
# bounded-HBM residency (ISSUE 18): the cfg18 row on the chip — a doc
# population 10x+ the device byte budget served through the paging mesh
# (demand page-ins through the disk spill tier every round, learned
# working-set eviction); the FIRST run where page-in dwell is real h2d
# staging latency and the peak footprint gauge is real HBM, not the cpu
# sanity band. Peak <= budget at every rep boundary, zero overruns, and
# byte-identical captures vs the unbounded reference all asserted
# inside the measurement; appended to BENCH_SESSIONS.jsonl
run "cfg18_residency" 1200 python -m benchmarks.run_all --residency-session
# learned-index host planning (ISSUE 19): the cfg19 row on the chip
# host — the cfg12t population stream A/B'd across AMTPU_LEARNED_INDEX
# with the production planner config on both legs; byte-identical final
# text, learned-site engagement, the rank_resolve bar (cfg12t-shape
# scaled <= 0.36 s, >= 2x under the same-run exact leg), zero
# model-wrong-answers on the untimed audit pass and zero demotions all
# asserted inside the measurement; appended to BENCH_SESSIONS.jsonl
run "cfg19_learned_index" 1800 python -m benchmarks.run_all --learned-session
# parallel mesh execution (ISSUE 20): the cfg20 row — the same mesh +
# map-population stream with the per-lane worker threads ON vs OFF
# (AMTPU_PARALLEL_LANES), byte-identical sample captures + per-lane
# counters + the zero-collective audit + zero steady-state recompiles
# asserted inside the measurement; the 1.5x speedup bar asserts on
# >= 4-core hosts (the chip host qualifies; this box's 1-core dryrun
# records the honest gated ratio). Subprocess with the 8-virtual-device
# env, like cfg12; appended to BENCH_SESSIONS.jsonl
run "cfg20_parallel" 1800 python -m benchmarks.run_all --parallel-session
if [ "${AMTPU_SESSION_DRYRUN:-0}" = "1" ]; then
  # NO --record in a dry run: write_record replaces same-platform rows,
  # and a pipeline-validation pass must never overwrite the curated cpu
  # record rows; --quick still validates the run_all invocation
  run "configs_quick" 1800 python -m benchmarks.run_all --quick
else
  run "configs_record" 3600 python -m benchmarks.run_all --record "${AMTPU_ROUND:-5}"
fi
run "pallas_ab" 900 python profile_bench.py --pallas
run "int64_ab"  600 python profile_bench.py --int64
run "trace"     600 python profile_bench.py --trace

# best-effort tail: full suite on the chip is dispatch-bound through the
# tunnel (~2 min/test) — worth having if the window holds, never a gate
if [ "${AMTPU_SESSION_DRYRUN:-0}" != "1" ]; then
  run "pytest_tail" 1200 env AUTOMERGE_TPU_TESTS_ON_TPU=1 \
    python -m pytest tests/test_segments.py tests/test_engine_parity.py \
                     tests/test_fast_local.py -q
fi

if [ "${AMTPU_SESSION_DRYRUN:-0}" = "1" ]; then
  # a DIFFERENT marker on purpose: probe.sh --forever stops at the real
  # "chip session done" marker, and a dry run must not stop the probing
  echo "=== chip session DRYRUN-complete $(date -u +%T) ===" >> "$LOG"
else
  echo "=== chip session done $(date -u +%T) ===" >> "$LOG"
fi

"""Failure atomicity + register tie-break semantics (advisor round-1 items).

A raising batch must leave the document state untouched — including the
causal clock, or a corrected redelivery of the same (actor, seq) is silently
skipped as a duplicate. And same-actor register ties (one change assigning a
key twice) must resolve like the reference's sortBy(actor).reverse(): the
last-written op wins (/root/reference/backend/op_set.js:245).
"""

import pytest

from automerge_tpu._common import ROOT_ID
from automerge_tpu.backend import Backend
from automerge_tpu.engine import DeviceMapDoc, DeviceTextDoc


def ins(obj, key, elem):
    return {"action": "ins", "obj": obj, "key": key, "elem": elem}


def setop(obj, key, value):
    return {"action": "set", "obj": obj, "key": key, "value": value}


class TestClockRollbackOnFailedIngest:
    def test_redelivery_after_failed_batch_applies(self):
        doc = DeviceTextDoc("obj1")
        bad = {"actor": "a", "seq": 1, "deps": {},
               "ops": [ins("obj1", "ghost:99", 1),
                       setop("obj1", "a:1", "x")]}
        with pytest.raises(ValueError, match="unknown parent"):
            doc.apply_changes([bad])
        assert doc.clock == {}
        assert ("a", 1) not in doc._all_deps

        good = {"actor": "a", "seq": 1, "deps": {},
                "ops": [ins("obj1", "_head", 1), setop("obj1", "a:1", "x")]}
        doc.apply_changes([good])
        assert doc.text() == "x"
        assert doc.clock == {"a": 1}

    def test_prior_state_survives_failed_batch(self):
        doc = DeviceTextDoc("obj1")
        doc.apply_changes([{"actor": "a", "seq": 1, "deps": {},
                            "ops": [ins("obj1", "_head", 1),
                                    setop("obj1", "a:1", "h")]}])
        bad = {"actor": "b", "seq": 1, "deps": {},
               "ops": [ins("obj1", "nowhere:7", 1),
                       setop("obj1", "b:1", "y")]}
        with pytest.raises(ValueError):
            doc.apply_changes([bad])
        assert doc.clock == {"a": 1}
        assert doc.text() == "h"
        # the failed actor can still deliver a corrected change
        doc.apply_changes([{"actor": "b", "seq": 1, "deps": {},
                            "ops": [ins("obj1", "a:1", 1),
                                    setop("obj1", "b:1", "i")]}])
        assert doc.text() == "hi"


class TestQueueSurvivesFailedRound:
    def test_previously_queued_change_not_dropped(self):
        doc = DeviceTextDoc("obj1")
        # B2 queues awaiting b:1
        b2 = {"actor": "b", "seq": 2, "deps": {},
              "ops": [ins("obj1", "b:1", 2), setop("obj1", "b:2", "2")]}
        doc.apply_changes([b2])
        assert len(doc.queue) == 1
        # bad b1 unblocks B2's round but fails its own; B2 must requeue
        bad_b1 = {"actor": "b", "seq": 1, "deps": {},
                  "ops": [ins("obj1", "ghost:1", 1), setop("obj1", "b:1", "x")]}
        with pytest.raises(ValueError, match="unknown parent"):
            doc.apply_changes([bad_b1])
        assert doc.clock == {}
        assert len(doc.queue) == 1  # B2 still waiting
        # corrected b1: both apply
        good_b1 = {"actor": "b", "seq": 1, "deps": {},
                   "ops": [ins("obj1", "_head", 1), setop("obj1", "b:1", "1")]}
        doc.apply_changes([good_b1])
        assert doc.text() == "12"
        assert doc.queue == []


class TestSameActorTieBreak:
    """One change assigning the same key twice: the LATER op supersedes
    its predecessor — no self-conflict survives. (Deliberate deviation
    from the reference's observable artifact: keeping both same-actor ops
    in the register makes the winner application-order-dependent — the
    redo-of-conflict convergence bug, tests/test_integration.py
    TestRedoConflictConvergence — and the reference's per-actor conflict
    map rendered a same-actor 'conflict' nonsensically anyway.)"""

    CHANGE = {"actor": "a", "seq": 1, "deps": {},
              "ops": [setop(ROOT_ID, "k", 1), setop(ROOT_ID, "k", 2)]}

    def test_oracle_last_written_wins(self):
        state = Backend.init()
        state, patch = Backend.apply_changes(state, [self.CHANGE])
        final = patch["diffs"][-1]
        assert final["value"] == 2
        assert not final.get("conflicts")   # predecessor superseded

    def test_engine_last_written_wins(self):
        doc = DeviceMapDoc(ROOT_ID)
        doc.apply_changes([self.CHANGE])
        assert doc.to_dict() == {"k": 2}
        assert doc.conflicts_for("k") in (None, {})

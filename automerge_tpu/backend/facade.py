"""Functional backend API over the mutable op-set index.

Counterpart of the reference's ``backend/index.js`` (/root/reference/backend/
index.js:125-321): ``(state, changes) -> (state', patch)`` with patches in the
reference's exact wire format. Persistence of old states is provided not by
persistent data structures but by an append-only command log: every
``BackendState`` is (shared index, log version, cheap snapshots); applying to
a stale state forks the index by deterministic replay. Forward application is
O(change); branching pays O(history) once per divergence.
"""

from __future__ import annotations

from typing import Optional

from .._common import ROOT_ID, less_or_equal, parse_elem_id
from ..resilience.validation import prevalidated, validate_changes
from .op_set import OpSetIndex


class BackendState:
    """An immutable view of one point in a document lineage."""

    __slots__ = ("_index", "_version", "_fork_cache",
                 "clock", "deps", "can_undo", "can_redo", "queue", "history_len")

    def __init__(self, index: OpSetIndex, version: int):
        self._index = index
        self._version = version
        self._fork_cache: Optional[OpSetIndex] = None
        self.clock = dict(index.clock)
        self.deps = dict(index.deps)
        self.can_undo = index.undo_pos > 0
        self.can_redo = len(index.redo_stack) > 0
        self.queue = tuple(index.queue)
        self.history_len = len(index.history)

    # -- index access ---------------------------------------------------

    def _is_current(self) -> bool:
        return len(self._index.commands) == self._version

    def writable_index(self) -> OpSetIndex:
        """The index positioned exactly at this state, ready to mutate."""
        if self._is_current():
            return self._index
        return self._index.fork(self._version)

    def read_index(self) -> OpSetIndex:
        """An index whose deep state (object trees, stacks) matches this state."""
        if self._is_current():
            return self._index
        if self._fork_cache is None:
            self._fork_cache = self._index.fork(self._version)
        return self._fork_cache

    def history(self) -> list:
        return self._index.history[: self.history_len]


def init() -> BackendState:
    return BackendState(OpSetIndex(), 0)


def _snapshot(index: OpSetIndex) -> BackendState:
    return BackendState(index, len(index.commands))


def _make_patch(state: BackendState, diffs: list) -> dict:
    return {"clock": dict(state.clock), "deps": dict(state.deps),
            "canUndo": state.can_undo, "canRedo": state.can_redo, "diffs": diffs}


def _clean_change(change: dict) -> dict:
    if "requestType" in change or "undoable" in change:
        return {k: v for k, v in change.items() if k not in ("requestType", "undoable")}
    return change


def _restore(index):
    """Rebuild `index` in place from its command log after a failed mutation.

    A change that raises mid-application (unknown object, inconsistent seq
    reuse, …) has already mutated the shared index; replaying the log into a
    fresh index and swapping its guts back restores the invariant that the
    index equals its log, so every BackendState holding a reference stays
    valid. The reference got this for free from immutability; here the error
    path pays an O(history) replay instead.
    """
    clean = index.fork(len(index.commands))
    for slot in vars(clean):
        setattr(index, slot, getattr(clean, slot))


def _apply(state: BackendState, changes, undoable: bool):
    index = state.writable_index()
    cleaned = [_clean_change(c) for c in changes]
    diffs = []
    try:
        for change in cleaned:
            diffs.extend(index.add_change(change, undoable))
    except Exception:
        _restore(index)
        raise
    index.record(("apply", cleaned, undoable))
    new_state = _snapshot(index)
    return new_state, _make_patch(new_state, diffs)


def apply_changes(state: BackendState, changes):
    """Apply remote changes; returns (state', patch) (backend/index.js:166-168).

    Structurally malformed changes raise ``ProtocolError`` before any index
    mutation (lenient mode: unknown op *action strings* pass through to the
    op-set's authoritative ``Unknown operation type`` rejection)."""
    return _apply(state, validate_changes(changes, strict=False), False)


def apply_local_change(state: BackendState, change: dict):
    """Apply a frontend change request (backend/index.js:178-201)."""
    if not isinstance(change.get("actor"), str) or not isinstance(change.get("seq"), int):
        raise TypeError("Change request requires `actor` and `seq` properties")
    if change["seq"] <= state.clock.get(change["actor"], 0):
        raise ValueError("Change request has already been applied")

    request_type = change.get("requestType")
    if request_type == "change":
        undoable = change.get("undoable", True) is not False
        state, patch = _apply(state, [change], undoable)
    elif request_type == "undo":
        state, patch = undo(state, change)
    elif request_type == "redo":
        state, patch = redo(state, change)
    else:
        raise ValueError(f"Unknown requestType: {request_type}")
    patch["actor"] = change["actor"]
    patch["seq"] = change["seq"]
    return state, patch


def undo(state: BackendState, request: dict):
    index = state.writable_index()
    try:
        diffs = index.do_undo(request)
    except Exception:
        _restore(index)
        raise
    index.record(("undo", request))
    new_state = _snapshot(index)
    return new_state, _make_patch(new_state, diffs)


def redo(state: BackendState, request: dict):
    index = state.writable_index()
    try:
        diffs = index.do_redo(request)
    except Exception:
        _restore(index)
        raise
    index.record(("redo", request))
    new_state = _snapshot(index)
    return new_state, _make_patch(new_state, diffs)


class MaterializationContext:
    """Builds the diff list that constructs the current document from scratch.

    Counterpart of backend/index.js:5-122: children-before-parents emission so
    the frontend can resolve links as it applies the diffs.
    """

    def __init__(self, index: OpSetIndex):
        self.index = index
        self.diffs: dict[str, list] = {}
        self.children: dict[str, list] = {}

    def _get_op_value(self, op: dict):
        if op["action"] == "link":
            return self.instantiate_object(op["value"])
        if op["action"] == "set":
            result = {"value": op["value"]}
            if op.get("datatype"):
                result["datatype"] = op["datatype"]
            return result
        raise TypeError(f"Unexpected operation action: {op['action']}")

    def _unpack_value(self, parent_id: str, diff: dict, data: dict):
        diff.update(data)
        if data.get("link"):
            self.children[parent_id].append(data["value"])

    def _unpack_conflicts(self, parent_id: str, diff: dict, conflicts):
        if conflicts:
            diff["conflicts"] = []
            for actor, value in conflicts.items():
                conflict = {"actor": actor}
                self._unpack_value(parent_id, conflict, value)
                diff["conflicts"].append(conflict)

    def _instantiate_map(self, object_id: str, obj_type: str):
        diffs = self.diffs[object_id]
        if object_id != ROOT_ID:
            diffs.append({"obj": object_id, "type": obj_type, "action": "create"})
        conflicts = self.index.get_object_conflicts(object_id, self._get_op_value)
        for key in self.index.get_object_fields(object_id):
            diff = {"obj": object_id, "type": obj_type, "action": "set", "key": key}
            ops = self.index.get_field_ops(object_id, key)
            self._unpack_value(object_id, diff, self._get_op_value(ops[0]))
            self._unpack_conflicts(object_id, diff, conflicts.get(key))
            diffs.append(diff)

    def _instantiate_list(self, object_id: str, obj_type: str):
        diffs = self.diffs[object_id]
        max_counter = 0
        diffs.append({"obj": object_id, "type": obj_type, "action": "create"})
        for item in self.index.list_iterator(object_id, self._get_op_value):
            max_counter = max(max_counter, parse_elem_id(item["elemId"])[1])
            if "index" in item:
                diff = {"obj": object_id, "type": obj_type, "action": "insert",
                        "index": item["index"], "elemId": item["elemId"]}
                self._unpack_value(object_id, diff, item["value"])
                self._unpack_conflicts(object_id, diff, item["conflicts"])
                diffs.append(diff)
        diffs.append({"obj": object_id, "type": obj_type, "action": "maxElem", "value": max_counter})

    def instantiate_object(self, object_id: str):
        if object_id in self.diffs:
            return {"value": object_id, "link": True}
        rec = self.index.by_object[object_id]
        self.diffs[object_id] = []
        self.children[object_id] = []
        obj_type = rec.obj_type
        if object_id == ROOT_ID or obj_type == "makeMap":
            self._instantiate_map(object_id, "map")
        elif obj_type == "makeTable":
            self._instantiate_map(object_id, "table")
        elif obj_type == "makeList":
            self._instantiate_list(object_id, "list")
        elif obj_type == "makeText":
            self._instantiate_list(object_id, "text")
        else:
            raise ValueError(f"Unknown object type: {obj_type}")
        return {"value": object_id, "link": True}

    def make_patch(self, object_id: str, diffs: list):
        for child_id in self.children[object_id]:
            self.make_patch(child_id, diffs)
        diffs.extend(self.diffs[object_id])


def get_patch(state: BackendState) -> dict:
    """Patch that builds the whole document from scratch (backend/index.js:207-213)."""
    index = state.read_index()
    context = MaterializationContext(index)
    context.instantiate_object(ROOT_ID)
    diffs: list = []
    context.make_patch(ROOT_ID, diffs)
    return _make_patch(state, diffs)


def get_changes(old_state: BackendState, new_state: BackendState) -> list:
    if not less_or_equal(old_state.clock, new_state.clock):
        raise ValueError("Cannot diff two states that have diverged")
    return new_state._index.get_missing_changes(old_state.clock, new_state.clock)


def get_changes_for_actor(state: BackendState, actor_id: str) -> list:
    return state._index.get_changes_for_actor(actor_id, 0, state.clock)


def get_missing_changes(state: BackendState, clock: dict) -> list:
    return state._index.get_missing_changes(clock, state.clock)


def get_missing_deps(state: BackendState) -> dict:
    return OpSetIndex.missing_deps_of_queue(state.queue, state.clock)


def merge(local: BackendState, remote: BackendState):
    """Apply changes present in `remote` but not `local` (backend/index.js:246-249)."""
    changes = remote._index.get_missing_changes(local.clock, remote.clock)
    # extracted from an admitted local lineage: already schema-valid, skip
    # the per-op validation walk on this in-process hot path
    with prevalidated():
        return apply_changes(local, changes)


class Backend:
    """Namespace object mirroring the reference's Backend module interface,
    for injection into the frontend (frontend/index.js:110-114 seam)."""

    init = staticmethod(init)
    applyChanges = staticmethod(apply_changes)
    applyLocalChange = staticmethod(apply_local_change)
    getPatch = staticmethod(get_patch)
    getChanges = staticmethod(get_changes)
    getChangesForActor = staticmethod(get_changes_for_actor)
    getMissingChanges = staticmethod(get_missing_changes)
    getMissingDeps = staticmethod(get_missing_deps)
    merge = staticmethod(merge)
    # snake_case aliases
    apply_changes = staticmethod(apply_changes)
    apply_local_change = staticmethod(apply_local_change)
    get_patch = staticmethod(get_patch)
    get_changes = staticmethod(get_changes)
    get_changes_for_actor = staticmethod(get_changes_for_actor)
    get_missing_changes = staticmethod(get_missing_changes)
    get_missing_deps = staticmethod(get_missing_deps)
    undo = staticmethod(undo)
    redo = staticmethod(redo)

"""Device-side batch ingestion for the columnar text/list engine.

The reference applies ops one at a time (`applyOps`/`applyInsert`/
`applyAssign`, /root/reference/backend/op_set.js:63-283), with an
order-statistic skip list for elemId↔index queries. Here one causally-ready
*round* of changes — often millions of ops — is a single jitted XLA program:

- insert slots are a prefix sum over the ins mask (op order == slot order);
- the elemId→slot index is a sorted packed-key array, maintained by a
  two-pointer merge (two `searchsorted` + scatters, no monolithic re-sort);
- parent/target resolution is one batched binary search over the merged
  index (covers in-round references: a change may target elements that
  another change in the same round inserted);
- LWW register fast path: single `set` on an element with an empty register
  resolves with pure scatters. Everything else (dels, counter incs,
  concurrent multi-writer registers, rich values) is flagged into a `slow`
  mask the host resolves against its conflict/value-pool state — exactly the
  reference's applyAssign semantics, just partitioned so the device does the
  overwhelmingly common case at memory bandwidth.

The kernel also recomputes the chain-segment census (`n_segs`) used to size
the condensed linearization (see `materialize_text`), so materialization
needs no extra host↔device round trip.

All shapes are static; callers bucket capacities with `bucket()` so XLA
retraces rarely. Packed elemId keys are (actor_rank << 32 | ctr) int64 —
actor ranks are assigned in lexicographic order of actor-id strings, so
integer compares reproduce the reference's string tie-breaks
(op_set.js:245,432-436).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .._common import HEAD_PARENT, KIND_DEL, KIND_INC, KIND_INS, KIND_SET

# Packed-key sentinel: larger than any real (actor_rank, ctr) key.
INF_KEY = jnp.int64(1) << 62
_SENT32 = (1 << 31) - 1


def bucket(n: int, minimum: int = 256) -> int:
    """Half-octave size buckets (2^k and 3·2^(k-1)): ≤25% padding waste."""
    cap = minimum
    while cap < n:
        cap = cap * 3 // 2 if (cap & (cap - 1)) == 0 else (cap // 3) * 4
    return cap


def _pack(actor: jax.Array, ctr: jax.Array) -> jax.Array:
    return (actor.astype(jnp.int64) << 32) | ctr.astype(jnp.int64)


def _segment_census(parent, ctr, actor, n_live, cap):
    """Chain-contraction structure of the element table.

    A slot i continues a chain iff its parent is slot i-1 and it is i-1's
    Lamport-maximal child (so the pair is always adjacent in RGA order).
    Returns (is_elem, seg_start, seg_head, offset, rank_incl, n_segs).
    """
    idx = jnp.arange(cap, dtype=jnp.int32)
    is_elem = (idx >= 1) & (idx <= n_live)
    pk2 = jnp.where(is_elem, _pack(ctr, actor), -1)
    maxkey = jnp.full(cap, -1, jnp.int64).at[
        jnp.where(is_elem, parent, cap)].max(pk2, mode="drop")
    prev_max = jnp.concatenate([jnp.full(1, -1, jnp.int64), maxkey[:-1]])
    chain = is_elem & (parent == idx - 1) & (idx - 1 >= 1) & (pk2 == prev_max)
    seg_start = is_elem & ~chain
    rank_incl = jnp.cumsum(seg_start.astype(jnp.int32))
    seg_head = jax.lax.cummax(jnp.where(seg_start, idx, 0))
    offset = idx - seg_head
    n_segs = rank_incl[-1]
    return is_elem, seg_start, seg_head, offset, rank_incl, n_segs


@partial(jax.jit, static_argnames=("out_cap",))
def ingest_round(
    # document state, capacity C (all device arrays)
    parent, ctr, actor, value, has_value, win_actor, win_seq, win_counter,
    idx_keys, idx_slots,          # sorted packed-key index, INF-padded, [C]
    n_elems,                      # live element count (scalar i32)
    # batch op columns, capacity M (padded with kind = -1)
    op_kind, op_ta, op_tc, op_pa, op_pc, op_value, op_row,
    # batch tables
    batch_rank,                   # [A] batch actor idx -> global rank
    row_actor, row_seq,           # [R] per-change global rank / seq
    conflict_slots,               # [K] slots with host-held conflicts (pad C)
    *, out_cap: int,
):
    """Apply one causally-ready round of ops. Returns the updated state at
    capacity `out_cap`, a slow-op mask for the host, and a stats vector
    [dups, missing_parents, missing_targets, n_new, n_segs, n_slow]."""
    C = parent.shape[0]
    M = op_kind.shape[0]
    kind = op_kind.astype(jnp.int32)
    is_ins = kind == KIND_INS
    is_assign = (kind == KIND_SET) | (kind == KIND_DEL) | (kind == KIND_INC)

    g_ta = batch_rank[jnp.clip(op_ta, 0, None)]

    # --- insert slot assignment: op order == slot order (prefix sum) ---
    new_slot = n_elems + jnp.cumsum(is_ins.astype(jnp.int32))
    n_new = jnp.sum(is_ins.astype(jnp.int32))

    # --- sort new element keys (two i32 keys: no 64-bit sort) ---
    sort_a = jnp.where(is_ins, g_ta, _SENT32)
    sort_c = jnp.where(is_ins, op_tc, _SENT32)
    sa, sc, sslot = jax.lax.sort((sort_a, sort_c, new_slot), num_keys=2)
    skeys = jnp.where(sa == _SENT32, INF_KEY, _pack(sa, sc))

    # --- merge the sorted new keys into the sorted index (no re-sort) ---
    posA = jnp.arange(C, dtype=jnp.int32) + jnp.searchsorted(
        skeys, idx_keys, side="left").astype(jnp.int32)
    posB = jnp.arange(M, dtype=jnp.int32) + jnp.searchsorted(
        idx_keys, skeys, side="right").astype(jnp.int32)
    total = C + M
    mk = jnp.full(total, INF_KEY, jnp.int64).at[posA].set(idx_keys).at[posB].set(skeys)
    ms = jnp.zeros(total, jnp.int32).at[posA].set(idx_slots).at[posB].set(sslot)
    n_dup = jnp.sum((mk[1:] == mk[:-1]) & (mk[:-1] < INF_KEY))
    if total >= out_cap:
        # all real keys fit in the prefix: live + new <= out_cap by contract
        out_keys, out_slots = mk[:out_cap], ms[:out_cap]
    else:
        pad = out_cap - total
        out_keys = jnp.concatenate([mk, jnp.full(pad, INF_KEY, jnp.int64)])
        out_slots = jnp.concatenate([ms, jnp.zeros(pad, jnp.int32)])

    # --- one binary search resolves every op's reference ---
    is_head = op_pa == HEAD_PARENT
    g_pa = batch_rank[jnp.clip(op_pa, 0, None)]
    q_key = jnp.where(is_ins, _pack(g_pa, op_pc), _pack(g_ta, op_tc))
    qi = jnp.clip(jnp.searchsorted(out_keys, q_key, side="left").astype(jnp.int32),
                  0, out_cap - 1)
    q_found = out_keys[qi] == q_key
    q_slot = jnp.where(q_found, out_slots[qi], out_cap)

    n_missing_parent = jnp.sum(is_ins & ~is_head & ~q_found)
    n_missing_target = jnp.sum(is_assign & ~q_found)

    # --- extend tables to out_cap and scatter the new elements ---
    def ext(a, fill):
        if C >= out_cap:
            return a
        return jnp.concatenate(
            [a, jnp.full(out_cap - C, fill, a.dtype)])

    ins_idx = jnp.where(is_ins, new_slot, out_cap)  # OOB sentinel drops pads
    parent_n = ext(parent, 0).at[ins_idx].set(
        jnp.where(is_head, 0, q_slot).astype(jnp.int32), mode="drop")
    ctr_n = ext(ctr, 0).at[ins_idx].set(op_tc, mode="drop")
    actor_n = ext(actor, 0).at[ins_idx].set(g_ta, mode="drop")
    value_n = ext(value, 0).at[ins_idx].set(0, mode="drop")
    has_n = ext(has_value, False).at[ins_idx].set(False, mode="drop")
    wa_n = ext(win_actor, -1).at[ins_idx].set(-1, mode="drop")
    ws_n = ext(win_seq, 0).at[ins_idx].set(0, mode="drop")
    wc_n = ext(win_counter, False).at[ins_idx].set(False, mode="drop")

    # --- register fast path ---
    tslot = jnp.where(is_assign, q_slot, out_cap)
    tclip = jnp.clip(tslot, 0, out_cap - 1)
    counts = jnp.zeros(out_cap + 1, jnp.int32).at[
        jnp.clip(tslot, 0, out_cap)].add(is_assign.astype(jnp.int32))
    cmask = jnp.zeros(out_cap + 1, bool).at[
        jnp.clip(conflict_slots, 0, out_cap)].set(True)
    fast = (is_assign & (kind == KIND_SET) & q_found
            & (counts[tclip] == 1) & ~has_n[tclip] & (wa_n[tclip] < 0)
            & ~cmask[tclip] & (op_value >= 0))
    f_idx = jnp.where(fast, tslot, out_cap)
    value_n = value_n.at[f_idx].set(op_value, mode="drop")
    has_n = has_n.at[f_idx].set(True, mode="drop")
    wa_n = wa_n.at[f_idx].set(row_actor[op_row], mode="drop")
    ws_n = ws_n.at[f_idx].set(row_seq[op_row], mode="drop")
    wc_n = wc_n.at[f_idx].set(False, mode="drop")
    slow = is_assign & ~fast

    # --- segment census on the post-round table (for materialization) ---
    n_live = n_elems + n_new
    _, _, _, _, _, n_segs = _segment_census(
        parent_n, ctr_n, actor_n, n_live, out_cap)

    stats = jnp.stack([
        n_dup.astype(jnp.int32), n_missing_parent.astype(jnp.int32),
        n_missing_target.astype(jnp.int32), n_new,
        n_segs, jnp.sum(slow.astype(jnp.int32))])
    return (parent_n, ctr_n, actor_n, value_n, has_n, wa_n, ws_n, wc_n,
            out_keys, out_slots, slow, tslot, stats)


def _linearize_segments(parent, attach_off, ctr, actor, weight, valid):
    """Condensed-tree linearization (see ops/linearize.py for the derivation):
    per-parent children sort descending by (attach, ctr, actor), successor
    chain by pointer doubling, weighted list ranking."""
    import math
    n = parent.shape[0]
    steps = max(1, math.ceil(math.log2(max(2, n))))
    idx = jnp.arange(n, dtype=jnp.int32)
    is_seg = valid & (idx != 0)
    big = jnp.int32(n + 1)

    sort_parent = jnp.where(is_seg, parent, big)
    neg_off = jnp.where(is_seg, -attach_off, big)
    neg_ctr = jnp.where(is_seg, -ctr, big)
    neg_actor = jnp.where(is_seg, -actor, big)
    p_s, _, _, _, idx_s = jax.lax.sort(
        (sort_parent, neg_off, neg_ctr, neg_actor, idx), num_keys=4)

    in_group = p_s < big
    same_next = jnp.concatenate(
        [(p_s[1:] == p_s[:-1]) & in_group[1:], jnp.zeros(1, bool)])
    next_in_sorted = jnp.concatenate([idx_s[1:], jnp.full(1, -1, idx_s.dtype)])
    next_sib = jnp.full((n,), -1, jnp.int32)
    next_sib = next_sib.at[idx_s].set(jnp.where(same_next, next_in_sorted, -1))

    group_start = jnp.concatenate(
        [jnp.ones(1, bool), p_s[1:] != p_s[:-1]]) & in_group
    first_child = jnp.full((n,), -1, jnp.int32)
    first_child = first_child.at[jnp.where(group_start, p_s, big - 1)].set(
        jnp.where(group_start, idx_s, -1), mode="drop")

    has_next = next_sib >= 0
    safe_parent = jnp.where(is_seg, parent, 0)
    anc = jnp.where(has_next | (idx == 0), idx, safe_parent)
    anc = jax.lax.fori_loop(0, steps, lambda _, a: a[a], anc)

    succ = jnp.where(first_child >= 0, first_child, next_sib[anc])

    end = jnp.int32(n)
    nxt = jnp.where(succ >= 0, succ, end)
    nxt = jnp.where(is_seg | (idx == 0), nxt, idx)
    nxt = jnp.concatenate([nxt, jnp.full(1, end, jnp.int32)])
    dist = jnp.where(is_seg, weight, 0).astype(jnp.int32)
    dist = jnp.concatenate([dist, jnp.zeros(1, jnp.int32)])

    def rank_step(_, carry):
        d, nx = carry
        return d + d[nx], nx[nx]

    dist, nxt = jax.lax.fori_loop(0, steps + 1, rank_step, (dist, nxt))
    start = dist[0] - dist[:n]
    return jnp.where(is_seg, start, jnp.where(idx == 0, 0, big))


@partial(jax.jit, static_argnames=("S",))
def materialize_text(parent, ctr, actor, value, has_value, n_elems, *, S: int):
    """RGA positions + visible compaction, fully on device.

    Chain segments are contracted host-free: the census is recomputed (cheap
    elementwise + one scatter-max), segments compact into S nodes (S is a
    static bucket ≥ n_segs+1, known from ingest stats), the condensed tree
    linearizes in O(S log S), and element position = segment start + offset.

    Returns (pos[C], codes[C], n_vis): `pos` includes tombstones (head = -1,
    padding > n), `codes` is visible values scattered into list order.
    """
    C = parent.shape[0]
    idx = jnp.arange(C, dtype=jnp.int32)
    is_elem, seg_start, seg_head, offset, rank_incl, n_segs = _segment_census(
        parent, ctr, actor, n_elems, C)

    heads = jnp.zeros(S, jnp.int32).at[
        jnp.where(seg_start, rank_incl, S)].set(idx, mode="drop")
    node_of = rank_incl[seg_head]              # node id of each slot's segment
    sizes = jnp.zeros(C, jnp.int32).at[seg_head].add(is_elem.astype(jnp.int32))

    p_slot = parent[heads]
    node_parent = node_of[p_slot]
    attach = offset[p_slot]
    nctr = ctr[heads]
    nactor = actor[heads]
    weight = sizes[heads]
    valid = jnp.arange(S, dtype=jnp.int32) <= n_segs
    starts = _linearize_segments(node_parent, attach, nctr, nactor, weight, valid)

    pos = jnp.where(is_elem, starts[node_of] + offset,
                    jnp.where(idx == 0, -1, C + 1))

    vis = has_value & is_elem
    slot_p = jnp.clip(pos + 1, 0, C + 1)
    by_pos = jnp.zeros(C + 2, jnp.int32).at[slot_p].add(vis.astype(jnp.int32))
    cum = jnp.cumsum(by_pos)
    vis_rank = cum[slot_p] - by_pos[slot_p]
    codes = jnp.full(C, -1, value.dtype).at[
        jnp.where(vis, vis_rank, C)].set(value, mode="drop")
    # n_segs returned so the host can detect S overflow (e.g. an actor remap
    # changed Lamport sibling order and broke chain edges) and retry bigger
    return pos, codes, cum[C + 1], n_segs


@jax.jit
def remap_actors(actor, win_actor, ctr, remap, n_elems):
    """Re-rank actor ids after interning breaks lexicographic rank order.

    Rebuilds the packed-key index (ranks are embedded in keys). Rare: only
    when a new actor id sorts before an existing one.
    """
    C = actor.shape[0]
    idx = jnp.arange(C, dtype=jnp.int32)
    live = (idx >= 1) & (idx <= n_elems)
    hi = remap.shape[0] - 1
    actor_n = jnp.where(live, remap[jnp.clip(actor, 0, hi)], actor)
    wa_n = jnp.where(win_actor >= 0, remap[jnp.clip(win_actor, 0, hi)],
                     win_actor)
    keys = jnp.where(live, _pack(actor_n, ctr), INF_KEY)
    sk, ss = jax.lax.sort((keys, idx), num_keys=1)
    return actor_n, wa_n, sk, ss


@jax.jit
def gather_registers(value, has_value, win_actor, win_seq, win_counter, slots):
    """Fetch register state at `slots` (clipped; caller masks) for the host
    slow path."""
    s = jnp.clip(slots, 0, value.shape[0] - 1)
    return (value[s], has_value[s], win_actor[s], win_seq[s], win_counter[s])


@jax.jit
def scatter_registers(value, has_value, win_actor, win_seq, win_counter,
                      slots, v, h, wa, ws, wc):
    """Write back host-resolved registers (OOB sentinel slots drop)."""
    return (value.at[slots].set(v, mode="drop"),
            has_value.at[slots].set(h, mode="drop"),
            win_actor.at[slots].set(wa, mode="drop"),
            win_seq.at[slots].set(ws, mode="drop"),
            win_counter.at[slots].set(wc, mode="drop"))

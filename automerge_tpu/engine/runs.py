"""Typing-run detection over columnar op batches.

A *run* is an INS immediately followed by its SET, chained so each next INS
continues the previous element with a consecutive counter — the shape every
text editor produces. Runs are the engine's unit of bulk transfer: ~20-byte
descriptors + a value blob instead of 2 op rows per character
(ops/ingest.py:expand_runs*). Shared by the single-doc engine
(text_doc.DeviceTextDoc) and the vmapped doc-set engine
(doc_set.DeviceTextDocSet).

Detection dispatches to the native single-pass C++ walker
(native/codec.cpp:amtpu_detect_runs) when available and falls back to the
vectorized numpy formulation; both are bit-identical
(tests/test_native_codec pins parity on random batches).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .._common import KIND_INS, KIND_SET
from .. import obs


@dataclass
class RoundPlan:
    """Run/residual partition of one causally-ready round's op columns."""

    n_ops: int
    n_ins: int
    hpos: np.ndarray         # run-head op positions
    run_len: np.ndarray      # int64[n_runs]
    head_slot: np.ndarray    # int64[n_runs]: slot of each run's first elem
    rpos: np.ndarray         # residual op positions
    res_new_slot: np.ndarray  # int64[n_res]: slot per residual INS (-1 else)
    blob: np.ndarray         # int32[n_pairs]: run SET values, op order
    blob_lt_128: bool
    blob_lt_256: bool

    def rebase(self, delta: int) -> "RoundPlan":
        """The same partition with inserted-element slots shifted by
        ``delta``. Only `head_slot`/`res_new_slot` encode the document's
        pre-round element count (`base_elems`); everything else is a pure
        function of the op columns — which is what makes the detection
        cacheable on the (immutable) batch and reusable across documents
        of different sizes (replica fan-out or replay applying one decoded
        batch to several docs; the bench re-applies one batch per rep).
        Arrays the shift does not touch are shared, not copied: every
        downstream consumer treats the plan as read-only."""
        if delta == 0:
            return self
        return RoundPlan(
            n_ops=self.n_ops, n_ins=self.n_ins, hpos=self.hpos,
            run_len=self.run_len,
            head_slot=self.head_slot + delta,
            rpos=self.rpos,
            res_new_slot=np.where(self.res_new_slot >= 0,
                                  self.res_new_slot + delta,
                                  self.res_new_slot),
            blob=self.blob, blob_lt_128=self.blob_lt_128,
            blob_lt_256=self.blob_lt_256)

    @property
    def n_runs(self) -> int:
        return len(self.hpos)

    @property
    def n_pairs(self) -> int:
        return len(self.blob)

    @property
    def res_is_ins(self) -> np.ndarray:
        return self.res_new_slot >= 0

    @property
    def n_res_ins(self) -> int:
        return int((self.res_new_slot >= 0).sum())


def detect_runs(kind, ta, tc, pa, pc, val64, op_row, base_elems: int
                ) -> RoundPlan:
    """Partition one round's op columns into runs and residual ops.

    `base_elems` is the document's live element count before this round;
    inserted elements take slots base_elems+1.. in op order.

    Batches above `_SHARD_MIN_OPS` shard across the planning worker pool
    (engine/pipeline.planner_pool): the walk is embarrassingly parallel
    once split at change boundaries — every pair/continuation predicate
    compares adjacent ops of EQUAL change row, so no run or pair spans a
    boundary where the change row differs, and per-shard detection with a
    slot base offset by the preceding shards' insert counts concatenates
    into the exact unsharded partition (pinned bit-identical by
    tests/test_pipeline.py). The native walker and the numpy passes both
    release the GIL, so shards run at real parallelism on multicore
    hosts; one worker (AMTPU_PLAN_WORKERS=1) short-circuits to the
    single-shard path."""
    n_ops = len(kind)
    _t0 = obs.now() if obs.ENABLED else 0
    plan = None
    if n_ops >= _SHARD_MIN_OPS:
        plan = _detect_runs_sharded(kind, ta, tc, pa, pc, val64, op_row,
                                    base_elems)
    if plan is None:
        plan = _detect_runs_single(kind, ta, tc, pa, pc, val64, op_row,
                                   base_elems)
    if obs.ENABLED:
        # the cold-prepare term cfg12t attributes (span-derived, the
        # PR-6 contract): the cross-doc planner's whole point is that
        # this span fires once per distinct batch shape, not per doc
        obs.span("plan", "detect_runs", _t0, args={
            "n_ops": n_ops, "n_runs": plan.n_runs})
    return plan


def _detect_runs_single(kind, ta, tc, pa, pc, val64, op_row,
                        base_elems: int) -> RoundPlan:
    n_ops = len(kind)
    from ..native import detect_runs_native
    native = detect_runs_native(kind, ta, tc, pa, pc, val64, op_row,
                                base_elems)
    if native is not None:
        (hpos, run_len, head_slot, rpos, res_new_slot, blob, n_ins,
         lt128, lt256) = native
        return RoundPlan(n_ops=n_ops, n_ins=int(n_ins), hpos=hpos,
                         run_len=run_len, head_slot=head_slot, rpos=rpos,
                         res_new_slot=res_new_slot, blob=blob,
                         blob_lt_128=lt128, blob_lt_256=lt256)
    return _detect_runs_numpy(kind, ta, tc, pa, pc, val64, op_row,
                              base_elems)


_SHARD_MIN_OPS = 1 << 18     # below this, thread fan-out costs more than
                             # the walk itself


def _detect_runs_sharded(kind, ta, tc, pa, pc, val64, op_row,
                         base_elems: int):
    """Parallel shard-and-concatenate form of `_detect_runs_single`;
    None when sharding is unavailable (one worker, or no usable change
    boundary to split at)."""
    from .pipeline import plan_workers, planner_pool
    pool = planner_pool()
    if pool is None:
        return None
    w = plan_workers()
    n_ops = len(kind)
    bounds = np.flatnonzero(op_row[1:] != op_row[:-1]) + 1
    if not len(bounds):
        return None
    targets = np.arange(1, w) * (n_ops // w)
    cuts = np.unique(bounds[np.clip(
        np.searchsorted(bounds, targets), 0, len(bounds) - 1)])
    # bounds lie in [1, n_ops-1], so the endpoints stay sorted-unique
    cuts = np.concatenate(([0], cuts, [n_ops]))
    if len(cuts) < 3:
        return None

    is_ins = kind == KIND_INS
    shard_ins = np.add.reduceat(is_ins.astype(np.int64), cuts[:-1])
    shard_base = base_elems + np.concatenate(
        ([0], np.cumsum(shard_ins)[:-1]))

    def one(i):
        s, e = int(cuts[i]), int(cuts[i + 1])
        return _detect_runs_single(
            kind[s:e], ta[s:e], tc[s:e], pa[s:e], pc[s:e], val64[s:e],
            op_row[s:e], int(shard_base[i]))

    plans = list(pool.map(one, range(len(cuts) - 1)))
    offs = cuts[:-1]
    return RoundPlan(
        n_ops=n_ops,
        n_ins=int(shard_ins.sum()),
        hpos=np.concatenate([p.hpos + o for p, o in zip(plans, offs)]),
        run_len=np.concatenate([p.run_len for p in plans]),
        head_slot=np.concatenate([p.head_slot for p in plans]),
        rpos=np.concatenate([p.rpos + o for p, o in zip(plans, offs)]),
        res_new_slot=np.concatenate([p.res_new_slot for p in plans]),
        blob=np.concatenate([p.blob for p in plans]),
        blob_lt_128=all(p.blob_lt_128 for p in plans),
        blob_lt_256=all(p.blob_lt_256 for p in plans))


def _detect_runs_numpy(kind, ta, tc, pa, pc, val64, op_row,
                       base_elems: int) -> RoundPlan:
    n_ops = len(kind)
    is_ins = kind == KIND_INS
    n_ins = int(is_ins.sum())
    new_slot = np.where(is_ins, base_elems + np.cumsum(is_ins), 0)

    is_pair = np.zeros(n_ops, bool)
    if n_ops >= 2:
        is_pair[:-1] = ((kind[:-1] == KIND_INS) & (kind[1:] == KIND_SET)
                        & (op_row[1:] == op_row[:-1])
                        & (ta[1:] == ta[:-1]) & (tc[1:] == tc[:-1])
                        & (val64[1:] >= 0) & (val64[1:] < 2**31))
    cont = np.zeros(n_ops, bool)
    if n_ops >= 3:
        cont[2:] = (is_pair[2:] & is_pair[:-2]
                    & (op_row[2:] == op_row[:-2]) & (ta[2:] == ta[:-2])
                    & (tc[2:] == tc[:-2] + 1) & (pa[2:] == ta[:-2])
                    & (pc[2:] == tc[:-2]))
    run_head = is_pair & ~cont
    covered = np.zeros(n_ops, bool)
    covered[is_pair] = True
    covered[1:] |= is_pair[:-1]

    hpos = np.flatnonzero(run_head)
    pair_pos = np.flatnonzero(is_pair)
    if len(hpos):
        run_len = np.diff(np.append(
            np.searchsorted(pair_pos, hpos), len(pair_pos))).astype(np.int64)
        blob = val64[pair_pos + 1].astype(np.int32)
    else:
        run_len = np.empty(0, np.int64)
        blob = np.empty(0, np.int32)
    rpos = np.flatnonzero(~covered)
    res_new_slot = np.where(kind[rpos] == KIND_INS,
                            new_slot[rpos], -1).astype(np.int64)
    # the pair predicate guarantees 0 <= value < 2^31, so the int32 blob
    # holds the exact values — derive the flags from it directly
    return RoundPlan(
        n_ops=n_ops, n_ins=n_ins, hpos=hpos.astype(np.int64),
        run_len=run_len, head_slot=new_slot[hpos].astype(np.int64),
        rpos=rpos.astype(np.int64), res_new_slot=res_new_slot, blob=blob,
        blob_lt_128=bool((blob < 128).all()),
        blob_lt_256=bool((blob < 256).all()))

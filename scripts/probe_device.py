"""The ONE strict device probe every gate site shares.

Exits 0 iff a jax device actually performs a computation on an acceptable
platform (non-cpu unless ``--allow-cpu``). Round 4 was lost to gate drift
across probe sites (`probe.sh` (then probe_loop.sh) asserted ``platform == 'tpu'`` while
the chip stamps ``'axon'`` — VERDICT r4 Weak #1); the acceptance rule
itself lives in ``benchmarks.common.is_chip_platform`` so every gate
shares one definition. Callers:

  scripts/probe.sh           (tunnel watch -> auto-launch chip session)
  scripts/chip_session.sh    (session entry gate)
  benchmarks/common.py       (preflight_device, via subprocess)

The computation check matters: a registered-but-dead tunnel plugin can
enumerate devices and still hang or fail on the first real dispatch, and
a silent CPU fallback would otherwise run a whole measurement queue
off-chip. Checks are explicit ``raise SystemExit`` — a bare ``assert``
would be compiled out under PYTHONOPTIMIZE and pass unconditionally.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.common import is_chip_platform  # noqa: E402  (stdlib-only)


def main(argv) -> int:
    allow_cpu = "--allow-cpu" in argv
    import jax
    import jax.numpy as jnp
    devices = jax.devices()
    platform = devices[0].platform
    if not allow_cpu and not is_chip_platform(platform):
        raise SystemExit(f"probe: platform {platform!r} is not a chip "
                         f"(devices: {devices})")
    if int(jnp.arange(8).sum()) != 28:
        raise SystemExit("probe: device computation returned wrong result")
    print("CHIP UP:", platform, devices)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))

"""The driver-facing bench contract must survive a down tunnel.

Round 3's headline was lost to a single failed device probe at driver-run
time (BENCH_r03.json rc=3). bench.py now (a) retries the preflight with
backoff over a bounded budget and (b) falls back to the last locally
recorded on-chip run, explicitly marked stale. These tests pin that
contract by running bench.py as the driver does — a fresh subprocess —
with the probe budget forced tiny and the device made unreachable.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAST_GOOD = os.path.join(REPO, "BENCH_LAST_GOOD.json")


def _run_bench(env_extra):
    env = dict(os.environ)
    # a lingering probe-skip knob (chip_session.sh exports it) would
    # bypass the very preflight these tests exercise
    env.pop("AMTPU_SKIP_PREFLIGHT", None)
    # make the probe fail REGARDLESS of tunnel health: pin the platform to
    # axon (no CPU fallback can satisfy the probe) and point the plugin at
    # a TEST-NET address that is never routable — NOT 127.0.0.1, which is
    # this environment's real loopback relay
    env.update({"AMTPU_PREFLIGHT_BUDGET_S": "1",
                "AMTPU_PREFLIGHT_PROBE_S": "15",
                "JAX_PLATFORMS": "axon",
                "PALLAS_AXON_POOL_IPS": "203.0.113.1",
                **env_extra})
    return subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                          capture_output=True, text=True, env=env,
                          timeout=300, cwd=REPO)


@pytest.fixture()
def stash_last_good():
    """Preserve any real BENCH_LAST_GOOD.json around the test."""
    stash = None
    if os.path.exists(LAST_GOOD):
        fd, stash = tempfile.mkstemp(prefix="bench_last_good_stash_")
        os.close(fd)
        shutil.move(LAST_GOOD, stash)
    try:
        yield
    finally:
        if os.path.exists(LAST_GOOD):
            os.remove(LAST_GOOD)
        if stash:
            shutil.move(stash, LAST_GOOD)


def test_no_device_no_record_exits_3(stash_last_good):
    out = _run_bench({})
    assert out.returncode == 3, (out.stdout, out.stderr)
    assert "no last-good on-chip record" in out.stderr


def test_no_device_serves_stale_last_good(stash_last_good):
    # "axon" is the platform string the chip ACTUALLY stamps (BASELINE.md,
    # every observed chip log) — the fallback must serve it unchanged
    rec = {"metric": "ops_per_sec_merged_text_10k_actors_1M_doc",
           "value": 123, "unit": "ops/s", "vs_baseline": 0.001,
           "platform": "axon", "recorded_at_utc": "2026-07-30T00:00:00Z"}
    with open(LAST_GOOD, "w") as fh:
        json.dump(rec, fh)
    out = _run_bench({})
    assert out.returncode == 0, (out.stdout, out.stderr)
    line = json.loads(out.stdout.strip().splitlines()[-1])
    assert line["value"] == 123
    assert line["stale"] is True
    assert "last locally recorded on-chip run" in line["stale_reason"]


def test_chip_platform_gate_accepts_axon():
    """Round 4's refresh gate (`platform == "tpu"`) dead-wired the
    last-good mechanism: the chip stamps "axon", so a successful on-chip
    run never refreshed the fallback (VERDICT r4 Weak #1). The gate must
    accept every non-cpu platform the device could report."""
    from benchmarks.common import is_chip_platform
    assert is_chip_platform("axon")   # this environment's chip
    assert is_chip_platform("tpu")    # a locally attached chip
    assert not is_chip_platform("cpu")

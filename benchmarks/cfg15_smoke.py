"""Device-truth smoke: traced cfg15 quick run + scrape validation.

Usage: python -m benchmarks.cfg15_smoke

The CI entry for the device-truth telemetry tier (obs/device_truth.py,
INTERNALS §19). One process, three checks:

1. the cfg15 quick record through `bench.measure_device_truth` — zero
   steady-state compile events asserted in-run, nonzero exact h2d/d2h
   byte meters, dtype x shape peak footprint, cost-model flops/bytes
   per op present;
2. the `amtpu_device_*` families on a LIVE SyncService scrape page —
   the full service exposition (with device families appended) must be
   validate_prom-clean, and the device families must actually carry
   kernel/compile/footprint samples from the run above;
3. the exported Chrome trace must hold device-truth "C"-phase counter
   samples and pass validate_chrome_trace (Perfetto counter tracks).
"""

import os

os.environ.setdefault("AMTPU_SKIP_PREFLIGHT", "1")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from benchmarks.common import setup_jax_cache  # noqa: E402

setup_jax_cache()


def main():
    from automerge_tpu import obs
    from automerge_tpu.obs import device_truth as dt
    from automerge_tpu.obs import prom
    from automerge_tpu.obs.export import (to_chrome_trace,
                                          validate_chrome_trace)
    import bench as B

    # (1) the cfg15 quick record, traced so counter samples land
    with obs.tracing():
        t0 = obs.now()
        rec = B.measure_device_truth(quick=True, reps=5)
        recs = obs.snapshot()
    assert rec["recompiles_at_steady_state"] == 0, rec
    assert rec["compile_count"] > 0, rec
    assert rec["bytes_staged_per_op"] > 0, rec
    assert rec["d2h_bytes_per_op"] > 0, rec
    assert rec["peak_device_bytes"] > 0, rec
    assert rec["cost_model_bytes_per_op"] > 0, rec
    print(f"cfg15 quick: {rec['value']} ops/s, "
          f"{rec['compile_count']} warmup compiles, "
          f"{rec['bytes_staged_per_op']} staged B/op, "
          f"peak {rec['peak_device_bytes']} device B")

    # (2) the live scrape: service page + amtpu_device_* families
    from automerge_tpu.service import ServiceConfig, SyncService
    svc = SyncService(ServiceConfig())
    page = svc.scrape()
    res = prom.validate_prom(page)
    assert "amtpu_device_compiles_total" in page, "device families absent"
    assert "amtpu_device_peak_footprint_bytes" in page
    assert "amtpu_device_staged_bytes_total" in page
    assert 'direction="h2d"' in page
    n_dev = sum(1 for ln in page.splitlines()
                if ln.startswith("amtpu_device_"))
    assert n_dev >= 5, f"only {n_dev} device samples on the scrape"
    print(f"scrape: {res['families']} families, {res['samples']} samples "
          f"({n_dev} amtpu_device_*), validate_prom clean")

    # (3) counter tracks in the exported trace
    trace = to_chrome_trace(recs, t0_ns=t0)
    tres = validate_chrome_trace(trace)
    assert tres["n_counter_samples"] > 0, tres
    names = {e["name"] for e in trace["traceEvents"] if e.get("ph") == "C"}
    assert "amtpu_device_compiles_total" in names, names
    print(f"trace: {tres['n_spans']} spans, "
          f"{tres['n_counter_samples']} counter samples, schema valid")


if __name__ == "__main__":
    main()

"""User-visible document value types: materialized views + CRDT wrappers.

Counterparts of the reference's frontend value layer — plain JS objects/arrays
with symbol-keyed metadata plus Text/Table/Counter classes
(/root/reference/frontend/{text,table,counter}.js, constants.js). In Python the
materialized document is built from ``dict``/``list`` subclasses carrying the
same metadata as instance attributes, so documents compare equal to plain
dicts/lists and serialize naturally.

Documents are immutable by convention; with ``freeze=True`` on init, mutation
attempts raise (the reference's deep-freeze option, README.md:208-212).
"""

from __future__ import annotations

import bisect
import datetime as _dt
from typing import Any, Iterator, Optional


def _frozen_guard(self):
    if getattr(self, "_frozen", False):
        raise TypeError("Cannot modify a frozen document object outside a change block")


class MapDoc(dict):
    """A materialized map object: a dict plus CRDT metadata."""

    _object_id: Optional[str] = None
    _frozen = False

    def __init__(self, *args, object_id=None, **kwargs):
        super().__init__(*args, **kwargs)
        self._object_id = object_id
        self._conflicts: dict = {}

    # mutation guards (active once frozen)
    def __setitem__(self, key, value):
        _frozen_guard(self)
        super().__setitem__(key, value)

    def __delitem__(self, key):
        _frozen_guard(self)
        super().__delitem__(key)

    def update(self, *args, **kwargs):
        _frozen_guard(self)
        super().update(*args, **kwargs)

    def pop(self, *args):
        _frozen_guard(self)
        return super().pop(*args)

    def clear(self):
        _frozen_guard(self)
        super().clear()

    def _freeze(self):
        self._frozen = True


class ListDoc(list):
    """A materialized list object: a list plus CRDT metadata."""

    _object_id: Optional[str] = None
    _frozen = False

    def __init__(self, *args, object_id=None):
        super().__init__(*args)
        self._object_id = object_id
        self._conflicts: list = []    # per-index conflict dicts (or None)
        self._elem_ids: list = []     # per-index elemId strings
        self._max_elem: int = 0

    def __setitem__(self, key, value):
        _frozen_guard(self)
        super().__setitem__(key, value)

    def __delitem__(self, key):
        _frozen_guard(self)
        super().__delitem__(key)

    def append(self, value):
        _frozen_guard(self)
        super().append(value)

    def insert(self, index, value):
        _frozen_guard(self)
        super().insert(index, value)

    def extend(self, values):
        _frozen_guard(self)
        super().extend(values)

    def pop(self, *args):
        _frozen_guard(self)
        return super().pop(*args)

    def remove(self, value):
        _frozen_guard(self)
        super().remove(value)

    def clear(self):
        _frozen_guard(self)
        super().clear()

    def _freeze(self):
        self._frozen = True


class Counter:
    """Convergent integer changed only by increment/decrement
    (frontend/counter.js:6-44)."""

    def __init__(self, value: int = 0):
        object.__setattr__(self, "value", value)

    def __setattr__(self, name, value):
        raise AttributeError("Counter is immutable; use increment()/decrement() in a change block")

    def __int__(self):
        return int(self.value)

    def __index__(self):
        return int(self.value)

    def __eq__(self, other):
        if isinstance(other, Counter):
            return self.value == other.value
        if isinstance(other, (int, float)):
            return self.value == other
        return NotImplemented

    def __hash__(self):
        return hash(("Counter", self.value))

    def __lt__(self, other):
        return self.value < (other.value if isinstance(other, Counter) else other)

    def __add__(self, other):
        return self.value + other

    __radd__ = __add__

    def __repr__(self):
        return f"Counter({self.value})"

    def __str__(self):
        return str(self.value)

    def to_json(self):
        return self.value


class WriteableCounter(Counter):
    """Counter view inside a change block (frontend/counter.js:50-68)."""

    def __init__(self, value, context, object_id, key):
        super().__init__(value)
        object.__setattr__(self, "context", context)
        object.__setattr__(self, "object_id", object_id)
        object.__setattr__(self, "key", key)

    def increment(self, delta: int = 1) -> int:
        self.context.increment(self.object_id, self.key, delta)
        object.__setattr__(self, "value", self.value + delta)
        return self.value

    def decrement(self, delta: int = 1) -> int:
        return self.increment(-delta)


class ChunkedElems:
    """Copy-on-write chunked sequence backing ``Text.elems``.

    The frontend's immutable-snapshot contract means every change that
    touches a Text produces a NEW elems sequence while the old document
    keeps the old one. With a flat list, the snapshot is an O(n) copy per
    change — ~1 ms per keystroke on a 100k-char document, and the
    dominant term in the interactive loop (the reference pays the same
    shape via Immutable.js `List`, frontend/apply_patch.js — its
    persistent vectors ARE structural sharing; this class is the Python
    equivalent). Here `copy()` shares chunk references in O(n_chunks) and
    each mutation privatizes only the chunk it lands in, so a 10-char
    insert costs one ~CHUNK-element chunk copy instead of 100k.

    Supports exactly the sequence surface the frontend uses: int/slice
    reads, int writes, `insert`, slice-insertion (`e[i:i] = run`),
    contiguous-range deletion, `len`, iteration.
    """

    __slots__ = ("_chunks", "_shared", "_starts", "_len")
    CHUNK = 2048

    def __init__(self, seq=None):
        data = list(seq) if seq is not None else []
        C = self.CHUNK
        self._chunks = ([data[i: i + C] for i in range(0, len(data), C)]
                        or [[]])
        self._shared = [False] * len(self._chunks)
        self._len = len(data)
        self._starts = None

    def copy(self) -> "ChunkedElems":
        """O(n_chunks) snapshot: both sides share every chunk until one
        of them writes."""
        new = ChunkedElems.__new__(ChunkedElems)
        new._chunks = list(self._chunks)
        new._len = self._len
        new._starts = self._starts   # rebuilt fresh on demand, never
        self._shared = [True] * len(self._chunks)   # mutated in place
        new._shared = [True] * len(self._chunks)
        return new

    # -- index bookkeeping ------------------------------------------
    def _offsets(self):
        if self._starts is None:
            starts, acc = [], 0
            for c in self._chunks:
                starts.append(acc)
                acc += len(c)
            self._starts = starts
        return self._starts

    def _locate(self, i):
        starts = self._offsets()
        ci = bisect.bisect_right(starts, i) - 1
        return ci, i - starts[ci]

    def _own(self, ci):
        if self._shared[ci]:
            self._chunks[ci] = list(self._chunks[ci])
            self._shared[ci] = False
        return self._chunks[ci]

    def _norm(self, i):
        if i < 0:
            i += self._len
        if not 0 <= i < self._len:
            raise IndexError("ChunkedElems index out of range")
        return i

    # -- reads -------------------------------------------------------
    def __len__(self):
        return self._len

    def __iter__(self):
        for c in self._chunks:
            yield from c

    def __getitem__(self, i):
        if isinstance(i, slice):
            start, stop, step = i.indices(self._len)
            if step == 1:
                return self._slice(start, stop)
            return [self[j] for j in range(start, stop, step)]
        i = self._norm(i)
        ci, off = self._locate(i)
        return self._chunks[ci][off]

    def _slice(self, start, stop):
        out = []
        if start >= stop:
            return out
        ci, off = self._locate(start)
        remaining = stop - start
        while remaining > 0:
            take = self._chunks[ci][off: off + remaining]
            out.extend(take)
            remaining -= len(take)
            ci += 1
            off = 0
        return out

    # -- writes ------------------------------------------------------
    def __setitem__(self, i, v):
        if isinstance(i, slice):
            start, stop, step = i.indices(self._len)
            if step != 1:
                raise TypeError("extended-step slice assignment "
                                "unsupported")
            if start != stop:
                self._del_range(start, stop)
            self._insert_run(start, list(v))
            return
        i = self._norm(i)
        ci, off = self._locate(i)
        self._own(ci)[off] = v

    def insert(self, i, v):
        if i < 0:
            i += self._len
        self._insert_run(max(0, min(i, self._len)), [v])

    def __delitem__(self, i):
        if isinstance(i, slice):
            start, stop, step = i.indices(self._len)
            if step != 1:
                raise TypeError("extended-step slice deletion unsupported")
            self._del_range(start, stop)
            return
        i = self._norm(i)
        self._del_range(i, i + 1)

    def _insert_run(self, idx, items):
        n = len(items)
        if not n:
            return
        C = self.CHUNK
        if n > C:
            # bulk run (a remote peer's merged typing run): split the
            # target chunk once and splice pre-chunked pieces between the
            # halves — inserting into a chunk and re-splitting would copy
            # the run twice more
            pieces = [items[i: i + C] for i in range(0, n, C)]
            if self._len == 0:                  # replace the [[]] sentinel
                self._chunks = pieces
                self._shared = [False] * len(pieces)
            elif idx >= self._len:
                self._chunks.extend(pieces)
                self._shared.extend([False] * len(pieces))
            else:
                ci, off = self._locate(idx)
                c = self._chunks[ci]
                halves = ([c[:off]] if off else []) + pieces + \
                    ([c[off:]] if off < len(c) else [])
                self._chunks[ci: ci + 1] = halves
                self._shared[ci: ci + 1] = [False] * len(halves)
            self._len += n
            self._starts = None
            return
        if idx >= self._len:                    # append
            ci = len(self._chunks) - 1
            off = len(self._chunks[ci])
        else:
            ci, off = self._locate(idx)
        c = self._own(ci)
        c[off:off] = items
        self._len += n
        self._starts = None
        if len(c) > 2 * C:                      # keep chunks bounded
            pieces = [c[i: i + C] for i in range(0, len(c), C)]
            self._chunks[ci: ci + 1] = pieces
            self._shared[ci: ci + 1] = [False] * len(pieces)

    def _del_range(self, start, stop):
        stop = min(stop, self._len)
        if start >= stop:
            return
        ci, off = self._locate(start)
        remaining = stop - start
        while remaining > 0:
            size = len(self._chunks[ci])
            if off == 0 and remaining >= size and len(self._chunks) > 1:
                # whole-chunk delete: drop the reference — privatizing a
                # shared chunk only to discard it would be the O(n) copy
                # this class exists to avoid
                del self._chunks[ci]
                del self._shared[ci]            # next chunk slides to ci
                remaining -= size
                continue
            c = self._own(ci)
            take = min(size - off, remaining)
            del c[off: off + take]
            remaining -= take
            if not c and len(self._chunks) > 1:
                del self._chunks[ci]
                del self._shared[ci]
            else:
                ci += 1
            off = 0
        self._len -= stop - start
        self._starts = None

    def __eq__(self, other):
        if isinstance(other, (ChunkedElems, list)):
            return len(self) == len(other) and all(
                a == b for a, b in zip(self, other))
        return NotImplemented

    def __repr__(self):
        return f"ChunkedElems({list(self)!r})"


class Text:
    """Sequence-of-characters (or embedded objects) CRDT view
    (frontend/text.js:3-165). ``elems`` entries are dicts
    {'value', 'elemId'?, 'conflicts'?}.
    """

    def __init__(self, text=None):
        self._object_id: Optional[str] = None
        self._max_elem: int = 0
        self.context = None
        if isinstance(text, str):
            self.elems = ChunkedElems({"value": ch} for ch in text)
        elif isinstance(text, (list, tuple)):
            self.elems = ChunkedElems({"value": v} for v in text)
        elif text is None:
            self.elems = ChunkedElems()
        else:
            raise TypeError(f"Unsupported initial value for Text: {text!r}")

    def __len__(self) -> int:
        return len(self.elems)

    def get(self, index: int):
        return self.elems[index]["value"]

    def get_elem_id(self, index: int):
        return self.elems[index].get("elemId")

    def __getitem__(self, index):
        if isinstance(index, slice):
            return [e["value"] for e in self.elems[index]]
        return self.elems[index]["value"]

    def __iter__(self) -> Iterator:
        return (e["value"] for e in self.elems)

    def __eq__(self, other):
        if isinstance(other, Text):
            return [e["value"] for e in self.elems] == [e["value"] for e in other.elems]
        if isinstance(other, str):
            return str(self) == other
        return NotImplemented

    def __hash__(self):
        return hash(str(self))

    def __str__(self) -> str:
        return "".join(e["value"] for e in self.elems if isinstance(e["value"], str))

    def __repr__(self):
        return f"Text({str(self)!r})"

    def to_spans(self) -> list:
        """Runs of characters interleaved with non-character elements
        (frontend/text.js:70-88): Text(['a','b',{'x':3},'c']) -> ['ab',{'x':3},'c'].
        """
        spans: list = []
        chars = ""
        for elem in self.elems:
            if isinstance(elem["value"], str):
                chars += elem["value"]
            else:
                if chars:
                    spans.append(chars)
                    chars = ""
                spans.append(elem["value"])
        if chars:
            spans.append(chars)
        return spans

    def to_json(self) -> str:
        return str(self)

    def get_writeable(self, context) -> "Text":
        if not self._object_id:
            raise ValueError("get_writeable() requires the objectId to be set")
        instance = Text()
        instance._object_id = self._object_id
        instance.elems = self.elems
        instance._max_elem = self._max_elem
        instance.context = context
        return instance

    # -- mutators: delegate to the change context when attached --

    def set(self, index: int, value) -> "Text":
        if self.context:
            self.context.set_list_index(self._object_id, index, value)
        elif not self._object_id:
            self.elems[index] = {"value": value}
        else:
            raise TypeError("Text object cannot be modified outside of a change block")
        return self

    def insert_at(self, index: int, *values) -> "Text":
        if self.context:
            self.context.splice(self._object_id, index, 0, list(values))
        elif not self._object_id:
            self.elems[index:index] = [{"value": v} for v in values]
        else:
            raise TypeError("Text object cannot be modified outside of a change block")
        return self

    def delete_at(self, index: int, num_delete: int = 1) -> "Text":
        if self.context:
            self.context.splice(self._object_id, index, num_delete, [])
        elif not self._object_id:
            del self.elems[index:index + num_delete]
        else:
            raise TypeError("Text object cannot be modified outside of a change block")
        return self


def instantiate_text(object_id, elems, max_elem) -> Text:
    instance = Text()
    instance._object_id = object_id
    instance.elems = (elems if isinstance(elems, ChunkedElems)
                      else ChunkedElems(elems))
    instance._max_elem = max_elem or 0
    return instance


def _compare_rows(properties, row1, row2):
    for prop in properties:
        v1, v2 = row1.get(prop), row2.get(prop)
        if v1 == v2:
            continue
        if isinstance(v1, (int, float)) and isinstance(v2, (int, float)):
            return -1 if v1 < v2 else 1
        s1, s2 = str(v1), str(v2)
        if s1 == s2:
            continue
        return -1 if s1 < s2 else 1
    return 0


class Table:
    """Relational-style unordered row collection keyed by row object ID
    (frontend/table.js:25-204)."""

    def __init__(self):
        self._object_id: Optional[str] = None
        self._conflicts: dict = {}
        self._frozen = False
        self.entries: dict = {}

    def by_id(self, row_id: str):
        return self.entries.get(row_id)

    @property
    def ids(self) -> list:
        return [key for key, entry in self.entries.items()
                if isinstance(entry, dict) and entry.get("id") == key]

    @property
    def count(self) -> int:
        return len(self.ids)

    @property
    def rows(self) -> list:
        return [self.by_id(i) for i in self.ids]

    def filter(self, callback) -> list:
        return [row for row in self.rows if callback(row)]

    def find(self, callback):
        for row in self.rows:
            if callback(row):
                return row
        return None

    def map(self, callback) -> list:
        return [callback(row) for row in self.rows]

    def sort(self, arg=None) -> list:
        import functools
        if callable(arg):
            return sorted(self.rows, key=functools.cmp_to_key(arg))
        if isinstance(arg, str):
            props = [arg]
        elif isinstance(arg, (list, tuple)):
            props = list(arg)
        elif arg is None:
            props = ["id"]
        else:
            raise TypeError(f"Unsupported sorting argument: {arg!r}")
        return sorted(self.rows, key=functools.cmp_to_key(
            lambda r1, r2: _compare_rows(props, r1, r2)))

    def __iter__(self):
        return iter(self.rows)

    def __len__(self):
        return self.count

    def __eq__(self, other):
        if isinstance(other, Table):
            return self.entries == other.entries
        return NotImplemented

    def _clone(self) -> "Table":
        if not self._object_id:
            raise ValueError("clone() requires the objectId to be set")
        return instantiate_table(self._object_id, dict(self.entries))

    def _set(self, row_id: str, value):
        if self._frozen:
            raise TypeError("A table can only be modified in a change function")
        if isinstance(value, dict):
            value["id"] = row_id
        self.entries[row_id] = value

    def remove(self, row_id: str):
        if self._frozen:
            raise TypeError("A table can only be modified in a change function")
        del self.entries[row_id]

    def _freeze(self):
        self._frozen = True

    def get_writeable(self, context) -> "WriteableTable":
        if not self._object_id:
            raise ValueError("get_writeable() requires the objectId to be set")
        instance = WriteableTable.__new__(WriteableTable)
        instance._object_id = self._object_id
        instance._conflicts = self._conflicts
        instance._frozen = False
        instance.context = context
        return instance

    def to_json(self) -> dict:
        return {row_id: self.by_id(row_id) for row_id in self.ids}


class WriteableTable(Table):
    """Table view inside a change block: reads come from the context's current
    overlay, so captured references never go stale."""

    @property
    def entries(self) -> dict:
        return self.context.get_object(self._object_id).entries

    def by_id(self, row_id: str):
        entry = self.entries.get(row_id)
        if isinstance(entry, dict) and entry.get("id") == row_id:
            return self.context.instantiate_proxy(row_id)
        return None

    def add(self, row: dict) -> str:
        """Adds a row (column-name -> value), returns its generated row ID."""
        return self.context.add_table_row(self._object_id, row)

    def remove(self, row_id: str):
        entry = self.entries.get(row_id)
        if isinstance(entry, dict) and entry.get("id") == row_id:
            self.context.delete_table_row(self._object_id, row_id)
        else:
            raise KeyError(f"There is no row with ID {row_id} in this table")


def instantiate_table(object_id, entries=None) -> Table:
    instance = Table()
    instance._object_id = object_id
    instance.entries = entries if entries is not None else {}
    return instance


def timestamp_to_datetime(ms: int) -> _dt.datetime:
    return _dt.datetime.fromtimestamp(ms / 1000, tz=_dt.timezone.utc)


def datetime_to_timestamp(value: _dt.datetime) -> int:
    return int(value.timestamp() * 1000)

"""Host-side elemId -> device-slot index, compressed as counter ranges.

The reference resolves elemId references through per-object Immutable.js maps
(`_insertion`, /root/reference/backend/op_set.js:95-98,461-470). The device
engine instead keeps element *tables* on the TPU and resolves references on
the host, where the op columns originate anyway. Two facts make this cheap:

- elemIds minted by one actor have consecutive counters within a typing run,
  and runs land in consecutive device slots, so the index stores *ranges*
  ((actor, ctr0) .. +len -> slot0 .. +len), not individual elements;
- lookups are numpy ``searchsorted`` over the packed range starts — C-speed
  binary search, no device round trip, no int64 emulation on the TPU (int64
  sorts/searches run emulated and severalfold slower than int32 on v5e;
  design assumption, docs/MEASUREMENTS.md).

Keys pack as (actor_rank << 32 | ctr); counters stay < 2^31 so keys within a
range are consecutive integers and slot arithmetic is a subtraction.
"""

from __future__ import annotations

import numpy as np

from .._common import check_int32_envelope


def pack_keys(actor: np.ndarray, ctr: np.ndarray) -> np.ndarray:
    """(actor_rank, ctr) -> packed int64 key. Loud on envelope overflow:
    a ctr or rank past 2^31-1 (or negative) would corrupt the packing —
    adjacent keys would collide or reorder — instead of failing, so the
    guard raises OverflowError before any key escapes (VERDICT r5 item 3;
    tests/test_int32_guards.py)."""
    check_int32_envelope("elemId counter", ctr)
    check_int32_envelope("actor rank", actor)
    return (actor.astype(np.int64) << 32) | ctr.astype(np.int64)


def unpack_key(key: int) -> tuple:
    """packed key -> (actor_rank, ctr)."""
    return key >> 32, key & 0xFFFFFFFF


class DuplicateElemId(ValueError):
    """An inserted elemId overlaps an existing one (`key` is packed).

    The engine decodes `key` against its actor table for the user-facing
    message (the reference's duplicate-insertion inconsistency check,
    op_set.js applyInsert)."""

    def __init__(self, key: int):
        super().__init__("Duplicate list element ID")
        self.key = key


class ElemRangeIndex:
    """Sorted, coalesced (key range -> slot range) map."""

    __slots__ = ("starts", "lens", "slots", "_slot_view")

    def __init__(self):
        self.starts = np.empty(0, np.int64)   # packed first key of each range
        self.lens = np.empty(0, np.int64)
        self.slots = np.empty(0, np.int64)    # device slot of the first key
        self._slot_view = None                # lazy slot-sorted view

    @property
    def n_ranges(self) -> int:
        return len(self.starts)

    def merge(self, starts: np.ndarray, lens: np.ndarray,
              slots: np.ndarray) -> "ElemRangeIndex":
        """Return a new index with the ranges inserted (the caller commits it
        only after every other validity check passes, so a raising batch
        leaves the document untouched). Raises ValueError on any key overlap
        (the reference's duplicate-elemId inconsistency, op_set.js
        applyInsert)."""
        if len(starts) == 0:
            return self
        # sort only the NEW ranges (K log K), then place them into the
        # already-sorted index with one searchsorted + insert (O(R + K))
        # instead of re-argsorting all R + K ranges per round — the index
        # grows with document lifetime, the round's minted ranges do not.
        # Equal-start collisions order new-before-old; both orders raise
        # DuplicateElemId below (every range has len >= 1).
        new_starts = starts.astype(np.int64)
        new_lens = lens.astype(np.int64)
        new_slots = slots.astype(np.int64)
        if len(new_starts) > 1:
            order = np.argsort(new_starts, kind="stable")
            new_starts = new_starts[order]
            new_lens = new_lens[order]
            new_slots = new_slots[order]
        if self.n_ranges == 0:
            starts, lens, slots = new_starts, new_lens, new_slots
        else:
            pos = np.searchsorted(self.starts, new_starts, side="left")
            starts = np.insert(self.starts, pos, new_starts)
            lens = np.insert(self.lens, pos, new_lens)
            slots = np.insert(self.slots, pos, new_slots)
        ends = starts + lens
        if len(starts) > 1:
            bad = np.flatnonzero(ends[:-1] > starts[1:])
            if len(bad):
                raise DuplicateElemId(int(starts[bad[0] + 1]))
        # coalesce key- and slot-contiguous neighbors to keep the index small
        if len(starts) > 1:
            joined = (ends[:-1] == starts[1:]) & \
                     (slots[:-1] + lens[:-1] == slots[1:])
            if joined.any():
                head = np.concatenate([[True], ~joined])
                group = np.cumsum(head) - 1
                n = int(group[-1]) + 1
                g_start = starts[head]
                g_slot = slots[head]
                g_len = np.zeros(n, np.int64)
                np.add.at(g_len, group, lens)
                starts, lens, slots = g_start, g_len, g_slot
        out = ElemRangeIndex()
        out.starts, out.lens, out.slots = starts, lens, slots
        return out

    def lookup(self, keys: np.ndarray):
        """-> (slots int64, found bool) for packed query keys."""
        if self.n_ranges == 0:
            return (np.zeros(len(keys), np.int64),
                    np.zeros(len(keys), bool))
        pos = np.searchsorted(self.starts, keys, side="right") - 1
        safe = np.clip(pos, 0, None)
        found = (pos >= 0) & (keys < self.starts[safe] + self.lens[safe])
        slot = np.where(found, self.slots[safe] + (keys - self.starts[safe]), 0)
        return slot, found

    def slot_to_key(self, slots: np.ndarray):
        """Reverse lookup: device slots -> (actor_rank, ctr) of the element
        occupying each slot. Every live slot >= 1 is covered (each was
        registered when its insert was planned); raises on a slot outside
        every range. The slot-sorted view is cached — instances are
        immutable after `merge` except for `remap_actors`, which drops it."""
        view = self._slot_view
        if view is None:
            order = np.argsort(self.slots, kind="stable")
            view = (self.slots[order], self.lens[order], self.starts[order])
            self._slot_view = view
        s_slots, s_lens, s_starts = view
        slots = np.asarray(slots, np.int64)
        pos = np.searchsorted(s_slots, slots, side="right") - 1
        safe = np.clip(pos, 0, None)
        ok = (pos >= 0) & (slots < s_slots[safe] + s_lens[safe])
        if not ok.all():
            raise KeyError(
                f"slot {int(slots[np.flatnonzero(~ok)[0]])} not in index")
        key = s_starts[safe] + (slots - s_slots[safe])
        return key >> 32, key & 0xFFFFFFFF

    def remap_actors(self, remap: np.ndarray):
        """Re-rank the actor halves of the keys after interning inserted a
        new actor id below existing ones (rank order == lex order)."""
        if self.n_ranges == 0:
            return
        actor = (self.starts >> 32).astype(np.int64)
        ctr = self.starts & 0xFFFFFFFF
        self.starts = (remap[actor].astype(np.int64) << 32) | ctr
        order = np.argsort(self.starts, kind="stable")
        self.starts = self.starts[order]
        self.lens = self.lens[order]
        self.slots = self.slots[order]
        self._slot_view = None

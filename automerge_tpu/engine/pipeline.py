"""Host-side planning parallelism + the pipelined ingestion driver.

Round-5 profiling (docs/PROFILE_r5.md, BENCH_LAST_GOOD.json) put the
device commit region at ~87 ms while end-to-end trailed 3.5x behind it:
host planning (`prepare_s` 0.215 s) and the d2h text pull each outweigh
the commit, and the in-process overlap schedule LOST to serial even
though the same seam paid 1.697x on separate processors (cfg5d on-chip).
This module closes the planning half of that gap:

- `planner_pool()` — one small shared ThreadPoolExecutor. Every heavy
  planning pass (the native run-detection walker, numpy column passes)
  releases the GIL, so sharding one batch's planning across a few
  threads runs at real parallelism on multicore hosts and costs nothing
  on one core (`AMTPU_PLAN_WORKERS=1` disables sharding).
- `stage_h2d()` — chunked, asynchronous host->device staging via
  `jax.device_put`. Large value blobs split into chunks so transfers
  start flowing while later planning still runs, instead of one
  monolithic copy at the end; the prepare-side completion barrier
  (engine/base.py prepare_batch) is unchanged and still guarantees the
  plan's buffers are resident before commit.
- `PipelinedIngestor` — the K-deep in-flight batch ring (INTERNALS §9):
  a worker thread prepares batch k+1 *chained onto* batch k's
  still-uncommitted plan (engine/base.py `prepare_batch(after=...)`)
  while the caller thread commits batch k and the device executes its
  kernels. `slots` PreparedBatch slots bound the speculation (default
  `AMTPU_PIPELINE_DEPTH`, 4): at depth K the worker can run K-1 chained
  plans ahead of the commit front, so a long stream of
  causally-independent batches keeps host planning, h2d staging, commit
  bookkeeping, and device execution ALL saturated — one slow phase no
  longer stalls the others (double buffering only hid one phase; the
  ring amortizes all of them). Every commit is generation-checked, and
  a mismatch (the document mutated outside the pipeline) falls back to
  a fresh inline prepare instead of corrupting state. `stats` reports
  how the session actually ran (chained vs serial prepares, fallbacks,
  committed batches) — `bench.py --pipeline` records them next to the
  throughput number.

Jiffy's batch-update/snapshot split and PAM's bulk-parallel map
construction (PAPERS.md) are the shape being reproduced: bulk-plan on
the host in parallel, commit as pure dispatch.

The ring is planner-agnostic: the columnar planner (INTERNALS §10,
`engine/wire_columns.py` + `base._schedule_columnar`) chains its
pre-grouped plans through `prepare_batch(after=...)` unchanged — the
worker thread just plans in column space (batch-level decode caches
shared across the stream), and `AMTPU_COLUMNAR_PLAN=0` runs the same
ring over the legacy per-change planner
(tests/test_columnar_plan.py::test_ring_integration_both_planners).

The same worker-thread/queue/overlap discipline, lifted from per-doc to
per-lane, is `shard/parallel.LaneExecutor` (INTERNALS §24): one
persistent worker per shard lane runs whole stacked ingest rounds under
the lane's device context while the caller pre-decodes the NEXT round's
wire payloads — the ring's "plan k+1 while k commits" seam at mesh
granularity. Both layers share :func:`device_ctx_factory` for device
pinning.
"""

from __future__ import annotations

import os
import queue
import threading

import numpy as np

from .. import obs

_POOL = None
_POOL_LOCK = threading.Lock()


def plan_workers() -> int:
    """Worker count for sharded planning. 1 disables sharding."""
    try:
        w = int(os.environ.get("AMTPU_PLAN_WORKERS", "0"))
    except ValueError:
        w = 0
    if w <= 0:
        w = min(4, os.cpu_count() or 1)
    return max(1, w)


def pipeline_depth() -> int:
    """Default in-flight slot count of the batch ring (K). K-1 chained
    plans can run ahead of the commit front; 4 keeps planning, staging,
    commit, and device execution all occupied without unbounded
    speculation (each slot pins its plan's staged device buffers until
    commit). AMTPU_PIPELINE_DEPTH overrides; 1 degrades to serial."""
    try:
        k = int(os.environ.get("AMTPU_PIPELINE_DEPTH", "0"))
    except ValueError:
        k = 0
    return k if k >= 1 else 4


def planner_pool():
    """The ONE shared planning pool (lazy; None when workers == 1)."""
    global _POOL
    if plan_workers() == 1:
        return None
    with _POOL_LOCK:
        if _POOL is None:
            from concurrent.futures import ThreadPoolExecutor
            _POOL = ThreadPoolExecutor(
                max_workers=plan_workers(),
                thread_name_prefix="amtpu-plan")
    return _POOL


def device_ctx_factory(device):
    """A zero-arg context-manager factory pinning work to `device`
    (``jax.default_device``), or a nullcontext factory when `device` is
    None. The one device-pinning idiom shared by the per-doc ring
    (:class:`PipelinedIngestor`) and the per-lane executor
    (shard/parallel, INTERNALS §24) — resolved once so the hot paths
    never re-import jax per call."""
    if device is None:
        import contextlib

        def _null():
            return contextlib.nullcontext()
        return _null
    import jax
    return lambda: jax.default_device(device)


def _chunk_elems(arr: np.ndarray) -> int:
    """Elements per staging chunk (env-tunable byte budget)."""
    try:
        mb = float(os.environ.get("AMTPU_STAGE_CHUNK_MB", "4"))
    except ValueError:
        mb = 4.0
    if mb <= 0:
        return 0
    return max(1, int(mb * (1 << 20)) // max(1, arr.dtype.itemsize))


def stage_h2d(arr: np.ndarray):
    """Asynchronously stage a host array to the default device.

    1-D arrays above the chunk budget ship as several `jax.device_put`
    calls reassembled with one device-side concatenate: each chunk's
    transfer is enqueued immediately (device_put does not block), so
    byte movement overlaps the remaining host planning instead of
    serializing after it. Small arrays and matrices ship whole. The
    caller still owns the completion barrier."""
    import jax
    import jax.numpy as jnp
    ce = _chunk_elems(arr)
    if arr.ndim != 1 or ce == 0 or len(arr) <= ce:
        return jax.device_put(arr)
    parts = [jax.device_put(arr[i: i + ce])
             for i in range(0, len(arr), ce)]
    return jnp.concatenate(parts)


class PipelineError(RuntimeError):
    """A background prepare failed; the original exception chains."""


_SERIAL = object()   # worker marker: batch not chainable, prepare inline


class PipelinedIngestor:
    """K-deep in-flight batch ring for one CausalDeviceDoc.

    Contract: while a pipeline session is open, the document is mutated
    ONLY through it. The worker thread prepares each fed batch chained
    onto the previous (still pending) plan's shadow state
    (`prepare_batch(after=...)`), so planning of batch k+1 overlaps both
    the caller's commit bookkeeping for batch k and the device's kernel
    execution; `slots` bounds the speculation depth (2 = classic double
    buffering; default AMTPU_PIPELINE_DEPTH, 4 — the sustained-streaming
    ring). Commits stay generation-checked: if the document moved
    under a pending plan (outside mutation, or a chained base that
    failed), `flush()` degrades that batch to a fresh inline
    prepare+commit — semantics are always exactly apply_batch's.

    `donate=True` additionally switches the document onto the donated
    commit kernels for the session (ops/ingest.py `*_donated`): XLA may
    write each round's output tables in place of the inputs, so
    steady-state device allocation is flat across the ring instead of
    holding K dead table generations. The flag is restored on close();
    see engine/base.py `donate_buffers` for why it is incompatible with
    the checkpoint writer's zero-copy grab.

    Batches whose actor interning would reorder existing ranks cannot be
    planned concurrently with an uncommitted base (the remap would
    invalidate the base plan's staged columns — see
    engine/base.py prepare_batch); the worker marks those and the caller
    prepares them serially after the preceding commit. Wide merge loads
    intern fresh actors in lexicographic append position, so the chained
    path is the common case.
    """

    def __init__(self, doc, slots: int = None, donate: bool = False,
                 device=None):
        self.doc = doc
        #: shard-lane pinning (INTERNALS §15): every prepare (worker
        #: thread h2d staging) and commit (caller thread dispatch) runs
        #: inside ``jax.default_device(device)``, so a per-lane ring
        #: keeps its document's tables and staged plan buffers on ITS
        #: lane's device. None = the process default, unchanged.
        self.device = device
        self._n_slots = max(1, pipeline_depth() if slots is None else slots)
        self._slots = threading.Semaphore(self._n_slots)
        self._in: "queue.Queue" = queue.Queue()
        self._out: "queue.Queue" = queue.Queue()
        self._n_fed = 0
        self._total_fed = 0
        self._cv = threading.Condition()
        self._n_committed = 0
        self._fallbacks = 0     # commits that degraded to a fresh prepare
        self._chained = 0       # background prepares chained onto a base
        self._serial = 0        # batches the caller had to prepare inline
        # running min/max of the per-commit device-interaction deltas
        # (doc.last_commit_stats): the ring's public budget surface, so
        # consumers never re-implement the drain loop to sample it
        self._budget = {"dispatches_min": None, "dispatches_max": 0,
                        "syncs_min": None, "syncs_max": 0}
        self._closing = False
        self._donate = donate
        self._donate_prior = getattr(doc, "donate_buffers", False)
        if donate:
            doc.donate_buffers = True
        # serializes prepare_batch calls between the worker and the
        # caller's degraded-path inline re-prepares (commit_next): two
        # concurrent UNCHAINED prepares could race actor interning
        self._prep_lock = threading.Lock()
        self._device_ctx = self._make_device_ctx()
        self._thread = threading.Thread(
            target=self._worker, name="amtpu-pipeline", daemon=True)
        self._started = False

    def _make_device_ctx(self):
        return device_ctx_factory(self.device)

    # -- context manager -------------------------------------------------
    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        # a clean exit commits everything still in flight — silently
        # dropping fed batches would violate the apply_batch-equivalence
        # contract; an exceptional exit just tears the worker down
        try:
            if exc_type is None:
                self.flush()
        finally:
            self.close()
        return False

    def close(self):
        """Terminal: a closed ingestor cannot be fed again (its worker
        thread is joined; start a new instance for a new session)."""
        with self._cv:
            self._closing = True
            self._cv.notify_all()       # unpark a quiescence wait
        if self._started:
            self._in.put(None)
            self._thread.join()
            self._started = False
        if self._donate:
            self.doc.donate_buffers = self._donate_prior

    @property
    def stats(self) -> dict:
        """How the session actually ran: ring depth, committed batches,
        chained vs caller-inline (serial) prepares, and degraded-path
        fallbacks. Carried in bench --pipeline records so a ring that
        silently degraded to serial planning cannot pass as pipelined."""
        with self._cv:
            return {"depth": self._n_slots,
                    "committed": self._n_committed,
                    "chained_prepares": self._chained,
                    "fresh_prepares": (self._n_committed - self._chained
                                       - self._serial),
                    "serial_prepares": self._serial,
                    "fallbacks": self._fallbacks,
                    "per_commit_budget": dict(self._budget)}

    # -- feeding / committing --------------------------------------------
    def feed(self, batch):
        """Queue a batch for background planning. At the `slots` bound,
        feed COMMITS the oldest in-flight batch inline instead of
        blocking — commits happen on the caller thread only, so waiting
        on the semaphore with a full pipeline would deadlock (nobody
        else can drain it)."""
        if self._closing:
            raise RuntimeError("PipelinedIngestor is closed")
        if not self._started:
            self._thread.start()
            self._started = True
        while not self._slots.acquire(blocking=False):
            self.commit_next()
        self._in.put((self._total_fed, batch))
        self._total_fed += 1
        self._n_fed += 1

    def commit_next(self):
        """Commit the oldest fed batch (blocking on its prepare)."""
        if self._n_fed <= 0:
            raise RuntimeError("commit_next with no batch fed")
        self._n_fed -= 1
        k, batch, plan, err = self._out.get()
        _t0 = obs.now() if obs.ENABLED else 0
        serial = fallback = False
        try:
            if err is not None:
                raise PipelineError(
                    "background prepare failed") from err
            if plan is _SERIAL:
                serial = True
                with self._cv:
                    self._serial += 1
                with self._prep_lock, self._device_ctx():
                    plan = self.doc.prepare_batch(batch)
            try:
                with self._device_ctx():
                    self.doc.commit_prepared(plan)
            except ValueError:
                # generation mismatch: the document moved under the
                # pending plan — re-plan against live state and commit
                # (the documented degraded path, never silent corruption).
                # Bump the fallback epoch so the worker abandons the now-
                # dead chain base instead of chaining onto it forever.
                fallback = True
                if obs.ENABLED:
                    obs.event("ring", "fallback",
                              args={"doc": self.doc.obj_id, "slot": k})
                with self._cv:
                    self._fallbacks += 1
                with self._prep_lock, self._device_ctx():
                    plan = self.doc.prepare_batch(batch)
                with self._device_ctx():
                    self.doc.commit_prepared(plan)
        finally:
            with self._cv:
                self._n_committed += 1
                self._cv.notify_all()
            self._slots.release()
            if obs.ENABLED:
                obs.span("ring", "commit", _t0, args={
                    "doc": self.doc.obj_id, "slot": k,
                    "gen": self.doc._gen, "serial": serial,
                    "fallback": fallback})
        # reached on successful commits only: fold the committed batch's
        # device-interaction delta into the public budget surface
        st = getattr(self.doc, "last_commit_stats", None)
        if st:
            with self._cv:
                b = self._budget
                for key in ("dispatches", "syncs"):
                    b[key + "_max"] = max(b[key + "_max"], st[key])
                    b[key + "_min"] = (st[key] if b[key + "_min"] is None
                                       else min(b[key + "_min"], st[key]))

    def flush(self):
        """Commit every batch still in flight; returns the document."""
        while self._n_fed:
            self.commit_next()
        return self.doc

    def run(self, batches):
        """Pipeline a whole sequence: feed + commit with `slots` lag."""
        for b in batches:
            self.feed(b)
            # drain down to (slots - 1) speculative plans so the worker
            # keeps its lookahead while feed() can never block on an
            # exhausted semaphore (slots=1 degrades to a serial schedule)
            while self._n_fed >= self._n_slots:
                self.commit_next()
        return self.flush()

    # -- worker ----------------------------------------------------------
    def _worker(self):
        base = None       # the previous (possibly uncommitted) plan
        seen_fallbacks = 0
        while True:
            item = self._in.get()
            if item is None:
                return
            k, batch = item
            plan = err = None
            try:
                with self._cv:
                    if self._fallbacks != seen_fallbacks:
                        # a commit degraded to a fresh inline prepare:
                        # any pending chain base is dead (its
                        # committed_gen will never match) — drop it and
                        # re-enter via the quiescence path
                        seen_fallbacks = self._fallbacks
                        base = None
                if base is None:
                    # no pending plan to chain onto: a live-state prepare
                    # must not race a commit still mutating the document,
                    # so wait until every earlier batch has committed
                    with self._cv:
                        self._cv.wait_for(
                            lambda: self._n_committed >= k
                            or self._closing)
                    if self._closing and self._n_committed < k:
                        # abandoned session: hand the batch back serial
                        if obs.ENABLED:
                            obs.event("ring", "abort", args={
                                "doc": self.doc.obj_id, "slot": k})
                        self._out.put((k, batch, _SERIAL, None))
                        continue
                try:
                    _t0 = obs.now() if obs.ENABLED else 0
                    with self._prep_lock, self._device_ctx():
                        plan = self.doc.prepare_batch(batch, after=base)
                    if obs.ENABLED:
                        obs.span("ring", "plan", _t0, args={
                            "doc": self.doc.obj_id, "slot": k,
                            "chained": base is not None})
                    if base is not None:
                        with self._cv:
                            self._chained += 1
                except ValueError:
                    # not chainable (actor remap / missing shadow):
                    # the caller prepares this one inline after the
                    # preceding commit lands
                    plan = _SERIAL
                    if obs.ENABLED:
                        obs.event("ring", "serial", args={
                            "doc": self.doc.obj_id, "slot": k})
            except BaseException as e:   # pragma: no cover - defensive
                err = e
                plan = None
            self._out.put((k, batch, plan, err))
            base = plan if plan not in (None, _SERIAL) else None

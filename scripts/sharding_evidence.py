"""Evidence for the elem-axis sharding story: compiled-HLO collective audit
+ 1-vs-N virtual-device scaling of the sharded merge.

Writes docs/SHARDING_r3.md. Run with the scrubbed CPU env:
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
      XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python scripts/sharding_evidence.py
"""

import os
import re
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from automerge_tpu.parallel.mesh import (example_doc_tables, make_mesh,  # noqa: E402
                                         merge_step)

COLLECTIVES = ("all-gather", "all-reduce", "all-to-all", "collective-permute",
               "reduce-scatter")


def audit(mesh, n_docs, cap):
    shard = NamedSharding(mesh, P("doc", "elem"))
    fn = jax.jit(jax.vmap(merge_step), in_shardings=(shard,) * 6,
                 out_shardings=(shard, shard, NamedSharding(mesh, P("doc"))))
    tables = [jax.device_put(np.asarray(t), shard)
              for t in example_doc_tables(n_docs, cap, seed=3)]
    compiled = fn.lower(*tables).compile()
    hlo = compiled.as_text()
    counts = {c: len(re.findall(rf"\b{c}\b", hlo)) for c in COLLECTIVES}
    counts = {c: n for c, n in counts.items() if n}
    # largest replicated intermediate: scan for full-shape ops vs sharded
    full_shape = f"s32[{n_docs},{cap}]"
    n_full = hlo.count(full_shape + "{")  # layout-annotated full tensors
    return counts, n_full, tables, fn


def scaling(cap_per_dev=2048, n_docs=8):
    """Wall time of the sharded merge at 1 vs N virtual devices, same total
    work (CPU devices: indicative of work distribution, not TPU rates)."""
    rows = []
    n = len(jax.devices())
    for doc_axis, elem_axis in ((1, 1), (n, 1), (1, n)):
        devs = jax.devices()[: doc_axis * elem_axis]
        grid = np.asarray(devs).reshape(doc_axis, elem_axis)
        from jax.sharding import Mesh
        mesh = Mesh(grid, ("doc", "elem"))
        shard = NamedSharding(mesh, P("doc", "elem"))
        fn = jax.jit(jax.vmap(merge_step), in_shardings=(shard,) * 6,
                     out_shardings=(shard, shard,
                                    NamedSharding(mesh, P("doc"))))
        tables = [jax.device_put(np.asarray(t), shard)
                  for t in example_doc_tables(n_docs, cap_per_dev, seed=5)]
        jax.block_until_ready(fn(*tables))  # compile
        t0 = time.perf_counter()
        for _ in range(3):
            out = fn(*tables)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / 3
        rows.append((f"({doc_axis} doc, {elem_axis} elem)", dt * 1e3))
    return rows


def main():
    n = len(jax.devices())
    mesh = make_mesh()
    counts_mixed, full_mixed, _, _ = audit(mesh, n_docs=8, cap=2048)
    mesh_elem = make_mesh(doc_axis=1)
    counts_elem, full_elem, _, _ = audit(mesh_elem, n_docs=1, cap=8192)
    mesh_doc = make_mesh(doc_axis=n)
    counts_doc, _, _, _ = audit(mesh_doc, n_docs=n * 2, cap=1024)
    rows = scaling()

    doc = f"""# Sharding evidence — round 3 ({n} virtual CPU devices)

Claim under test (parallel/mesh.py): documents shard over the `doc` axis
with no cross-device traffic; one huge document shards along `elem`, with
XLA inserting collectives for the linearization's sort and pointer-doubling
gathers. The round-2 verdict asked for proof the compiled program does not
simply all-gather the whole table.

## Compiled-HLO collective audit

`sharded_merge_step` lowered + compiled with explicit in/out shardings,
then grepped for collective ops:

| mesh | shapes | collectives in compiled module |
|---|---|---|
| {tuple(mesh_doc.shape.items())} | {n * 2} docs x 1024 (doc-only) | {counts_doc or "NONE"} |
| {tuple(mesh.shape.items())} | 8 docs x 2048 | {counts_mixed or "none"} |
| {tuple(mesh_elem.shape.items())} | 1 doc x 8192 (elem-only) | {counts_elem or "none"} |

Reading: the doc-only mesh compiles with **{counts_doc and "collectives" or "ZERO collectives"}**
— the vmap dimension is embarrassingly parallel, as claimed. On the `elem` axis
the sort and pointer-doubling gathers are NOT locally partitionable, and
the partitioner inserts the gathers/permutes above — i.e. the element axis
pays real communication, it is not silently replicated-per-device; output
buffers stay sharded (asserted in tests/test_parallel.py, incl. a single
document spanning every shard many times over).

## Honest finding

XLA's SPMD partitioner resolves the linearization's `sort` by gathering
the sort operand across the elem axis (visible as all-gather/all-to-all
above) — the standard behavior for unpartitionable ops. So elem-axis
sharding today buys **memory capacity** (a document larger than one
device's HBM) and parallel elementwise/scan phases, while the sort phase
serializes through collectives. The designed fix is the Pallas
fused-segment-scan building block (ops/scan_pallas.py): block-local scans
with explicit carry exchange, avoiding the gather — wiring it into the
sharded path is future work and is tracked in docs/PROFILE_r3.md.

## 1-vs-{n} virtual-device scaling (same per-device work, CPU: indicative
of distribution, not TPU rates)

| mesh (doc, elem) | wall/step |
|---|---|
""" + "".join(f"| {name} | {ms:.1f} ms |\n" for name, ms in rows) + f"""
Generated by scripts/sharding_evidence.py on {n} virtual CPU devices.
"""
    out = os.path.join(os.path.dirname(__file__), "..", "docs",
                       "SHARDING_r3.md")
    with open(out, "w") as fh:
        fh.write(doc)
    print(doc)


if __name__ == "__main__":
    main()

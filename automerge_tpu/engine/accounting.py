"""Device dispatch & blocking-sync accounting for the streaming tier.

The sustained-throughput story (INTERNALS §9) only holds if the engine's
device-interaction COUNT is bounded: on a remote-attached chip every
program launch pays dispatch overhead and every blocking sync pays a full
link round trip (~70 ms through this environment's WAN tunnel, ~1 ms on
PCIe), so an accidental extra sync per batch is invisible on cpu and
catastrophic at deployment. Counting is therefore first-class and
ASSERTED, not profiled after the fact:

- a **dispatch** is one jitted device program launched by the engine
  (merge/materialize/residual/scatter/linearize kernels);
- a **blocking sync** is one forced device->host completion — a d2h
  fetch the host logic consumes (`np.asarray` of a device array, scalar
  reads) or an explicit `block_until_ready`. Async h2d staging
  (`device_put`) is neither: it overlaps planning by design and is
  tracked separately as `staged_h2d_bytes`.

Counters live in three places, updated together by the engine's
`_count_dispatch`/`_count_sync` hooks (engine/base.py):

- per-document (`CausalDeviceDoc.dispatch_stats`), with the last
  committed batch's delta broken out (`last_commit`), so the pipeline
  ring can assert its per-batch budget;
- the process-wide totals here, so call sites that span documents (the
  interactive `am.change` path through backend/device.py) can measure a
  whole operation with `track()` regardless of which docs it touched;
- a per-THREAD mirror (`thread_snapshot`/`track(...).thread_stats`):
  `track()`'s process delta is documented non-isolated against
  concurrent device work on other threads, and nothing used to enforce
  that — the thread-local mirror gives the budget tests
  (tests/test_dispatch_budget.py) a delta that is correct by
  construction even while a pipeline ring or checkpoint worker runs.
  The process totals stay bit-compatible: same dict, same keys, same
  update points.

Since ISSUE 6 counts also carry a KERNEL LABEL: `record_dispatch(...,
label="apply_mixed_round")` aggregates a per-label histogram
(`labeled_snapshot()`) and feeds the obs flight-recorder counters
(`device.dispatch:<label>`), so "7 dispatches" decomposes into WHICH
programs launched — the two integers stay, the histogram rides along.
Blocking syncs may additionally carry the measured blocked duration
(`dur_ns`), giving a labeled time histogram of where the host actually
waited on the device.

The regression bars: tests/test_dispatch_budget.py pins the write-behind
`am.change` path and the ring's per-commit budget; `bench.py --pipeline`
and benchmarks cfg7 carry the measured counts in their records.

Since ISSUE 15 the same counters also meter BYTES, not just counts:
`record_h2d(nbytes)` at the engine's staging seams (prepare_batch's
summed plan staging, the stacked round uploads, the slow-register
writeback) and the `d2h_bytes=` argument of `record_sync` at every
blocking fetch site — so `track()` deltas carry exact
`h2d_bytes`/`d2h_bytes` and the device-truth tier (obs/device_truth.py,
INTERNALS §19) can report bytes-staged-per-op without estimating.
"""

from __future__ import annotations

import threading

from .. import obs

_LOCK = threading.Lock()

# process-wide running totals; monotonically increasing
TOTALS = {"dispatches": 0, "syncs": 0, "h2d_bytes": 0, "d2h_bytes": 0}

# per-label histograms: label -> {"n": launches/syncs, "ns": total
# blocked ns (syncs with a measured duration only)}. Same lock as TOTALS.
LABELS = {"dispatch": {}, "sync": {}}

# per-thread mirror of TOTALS (each thread only ever touches its own
# dict, so reads of ANOTHER thread's counters see, at worst, a value
# that is one in-flight increment stale — fine for deltas taken on the
# measuring thread itself)
_TLS = threading.local()


def _thread_totals() -> dict:
    t = getattr(_TLS, "totals", None)
    if t is None:
        t = _TLS.totals = {"dispatches": 0, "syncs": 0,
                           "h2d_bytes": 0, "d2h_bytes": 0}
    return t


def _bump_label(kind: str, label, n: int, dur_ns: int = 0):
    h = LABELS[kind]
    agg = h.get(label)
    if agg is None:
        h[label] = {"n": n, "ns": dur_ns}
    else:
        agg["n"] += n
        agg["ns"] += dur_ns


def record_dispatch(n: int = 1, acct: dict = None, label: str = None):
    """Count `n` device program launches (and mirror into a per-doc
    counter dict under the same lock — the pipeline ring's worker thread
    and caller thread both dispatch against one document). `label` names
    the kernel for the labeled histogram + obs counters."""
    with _LOCK:
        TOTALS["dispatches"] += n
        if acct is not None:
            acct["dispatches"] += n
        if label is not None:
            _bump_label("dispatch", label, n)
    _thread_totals()["dispatches"] += n
    if obs.ENABLED and label is not None:
        obs.counter("device", f"dispatch:{label}", n)


def record_sync(n: int = 1, acct: dict = None, label: str = None,
                dur_ns: int = 0, d2h_bytes: int = 0):
    """Count `n` blocking device->host syncs; `dur_ns` (optional) is the
    measured blocked time for the labeled duration histogram;
    `d2h_bytes` (optional) the exact bytes the fetch pulled host-side —
    fed at the site where the numpy result is at hand, so the meter is
    exact, never estimated."""
    with _LOCK:
        TOTALS["syncs"] += n
        if d2h_bytes:
            TOTALS["d2h_bytes"] += d2h_bytes
        if acct is not None:
            acct["syncs"] += n
            if d2h_bytes:
                acct["d2h_bytes"] = acct.get("d2h_bytes", 0) + d2h_bytes
        if label is not None:
            _bump_label("sync", label, n, dur_ns)
    t = _thread_totals()
    t["syncs"] += n
    if d2h_bytes:
        t["d2h_bytes"] += d2h_bytes
    if obs.ENABLED and label is not None:
        obs.counter("device", f"sync:{label}", n)


def record_h2d(nbytes: int, acct: dict = None):
    """Count exact host->device staged bytes at an engine staging seam
    (prepare_batch plan staging, stacked round uploads, slow-register
    writeback). Transfer COUNTS stay where they were (dispatches /
    staged upload stats); this meters volume."""
    if not nbytes:
        return
    with _LOCK:
        TOTALS["h2d_bytes"] += nbytes
        if acct is not None:
            acct["h2d_bytes"] = acct.get("h2d_bytes", 0) + nbytes
    _thread_totals()["h2d_bytes"] += nbytes


def snapshot() -> dict:
    with _LOCK:
        return dict(TOTALS)


def delta_since(snap: dict) -> dict:
    cur = snapshot()
    return {k: cur[k] - snap.get(k, 0) for k in cur}


def thread_snapshot() -> dict:
    """This thread's own running totals (no lock needed: thread-local)."""
    return dict(_thread_totals())


def labeled_snapshot() -> dict:
    """Copy of the per-label histograms:
    {"dispatch": {label: {"n", "ns"}}, "sync": {...}}."""
    with _LOCK:
        return {k: {lbl: dict(agg) for lbl, agg in h.items()}
                for k, h in LABELS.items()}


class track:
    """Context manager measuring the dispatch/sync delta of a region:

        with accounting.track() as t:
            doc = am.change(doc, ...)
        assert t.stats["dispatches"] <= BUDGET

    `stats` is the PROCESS-wide delta (covers every document the region
    touched, but also any concurrent device work on other threads).
    `thread_stats` is the delta of THIS thread's own counters — isolated
    against concurrent threads by construction, the form the budget
    tests assert on. For single-threaded regions the two are equal."""

    def __init__(self):
        self.stats: dict = {}
        self.thread_stats: dict = {}

    def __enter__(self):
        self._snap = snapshot()
        self._tsnap = thread_snapshot()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.stats = delta_since(self._snap)
        tcur = thread_snapshot()
        self.thread_stats = {k: tcur[k] - self._tsnap.get(k, 0)
                             for k in tcur}
        return False

"""BASELINE.md benchmark configs 1-5 + conflict-heavy (6),
interactive-latency (7), and frontend-splice (8) configs.

Usage: python -m benchmarks.run_all [--quick] [--record ROUND]

One JSON line per config on stdout; `--record 3` additionally writes them
to BENCH_CONFIGS_r03.json (the per-round committed record). Config 5 (the
headline 1M-char / 10k-actor merge) is bench.py at the repo root — the
driver runs it separately; --record re-runs it here in a subprocess so the
record file covers the whole surface. --quick shrinks configs 3 and 4 for
fast iteration.

Each config asserts it exercised the path it claims (e.g. cfg4 asserts the
nested Trellis document stayed on the DEVICE tier with zero graduations;
cfg6 asserts the residual/slow register path actually ran).
"""

import json
import sys
import time

import numpy as np

from benchmarks.common import (TRACKING_ONLY, emit, setup_jax_cache, timed,
                               write_record)

setup_jax_cache()


def config1_text_two_actor(n_chars: int = 1000):
    """Single Text doc, 2 actors, concurrent 1k-char insert (facade path)."""
    import automerge_tpu as am

    def run():
        a = am.change(am.init("actor-a"),
                      lambda d: d.__setitem__("t", am.Text("x" * 10)))
        b = am.merge(am.init("actor-b"), a)
        half = n_chars // 2
        a2 = am.change(a, lambda d: d["t"].insert_at(5, *("a" * half)))
        b2 = am.change(b, lambda d: d["t"].insert_at(5, *("b" * half)))
        m1 = am.merge(a2, b2)
        m2 = am.merge(b2, a2)
        assert str(m1["t"]) == str(m2["t"])
        assert len(str(m1["t"])) == 10 + n_chars

    dt = timed(run, warmups=1, reps=2)
    emit("cfg1_text_2actor_concurrent_insert", n_chars / dt, "chars/s",
         threshold=TRACKING_ONLY)


def config2_map_counter(n_actors: int = 100, n_keys: int = 100):
    """Map doc: n_actors concurrent actors each setting n_keys keys plus a
    shared counter, merged through the device map engine."""
    from automerge_tpu.engine import DeviceMapDoc, MapChangeBatch

    base = {"actor": "base", "seq": 1, "deps": {}, "ops":
            [{"action": "set", "obj": "m", "key": "count", "value": 0,
              "datatype": "counter"}]}
    changes = []
    for a in range(n_actors):
        ops = [{"action": "set", "obj": "m", "key": f"k{a}-{i}", "value": i}
               for i in range(n_keys)]
        ops.append({"action": "inc", "obj": "m", "key": "count", "value": 1})
        changes.append({"actor": f"actor-{a:04d}", "seq": 1,
                        "deps": {"base": 1}, "ops": ops})
    batch = MapChangeBatch.from_changes(changes, "m")
    n_ops = batch.n_ops

    def run():
        doc = DeviceMapDoc("m")
        doc.apply_changes([base])
        doc.apply_batch(batch)
        assert doc.get("count") == n_actors
        assert len(doc) == n_actors * n_keys + 1

    dt = timed(run, warmups=1, reps=2)
    emit("cfg2_map_counter_100x100", n_ops / dt, "ops/s",
         threshold=TRACKING_ONLY)


def config3_docset(n_docs: int = 1000, n_actors: int = 10,
                   chars_per_actor: int = 50):
    """DocSet of n_docs text docs, n_actors concurrent writers per doc,
    merged in ONE vmapped device program over the doc axis (the reference
    loops one doc at a time, src/doc_set.js:29-37)."""
    from automerge_tpu.engine import DeviceTextDocSet, TextChangeBatch
    from automerge_tpu.engine.columnar import HEAD_PARENT, KIND_INS, KIND_SET

    def doc_batch(obj_id: str, seed: int) -> TextChangeBatch:
        """n_actors concurrent typing runs from the head of an empty doc."""
        run = chars_per_actor
        n_ops = n_actors * run * 2
        actors = [f"actor-{i:03d}" for i in range(n_actors)]
        op_change = np.repeat(np.arange(n_actors, dtype=np.int32), run * 2)
        kind = np.tile(np.array([KIND_INS, KIND_SET], np.int8),
                       n_actors * run)
        ta = np.repeat(np.arange(n_actors, dtype=np.int32), run * 2)
        tc = np.zeros(n_ops, np.int32)
        pa = np.zeros(n_ops, np.int32)
        pc = np.zeros(n_ops, np.int32)
        val = np.zeros(n_ops, np.int64)
        ctrs = np.arange(1, run + 1, dtype=np.int32)
        for a in range(n_actors):
            s = a * run * 2
            tc[s: s + 2 * run: 2] = ctrs
            tc[s + 1: s + 2 * run: 2] = ctrs
            pa[s] = HEAD_PARENT
            pa[s + 2: s + 2 * run: 2] = a
            pc[s + 2: s + 2 * run: 2] = ctrs[:-1]
            val[s + 1: s + 2 * run: 2] = 97 + ((a + seed) % 26)
        return TextChangeBatch(
            obj_id=obj_id, actors=actors,
            seqs=np.ones(n_actors, np.int32), deps=[{}] * n_actors,
            messages=[None] * n_actors, op_change=op_change, op_kind=kind,
            op_target_actor=ta, op_target_ctr=tc, op_parent_actor=pa,
            op_parent_ctr=pc, op_value=val, actor_table=actors,
            value_pool=[])

    batches = [doc_batch(f"d{d}", d) for d in range(n_docs)]
    n_ops = sum(b.n_ops for b in batches)

    def run():
        ds = DeviceTextDocSet([f"d{d}" for d in range(n_docs)],
                              capacity=n_actors * chars_per_actor + 64)
        ds.apply_batches({f"d{d}": b for d, b in enumerate(batches)})
        total = sum(len(t) for t in ds.texts().values())
        assert total == n_docs * n_actors * chars_per_actor

    dt = timed(run, warmups=1, reps=1)
    emit("cfg3_docset_1k_docs", n_ops / dt, "ops/s",
         threshold=TRACKING_ONLY)
    emit("cfg3_docset_docs_per_sec", n_docs / dt, "docs/s",
         threshold=TRACKING_ONLY)


def trellis_changes(n_actors: int, n_cards: int = 10):
    """The cfg4 workload: a shared nested board + n_actors concurrent
    mixed edits (task appends, title retitles, task deletes), minted on
    the oracle tier (the emitted change JSON is backend-independent, and
    building n_actors peers on the device tier would pay thousands of
    tunnel round trips in untimed setup). Returns (base doc, flattened
    changes, n_ops). Shared with benchmarks/cfg4_smoke.py so the CI
    smoke and the recorded config can never measure different shapes."""
    import automerge_tpu as am
    from automerge_tpu.backend import facade as oracle_backend

    base = am.change(am.init("base"), lambda d: d.update(
        {"cards": [{"title": f"card{i}", "tasks": [f"t{j}" for j in range(3)]}
                   for i in range(n_cards)]}))
    base_changes = am.get_all_changes(base)
    all_changes = []
    for a in range(n_actors):
        peer = am.apply_changes(
            am.init({"actorId": f"actor-{a:05d}",
                     "backend": oracle_backend.Backend}), base_changes)
        k = a % n_cards
        if a % 3 == 0:
            peer2 = am.change(peer, lambda d, k=k: d["cards"][k]["tasks"]
                              .append(f"new-{a}"))
        elif a % 3 == 1:
            peer2 = am.change(peer, lambda d, k=k: d["cards"][k]
                              .__setitem__("title", f"retitled-{a}"))
        else:
            peer2 = am.change(peer, lambda d, k=k: d["cards"][k]["tasks"]
                              .__delitem__(0))
        all_changes.extend(am.get_changes(base, peer2))
    n_ops = sum(len(c["ops"]) for c in all_changes)
    return base, all_changes, n_ops


def config4_trellis(n_actors: int = 1000, quick: bool = False):
    """Trellis-style nested cards[]/tasks[]: n_actors concurrent actors do
    mixed insert/update/delete on a shared board, merged on the DEVICE
    nested-document tier (asserted: no graduation). Since the stacked
    multi-object tier (engine/stacked.py, INTERNALS §12) the row also
    records the merge's device-dispatch terms — dispatch_per_op and the
    per-round stacked stats — so the old ~270-device_put per-object
    ceiling and its removal are both machine-visible, and the stacked
    path's object-count-independent budget is ASSERTED in the run."""
    import automerge_tpu as am
    from automerge_tpu import frontend as Frontend
    from automerge_tpu.backend import device as device_backend
    from automerge_tpu.engine import accounting, stacked

    if quick:
        n_actors = 100
    base, all_changes, n_ops = trellis_changes(n_actors)

    device_backend.GRADUATION_STATS.clear()
    acct: dict = {}

    def run():
        from automerge_tpu.engine.accounting import labeled_snapshot
        stacked.LAST_STATS.clear()
        before = labeled_snapshot()["dispatch"]
        with accounting.track() as tr:
            merged = am.apply_changes(base, all_changes)
        after = labeled_snapshot()["dispatch"]
        acct["merge_dispatches"] = tr.thread_stats["dispatches"]
        acct["merge_syncs"] = tr.thread_stats["syncs"]
        acct["labels"] = {
            lbl: agg["n"] - before.get(lbl, {}).get("n", 0)
            for lbl, agg in after.items()
            if agg["n"] - before.get(lbl, {}).get("n", 0) > 0}
        acct["stacked"] = dict(stacked.LAST_STATS)
        assert len(am.to_json(merged)["cards"]) == 10
        # path assertion: the nested board was served by the device tier
        assert isinstance(Frontend.get_backend_state(merged),
                          device_backend.DeviceBackendState)
        assert device_backend.GRADUATION_STATS == {}

    dt = timed(run, warmups=0, reps=1)
    st = acct["stacked"]
    extra = {}
    if st:
        # the tentpole's acceptance criterion, enforced in the recorded
        # run itself: dispatches <= 8 + 16/round, object-count-independent
        stacked.assert_round_budget(st)
        extra["stacked"] = st
        extra["dispatch_per_round"] = round(
            st["dispatches"] / max(1, st["rounds"]), 2)
        extra["dispatch_budget"] = (
            "asserted in code: stacked merge <= "
            f"{stacked.APPLY_DISPATCH_BASE} + "
            f"{stacked.PASS_DISPATCH_BUDGET} device programs per "
            "round-pass (>= 1 pass per causal round), independent of "
            "object count (engine/stacked.py)")
    else:
        extra["dispatch_budget"] = ("per-object comparator "
                                    "(AMTPU_STACKED_ROUNDS=0): unbudgeted")
    emit(f"cfg4_trellis_nested_{n_actors}_actors", n_ops / dt, "ops/s",
         tier="device",
         merge_dispatch_total=acct["merge_dispatches"],
         dispatch_per_op=round(acct["merge_dispatches"] / n_ops, 4),
         merge_sync_total=acct["merge_syncs"],
         dispatch_labels=acct["labels"],
         **extra,
         threshold=TRACKING_ONLY)


def config6_conflict_heavy(n_actors: int = 200, n_targets: int = 500):
    """Residual/slow-path config: n_actors concurrently overwrite the SAME
    n_targets elements (multi-writer registers -> conflicts), plus deletes
    and counter increments — everything the dense run path skips. Times
    apply_residual + the host slow register path (asserted: conflicts
    minted, i.e. the slow path actually ran)."""
    from automerge_tpu.engine import DeviceTextDoc, TextChangeBatch

    base_ops = []
    for i in range(1, n_targets + 1):
        key = "_head" if i == 1 else f"base:{i - 1}"
        base_ops.append({"action": "ins", "obj": "t", "key": key, "elem": i})
        base_ops.append({"action": "set", "obj": "t", "key": f"base:{i}",
                         "value": chr(97 + i % 26)})
    base = {"actor": "base", "seq": 1, "deps": {}, "ops": base_ops}

    changes = []
    for a in range(n_actors):
        ops = []
        for i in range(1, n_targets + 1):
            if (a + i) % 5 == 0:
                ops.append({"action": "del", "obj": "t",
                            "key": f"base:{i}"})
            else:
                ops.append({"action": "set", "obj": "t", "key": f"base:{i}",
                            "value": chr(65 + (a + i) % 26)})
        changes.append({"actor": f"actor-{a:04d}", "seq": 1,
                        "deps": {"base": 1}, "ops": ops})
    batch = TextChangeBatch.from_changes(changes, "t")
    n_ops = batch.n_ops
    state = {}

    def run():
        doc = DeviceTextDoc("t")
        doc.apply_changes([base])
        doc.apply_batch(batch)
        doc.text()
        state["doc"] = doc

    dt = timed(run, warmups=1, reps=2)
    doc = state["doc"]
    # path assertions: genuine multi-writer registers resolved on the host
    # slow path and survive as conflicts
    assert doc.conflicts, "conflict-heavy config minted no conflicts"
    emit(f"cfg6_conflict_heavy_{n_actors}x{n_targets}", n_ops / dt, "ops/s",
         n_conflicts=len(doc.conflicts), threshold=TRACKING_ONLY)


def config11_service(n_sessions: int = 200, room_size: int = 5,
                     n_rounds: int = 10, quick: bool = False,
                     record_session: bool = False):
    """Multi-tenant sync service throughput (automerge_tpu/service,
    INTERNALS §13) — the ISSUE 8 service bench row (specified there as
    "cfg6"; cfg6 was already the conflict-heavy config, so the service
    row is cfg11). N tenant sessions over lossless queue transports into
    one tick-scheduled SyncService, every client editing each round;
    measured from first edit to full quiescence (admission + grouped
    gate deliveries + hub fan-out + client applies all inside dt).
    Records the acceptance terms: sessions, aggregate_ops_per_sec,
    shed_total, evictions, p99_tick_ms (+ deferrals and the bound
    peaks). Chaos/churn live in scripts/soak.py --service; this row is
    the clean-path capacity number."""
    import time as _time
    from collections import deque

    import automerge_tpu as am
    from automerge_tpu import Connection, DocSet, Text
    from automerge_tpu.resilience import ResilientChannel
    from automerge_tpu.service import ServiceConfig, SyncService, \
        TenantBudget

    if quick:
        n_sessions, n_rounds = 50, 6

    class Client:
        def __init__(self, svc, tid, room_id, base):
            self.svc, self.tid, self.room_id = svc, tid, room_id
            self.to_server, self.to_client = deque(), deque()
            self.ds = DocSet()
            self.ds.set_doc(room_id,
                            am.apply_changes(am.init(f"c-{tid}"), base))
            svc.connect(tid, room_id, self.to_client.append)
            self.chan = ResilientChannel(self.to_server.append, None)
            self.conn = Connection(self.ds, self.chan.send)
            self.chan._deliver = self.conn.receive_msg
            self.conn.open()

        def pump(self):
            while self.to_server:
                env = self.to_server.popleft()
                sess = self.svc.session(self.tid)
                if sess is not None:
                    sess.on_wire(env)
            while self.to_client:
                self.chan.on_wire(self.to_client.popleft())
            self.chan.tick()

    svc = SyncService(ServiceConfig(
        default_budget=TenantBudget(ops_per_tick=256, inbox_cap=64)))
    n_rooms = max(1, n_sessions // room_size)
    bases = {}
    for g in range(n_rooms):
        rid = f"room-{g}"
        doc0 = am.change(am.init(f"{rid}-origin"), lambda d: (
            d.__setitem__("t", Text("svc")), d.__setitem__("m", {})))
        bases[rid] = am.get_all_changes(doc0)
        svc.seed_doc(rid, am.apply_changes(am.init(f"server-{g}"),
                                           bases[rid]))
    clients = [Client(svc, f"t{i}", f"room-{i % n_rooms}",
                      bases[f"room-{i % n_rooms}"])
               for i in range(n_sessions)]

    def settle(max_ticks=800):
        for _ in range(max_ticks):
            for c in clients:
                c.pump()
            svc.tick()
            if svc.idle() and all(c.chan.idle and not c.to_server
                                  and not c.to_client for c in clients):
                return
        raise AssertionError(f"service bench never quiesced: "
                             f"{svc.metrics()}")

    settle()                                 # join handshake off the clock
    ops_before = svc.stats["admitted_ops"]
    t0 = _time.perf_counter()
    for r in range(n_rounds):
        for i, c in enumerate(clients):
            c.ds.set_doc(c.room_id, am.change(
                c.ds.get_doc(c.room_id),
                lambda d, r=r, i=i: d["m"].__setitem__(f"k{i}", r)))
            c.pump()
        svc.tick()
    settle()
    dt = _time.perf_counter() - t0
    admitted = svc.stats["admitted_ops"] - ops_before
    assert admitted >= n_sessions * n_rounds, (admitted, svc.metrics())
    # convergence sanity: one spot-check room, server vs every member
    rid = "room-0"
    canon = lambda d: json.dumps(am.to_json(d), sort_keys=True)  # noqa: E731
    want = canon(svc.room(rid).doc_set.get_doc(rid))
    for c in clients:
        if c.room_id == rid:
            assert canon(c.ds.get_doc(rid)) == want, "room-0 diverged"
    svc.probe_lag()                  # fresh lag table for the record
    m = svc.metrics()
    emit(f"cfg11_service_{n_sessions}_sessions", admitted / dt, "ops/s",
         sessions=n_sessions, aggregate_ops_per_sec=round(admitted / dt, 1),
         shed_total=m["shed_total"], evictions=m["evictions"],
         p99_tick_ms=m["p99_tick_ms"], p50_tick_ms=m["p50_tick_ms"],
         deferrals=m["deferrals"], rooms=m["rooms"],
         peak_inbox=m["peak_inbox"], peak_parked=m["peak_parked"],
         admitted_ops=admitted,
         # telemetry-tier SLO terms (benchmarks/slo_gate.py checks
         # these against the committed rows): residual lag at
         # quiescence must be zero; peaks + shed rate are tracked
         max_lag_ops=m["max_lag_ops"], max_lag_ticks=m["max_lag_ticks"],
         peak_lag_ops=m["peak_lag_ops"],
         peak_lag_ticks=m["peak_lag_ticks"],
         shed_rate=round(m["shed_total"] / max(1, admitted), 6),
         tick_p99_ms_telemetry=svc.tick_p99_ms_telemetry(),
         threshold=TRACKING_ONLY)
    if record_session:
        import datetime

        import bench as B
        from benchmarks.common import RESULTS
        row = dict(RESULTS[-1])
        row["recorded_at_utc"] = datetime.datetime.now(
            datetime.timezone.utc).isoformat()
        row["git_sha"] = B._git_sha()
        try:
            import subprocess as _sp
            if _sp.run(["git", "status", "--porcelain"],
                       capture_output=True, text=True,
                       timeout=10).stdout.strip():
                row["git_dirty"] = True
        except Exception:
            pass
        row["timed_region"] = (
            f"{n_sessions} tenant sessions x {n_rounds} edit rounds "
            "through SyncService.tick (budgeted admission -> grouped "
            "per-doc gate delivery -> one hub flush per room -> client "
            "applies over lossless queue transports); dt = first edit "
            "-> full quiescence; value = admitted ops/s aggregate.")
        B.append_session_log(row)
        print(f"# appended to {B.SESSION_LOG_PATH}", file=sys.stderr)


def config12_sharded(quick: bool = False, record_session: bool = False):
    """Sharded serving tier (automerge_tpu/shard, INTERNALS §15): the
    ISSUE-10 cfg12 row — aggregate mesh ops/s across the full shard
    population vs the same workload on one shard. Runs in a SUBPROCESS
    with the scrubbed 8-virtual-cpu-device env (the sharding_evidence
    discipline: XLA_FLAGS must predate jax init, and this process may
    already hold a 1-device backend); `bench.py --sharded` asserts the
    budgets / zero-collective audit / >=4x bar inside the measurement
    and, with ``--session``, appends its own honest cpu row to
    BENCH_SESSIONS.jsonl. The emitted sweep row carries
    ``measured_platform`` so a chip sweep cannot launder the cpu dryrun
    as a chip measurement."""
    import os
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "AMTPU_SKIP_PREFLIGHT": "1",
           "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "")
                         + " --xla_force_host_platform_device_count=8"
                         ).strip()}
    env.pop("PALLAS_AXON_POOL_IPS", None)   # never init the tunnel plugin
    cmd = [sys.executable, os.path.join(root, "bench.py"), "--sharded"]
    if quick:
        cmd.append("--quick")
    if record_session:
        cmd.append("--session")
    out = subprocess.run(cmd, capture_output=True, text=True, cwd=root,
                         env=env, timeout=3000)
    if out.returncode != 0:
        raise RuntimeError(
            f"cfg12 sharded bench failed rc={out.returncode}: "
            f"{out.stderr[-800:]}")
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    emit("cfg12_sharded_aggregate_ops_per_sec", rec["value"], "ops/s",
         vs_baseline=rec["vs_baseline"],
         n_shards=rec["n_shards"], n_docs=rec["n_docs"],
         single_shard_ops_per_sec=rec["single_shard_ops_per_sec"],
         scaleup_vs_single_shard=rec["scaleup_vs_single_shard"],
         value_spread_pct=rec["value_spread_pct"],
         zero_collectives=rec["zero_collectives"],
         collective_audit=rec["collective_audit"],
         sharded_applies=rec["sharded_applies"],
         single_shard_applies=rec["single_shard_applies"],
         measured_platform=rec["platform"],
         threshold=rec["threshold"])
    if record_session:
        print(f"# cfg12 session row appended by bench.py --sharded "
              f"--session (platform {rec['platform']})", file=sys.stderr)


def config12t_text_prepare(quick: bool = False,
                           record_session: bool = False):
    """Cross-doc cold text planning (ISSUE 12, INTERNALS §16): the
    cfg12t microbench — span-derived detect_runs / index_merge /
    rank_resolve terms A/B'd against the per-doc planner + sorted-insert
    index, with the bulk-merge budget asserted inside the measurement.
    Subprocess for the same reason as cfg12 (a clean obs/jax state; with
    ``--session`` the row appends itself to BENCH_SESSIONS.jsonl)."""
    import os
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "AMTPU_SKIP_PREFLIGHT": "1"}
    cmd = [sys.executable, os.path.join(root, "bench.py"),
           "--text-prepare"]
    if quick:
        cmd.append("--quick")
    if record_session:
        cmd.append("--session")
    out = subprocess.run(cmd, capture_output=True, text=True, cwd=root,
                         env=env, timeout=1200)
    if out.returncode != 0:
        raise RuntimeError(
            f"cfg12t text-prepare bench failed rc={out.returncode}: "
            f"{out.stderr[-800:]}")
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    emit("cfg12t_text_cold_prepare_ops_per_sec", rec["value"], "ops/s",
         n_docs=rec["n_docs"],
         per_doc_ops_per_sec=rec["per_doc_ops_per_sec"],
         speedup_vs_per_doc=rec["speedup_vs_per_doc"],
         value_spread_pct=rec["value_spread_pct"],
         plan_terms_s=rec["plan_terms_s"],
         per_doc_plan_terms_s=rec["per_doc_plan_terms_s"],
         index_merges_per_doc_round=rec["index_merges_per_doc_round"],
         cross_doc=rec["cross_doc"],
         measured_platform=rec["platform"],
         threshold=rec["threshold"])


def config19_learned_index(quick: bool = False,
                           record_session: bool = False):
    """Learned-index host planning A/B (ISSUE 19, INTERNALS §23): the
    cfg19 row — the cfg12t population stream with the production
    planner config on BOTH legs, A/B'd across AMTPU_LEARNED_INDEX
    alone. Byte-identical final text, learned-site engagement, the
    rank_resolve bar (cfg12t-shape scaled <= 0.36 s, >= 2x under the
    same-run exact leg), zero model-wrong-answers on the untimed
    audit pass and zero demotions all asserted inside the measurement.
    Subprocess for a clean obs/jax state; ``--session`` appends the
    row to BENCH_SESSIONS.jsonl."""
    import os
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "AMTPU_SKIP_PREFLIGHT": "1"}
    cmd = [sys.executable, os.path.join(root, "bench.py"), "--learned"]
    if quick:
        cmd.append("--quick")
    if record_session:
        cmd.append("--session")
    out = subprocess.run(cmd, capture_output=True, text=True, cwd=root,
                         env=env, timeout=1800)
    if out.returncode != 0:
        raise RuntimeError(
            f"cfg19 learned-index bench failed rc={out.returncode}: "
            f"{out.stderr[-800:]}")
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    emit("cfg19_learned_index_ops_per_sec", rec["value"], "ops/s",
         n_docs=rec["n_docs"],
         exact_ops_per_sec=rec["exact_ops_per_sec"],
         speedup_vs_exact=rec["speedup_vs_exact"],
         value_spread_pct=rec["value_spread_pct"],
         rank_resolve_s=rec["rank_resolve_s"],
         exact_rank_resolve_s=rec["exact_rank_resolve_s"],
         rank_resolve_speedup=rec["rank_resolve_speedup"],
         model_wrong_answers=rec["model_wrong_answers"],
         model_misses=rec["model_misses"],
         model_refits=rec["model_refits"],
         demotions=rec["demotions"],
         audit_lookups_checked=rec["audit_lookups_checked"],
         site_stats=rec["site_stats"],
         measured_platform=rec["platform"],
         threshold=rec["threshold"])


def config20_parallel(quick: bool = False, record_session: bool = False):
    """Parallel mesh execution A/B (ISSUE 20, INTERNALS §24): the cfg20
    row — the SAME mesh size + map-population stream with the per-lane
    worker threads ON vs OFF (AMTPU_PARALLEL_LANES), byte-identical
    sample captures + per-lane counters asserted across the legs on
    every paired attempt, the overlap seam asserted engaged, the
    zero-collective audit and zero steady-state recompiles asserted
    in-run, and the 1.5x speedup bar asserted on >= 4-core hosts
    (n_cores is recorded; 1-core boxes record the honest ratio).
    Subprocess with the scrubbed 8-virtual-cpu-device env for the same
    reason as cfg12 (XLA_FLAGS must predate jax init); ``--session``
    appends the honest row to BENCH_SESSIONS.jsonl."""
    import os
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "AMTPU_SKIP_PREFLIGHT": "1",
           "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "")
                         + " --xla_force_host_platform_device_count=8"
                         ).strip()}
    env.pop("PALLAS_AXON_POOL_IPS", None)   # never init the tunnel plugin
    env.pop("AMTPU_PARALLEL_LANES", None)   # the bench drives the flag
    cmd = [sys.executable, os.path.join(root, "bench.py"), "--parallel"]
    if quick:
        cmd.append("--quick")
    if record_session:
        cmd.append("--session")
    out = subprocess.run(cmd, capture_output=True, text=True, cwd=root,
                         env=env, timeout=3000)
    if out.returncode != 0:
        raise RuntimeError(
            f"cfg20 parallel-mesh bench failed rc={out.returncode}: "
            f"{out.stderr[-800:]}")
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    emit("cfg20_parallel_mesh_aggregate_ops_per_sec", rec["value"],
         "ops/s",
         n_shards=rec["n_shards"], n_docs=rec["n_docs"],
         n_cores=rec["n_cores"],
         sequential_ops_per_sec=rec["sequential_ops_per_sec"],
         parallel_speedup_vs_sequential=rec[
             "parallel_speedup_vs_sequential"],
         speedup_bar_applicable=rec["speedup_bar_applicable"],
         value_spread_pct=rec["value_spread_pct"],
         executor=rec["executor"],
         zero_collectives=rec["zero_collectives"],
         recompiles=rec["recompiles"],
         measured_platform=rec["platform"],
         threshold=rec["threshold"])
    if record_session:
        print(f"# cfg20 session row appended by bench.py --parallel "
              f"--session (platform {rec['platform']})", file=sys.stderr)


def config13_wire(quick: bool = False, record_session: bool = False):
    """Binary columnar wire A/B at service scale (ISSUE 13, INTERNALS
    §17): the cfg13 row — dict vs AMTPUWIRE1 frames on the SAME seeded
    service session, byte-identical committed state asserted in-run,
    span-derived service-ingest decode term >= 5x smaller, binary
    decode under 5% of the tick budget, wire bytes/op recorded for both
    legs. Subprocess for a clean obs/jax state; ``--session`` appends
    the row to BENCH_SESSIONS.jsonl."""
    import os
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "AMTPU_SKIP_PREFLIGHT": "1"}
    cmd = [sys.executable, os.path.join(root, "bench.py"), "--wire"]
    if quick:
        cmd.append("--quick")
    if record_session:
        cmd.append("--session")
    out = subprocess.run(cmd, capture_output=True, text=True, cwd=root,
                         env=env, timeout=1200)
    if out.returncode != 0:
        raise RuntimeError(
            f"cfg13 wire bench failed rc={out.returncode}: "
            f"{out.stderr[-800:]}")
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    emit("cfg13_wire_service_ops_per_sec", rec["value"], "ops/s",
         sessions=rec["sessions"],
         dict_ops_per_sec=rec["dict_ops_per_sec"],
         decode_s=rec["decode_s"],
         dict_decode_s=rec["dict_decode_s"],
         decode_speedup_vs_dict=rec["decode_speedup_vs_dict"],
         decode_share_of_tick=rec["decode_share_of_tick"],
         wire_bytes_per_op=rec["wire_bytes_per_op"],
         dict_wire_bytes_per_op=rec["dict_wire_bytes_per_op"],
         measured_platform=rec["platform"],
         threshold=rec["threshold"])


def config14_lineage(quick: bool = False, record_session: bool = False):
    """Change-lineage overhead A/B at service scale (ISSUE 14,
    INTERNALS §18): the cfg14 row — the cfg11-shaped seeded service
    session with lineage off vs deterministic 1/64 sampling,
    byte-identical committed state and 100% clean-path chain
    completeness asserted in-run, sampled overhead <= 5%, visibility
    quantiles + per-stage dwell maxima recorded. Subprocess for a clean
    obs/lineage/jax state; ``--session`` appends the row to
    BENCH_SESSIONS.jsonl."""
    import os
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "AMTPU_SKIP_PREFLIGHT": "1"}
    env.pop("AMTPU_LINEAGE_RATE", None)   # the bench drives the flag
    cmd = [sys.executable, os.path.join(root, "bench.py"), "--lineage"]
    if quick:
        cmd.append("--quick")
    if record_session:
        cmd.append("--session")
    out = subprocess.run(cmd, capture_output=True, text=True, cwd=root,
                         env=env, timeout=1200)
    if out.returncode != 0:
        raise RuntimeError(
            f"cfg14 lineage bench failed rc={out.returncode}: "
            f"{out.stderr[-800:]}")
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    emit("cfg14_lineage_service_ops_per_sec", rec["value"], "ops/s",
         sessions=rec["sessions"],
         lineage_rate=rec["lineage_rate"],
         lineage_off_ops_per_sec=rec["lineage_off_ops_per_sec"],
         off_ratio_vs_baseline=rec["off_ratio_vs_baseline"],
         overhead_pct=rec["overhead_pct"],
         sampled_chains=rec["sampled_chains"],
         hops_per_sampled_change=rec["hops_per_sampled_change"],
         visibility_p50_ms=rec["visibility_p50_ms"],
         visibility_p99_ms=rec["visibility_p99_ms"],
         max_quarantine_dwell_ms=rec["max_quarantine_dwell_ms"],
         measured_platform=rec["platform"],
         threshold=rec["threshold"])


def config15_device_truth(quick: bool = False,
                          record_session: bool = False):
    """Device-truth observability row (ISSUE 15, INTERNALS §19): the
    cfg15 steady-state stream — zero compile events asserted inside the
    timed reps, exact h2d/d2h staged bytes per op, dtype x shape peak
    device footprint, cost-model flops/bytes per op, and the
    persistent-compile-cache state. Subprocess for a clean registry/jax
    state; ``--session`` appends the row to BENCH_SESSIONS.jsonl."""
    import os
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "AMTPU_SKIP_PREFLIGHT": "1"}
    cmd = [sys.executable, os.path.join(root, "bench.py"),
           "--device-truth"]
    if quick:
        cmd.append("--quick")
    if record_session:
        cmd.append("--session")
    out = subprocess.run(cmd, capture_output=True, text=True, cwd=root,
                         env=env, timeout=1200)
    if out.returncode != 0:
        raise RuntimeError(
            f"cfg15 device-truth bench failed rc={out.returncode}: "
            f"{out.stderr[-800:]}")
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    emit("cfg15_device_truth_ops_per_sec", rec["value"], "ops/s",
         compile_count=rec["compile_count"],
         recompiles_at_steady_state=rec["recompiles_at_steady_state"],
         bytes_staged_per_op=rec["bytes_staged_per_op"],
         d2h_bytes_per_op=rec["d2h_bytes_per_op"],
         peak_device_bytes=rec["peak_device_bytes"],
         cost_model_flops_per_op=rec["cost_model_flops_per_op"],
         cost_model_bytes_per_op=rec["cost_model_bytes_per_op"],
         compile_cache_entries=rec["compile_cache"]["entries"],
         measured_platform=rec["platform"],
         threshold=rec["threshold"])


def config16_federation(n_rounds: int = 12, n_rooms: int = 4,
                        quick: bool = False,
                        record_session: bool = False):
    """Geo-federation replication throughput (ISSUE 16, INTERNALS §20):
    the cfg16 row — three FederatedRegions full-meshed over the seeded
    ``cross_region`` WAN chaos profile, every region writing every room
    every round (concurrent cross-region merge), measured from first
    write to full fabric quiescence.  value = replica-commits/s: each
    write must become visible on ALL three regions, so the fabric does
    3x the write volume in committed replica state.  Lineage runs at
    rate=1 inside the timed region, so the row records the REAL
    cross-region visibility quantiles (origin -> remote commit across
    the WAN), plus the SLO terms the gate checks: residual lag tokens
    (absolute zero bar) and group-token economy.  Clean-path capacity:
    no partitions here — chaos partitions + region kill/rejoin live in
    scripts/soak.py --federation."""
    import time as _time

    import automerge_tpu as am
    from automerge_tpu.federation import FederatedRegion, connect_regions
    from automerge_tpu.obs import lineage
    from automerge_tpu.service import ServiceConfig, SyncService

    if quick:
        n_rounds, n_rooms = 6, 2

    was_enabled = lineage.ENABLED
    lineage.enable(rate=1)
    lineage.clear()
    try:
        names = ["us", "eu", "ap"]
        regions = {n: FederatedRegion(
            SyncService(ServiceConfig(region=n)), n) for n in names}
        s = 16
        for i in range(len(names)):
            for j in range(i + 1, len(names)):
                connect_regions(regions[names[i]], regions[names[j]],
                                profile="cross_region", seed=s)
                s += 10
        room_ids = [f"room-{g}" for g in range(n_rooms)]
        for rid in room_ids:
            doc0 = am.change(am.init(f"{rid}-origin"),
                             lambda d: d.__setitem__("m", {}))
            base = am.get_all_changes(doc0)
            for r in regions.values():
                r.svc.seed_doc(rid, am.apply_changes(
                    am.init(f"srv-{r.name}-{rid}"), base))

        def pump_all():
            for r in regions.values():
                r.pump()
                r.svc.tick()

        def settle(max_rounds=4000):
            for q in range(max_rounds):
                pump_all()
                if q > 5 and all(r.idle() for r in regions.values()):
                    return
            raise AssertionError(
                f"federation bench never quiesced: "
                f"{ {n: r.lag_table() for n, r in regions.items()} }")

        settle()                    # join adverts off the clock
        lineage.clear()             # visibility stats: timed region only
        n_writes = 0
        t0 = _time.perf_counter()
        for rnd in range(n_rounds):
            for name, r in regions.items():
                for rid in room_ids:
                    ds = r.svc.room(rid).doc_set
                    ds.set_doc(rid, am.change(
                        ds.get_doc(rid),
                        lambda d, n=name, rnd=rnd:
                        d["m"].__setitem__(f"k-{n}", rnd)))
                    n_writes += 1
            pump_all()
        settle()
        dt = _time.perf_counter() - t0

        # convergence: canonical saves byte-identical on all 3 regions
        for rid in room_ids:
            saves = set()
            for r in regions.values():
                doc = r.svc.room(rid).doc_set.get_doc(rid)
                chs = sorted(am.get_all_changes(doc),
                             key=lambda c: (c["actor"], c["seq"]))
                saves.add(am.save(am.apply_changes(
                    am.init("canon-probe"), chs)))
            assert len(saves) == 1, f"cfg16 {rid}: replicas diverged"
        residual = sum(e["lag_tokens"] for r in regions.values()
                       for e in r.lag_table().values())
        led = lineage.ledger()
        links = [ln for r in regions.values()
                 for ln in r.links.values()]
        replica_commits = n_writes * len(regions)
        emit("cfg16_federation", replica_commits / dt, "ops/s",
             regions=len(regions), rooms=n_rooms, writes=n_writes,
             replica_commits=replica_commits,
             aggregate_replica_commits_per_sec=round(
                 replica_commits / dt, 1),
             cross_region_visibility_p50_ms=led.visibility_ms(0.50),
             cross_region_visibility_p99_ms=led.visibility_ms(0.99),
             residual_lag_tokens=residual,
             group_tokens_minted=sum(r.clock.stats["minted"]
                                     for r in regions.values()),
             group_tokens_observed=sum(r.clock.stats["observed"]
                                       for r in regions.values()),
             envelopes_shipped=sum(ln.stats["shipped"] for ln in links),
             envelopes_delivered=sum(ln.stats["delivered"]
                                     for ln in links),
             wan_profile="cross_region",
             threshold=TRACKING_ONLY)
    finally:
        if not was_enabled:
            lineage.disable()
        lineage.clear()
    if record_session:
        import datetime

        import bench as B
        from benchmarks.common import RESULTS
        row = dict(RESULTS[-1])
        row["recorded_at_utc"] = datetime.datetime.now(
            datetime.timezone.utc).isoformat()
        row["git_sha"] = B._git_sha()
        try:
            import subprocess as _sp
            if _sp.run(["git", "status", "--porcelain"],
                       capture_output=True, text=True,
                       timeout=10).stdout.strip():
                row["git_dirty"] = True
        except Exception:
            pass
        row["timed_region"] = (
            f"3 federated regions x {n_rooms} rooms x {n_rounds} write "
            "rounds over the seeded cross_region WAN chaos profile "
            "(group-token manifests -> RegionLink channels -> remote "
            "gate commits); dt = first write -> full fabric quiescence; "
            "value = replica-commits/s (every write visible on all 3 "
            "regions); lineage rate=1 inside the timed region supplies "
            "the cross-region visibility quantiles.")
        B.append_session_log(row)
        print(f"# appended to {B.SESSION_LOG_PATH}", file=sys.stderr)


def config17_fused(quick: bool = False, record_session: bool = False):
    """Fused-round megakernel A/B row (ISSUE 17, INTERNALS §21): the
    cfg17 bench pairs every rewritten kernel (solo mixed round, the
    both-lanes stacked megakernel, the combined scatter) with its XLA
    comparator on the SAME pre-generated stream — fused vs XLA seconds
    by cost-model attribution, roofline ratio both legs, dispatch count
    per round — with identical committed state, byte-identical frontend
    saves across AMTPU_FUSED_ROUNDS, the tightened round budget, and
    zero steady-state recompiles all asserted in-run. Subprocess for a
    clean registry/jax state; ``--session`` appends the row to
    BENCH_SESSIONS.jsonl."""
    import os
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "AMTPU_SKIP_PREFLIGHT": "1"}
    cmd = [sys.executable, os.path.join(root, "bench.py"), "--fused"]
    if quick:
        cmd.append("--quick")
    if record_session:
        cmd.append("--session")
    out = subprocess.run(cmd, capture_output=True, text=True, cwd=root,
                         env=env, timeout=1200)
    if out.returncode != 0:
        raise RuntimeError(
            f"cfg17 fused-round bench failed rc={out.returncode}: "
            f"{out.stderr[-800:]}")
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    emit("cfg17_fused_rounds_ops_per_sec", rec["value"], "ops/s",
         xla_ops_per_sec=rec["xla_ops_per_sec"],
         speedup_vs_xla=rec["speedup_vs_xla"],
         dispatch_per_round=rec["dispatch_per_round"],
         xla_dispatch_per_round=rec["xla_dispatch_per_round"],
         dispatch_reduction=rec["dispatch_reduction"],
         recompiles_at_steady_state=rec["recompiles_at_steady_state"],
         roofline_ratio_fused=rec["roofline_ratio_fused"],
         roofline_ratio_xla=rec["roofline_ratio_xla"],
         roofline_ratio_vs_xla=rec["roofline_ratio_vs_xla"],
         kernel_ab=rec["kernel_ab"],
         saves_byte_identical=rec["saves_byte_identical"],
         measured_platform=rec["platform"],
         threshold=rec["threshold"])


def config18_residency(quick: bool = False, record_session: bool = False):
    """Bounded-HBM residency row (ISSUE 18, INTERNALS §22): a doc
    population 10x+ the device byte budget served through the paging
    mesh — demand page-ins through the disk tier every round, rotating
    hot set for the steady-state hit rate, peak footprint gauge <= the
    budget, zero overruns, and byte-identical captures vs an unbounded
    reference all asserted in-run before the record is emitted.
    Subprocess for a clean registry/jax state; ``--session`` appends
    the row to BENCH_SESSIONS.jsonl."""
    import os
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "AMTPU_SKIP_PREFLIGHT": "1"}
    cmd = [sys.executable, os.path.join(root, "bench.py"), "--residency"]
    if quick:
        cmd.append("--quick")
    if record_session:
        cmd.append("--session")
    out = subprocess.run(cmd, capture_output=True, text=True, cwd=root,
                         env=env, timeout=1200)
    if out.returncode != 0:
        raise RuntimeError(
            f"cfg18 residency bench failed rc={out.returncode}: "
            f"{out.stderr[-800:]}")
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    emit("cfg18_residency_ops_per_sec", rec["value"], "ops/s",
         budget_bytes=rec["budget_bytes"],
         peak_footprint_bytes=rec["peak_footprint_bytes"],
         population_over_budget=rec["population_over_budget"],
         touched_docs=rec["touched_docs"],
         hit_rate=rec["hit_rate"],
         page_in_p99_ms=rec["page_in_p99_ms"],
         page_ins=rec["page_ins"],
         page_outs=rec["page_outs"],
         cold_ages=rec["cold_ages"],
         cold_loads=rec["cold_loads"],
         budget_overruns=rec["budget_overruns"],
         restore_h2d_bytes=rec["restore_h2d_bytes"],
         tier_counts=rec["tier_counts"],
         captures_byte_identical=rec["captures_byte_identical"],
         measured_platform=rec["platform"],
         threshold=rec["threshold"])


def config5b_residual_heavy(n_actors: int = 10_000, quick: bool = False):
    """Adversarial headline shape: 20% of ops are RESIDUALS (bare deletes
    of distinct base elements + bare inserts without values) that cannot
    ride the dense run path — they go through apply_residual_packed. The
    clean headline (cfg5/bench.py) has ZERO residuals in the timed region;
    this row bounds the cost of realistic mixed loads. Regression
    threshold: >= 25% of the clean headline's ops/s on the same platform.
    Path under test: ops/ingest.py apply_residual_packed."""
    import bench as B
    from automerge_tpu.engine import DeviceTextDoc, TextChangeBatch
    from automerge_tpu.engine.columnar import KIND_DEL, KIND_INS, KIND_SET

    if quick:
        n_actors = 500
    base_n = 100 * n_actors          # every actor gets a distinct del range
    run_pairs, n_del, n_bare = 400, 100, 100   # 800+100+100 = 1000 ops
    n_per = 2 * run_pairs + n_del + n_bare
    n_ops = n_actors * n_per
    actors = [f"actor-{i:06d}" for i in range(n_actors)]
    op_change = np.repeat(np.arange(n_actors, dtype=np.int32), n_per)
    kind = np.empty(n_ops, np.int8)
    ta = np.zeros(n_ops, np.int32)
    tc = np.zeros(n_ops, np.int32)
    pa = np.zeros(n_ops, np.int32)
    pc = np.zeros(n_ops, np.int32)
    val = np.zeros(n_ops, np.int64)
    pair_kind = np.tile(np.array([KIND_INS, KIND_SET], np.int8), run_pairs)
    ctrs = np.arange(1, run_pairs + 1, dtype=np.int32) + base_n + 1
    for a in range(n_actors):
        s = a * n_per
        e_run = s + 2 * run_pairs
        kind[s:e_run] = pair_kind
        ta[s:e_run] = a
        tc[s: e_run: 2] = ctrs
        tc[s + 1: e_run: 2] = ctrs
        pa[s] = n_actors                      # 'base' rank
        pc[s] = a * 100 + 1
        pa[s + 2: e_run: 2] = a
        pc[s + 2: e_run: 2] = ctrs[:-1]
        val[s + 1: e_run: 2] = 97 + (a % 26)
        # 100 bare deletes of this actor's distinct base range
        d0 = e_run
        kind[d0: d0 + n_del] = KIND_DEL
        ta[d0: d0 + n_del] = n_actors
        tc[d0: d0 + n_del] = a * 100 + 1 + np.arange(n_del)
        # 100 bare inserts (no value: invisible elements)
        b0 = d0 + n_del
        kind[b0: b0 + n_bare] = KIND_INS
        ta[b0: b0 + n_bare] = a
        tc[b0: b0 + n_bare] = ctrs[-1] + 1 + np.arange(n_bare)
        pa[b0: b0 + n_bare] = n_actors
        pc[b0: b0 + n_bare] = a * 100 + 50
    batch = TextChangeBatch(
        obj_id="t", actors=actors, seqs=np.ones(n_actors, np.int32),
        deps=[{"base": 1}] * n_actors, messages=[None] * n_actors,
        op_change=op_change, op_kind=kind, op_target_actor=ta,
        op_target_ctr=tc, op_parent_actor=pa, op_parent_ctr=pc,
        op_value=val, actor_table=actors + ["base"], value_pool=[])

    def merge_once(merge_batch, expect_vis):
        """bench.py's exact timing discipline (bench.py run_once): base
        doc built untimed, prepare (host plan + h2d staging) untimed,
        timed region = commit_prepared + codes-only materialize + the one
        scalar-fetch sync. Returns best-of-2 commit seconds after a
        warm-up pays the jit compiles."""
        def once():
            doc = DeviceTextDoc("t")
            doc.eager_materialize = True
            doc.apply_batch(B.base_batch("t", base_n))
            doc.text()
            prepared = doc.prepare_batch(merge_batch)
            t0 = time.perf_counter()
            doc.commit_prepared(prepared)
            doc._materialize(with_pos=False)
            scal = doc._scalars()
            dt = time.perf_counter() - t0
            assert int(scal[0]) == expect_vis, (int(scal[0]), expect_vis)
            return dt
        once()                      # warm-up: compiles at these shapes
        return min(once() for _ in range(2))

    # the CLEAN same-scale merge, timed with the identical discipline in
    # the same process — the only way the 4x bound is actually comparable
    # (round 4's version timed base-doc rebuild + double materialize for
    # the residual row but commit-only for clean: unfalsifiable).
    # Same 3-attempt contention discipline as cfg7/cfg8: the residual
    # region is scatter-bound on XLA:CPU and a probe-loop burst inside
    # either side's ~0.1-3 s pass skews the RATIO, not just the rate.
    clean = B.merge_batch("t", n_actors, n_per, base_n)
    import time as _time
    for attempt in range(3):
        clean_dt = merge_once(clean, base_n + n_actors * (n_per // 2))
        resid_dt = merge_once(
            batch, base_n - n_actors * n_del + n_actors * run_pairs)
        clean_rate = clean.n_ops / clean_dt
        resid_rate = n_ops / resid_dt
        slowdown = clean_rate / resid_rate
        if slowdown < 4.0:
            break
        if attempt < 2:
            _time.sleep(4)
    # the stated bound, ASSERTED so the suite fails when the residual
    # path regresses instead of recording an unfalsifiable string. The
    # bound holds wherever the device round trip is local: the residual
    # path's ONE in-region device->host fetch (slow-register info,
    # text_doc._execute_plan) costs ~1 ms on PCIe but 2+ WAN round trips
    # through this environment's ~70 ms-RTT chip tunnel, which dominates
    # the whole region (measured 26x there, 1.3-1.9x on cpu — the delta
    # IS the tunnel, scripts/chip_session.log 2026-07-31). The gate is
    # the MEASURED link latency (perf_asserts_enforced), not the platform
    # name, so a locally attached chip still enforces the bound.
    from benchmarks.common import perf_asserts_enforced, tracking_only_wan
    # the 4x bound is a claim about the RECORD scale (10k actors, where
    # per-round fixed costs — the S-sized planned-materialize stage, the
    # one packed d2h fetch, dispatch overhead — amortize over 10M ops);
    # --quick shrinks the shape 20x for iteration speed and sits at the
    # bound's edge by construction, so quick rows record tracking-only
    # with the measured ratio instead of gating on a miscalibrated bar
    enforce = perf_asserts_enforced() and not quick
    bound = ("<4x slower than clean same-scale merge, identical timed "
             "region (commit+materialize+sync)")
    if enforce:
        assert slowdown < 4.0, (
            f"residual-heavy merge {slowdown:.1f}x slower than the clean "
            f"same-scale merge (bound: <4x): clean {clean_rate:,.0f} ops/s "
            f"vs residual {resid_rate:,.0f} ops/s")
    emit(f"cfg5b_residual_heavy_{n_actors}_actors", resid_rate, "ops/s",
         vs_baseline=resid_rate / 100e6,
         residual_fraction=0.2,
         clean_same_scale_ops_per_sec=round(clean_rate),
         slowdown_vs_clean=round(slowdown, 2),
         threshold=(f"asserted in code: {bound}" if enforce
                    else ("tracking-only at --quick scale (bound "
                          "enforced at the 10k-actor record scale): "
                          + bound) if perf_asserts_enforced()
                    else tracking_only_wan(bound)))


def config5d_overlap(n_actors: int = 10_000, quick: bool = False):
    """The PreparedBatch pipelining seam, exercised end-to-end (VERDICT r4
    Next #4): two causally independent half-batches merge back-to-back;
    the overlapped schedule runs `prepare_batch` of half 2 (host planning
    + h2d staging) WHILE the device still executes half 1's commit — jax
    dispatch is asynchronous, and the engine's only forced syncs are the
    prepare-side `block_until_ready(staged)` (waits on the new round's
    transfers, not the running kernels) and the final scalar fetch. The
    serial comparator hard-barriers on half 1's output tables before
    planning half 2. e2e_overlapped ~ max(prepare, commit) per round where
    host and device are separate processors (the chip); on this box's ONE
    CPU core, host planning and 'device' compute share the core, so rough
    parity here + a gain on the chip row is the expected shape.

    Path under test: engine/base.py prepare_batch/commit_prepared (the
    seam's contract: plan binds to a generation; commit is bookkeeping +
    dispatch only)."""
    import bench as B
    from automerge_tpu.engine import DeviceTextDoc

    if quick:
        n_actors = 500
    base_n = 100 * n_actors
    half = n_actors // 2
    b1 = B.merge_batch("t", half, 1000, base_n, seed=1, actor_prefix="alpha")
    b2 = B.merge_batch("t", half, 1000, base_n, seed=2, actor_prefix="beta")
    n_ops = b1.n_ops + b2.n_ops
    expect = base_n + 2 * half * 500

    def run(overlap):
        # the ONE shared schedule harness (bench.run_overlapped);
        # barrier=True is the serial comparator — a pure completion
        # barrier between commits, so the A/B isolates scheduling alone
        return B.run_overlapped([b1, b2], expect, obj_id="t",
                                base_n=base_n, barrier=not overlap)

    run(True)                                  # warm-up: jit compiles
    serial = min(run(False) for _ in range(2))
    overlapped = min(run(True) for _ in range(2))
    gain = serial / overlapped
    # overlap must never LOSE meaningfully: it removes a barrier and adds
    # no work (generous margin absorbs one-core scheduling noise). On a
    # WAN-attached device, per-run transfer jitter can exceed the margin
    # and a spurious crash would cost the rest of the sweep's rows — the
    # tunnel row's evidence is the recorded overlap_gain itself; anywhere
    # the link is local (cpu, PCIe chip) the bound is enforced.
    from benchmarks.common import perf_asserts_enforced, tracking_only_wan
    enforce = perf_asserts_enforced()
    if enforce:
        assert overlapped <= serial * 1.15, (
            f"overlapped schedule slower than serial: {overlapped:.4f}s vs "
            f"{serial:.4f}s")
    emit(f"cfg5d_e2e_overlapped_{n_actors}_actors", n_ops / overlapped,
         "ops/s", vs_baseline=(n_ops / overlapped) / 100e6,
         e2e_serial_s=round(serial, 4),
         e2e_overlapped_s=round(overlapped, 4),
         overlap_gain=round(gain, 3),
         threshold=("asserted in code: overlapped <= 1.15x serial "
                    "(tracking: gain ~1 on one shared CPU core; the win "
                    "shows where host and device are separate processors)"
                    if enforce else
                    tracking_only_wan("overlapped <= 1.15x serial")))


def config5e_incremental_pull(n_base: int = 1_000_000, n_actors: int = 20,
                              ops_per_change: int = 100,
                              quick: bool = False):
    """Incremental text pull: a SMALL merge into a large warm document,
    then `text()`. The host string cache + dirty-span reconciliation
    (engine/text_doc._text_incremental) must ship O(edits) bytes d2h —
    asserted on the ENGINE-REPORTED span bytes, not wall clock, so the
    row gates identically on cpu and through the tunnel. Reports the
    bytes a full pull would have moved for scale."""
    import bench as B
    from automerge_tpu.engine import DeviceTextDoc

    if quick:
        n_base = 100_000
    doc = DeviceTextDoc("t")
    doc.eager_materialize = True
    doc.apply_batch(B.base_batch("t", n_base))
    doc.text()                         # warm pull seeds the host cache
    assert doc._text_cache is not None, "text cache failed to seed"
    batch = B.merge_batch("t", n_actors, ops_per_change, n_base, seed=11,
                          actor_prefix="inc")
    doc.apply_batch(batch)
    t0 = time.time()
    text = doc.text()
    pull_s = time.time() - t0
    edit_chars = n_actors * (ops_per_change // 2)
    assert len(text) == n_base + edit_chars
    stats = doc.pull_stats
    assert stats["mode"] == "incremental", stats
    # O(edits): the merge inserted edit_chars visible chars; allow slack
    # for the S-sized seg-info row but nothing close to the doc itself
    budget = 4 * edit_chars + stats.get("info_bytes", 0) + 4096
    assert stats["span_bytes"] <= budget, (stats, budget)
    emit(f"cfg5e_incremental_pull_{n_base // 1000}k_doc",
         stats["span_bytes"], "bytes_pulled",
         pull_s=round(pull_s, 4),
         n_spans=stats["n_spans"],
         info_bytes=stats.get("info_bytes", 0),
         full_pull_bytes=n_base + edit_chars,
         edit_chars=edit_chars,
         threshold="asserted in code: span_bytes <= 4x edit chars + "
                   "seg-info row (O(edits), not O(doc)); byte-count "
                   "gate, platform-independent")


def config5f_pipeline(quick: bool = False):
    """The sustained streaming tier (ISSUE 4 tentpole): B causally-
    independent batches through the K-deep PipelinedIngestor ring with
    buffer donation. Delegates to the ONE shared harness
    (bench.measure_pipeline) so this row and `bench.py --pipeline`
    can never measure different schedules; the harness itself asserts
    the machine checks (median-of->=5, per-batch dispatch/sync budget,
    ring actually chained) — a regression crashes the row rather than
    recording an unfalsifiable string."""
    import bench as B

    rec = B.measure_pipeline(quick=quick)
    emit("cfg5f_" + rec["metric"], rec["value"], rec["unit"],
         vs_baseline=rec["vs_baseline"],
         n_reps=rec["n_reps"],
         reps_ops_per_sec=rec["reps_ops_per_sec"],
         value_spread_pct=rec["value_spread_pct"],
         ring=rec["ring"],
         dispatches_per_batch_max=rec["dispatches_per_batch_max"],
         syncs_per_batch_max=rec["syncs_per_batch_max"],
         pipeline_gain_vs_serial=rec["pipeline_gain_vs_serial"],
         serial_profile=rec["serial_profile"],
         floor_met=rec["floor_met"],
         **({"shortfall": rec["shortfall"]} if "shortfall" in rec else {}),
         **({"threshold_met": rec["threshold_met"]}
            if "threshold_met" in rec else {}),
         threshold=rec["threshold"])


def config5c_two_causal_rounds(n_actors: int = 10_000, quick: bool = False):
    """Adversarial headline shape: every actor delivers TWO causally
    chained changes (seq 2 depends on seq 1), so the merge cannot be one
    round — admission schedules two rounds and the engine pays two
    prepare/commit cycles. Bounds the per-round overhead the single-round
    headline never shows. Path under test: engine/base.py _schedule +
    multi-round prepare."""
    import bench as B
    from automerge_tpu.engine import DeviceTextDoc, TextChangeBatch
    from automerge_tpu.engine.columnar import KIND_INS, KIND_SET

    if quick:
        n_actors = 500
    base_n = 50_000 if quick else 1_000_000
    pairs_per_change = 250           # 500 ops x 2 changes = 1k ops/actor
    n_changes = 2 * n_actors
    n_per = 2 * pairs_per_change
    n_ops = n_changes * n_per
    actors = [f"actor-{i:06d}" for i in range(n_actors)]
    # change rows: actor a seq 1 = row 2a, seq 2 = row 2a+1
    op_change = np.repeat(np.arange(n_changes, dtype=np.int32), n_per)
    kind = np.tile(np.array([KIND_INS, KIND_SET], np.int8),
                   n_changes * pairs_per_change)
    ta = np.repeat(np.arange(n_actors, dtype=np.int32), 2 * n_per)
    tc = np.zeros(n_ops, np.int32)
    pa = np.zeros(n_ops, np.int32)
    pc = np.zeros(n_ops, np.int32)
    val = np.zeros(n_ops, np.int64)
    rng = np.random.default_rng(7)
    targets = rng.integers(1, base_n, n_actors)
    c1 = np.arange(1, pairs_per_change + 1, dtype=np.int32) + base_n + 1
    c2 = c1 + pairs_per_change
    for a in range(n_actors):
        for half, ctrs in ((0, c1), (1, c2)):
            s = (2 * a + half) * n_per
            tc[s: s + n_per: 2] = ctrs
            tc[s + 1: s + n_per: 2] = ctrs
            if half == 0:
                pa[s] = n_actors
                pc[s] = int(targets[a])
            else:
                pa[s] = a                 # continue own seq-1 run
                pc[s] = c1[-1]
            pa[s + 2: s + n_per: 2] = a
            pc[s + 2: s + n_per: 2] = ctrs[:-1]
            val[s + 1: s + n_per: 2] = 97 + (a % 26)
    seqs = np.empty(n_changes, np.int32)
    seqs[0::2] = 1
    seqs[1::2] = 2
    shared = {"base": 1}
    batch = TextChangeBatch(
        obj_id="t", actors=[a for a in actors for _ in range(2)],
        seqs=seqs, deps=[shared] * n_changes,
        messages=[None] * n_changes, op_change=op_change, op_kind=kind,
        op_target_actor=ta, op_target_ctr=tc, op_parent_actor=pa,
        op_parent_ctr=pc, op_value=val, actor_table=actors + ["base"],
        value_pool=[])

    def run():
        doc = DeviceTextDoc("t")
        doc.eager_materialize = True
        doc.apply_batch(B.base_batch("t", base_n))
        doc.text()
        prepared = doc.prepare_batch(batch)
        assert len(prepared.rounds) == 2      # genuinely two causal rounds
        doc.commit_prepared(prepared)
        assert len(doc.text()) == base_n + n_ops // 2

    dt = timed(run, warmups=1, reps=1)
    emit(f"cfg5c_two_causal_rounds_{n_actors}_actors", n_ops / dt, "ops/s",
         vs_baseline=(n_ops / dt) / 100e6, n_rounds=2,
         threshold="tracking-only: measured against the 100M north star "
                   "(vs_baseline) but carries no asserted bound; "
                   "regressions caught by diffing same-platform rows "
                   "across round records")


def config7_interactive_latency(n_base: int = 100_000, n_changes: int = 60):
    """Interactive latency: ONE 10-op change applied to an n_base-element
    Text document through the full public API (the reference's core
    editing loop, frontend/index.js change -> backend applyLocalChange ->
    patch). Reports full-API and backend-only p50/p99 per-change wall
    time. Target: < 1 ms backend p50 — met by the write-behind host fast
    path (INTERNALS §4.8; measured 0.83 ms on the virtual CPU platform);
    the full-API number adds the frontend's immutable-snapshot cost."""
    import time as _time

    import automerge_tpu as am
    from automerge_tpu import Text

    from automerge_tpu import frontend as _F
    from automerge_tpu.backend import default as _B

    orig_alc = _B.Backend.apply_local_change
    be_box: list = []

    def timed_alc(state, request):
        t0 = _time.perf_counter()
        out = orig_alc(state, request)
        be_box.append(_time.perf_counter() - t0)
        return out

    skip = n_changes // 6                           # drop compile warmup

    def pcts(series):
        w = np.asarray(series[skip:]) * 1e3
        return (float(np.percentile(w, 50)), float(np.percentile(w, 99)))

    from automerge_tpu.engine import accounting
    acct_box: list = []            # (dispatches, syncs) per change

    def measure():
        """One full measurement: fresh doc, n_changes timed edits."""
        doc = am.change(am.init("user"),
                        lambda d: d.__setitem__("t", Text("x" * n_base)))
        lat = []
        be_box.clear()
        acct_box.clear()
        # the frontend resolves the backend through the injected class
        # (options.backend seam), so patch the class attribute
        _B.Backend.apply_local_change = staticmethod(timed_alc)
        try:
            for i in range(n_changes):
                t0 = _time.perf_counter()
                with accounting.track() as tr:
                    doc = am.change(
                        doc, lambda d, i=i: d["t"].insert_at(5000 + 11 * i,
                                                             *"helloworld"))
                lat.append(_time.perf_counter() - t0)
                acct_box.append((tr.stats["dispatches"], tr.stats["syncs"]))
        finally:
            _B.Backend.apply_local_change = staticmethod(orig_alc)
        assert len(doc["t"]) == n_base + 10 * n_changes
        assert _F.get_backend_state(doc) is not None
        return pcts(lat), pcts(be_box)

    # Up to 3 attempts, asserting only a PERSISTENT miss. A single
    # attempt on this one-core box is routinely poisoned by unrelated
    # load — the tunnel probe loop pays a ~3 s full-core jax import
    # every couple of minutes, which spans an entire 0.1 s pass — and
    # that says nothing about the engine. A genuine regression fails
    # every attempt; transient contention passes a later one (the sleep
    # escapes the burst window).
    P50_TARGET_MS, P99_TARGET_MS, ATTEMPTS = 1.5, 10.0, 3
    from benchmarks.common import perf_asserts_enforced, tracking_only_wan
    # the latency targets are calibrated for a local device round trip: a
    # write-behind flush landing inside a timed keystroke pays the link
    # RTT, which a WAN tunnel turns from ~1 ms (PCIe) into ~70+ ms — so
    # the gate is the measured RTT, and tunnel rows record tracking-only
    # rather than crashing the sweep
    enforce = perf_asserts_enforced()
    # the retry loop exists only to out-wait transient one-core contention
    # before asserting; with nothing to assert, one pass is the row
    attempts = ATTEMPTS if enforce else 1
    for attempt in range(attempts):
        (p50, p99), (be_p50, be_p99) = measure()
        if p50 <= P50_TARGET_MS and p99 <= P99_TARGET_MS:
            break
        if attempt < attempts - 1:
            _time.sleep(4)               # escape the contention burst
    # stated-and-asserted interactive targets (VERDICT r4 Next #5): the
    # ChunkedElems COW store removed the per-keystroke O(n) snapshot copy
    # (measured p50 3.12 -> 1.01 ms, p99 40.8 -> 2.4 ms at this size)
    if enforce:
        assert p50 <= P50_TARGET_MS, \
            f"interactive full-API p50 {p50:.2f} ms > {P50_TARGET_MS} ms"
        assert p99 <= P99_TARGET_MS, \
            f"interactive full-API p99 {p99:.2f} ms > {P99_TARGET_MS} ms"
    # device-interaction budget of the write-behind path (ISSUE 4,
    # INTERNALS §9): an interactive change must stay HOST work — device
    # dispatches and blocking syncs per am.change are measured
    # (engine/accounting.py) and asserted <= a small constant on EVERY
    # platform (counting is link-independent, unlike the latency bounds).
    # Steady state measures 0/0; the budget of 2 absorbs a deferred
    # flush landing inside a change without ever letting a per-keystroke
    # device round trip back in (tests/test_dispatch_budget.py pins the
    # same bar in CI).
    DISPATCH_BUDGET = SYNC_BUDGET = 2
    disp_max = max(d for d, _ in acct_box)
    sync_max = max(s for _, s in acct_box)
    assert disp_max <= DISPATCH_BUDGET, (
        f"write-behind change dispatched {disp_max} device programs "
        f"(budget {DISPATCH_BUDGET})")
    assert sync_max <= SYNC_BUDGET, (
        f"write-behind change blocked on {sync_max} device syncs "
        f"(budget {SYNC_BUDGET})")
    emit("cfg7_interactive_10op_change_100k_doc", p50, "ms_p50",
         p99_ms=round(p99, 2),
         backend_p50_ms=round(be_p50, 3),
         backend_p99_ms=round(be_p99, 3),
         dispatches_per_change_max=disp_max,
         syncs_per_change_max=sync_max,
         dispatch_budget=(f"asserted in code: <= {DISPATCH_BUDGET} "
                          "dispatches and <= 2 blocking syncs per "
                          "am.change, every platform (count, not time)"),
         n_changes=n_changes,
         threshold=(f"asserted in code: p50 <= {P50_TARGET_MS} ms, "
                    f"p99 <= {P99_TARGET_MS} ms (persistent across up to "
                    f"{ATTEMPTS} attempts; transient one-core contention "
                    "is not a regression)" if enforce else
                    tracking_only_wan(f"p50 <= {P50_TARGET_MS} ms, "
                                      f"p99 <= {P99_TARGET_MS} ms")),
         note="one 10-char insert per change through am.change; backend_* "
              "isolates apply_local_change (the device-tier write-behind "
              "fast path, INTERNALS 4.8); the remainder is frontend "
              "snapshot cost (ChunkedElems COW, types.py)")


def config7b_nested_under_large_root(n_root: int = 100_000,
                                     n_changes: int = 20):
    """Interactive latency for the REALISTIC nested-document shape: one
    small nested map edited under a large root. Round 5 found the parent
    relink pass scanning every root entry per nested change (~70 ms at
    this size); the keyed relink (InboundIndex.key_of,
    frontend/apply_patch.py) makes the cost the root's own clone, not a
    scan. Same 3-attempt contention discipline as cfg7."""
    import time as _time

    import automerge_tpu as am

    doc = am.init("user")
    for c in range(4):
        doc = am.change(doc, lambda d, c=c: [
            d.__setitem__(f"k{c}-{i}", i) for i in range(n_root // 4)])
    doc = am.change(doc, lambda d: d.__setitem__(
        "board", {"meta": {"title": "t"}}))

    P50_TARGET_MS, ATTEMPTS = 10.0, 3
    skip = n_changes // 5

    def measure(doc):
        lat = []
        for i in range(n_changes):
            t0 = _time.perf_counter()
            doc = am.change(doc, lambda d, i=i: d["board"]["meta"]
                            .__setitem__("title", f"v{i}"))
            lat.append(_time.perf_counter() - t0)
        assert am.to_json(doc)["board"]["meta"]["title"] == \
            f"v{n_changes - 1}"
        return float(np.percentile(np.asarray(lat[skip:]) * 1e3, 50)), doc

    from benchmarks.common import perf_asserts_enforced, tracking_only_wan
    enforce = perf_asserts_enforced()   # same measured-RTT gate as cfg7
    attempts = ATTEMPTS if enforce else 1
    for attempt in range(attempts):
        p50, doc = measure(doc)
        if p50 <= P50_TARGET_MS:
            break
        if attempt < attempts - 1:
            _time.sleep(4)
    if enforce:
        assert p50 <= P50_TARGET_MS, \
            f"nested-change p50 {p50:.2f} ms > {P50_TARGET_MS} ms"
    emit(f"cfg7b_nested_change_under_{n_root // 1000}k_root", p50,
         "ms_p50", n_changes=n_changes,
         threshold=(f"asserted in code: p50 <= {P50_TARGET_MS} ms "
                    f"(persistent across up to {ATTEMPTS} attempts); "
                    "was ~70 ms pre keyed-relink" if enforce else
                    tracking_only_wan(f"p50 <= {P50_TARGET_MS} ms")),
         note="one nested map key set per am.change under a "
              f"{n_root}-key root; cost = root clone, not a root scan "
              "(frontend/apply_patch.py InboundIndex.key_of)")


def config8_frontend_splice(n_big: int = 1_000_000, n_base_ab: int = 200_000,
                            n_ins_ab: int = 20_000):
    """Frontend patch application: a bulk text-insert patch landing in the
    MIDDLE of a large existing document (a remote peer's typing run merged
    into a big doc — the reference's splice-batching case,
    apply_patch.js:332-384). Element-wise application shifts the whole tail
    per insert (O(n_ins * n_base)); the splice-batched path is one slice
    assignment (O(n_base + n_ins)). Tail-append patches are linear either
    way, so the A/B uses a mid-document run. Host-only (no device).
    Regression threshold: batched >= 4x element-wise at the A/B size
    (was 10x against the flat-list elems store; the chunked COW store
    made element-wise insertion O(CHUNK) per insert, see the assert)."""
    import time as _time

    from automerge_tpu.frontend.apply_patch import apply_diffs
    from automerge_tpu.frontend.types import instantiate_text

    def base_doc(n):
        elems = [{"elemId": f"b:{i + 1}", "value": "x", "conflicts": None}
                 for i in range(n)]
        return instantiate_text("T", elems, n)

    def insert_diffs(n, at):
        return [{"type": "text", "obj": "T", "action": "insert",
                 "index": at + i, "elemId": f"a:{i + 1}", "value": "y"}
                for i in range(n)]

    def apply_once(n_base, n_ins, splice):
        cache = {"T": base_doc(n_base)}
        updated = {}
        diffs = insert_diffs(n_ins, at=1000)
        t0 = _time.perf_counter()
        apply_diffs(diffs, cache, updated, {}, splice_batch=splice)
        dt = _time.perf_counter() - t0
        assert len(updated["T"].elems) == n_base + n_ins
        return dt, updated["T"]

    # Pre-ChunkedElems, element-wise insertion shifted the flat list's
    # whole tail per insert (O(n_ins * n_base)) and batching won 40-50x.
    # The chunked COW elems store made element-wise O(n_ins * CHUNK), so
    # the remaining batched win is amortized per-insert bookkeeping
    # (~7-9x observed at 20k-into-200k); the threshold tracks that
    # regime. Same 3-attempt contention guard as cfg7: the batched pass
    # is ~0.07 s, and one probe-loop jax-import burst inside it would
    # inflate sp_s severalfold — a transient, not a regression.
    for attempt in range(3):
        el_s, el_doc = apply_once(n_base_ab, n_ins_ab, splice=False)
        sp_s, sp_doc = apply_once(n_base_ab, n_ins_ab, splice=True)
        assert [e["elemId"] for e in el_doc.elems] == \
            [e["elemId"] for e in sp_doc.elems]      # A/B parity
        speedup = el_s / sp_s
        if speedup >= 4:
            break
        if attempt < 2:
            _time.sleep(4)                 # escape the contention burst
    assert speedup >= 4, f"splice batching only {speedup:.1f}x"
    big_s, _ = apply_once(n_big, n_big, splice=True)
    emit(f"cfg8_frontend_apply_{n_big // 1000}k_insert_patch",
         n_big / big_s, "chars/s",
         elementwise_s_at_20k_into_200k=round(el_s, 4),
         batched_s_at_20k_into_200k=round(sp_s, 4),
         speedup=round(speedup, 1),
         threshold="asserted in code: batched >= 4x element-wise at the "
                   "20k-into-200k A/B size")


def config9_sync_fanout(n_peers: int = 20, n_changes: int = 50):
    """Multi-peer sync throughput: one author DocSet fanning every local
    change out to n_peers over the Connection protocol. The reference
    instantiates one Connection per peer, each re-diffing every doc per
    local change (src/connection.js:58-88); here all author-side
    Connections share one SyncHub (sync/hub.py) — one vectorized
    ClockMatrix comparison per change regardless of peer count. Measured:
    end-to-end deliveries (change applied at a peer) per second, full
    protocol included (clock bookkeeping, extraction, message pump,
    remote apply + frontend patch)."""
    import time as _time

    import automerge_tpu as am
    from automerge_tpu import Connection, DocSet, Text

    author_set = DocSet()
    author_set.set_doc("doc", am.change(
        am.init("author"), lambda d: d.__setitem__("t", Text("base"))))
    peer_sets = [DocSet() for _ in range(n_peers)]
    out_q = [[] for _ in range(n_peers)]
    in_q = [[] for _ in range(n_peers)]
    author_conns = [Connection(author_set, out_q[i].append)
                    for i in range(n_peers)]
    peer_conns = [Connection(peer_sets[i], in_q[i].append)
                  for i in range(n_peers)]
    for c in author_conns + peer_conns:
        c.open()

    def pump():
        moved = True
        while moved:
            moved = False
            for i in range(n_peers):
                while out_q[i]:
                    peer_conns[i].receive_msg(out_q[i].pop(0))
                    moved = True
                while in_q[i]:
                    author_conns[i].receive_msg(in_q[i].pop(0))
                    moved = True

    pump()                                   # initial advertisements
    t0 = _time.perf_counter()
    for k in range(n_changes):
        doc = author_set.get_doc("doc")
        author_set.set_doc("doc", am.change(
            doc, lambda d, k=k: d["t"].insert_at(0, *"0123456789")))
        pump()
    dt = _time.perf_counter() - t0
    # each change splices its run at position 0, so the LAST change's run
    # is frontmost and every run reads in order — full content equality
    # catches RGA mis-ordering that a length check would miss
    expect = "0123456789" * n_changes + "base"
    for ps in peer_sets:
        got = str(am.to_json(ps.get_doc("doc"))["t"])
        assert got == expect, (got[:40], len(got))
    deliveries = n_changes * n_peers
    emit(f"cfg9_sync_fanout_{n_peers}peers", deliveries / dt,
         "deliveries/s",
         changes_per_sec=round(n_changes / dt, 1),
         n_peers=n_peers, n_changes=n_changes,
         threshold=TRACKING_ONLY)


def config10_save_load(n_changes: int = 40, run_chars: int = 250):
    """Persistence round-trip (reference: src/automerge.js save/load —
    serialize the change history, rebuild by replay). Load used to grow
    each device doc through every capacity bucket, paying a fresh XLA
    compile per bucket shape (~12 s for this doc, round 5); creation
    sizing from the delivery's op totals (backend/device.py _distribute)
    pins the shapes, leaving one-time per-shape compiles (warm process:
    ~0.2 s). Reported warm: best of 2 loads after a throwaway first."""
    import time as _time

    import automerge_tpu as am
    from automerge_tpu import Text

    doc = am.change(am.init("u"), lambda d: d.__setitem__("t", Text("x")))
    for _ in range(n_changes):
        doc = am.change(doc, lambda d: d["t"]
                        .insert_at(0, *("ab" * (run_chars // 2))))
    n_chars = 1 + n_changes * run_chars
    t0 = _time.perf_counter()
    blob = am.save(doc)
    save_s = _time.perf_counter() - t0
    holder = {}

    def one_load():
        holder["back"] = am.load(blob)

    load_s = timed(one_load, warmups=1, reps=2)   # shared discipline
    assert str(am.to_json(holder["back"])["t"]) == str(am.to_json(doc)["t"])
    emit(f"cfg10_save_load_{n_chars // 1000}k_chars_{n_changes}_changes",
         n_chars / load_s, "chars_loaded/s",
         save_ms=round(save_s * 1e3, 1), load_ms=round(load_s * 1e3, 1),
         blob_kb=len(blob) // 1024,
         threshold=TRACKING_ONLY)


def main():
    from benchmarks.common import preflight_device
    # allow_cpu: off-chip smoke runs are legitimate here — every emitted
    # row is provenance-stamped with its platform, so a cpu run can never
    # masquerade as a chip measurement; the preflight only guards against
    # a HANGING tunnel eating the whole time budget
    if not preflight_device(allow_cpu=True):
        print("run_all: no reachable jax device (TPU tunnel down?) — "
              "refusing to hang", file=sys.stderr)
        sys.exit(3)
    quick = "--quick" in sys.argv
    if "--service-session" in sys.argv:
        # the chip_session.sh service step: ONLY the service row, full
        # JSON appended to BENCH_SESSIONS.jsonl (PR-4 credibility rules)
        config11_service(quick=quick, record_session=True)
        return
    if "--sharded-session" in sys.argv:
        # the chip_session.sh cfg12 step: ONLY the sharded row, the
        # subprocess's honest cpu-dryrun JSON appended to
        # BENCH_SESSIONS.jsonl (the acceptance bar is defined there)
        config12_sharded(quick=quick, record_session=True)
        return
    if "--text-prepare-session" in sys.argv:
        # the chip_session.sh cfg12t step: ONLY the cold-planning row
        config12t_text_prepare(quick=quick, record_session=True)
        return
    if "--wire-session" in sys.argv:
        # the chip_session.sh cfg13 step: ONLY the binary-wire A/B row
        config13_wire(quick=quick, record_session=True)
        return
    if "--lineage-session" in sys.argv:
        # the chip_session.sh cfg14 step: ONLY the lineage A/B row
        config14_lineage(quick=quick, record_session=True)
        return
    if "--device-truth-session" in sys.argv:
        # the chip_session.sh cfg15 step: ONLY the device-truth row
        config15_device_truth(quick=quick, record_session=True)
        return
    if "--federation-session" in sys.argv:
        # the chip_session.sh cfg16 step: ONLY the federation row
        config16_federation(quick=quick, record_session=True)
        return
    if "--fused-session" in sys.argv:
        # the chip_session.sh cfg17 step: ONLY the fused-round A/B row
        config17_fused(quick=quick, record_session=True)
        return
    if "--residency-session" in sys.argv:
        # the chip_session.sh cfg18 step: ONLY the bounded-HBM row
        config18_residency(quick=quick, record_session=True)
        return
    if "--learned-session" in sys.argv:
        # the chip_session.sh cfg19 step: ONLY the learned-index A/B row
        config19_learned_index(quick=quick, record_session=True)
        return
    if "--parallel-session" in sys.argv:
        # the chip_session.sh cfg20 step: ONLY the parallel-mesh A/B row
        config20_parallel(quick=quick, record_session=True)
        return
    record_round = None
    record_path = None
    if "--record" in sys.argv:
        import os
        record_round = int(sys.argv[sys.argv.index("--record") + 1])
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        record_path = os.path.join(
            root, f"BENCH_CONFIGS_r{record_round:02d}.json")

    def fold_headline():
        # cfg5 = the headline bench, folded into the record file FIRST —
        # a tunnel window that drops mid-sweep must keep the single most
        # valuable row (round 5's first window died 16 min in;
        # docs/PROFILE_r5.md "session v2")
        import json as _json
        import os
        import subprocess
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = {**os.environ, "AMTPU_SKIP_PREFLIGHT": "1"}  # probed already
        try:
            # bounded: with preflight skipped, a tunnel that dropped since
            # the session probe would hang the subprocess forever and eat
            # the whole configs step's outer timeout (losing all 13 rows)
            out = subprocess.run(
                [sys.executable, os.path.join(root, "bench.py")],
                capture_output=True, text=True, cwd=root, env=env,
                timeout=900)
        except subprocess.TimeoutExpired:
            print("# headline bench timed out (tunnel hang?); "
                  "continuing with configs", file=sys.stderr)
            return
        if out.returncode != 0:
            # non-gating: a transient headline failure must not cost the
            # window the 13 config rows behind it (they record without it)
            sys.stderr.write(out.stderr)
            print(f"# headline bench failed rc={out.returncode}; "
                  "continuing with configs", file=sys.stderr)
            return
        try:
            rec = _json.loads(out.stdout.strip().splitlines()[-1])
        except (IndexError, ValueError):
            # same non-gating stance for a malformed stdout (stray
            # library print, empty output): log and sweep on
            print(f"# headline bench stdout unparsable "
                  f"({out.stdout[-120:]!r}); continuing with configs",
                  file=sys.stderr)
            return
        if rec.get("stale"):
            # a stale record is the BEST-OF fallback from some earlier
            # chip session, not a measurement of this sweep — folding it
            # in would stamp it with this sweep's platform/round and
            # launder best-of semantics into a fresh row (ADVICE r5)
            print("# headline bench served a stale last-good record "
                  f"({rec.get('stale_reason', '')!r:.120}); not folding "
                  "it into this sweep's record", file=sys.stderr)
            return
        from benchmarks.common import RESULTS, _platform
        # stamp provenance on the folded-in headline row too (bench.py
        # emits raw JSON; the subprocess shares this process's platform)
        RESULTS.append({**rec, "metric": "cfg5_" + rec["metric"],
                        "platform": _platform()})
        print(_json.dumps(RESULTS[-1]), flush=True)

    steps = [
        config1_text_two_actor,
        config2_map_counter,
        lambda: config3_docset(n_docs=100 if quick else 1000),
        lambda: config4_trellis(quick=quick),
        lambda: config5b_residual_heavy(quick=quick),
        lambda: config5c_two_causal_rounds(quick=quick),
        lambda: config5d_overlap(quick=quick),
        lambda: config5e_incremental_pull(quick=quick),
        lambda: config5f_pipeline(quick=quick),
        config6_conflict_heavy,
        lambda: config7_interactive_latency(n_changes=20 if quick else 60),
        lambda: config7b_nested_under_large_root(
            n_root=20_000 if quick else 100_000),
        lambda: config8_frontend_splice(n_big=200_000 if quick else 1_000_000),
        lambda: config9_sync_fanout(n_peers=8 if quick else 20,
                                    n_changes=20 if quick else 50),
        lambda: config10_save_load(n_changes=15 if quick else 40),
        lambda: config11_service(quick=quick),
        lambda: config12_sharded(quick=quick),
        lambda: config12t_text_prepare(quick=quick),
        lambda: config13_wire(quick=quick),
        lambda: config14_lineage(quick=quick),
        lambda: config15_device_truth(quick=quick),
        lambda: config17_fused(quick=quick),
        lambda: config18_residency(quick=quick),
        lambda: config19_learned_index(quick=quick),
        lambda: config20_parallel(quick=quick),
    ]
    if record_path is not None:
        steps.insert(0, fold_headline)
    for step in steps:
        step()
        if record_path is not None:
            # incremental: every completed config survives a tunnel drop
            write_record(record_path)
    if record_path is None and not quick:
        print("# cfg5 (headline): python bench.py", file=sys.stderr)


if __name__ == "__main__":
    main()

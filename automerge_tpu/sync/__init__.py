from .connection import Connection  # noqa: F401
from .doc_set import DocSet  # noqa: F401
from .watchable_doc import WatchableDoc  # noqa: F401

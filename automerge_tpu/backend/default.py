"""Default backend binding for the facade and sync layers.

The public API routes through the device-engine backend (``device.py``) —
flat documents ride the TPU columnar engine, everything else graduates to
the oracle transparently. Set ``AUTOMERGE_TPU_BACKEND=oracle`` to pin the
pure-host oracle backend instead (the device module dispatches on state
type, so documents built under either binding interoperate).
"""

import os as _os

if _os.environ.get("AUTOMERGE_TPU_BACKEND") == "oracle":
    from . import facade as _impl
else:
    from . import device as _impl

init = _impl.init
apply_changes = _impl.apply_changes
apply_local_change = _impl.apply_local_change
get_patch = _impl.get_patch
get_changes = _impl.get_changes
get_changes_for_actor = _impl.get_changes_for_actor
get_missing_changes = _impl.get_missing_changes
get_missing_deps = _impl.get_missing_deps
merge = _impl.merge
undo = _impl.undo
redo = _impl.redo
Backend = _impl.Backend

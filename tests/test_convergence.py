"""Randomized convergence property tests.

The CRDT analogue of race detection (SURVEY.md §5): N actors make random
concurrent edits; the full change-set must materialize to the same document
under every delivery order. Nondeterminism sources are pinned (seeded RNG,
fixed actor ids).
"""

import itertools
import json
import random

import automerge_tpu as am
from automerge_tpu import Text


def random_edit(rng, doc, actor):
    """One random change: map set/delete, list ops, text ops, counter inc."""
    kind = rng.randrange(6)

    def cb(d):
        if kind == 0:
            d[rng.choice("abc")] = rng.randrange(100)
        elif kind == 1:
            key = rng.choice("abc")
            if key in d:
                del d[key]
            else:
                d[key] = None
        elif kind == 2:
            if "xs" not in d:
                d["xs"] = []
            else:
                d["xs"].insert(rng.randint(0, len(d["xs"])), f"{actor}-{rng.randrange(99)}")
        elif kind == 3:
            if "xs" in d and len(d["xs"]) > 0:
                d["xs"].delete_at(rng.randrange(len(d["xs"])))
            else:
                d["xs"] = [f"{actor}-init"]
        elif kind == 4:
            if "t" not in d:
                d["t"] = Text("seed")
            else:
                d["t"].insert_at(rng.randint(0, len(d["t"])), rng.choice("xyz"))
        else:
            if "n" not in d:
                d["n"] = am.Counter(0)
            else:
                d["n"].increment(rng.randrange(1, 5))
    return am.change(doc, cb)


def converged_json(changes, order):
    doc = am.init("observer")
    for i in order:
        doc = am.apply_changes(doc, [changes[i]])
    return am.to_json(doc)


def test_permutation_invariance_small():
    """All orderings of a small concurrent change-set converge identically."""
    rng = random.Random(42)
    base = am.change(am.init("base"), lambda d: d.update({"xs": ["x"], "t": Text("ab")}))
    base_changes = am.get_all_changes(base)

    actors = ["actor-a", "actor-b", "actor-c"]
    concurrent = []
    for actor in actors:
        doc = am.apply_changes(am.init(actor), base_changes)
        doc = random_edit(rng, doc, actor)
        concurrent.extend(am.get_changes(am.apply_changes(am.init("tmp"), base_changes), doc))

    results = set()
    for order in itertools.permutations(range(len(concurrent))):
        doc = am.init("observer")
        for ch in base_changes:
            doc = am.apply_changes(doc, [ch])
        for i in order:
            doc = am.apply_changes(doc, [concurrent[i]])
        results.add(json.dumps(am.to_json(doc), sort_keys=True))
    assert len(results) == 1, f"diverged into {len(results)} states"


def test_random_multi_actor_sessions():
    """Longer random sessions: merge in random orders, assert convergence."""
    for seed in range(5):
        rng = random.Random(1000 + seed)
        n_actors = rng.randint(2, 4)
        docs = {}
        base = am.change(am.init("base"), lambda d: d.update({"xs": [], "t": Text("")}))
        base_changes = am.get_all_changes(base)
        for i in range(n_actors):
            docs[i] = am.apply_changes(am.init(f"actor-{i}"), base_changes)

        # several rounds of concurrent edits + random pairwise syncs
        for _ in range(6):
            for i in range(n_actors):
                if rng.random() < 0.8:
                    docs[i] = random_edit(rng, docs[i], f"actor-{i}")
            i, j = rng.sample(range(n_actors), 2)
            docs[i] = am.merge(docs[i], docs[j])

        # full mesh sync in two different orders must agree
        all_changes = []
        for i in range(n_actors):
            all_changes.extend(am.get_all_changes(docs[i]))
        order1 = list(range(len(all_changes)))
        order2 = list(reversed(order1))
        rng.shuffle(order1)

        def apply_in(order):
            doc = am.init("observer")
            for k in order:
                doc = am.apply_changes(doc, [all_changes[k]])
            return am.to_json(doc)

        r1, r2 = apply_in(order1), apply_in(order2)
        assert r1 == r2, f"seed {seed}: diverged"


def test_merge_is_idempotent_and_commutative():
    a = am.change(am.init("actor-a"), lambda d: d.update({"x": 1}))
    b = am.change(am.init("actor-b"), lambda d: d.update({"y": 2}))
    ab = am.merge(a, b)
    ab2 = am.merge(ab, b)      # idempotent
    assert am.to_json(ab) == am.to_json(ab2)
    ba = am.merge(b, a)
    assert am.to_json(ab) == am.to_json(ba)  # commutative result

"""Text span/embed depth: control characters, embedded objects, and the
span contract a rich-text editor bridge builds on.

Counterpart of the reference's span sections
(/root/reference/test/text_test.js:368-437 and the Quill-delta bridge that
consumes to_spans)."""

import automerge_tpu as am
from automerge_tpu import Text


def make(initial=""):
    return am.change(am.init("writer"),
                     lambda d: d.__setitem__("t", Text(initial)))


class TestSpans:
    def test_empty(self):
        doc = make()
        assert doc["t"].to_spans() == []

    def test_plain_run(self):
        doc = make("hello")
        assert doc["t"].to_spans() == ["hello"]

    def test_embed_objects_split_runs(self):
        doc = make("ab")
        doc = am.change(doc, lambda d: d["t"].insert_at(1, {"bold": True}))
        assert doc["t"].to_spans() == ["a", {"bold": True}, "b"]
        # embeds are excluded from the plain string
        assert str(doc["t"]) == "ab"
        assert len(doc["t"]) == 3

    def test_leading_and_trailing_embeds(self):
        doc = make("x")
        doc = am.change(doc, lambda d: d["t"].insert_at(0, {"s": 1}))
        doc = am.change(doc, lambda d: d["t"].insert_at(2, {"e": 2}))
        assert doc["t"].to_spans() == [{"s": 1}, "x", {"e": 2}]

    def test_adjacent_embeds(self):
        doc = make("ab")
        doc = am.change(doc, lambda d: d["t"].insert_at(1, {"i": 1}, {"i": 2}))
        assert doc["t"].to_spans() == ["a", {"i": 1}, {"i": 2}, "b"]

    def test_deleting_embed_rejoins_runs(self):
        doc = make("ab")
        doc = am.change(doc, lambda d: d["t"].insert_at(1, {"m": 1}))
        doc = am.change(doc, lambda d: d["t"].delete_at(1))
        assert doc["t"].to_spans() == ["ab"]

    def test_control_characters_kept_in_string(self):
        doc = make("a\nb\tc")
        assert str(doc["t"]) == "a\nb\tc"
        assert doc["t"].to_spans() == ["a\nb\tc"]

    def test_embed_values_survive_merge(self):
        a = make("hi")
        a = am.change(a, lambda d: d["t"].insert_at(1, {"link": "url"}))
        b = am.merge(am.init("other"), a)
        b = am.change(b, lambda d: d["t"].insert_at(3, "!"))
        m1, m2 = am.merge(a, b), am.merge(b, a)
        assert m1["t"].to_spans() == m2["t"].to_spans() \
            == ["h", {"link": "url"}, "i!"]

    def test_spans_survive_save_load(self):
        doc = make("xy")
        doc = am.change(doc, lambda d: d["t"].insert_at(1, {"k": [1, 2]}))
        loaded = am.load(am.save(doc), "reader")
        assert loaded["t"].to_spans() == ["x", {"k": [1, 2]}, "y"]


class TestTextEditingDepth:
    def test_slice_and_iteration(self):
        doc = make("hello")
        t = doc["t"]
        assert t[1:4] == ["e", "l", "l"]
        assert list(t) == list("hello")
        assert t == "hello" and t == Text("hello")

    def test_get_elem_id_stability_across_edits(self):
        doc = make("abc")
        id_b = doc["t"].get_elem_id(1)
        doc = am.change(doc, lambda d: d["t"].insert_at(0, "z"))
        assert doc["t"].get_elem_id(2) == id_b

    def test_unicode_text(self):
        doc = make("héllo")
        doc = am.change(doc, lambda d: d["t"].insert_at(5, "🎉"))
        assert str(doc["t"]) == "héllo🎉"
        loaded = am.load(am.save(doc))
        assert str(loaded["t"]) == "héllo🎉"

    def test_overlapping_concurrent_deletes_converge(self):
        a = make("abcdef")
        b = am.merge(am.init("other"), a)
        a = am.change(a, lambda d: d["t"].delete_at(1, 3))   # remove bcd
        b = am.change(b, lambda d: d["t"].delete_at(2, 3))   # remove cde
        m1, m2 = am.merge(a, b), am.merge(b, a)
        assert str(m1["t"]) == str(m2["t"]) == "af"

"""Bounded parking lot for causally-premature changes.

A change whose dependencies the local document does not yet cover cannot be
applied; the backends queue such changes internally, but that queue is
unbounded — a misbehaving or malicious peer could grow it without limit by
streaming changes that reference deps it never sends. The inbound gate parks
premature changes here instead: bounded capacity, FIFO eviction, and
eviction statistics so operators can see loss happening (an evicted change
is gone until the transport layer re-requests or re-sends it — the
`ResilientChannel` retransmit path, or a peer reconnect).
"""

from __future__ import annotations

from collections import OrderedDict

from .. import obs

#: Default per-document bound, sized for real reordering windows (a few
#: hundred in-flight changes on a lossy multi-path mesh). DocIds are
#: peer-chosen, so this alone is not the hostile-peer memory bound — the
#: inbound gate adds an aggregate cap across all docs
#: (``inbound.GLOBAL_CAPACITY``) with largest-queue-first eviction.
DEFAULT_CAPACITY = 1024


class QuarantineQueue:
    """FIFO of premature changes keyed ``(actor, seq)``, bounded."""

    __slots__ = ("capacity", "_items", "stats")

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError(f"quarantine capacity must be >= 1, "
                             f"got {capacity}")
        self.capacity = capacity
        self._items: OrderedDict = OrderedDict()   # (actor, seq) -> change
        self.stats = {"parked": 0, "evicted": 0, "released": 0, "peak": 0}

    def __len__(self) -> int:
        return len(self._items)

    def park(self, change: dict, requeue: bool = False):
        """Admit one premature change; evicts the oldest entry on overflow.

        Returns the evicted change, or None. Re-parking the same
        ``(actor, seq)`` replaces the stored change in place (redelivered
        duplicates must not consume capacity). ``requeue`` marks a change
        coming back after an unsuccessful drain — it re-enters without
        counting as a fresh park in the stats."""
        key = (change["actor"], change["seq"])
        if key in self._items:
            self._items[key] = change
            return None
        evicted = None
        if len(self._items) >= self.capacity:
            _, evicted = self._items.popitem(last=False)
            self.stats["evicted"] += 1
            if obs.ENABLED:
                obs.event("quar", "evict", args={"reason": "capacity"})
        self._items[key] = change
        if not requeue:
            self.stats["parked"] += 1
            if obs.ENABLED:
                obs.event("quar", "park",
                          args={"actor": key[0], "seq": key[1]})
        if len(self._items) > self.stats["peak"]:
            self.stats["peak"] = len(self._items)
        return evicted

    def drain_oldest(self):
        """Evict and return the single oldest entry (the inbound gate's
        aggregate-bound eviction), or None when empty."""
        if not self._items:
            return None
        _, evicted = self._items.popitem(last=False)
        self.stats["evicted"] += 1
        if obs.ENABLED:
            obs.event("quar", "evict", args={"reason": "aggregate"})
        return evicted

    def drain(self) -> list:
        """Remove and return every parked change (admission order).

        The caller re-parks whatever is still premature; ``released`` is
        credited by the inbound gate for drained changes that actually
        applied, so re-parking does not inflate it."""
        items = list(self._items.values())
        self._items.clear()
        return items

"""Native (C++) runtime tier: wire-format codec.

The compute path is JAX/XLA (ops/); this package holds the host runtime
pieces where native code pays. `codec.cpp` decodes JSON change lists (the
sync wire format) straight into the engine's columnar batch arrays
(measured 3.5x the per-op Python decoder - JSON lexing dominates both -
and the run-detection walker 18x the numpy path; docs/MEASUREMENTS.md).

The library builds lazily with g++ (no pybind11 — plain ctypes over an
extern-C API) and caches next to the source; every entry point degrades to
the pure-Python decoder when the toolchain or the .so is unavailable, or
when the batch contains shapes the native scope excludes (rich values,
non-list objects) — correctness never depends on the native tier.
"""

from __future__ import annotations

import ctypes
import json
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "build", "libamtpu_codec.so")
_SRC = os.path.join(_HERE, "codec.cpp")

_lock = threading.Lock()
_lib = None
_lib_failed = False


def _host_supports_avx2() -> bool:
    """True iff THIS machine's CPU runs AVX2. g++ happily compiles
    -march=x86-64-v3 on an AVX2-less x86 host (the compiler never checks
    the host CPU), and the resulting .so dies with SIGILL at the first
    vectorized call — a hard process kill no except-clause can catch, so
    the gate must be the runtime capability, not compile success."""
    try:
        with open("/proc/cpuinfo") as fh:
            return "avx2" in fh.read()
    except OSError:          # non-Linux: stay on baseline codegen
        return False


def _build_flags() -> list:
    # x86-64-v3 (AVX2/FMA baseline) lets gcc vectorize the columnar
    # predicate loops in detect_runs (measured 77 -> 47.5 ms at 10M ops);
    # NOT -march=native, so the .so stays valid on any AVX2-capable host
    flags = ["-O3", "-shared", "-fPIC", "-std=c++17", "-pthread"]
    if _host_supports_avx2():
        flags.insert(0, "-march=x86-64-v3")
    return flags


_FLAGS_STAMP = os.path.join(_HERE, "build", "build_flags.txt")


def _load():
    """Build (if stale) and load the codec library; None if unavailable."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            flags = _build_flags()
            # the flags are part of the cache key: an mtime-only check
            # would keep serving a stale -O2 build (or an AVX2 build to
            # a host that can't run it) forever
            try:
                with open(_FLAGS_STAMP) as fh:
                    stamp_current = fh.read() == " ".join(flags)
            except OSError:
                stamp_current = False
            if (not os.path.exists(_SO) or not stamp_current
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                os.makedirs(os.path.dirname(_SO), exist_ok=True)
                subprocess.run(
                    ["g++", *flags, _SRC, "-o", _SO],
                    check=True, capture_output=True, timeout=120)
                with open(_FLAGS_STAMP, "w") as fh:
                    fh.write(" ".join(flags))
            lib = ctypes.CDLL(_SO)
            lib.amtpu_parse.restype = ctypes.c_void_p
            lib.amtpu_parse.argtypes = [ctypes.c_char_p, ctypes.c_long,
                                        ctypes.c_char_p]
            lib.amtpu_error.restype = ctypes.c_char_p
            lib.amtpu_error.argtypes = [ctypes.c_void_p]
            for name in ("amtpu_unsupported", "amtpu_n_changes",
                         "amtpu_n_ops", "amtpu_n_actors"):
                fn = getattr(lib, name)
                fn.restype = ctypes.c_long
                fn.argtypes = [ctypes.c_void_p]
            lib.amtpu_fill_ops.argtypes = [ctypes.c_void_p] + \
                [np.ctypeslib.ndpointer(dt, flags="C_CONTIGUOUS")
                 for dt in (np.int32, np.int8, np.int32, np.int32,
                            np.int32, np.int32, np.int64)]
            lib.amtpu_fill_seqs.argtypes = [
                ctypes.c_void_p,
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")]
            for name in ("amtpu_actors", "amtpu_actor_table", "amtpu_deps",
                         "amtpu_messages"):
                fn = getattr(lib, name)
                fn.restype = ctypes.c_char_p
                fn.argtypes = [ctypes.c_void_p]
            lib.amtpu_free.argtypes = [ctypes.c_void_p]
            i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
            lib.amtpu_detect_runs.restype = ctypes.c_void_p
            lib.amtpu_detect_runs.argtypes = [
                ctypes.c_int64,
                np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                ctypes.c_int64]
            for name in ("amtpu_plan_n_runs", "amtpu_plan_n_pairs",
                         "amtpu_plan_n_res", "amtpu_plan_n_ins"):
                fn = getattr(lib, name)
                fn.restype = ctypes.c_int64
                fn.argtypes = [ctypes.c_void_p]
            lib.amtpu_plan_blob_lt.restype = ctypes.c_int
            lib.amtpu_plan_blob_lt.argtypes = [ctypes.c_void_p,
                                               ctypes.c_int]
            lib.amtpu_plan_fill.argtypes = [
                ctypes.c_void_p, i64p, i64p, i64p, i64p, i64p,
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")]
            lib.amtpu_plan_free.argtypes = [ctypes.c_void_p]
            _lib = lib
        except Exception:
            _lib_failed = True
    return _lib


def available() -> bool:
    return _load() is not None


def detect_runs_native(kind, ta, tc, pa, pc, val64, op_row,
                       base_elems: int):
    """Single-pass C++ typing-run detection over op columns.

    Returns (hpos, run_len, head_slot, rpos, res_new_slot, blob, n_ins,
    blob_lt_128, blob_lt_256) or None when the native tier is unavailable.
    Bit-identical to the numpy detection (engine/runs.py) — pinned by
    tests/test_native_codec."""
    lib = _load()
    if lib is None:
        return None
    n = len(kind)
    h = lib.amtpu_detect_runs(
        n, np.ascontiguousarray(kind, np.int8),
        np.ascontiguousarray(ta, np.int32),
        np.ascontiguousarray(tc, np.int32),
        np.ascontiguousarray(pa, np.int32),
        np.ascontiguousarray(pc, np.int32),
        np.ascontiguousarray(val64, np.int64),
        np.ascontiguousarray(op_row, np.int32), base_elems)
    try:
        n_runs = lib.amtpu_plan_n_runs(h)
        n_pairs = lib.amtpu_plan_n_pairs(h)
        n_res = lib.amtpu_plan_n_res(h)
        hpos = np.empty(n_runs, np.int64)
        run_len = np.empty(n_runs, np.int64)
        head_slot = np.empty(n_runs, np.int64)
        rpos = np.empty(n_res, np.int64)
        res_new_slot = np.empty(n_res, np.int64)
        blob = np.empty(n_pairs, np.int32)
        lib.amtpu_plan_fill(h, hpos, run_len, head_slot, rpos,
                            res_new_slot, blob)
        return (hpos, run_len, head_slot, rpos, res_new_slot, blob,
                lib.amtpu_plan_n_ins(h),
                bool(lib.amtpu_plan_blob_lt(h, 128)),
                bool(lib.amtpu_plan_blob_lt(h, 256)))
    finally:
        lib.amtpu_plan_free(h)


def decode_text_changes(data, obj_id: str):
    """JSON change list (str/bytes) -> TextChangeBatch via the native codec.

    Returns None when the native tier is unavailable or the payload is out
    of its scope; the caller falls back to the Python decoder."""
    lib = _load()
    if lib is None:
        return None
    if isinstance(data, str):
        data = data.encode("utf-8")
    h = lib.amtpu_parse(data, len(data), obj_id.encode("utf-8"))
    try:
        if lib.amtpu_unsupported(h):
            return None
        n_changes = lib.amtpu_n_changes(h)
        n_ops = lib.amtpu_n_ops(h)
        op_change = np.empty(n_ops, np.int32)
        op_kind = np.empty(n_ops, np.int8)
        ta = np.empty(n_ops, np.int32)
        tc = np.empty(n_ops, np.int32)
        pa = np.empty(n_ops, np.int32)
        pc = np.empty(n_ops, np.int32)
        val = np.empty(n_ops, np.int64)
        if n_ops:
            lib.amtpu_fill_ops(h, op_change, op_kind, ta, tc, pa, pc, val)
        seqs = np.empty(n_changes, np.int32)
        if n_changes:
            lib.amtpu_fill_seqs(h, seqs)

        def split(raw):
            s = raw.decode("utf-8")
            return s.split("\n") if s else []

        from ..engine.columnar import intern_deps
        actors = split(lib.amtpu_actors(h))
        actor_table = split(lib.amtpu_actor_table(h))
        deps = intern_deps([json.loads(d) for d in split(lib.amtpu_deps(h))])
        raw_msgs = lib.amtpu_messages(h).decode("utf-8")
        messages = []
        if n_changes:
            for part in raw_msgs.split("\x1f"):
                messages.append(part[1:] if part[:1] == "1" else None)
        if not (len(actors) == len(deps) == len(messages) == n_changes):
            return None  # defensive: malformed joins -> python path

        from ..engine.columnar import TextChangeBatch
        return TextChangeBatch(
            obj_id=obj_id, actors=actors, seqs=seqs, deps=deps,
            messages=messages, op_change=op_change, op_kind=op_kind,
            op_target_actor=ta, op_target_ctr=tc, op_parent_actor=pa,
            op_parent_ctr=pc, op_value=val, actor_table=actor_table,
            value_pool=[])
    finally:
        lib.amtpu_free(h)

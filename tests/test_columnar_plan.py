"""Columnar-vs-legacy planner parity (INTERNALS §10).

The columnar planner (engine/wire_columns.py + engine/base.py
`_schedule_columnar`, the AMTPU_COLUMNAR_PLAN default) must produce
EXACTLY the legacy per-change planner's outcome on every input: same
committed device state (all nine element tables byte-identical), same
text, same clock/queue/conflicts, same backend patches. These tests pin
that contract over randomized batches covering the admission edge cases
— out-of-order seqs, duplicate deliveries, causally-premature changes,
multi-round chains, shared and distinct dep frontiers — plus the
decoder-level parity of the vectorized wire decoder.
"""

import os
import random

import numpy as np
import pytest

import bench as B
from automerge_tpu.engine import DeviceTextDoc, PipelinedIngestor
from automerge_tpu.engine.columnar import TextChangeBatch
from automerge_tpu.engine.map_doc import DeviceMapDoc
from automerge_tpu.engine.wire_columns import (
    _from_changes_numpy, change_columns, decode_text_changes_columnar)


# ---------------------------------------------------------------------------
# randomized wire-change generation (admission edge cases included)
# ---------------------------------------------------------------------------


def rand_text_changes(rng, n_changes=30, obj="t", n_actors=6,
                      premature=True, dups=True):
    """Randomized wire changes: typing runs, bare assigns, out-of-order
    seqs (shuffled delivery), duplicates, and causally-premature dep
    frontiers (changes that queue forever). Deliveries stay CONSISTENT:
    every foreign elemId reference is covered by a dep on its minting
    change, so both planners admit the exact same rows (an uncovered ref
    is an inconsistency the engine rejects by raising)."""
    changes = []
    elems = {}            # actor -> next elem counter
    known = ["_head"]     # insertable parents (elemIds + head)
    src = {}              # elemId -> (actor, seq) of the minting change
    seq_of = {}
    for _ in range(n_changes):
        actor = f"a{rng.randrange(n_actors):02d}"
        seq = seq_of.get(actor, 0) + 1
        seq_of[actor] = seq
        deps = {}
        ops = []

        def ref(eid):
            """Reference an elemId, covering it causally."""
            s = src.get(eid)
            if s is not None and s[0] != actor:
                deps[s[0]] = max(deps.get(s[0], 0), s[1])
            return eid

        premature_change = premature and rng.random() < 0.15
        if premature_change:
            # an unsatisfiable frontier: queues for the session; its ops
            # reference only its own fresh elements
            other = f"a{rng.randrange(n_actors):02d}"
            if other != actor:
                deps[other] = seq_of.get(other, 0) + rng.randrange(2, 4)
        for _ in range(rng.randrange(0, 5)):
            r = rng.random()
            if r < 0.55 or len(known) == 1 or premature_change:
                e = elems.get(actor, 0) + 1
                elems[actor] = e
                key = ("_head" if (premature_change or len(known) == 1
                                   or rng.random() < 0.3)
                       else ref(rng.choice(known[1:])))
                ops.append({"action": "ins", "obj": obj, "key": key,
                            "elem": e})
                eid = f"{actor}:{e}"
                ops.append({"action": "set", "obj": obj, "key": eid,
                            "value": chr(97 + rng.randrange(26))})
                known.append(eid)
                src[eid] = (actor, seq)
            elif r < 0.75:
                ops.append({"action": "set", "obj": obj,
                            "key": ref(rng.choice(known[1:])),
                            "value": chr(97 + rng.randrange(26))})
            elif r < 0.9:
                ops.append({"action": "del", "obj": obj,
                            "key": ref(rng.choice(known[1:]))})
            else:
                ops.append({"action": "inc", "obj": obj,
                            "key": ref(rng.choice(known[1:])),
                            "value": rng.randrange(-2, 5)})
        changes.append({"actor": actor, "seq": seq, "deps": deps,
                        "ops": ops})
        if premature_change:
            # the actor's later seqs would implicitly depend on the
            # queued change; stop minting from it so `known` stays
            # resolvable for other actors
            for eid in [k for k, v in src.items() if v == (actor, seq)]:
                known.remove(eid)
                del src[eid]
    rng.shuffle(changes)                   # out-of-order delivery
    if dups:
        for _ in range(rng.randrange(0, 3)):
            changes.insert(rng.randrange(len(changes) + 1),
                           dict(rng.choice(changes)))
    return changes


def engine_state(doc):
    """Everything the committed document state consists of, host-side."""
    out = {
        "text": doc.text(),
        "n_elems": doc.n_elems,
        "clock": dict(doc.clock),
        "queue": sorted((b.actors[r], int(b.seqs[r])) for b, r in doc.queue),
        "conflicts": {k: sorted((o["actor_rank"], o["seq"], o["value"],
                                 o["counter"]) for o in v)
                      for k, v in doc.conflicts.items()},
        "actor_table": list(doc.actor_table),
        "value_pool": [str(v) for v in doc.value_pool],
    }
    if doc.n_elems:
        mirrors = doc._fetch_mirrors(doc._TABLE_KEYS)
        n = doc.n_elems + 1
        out["tables"] = {k: v[:n].tobytes() for k, v in mirrors.items()}
    return out


def apply_with_flag(changes, flag, monkeypatch, *, prepared=False,
                    seed_doc=True):
    monkeypatch.setenv("AMTPU_COLUMNAR_PLAN", flag)
    doc = DeviceTextDoc("t")
    if seed_doc:
        doc.apply_changes([{"actor": "base", "seq": 1, "deps": {}, "ops": [
            {"action": "ins", "obj": "t", "key": "_head", "elem": 1},
            {"action": "set", "obj": "t", "key": "base:1", "value": "Z"},
        ]}])
    batch = TextChangeBatch.from_changes(changes, "t", _try_native=False)
    if flag == "1":
        # the random batches sit below the scheduler's derive gate
        # (_BULK_SCHEDULE_MIN); attach the columns as the protocol
        # boundary would for a bulk payload, so the columnar paths are
        # what this parity suite actually exercises
        change_columns(batch)
    if prepared:
        doc.commit_prepared(doc.prepare_batch(batch))
    else:
        doc.apply_batch(batch)
    return engine_state(doc)


@pytest.mark.parametrize("seed", range(8))
def test_planner_parity_random_batches(seed, monkeypatch):
    """Committed device state is byte-identical between the columnar and
    legacy planners over randomized out-of-order/duplicate/premature
    batches — via both apply_batch and the prepare/commit path."""
    rng = random.Random(seed)
    changes = rand_text_changes(rng, n_changes=10 + 5 * seed)
    legacy = apply_with_flag(list(changes), "0", monkeypatch)
    cols = apply_with_flag(list(changes), "1", monkeypatch)
    assert cols == legacy
    cols_prep = apply_with_flag(list(changes), "1", monkeypatch,
                                prepared=True)
    assert cols_prep == legacy


def test_planner_parity_forced_loop_vs_columnar(monkeypatch):
    """Columnar admission agrees with the per-change loop even below the
    bulk threshold (the loop is the ground-truth comparator)."""
    import automerge_tpu.engine.base as eb
    rng = random.Random(99)
    changes = rand_text_changes(rng, n_changes=40)
    monkeypatch.setattr(eb, "_BULK_SCHEDULE_MIN", 10**9)
    legacy = apply_with_flag(list(changes), "0", monkeypatch)
    cols = apply_with_flag(list(changes), "1", monkeypatch)
    assert cols == legacy


def test_wide_merge_parity(monkeypatch):
    """The headline shape (wide concurrent merge over one frontier) —
    fast path vs legacy, including a second (duplicate) delivery."""
    batch_changes = None
    states = {}
    for flag in ("0", "1"):
        monkeypatch.setenv("AMTPU_COLUMNAR_PLAN", flag)
        doc = DeviceTextDoc("t")
        doc.apply_batch(B.base_batch("t", 500))
        merge = B.merge_batch("t", 40, 20, 500, seed=3)
        dup = B.merge_batch("t", 40, 20, 500, seed=3)
        if flag == "1":
            change_columns(merge)     # below the scheduler derive gate
            change_columns(dup)
        doc.apply_batch(merge)
        doc.apply_batch(dup)          # duplicate delivery
        states[flag] = engine_state(doc)
        batch_changes = merge
    assert states["0"] == states["1"]
    assert batch_changes.n_ops == 40 * 20


def test_map_planner_parity(monkeypatch):
    """Map/counter documents run the same admission machinery."""
    rng = random.Random(5)
    seq_of = {}
    changes = []
    for _ in range(120):
        actor = f"m{rng.randrange(5)}"
        seq = seq_of.get(actor, 0) + 1
        seq_of[actor] = seq
        changes.append({
            "actor": actor, "seq": seq, "deps": {},
            "ops": [{"action": "set", "obj": "m",
                     "key": f"k{rng.randrange(9)}",
                     "value": rng.randrange(100)}]})
    random.Random(7).shuffle(changes)
    states = {}
    for flag in ("0", "1"):
        monkeypatch.setenv("AMTPU_COLUMNAR_PLAN", flag)
        doc = DeviceMapDoc("m")
        doc.apply_changes(list(changes))
        states[flag] = {
            "clock": dict(doc.clock),
            "values": {k: doc.get(k) for k in
                       (f"k{i}" for i in range(9))},
        }
    assert states["0"] == states["1"]


def test_backend_patch_parity(monkeypatch):
    """The device backend tier produces identical patches either way."""
    import json

    from automerge_tpu.backend import device as device_backend

    def run(flag):
        monkeypatch.setenv("AMTPU_COLUMNAR_PLAN", flag)
        state = device_backend.Backend.init()
        doc_change = {
            "actor": "alice", "seq": 1, "deps": {},
            "ops": [
                {"action": "makeText", "obj": "txt"},
                {"action": "link", "obj": "00000000-0000-0000-0000-000000000000",
                 "key": "text", "value": "txt"},
            ] + [op for k in range(1, 9) for op in (
                {"action": "ins", "obj": "txt",
                 "key": "_head" if k == 1 else f"alice:{k-1}", "elem": k},
                {"action": "set", "obj": "txt", "key": f"alice:{k}",
                 "value": chr(96 + k)})],
        }
        concurrent = [{
            "actor": f"bob{i}", "seq": 1, "deps": {"alice": 1},
            "ops": [
                {"action": "ins", "obj": "txt", "key": f"alice:{4 + i}",
                 "elem": 1},
                {"action": "set", "obj": "txt", "key": f"bob{i}:1",
                 "value": str(i)}],
        } for i in range(3)]
        state, p1 = device_backend.Backend.apply_changes(state, [doc_change])
        state, p2 = device_backend.Backend.apply_changes(state, concurrent)
        return json.dumps([p1, p2], sort_keys=True, default=str)

    assert run("0") == run("1")


def test_ring_integration_both_planners(monkeypatch):
    """The K-deep pipelined ring converges identically with either
    planner, stays fully chained, and the budget surface agrees."""
    texts = {}
    for flag in ("0", "1"):
        monkeypatch.setenv("AMTPU_COLUMNAR_PLAN", flag)
        doc = DeviceTextDoc("p")
        doc.eager_materialize = True
        doc.apply_batch(B.base_batch("p", 2000))
        doc.text()
        batches = [B.merge_batch("p", 50, 40, 2000, seed=20 + k,
                                 actor_prefix=f"s{k:03d}")
                   for k in range(4)]
        if flag == "1":
            for bb in batches:        # below the scheduler derive gate
                change_columns(bb)
        with PipelinedIngestor(doc, slots=3) as pipe:
            pipe.run(batches)
            stats = pipe.stats
        assert stats["committed"] == 4
        assert stats["fallbacks"] == 0
        assert stats["chained_prepares"] >= 3, (flag, stats)
        texts[flag] = doc.text()
    assert texts["0"] == texts["1"]


# ---------------------------------------------------------------------------
# wire decoder parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(6))
def test_numpy_decoder_parity(seed):
    """The vectorized wire decoder emits batches identical to the per-op
    walk on everything inside its scope."""
    rng = random.Random(seed)
    changes = rand_text_changes(rng, n_changes=25, premature=False)
    walk = TextChangeBatch.from_changes(list(changes), "t",
                                        _try_native=False)
    fast = _from_changes_numpy(list(changes), "t")
    assert fast is not None
    assert walk.actors == fast.actors
    assert walk.actor_table == fast.actor_table
    assert walk.deps == fast.deps
    assert walk.messages == fast.messages
    assert walk.value_pool == fast.value_pool
    for f in ("seqs", "op_change", "op_kind", "op_target_actor",
              "op_target_ctr", "op_parent_actor", "op_parent_ctr",
              "op_value"):
        assert np.array_equal(getattr(walk, f), getattr(fast, f)), f


def test_numpy_decoder_rich_values_fall_back():
    """Out-of-scope shapes (rich values, datatypes, links) return None so
    the caller falls back to the per-op decoder — never a wrong batch."""
    base = [{"actor": "a", "seq": 1, "deps": {}, "ops": [
        {"action": "ins", "obj": "t", "key": "_head", "elem": 1},
        {"action": "set", "obj": "t", "key": "a:1", "value": "multi-char"},
    ]}]
    assert _from_changes_numpy(base, "t") is None
    dt = [{"actor": "a", "seq": 1, "deps": {}, "ops": [
        {"action": "ins", "obj": "t", "key": "_head", "elem": 1},
        {"action": "set", "obj": "t", "key": "a:1", "value": "x",
         "datatype": "counter"},
    ]}]
    assert _from_changes_numpy(dt, "t") is None
    # in-scope BULK payloads attach the columns eagerly; tiny windows
    # stay on the walk (below _NUMPY_MIN_OPS) and derive lazily
    n = 40
    bulk = [{"actor": "a", "seq": 1, "deps": {}, "ops": [
        op for k in range(1, n + 1) for op in (
            {"action": "ins", "obj": "t",
             "key": "_head" if k == 1 else f"a:{k-1}", "elem": k},
            {"action": "set", "obj": "t", "key": f"a:{k}", "value": "x"})]}]
    batch = decode_text_changes_columnar(bulk, "t")
    assert getattr(batch, "_change_columns", None) is not None
    small = [{"actor": "a", "seq": 1, "deps": {}, "ops": [
        {"action": "ins", "obj": "t", "key": "_head", "elem": 1},
        {"action": "set", "obj": "t", "key": "a:1", "value": "x"},
    ]}]
    sbatch = decode_text_changes_columnar(small, "t")
    assert getattr(sbatch, "_change_columns", None) is None
    doc = DeviceTextDoc("t")
    doc.apply_batch(sbatch)
    assert doc.text() == "x"


def test_change_columns_shape():
    """The per-change columns capture the batch's admission-relevant
    structure exactly once and cache on the batch."""
    merge = B.merge_batch("t", 8, 10, 100, seed=1)
    cols = change_columns(merge)
    assert change_columns(merge) is cols            # cached
    assert cols.n_changes == 8
    assert cols.all_seq1 and cols.distinct_actors and cols.single_group
    assert cols.group_deps == [{"base": 1}]
    assert cols.table_sorted == sorted(set(merge.actor_table))
    assert list(cols.actor_idx) == sorted(
        range(8), key=lambda i: merge.actors[i]) or len(
            set(cols.actor_idx.tolist())) == 8
    # dep group CSR refers to the combined local actor space
    g0 = cols.g_actor[cols.g_off[0]:cols.g_off[1]]
    assert [cols.local_actors[j] for j in g0] == ["base"]


def test_rank_cache_invalidation(monkeypatch):
    """A later interning change (new actor reordering ranks) invalidates
    the per-(doc, generation) rank cache — stale ranks never commit."""
    monkeypatch.setenv("AMTPU_COLUMNAR_PLAN", "1")
    doc = DeviceTextDoc("t")
    doc.apply_batch(B.base_batch("t", 50))
    merge = B.merge_batch("t", 6, 10, 50, seed=2)
    cols = change_columns(merge)      # boundary decode (below the gate)
    doc.apply_batch(merge)
    assert cols.rank_cache[doc]["gen"] == doc._intern_gen
    # an actor ranking BELOW every existing one forces a remap
    doc.apply_changes([{"actor": "AAA", "seq": 1, "deps": {}, "ops": []}])
    assert cols.rank_cache[doc]["gen"] != doc._intern_gen
    # re-applying the batch (duplicate) must re-resolve, not reuse stale
    doc.apply_batch(merge)
    legacy = DeviceTextDoc("t")
    monkeypatch.setenv("AMTPU_COLUMNAR_PLAN", "0")
    legacy.apply_batch(B.base_batch("t", 50))
    legacy.apply_batch(B.merge_batch("t", 6, 10, 50, seed=2))
    legacy.apply_changes([{"actor": "AAA", "seq": 1, "deps": {},
                           "ops": []}])
    assert doc.text() == legacy.text()
    assert doc.clock == legacy.clock


def test_numpy_decoder_rejects_malformed_elem_ids():
    """A ctr that is not pure digits ('b:+5' int-parses but parse_elem_id
    rejects it) must NOT decode on the vectorized path — bare int() would
    silently alias the op onto element b:5."""
    bad = [{"actor": "a", "seq": 1, "deps": {}, "ops": [
        {"action": "ins", "obj": "t", "key": "b:+5", "elem": 1}]}]
    assert _from_changes_numpy(bad, "t") is None
    for key in ("b: 5", "b:5\n", "nocolon", 7):
        assert _from_changes_numpy(
            [{"actor": "a", "seq": 1, "deps": {}, "ops": [
                {"action": "del", "obj": "t", "key": key}]}], "t") is None


def test_apply_changes_routes_through_boundary_decoder():
    """`DeviceTextDoc.apply_changes` IS the columnar protocol boundary:
    bulk wire dicts decode through the vectorized decoder with columns
    attached eagerly; small windows keep the per-op walk but still get
    their columns; both merge identically."""
    n = 40   # 80 ops: above the numpy-decoder gate
    changes = [{"actor": "w", "seq": 1, "deps": {}, "ops": [
        op for k in range(1, n + 1) for op in (
            {"action": "ins", "obj": "t",
             "key": "_head" if k == 1 else f"w:{k-1}", "elem": k},
            {"action": "set", "obj": "t", "key": f"w:{k}",
             "value": chr(97 + k % 26)})]}]
    doc = DeviceTextDoc("t")
    batch = doc._decode_wire(changes)
    assert getattr(batch, "_change_columns", None) is not None
    assert _from_changes_numpy(changes, "t") is not None  # numpy scope
    doc.apply_batch(batch)
    small = DeviceTextDoc("s")
    small.apply_changes([{"actor": "w", "seq": 1, "deps": {}, "ops": [
        {"action": "ins", "obj": "s", "key": "_head", "elem": 1},
        {"action": "set", "obj": "s", "key": "w:1", "value": "q"}]}])
    assert small.text() == "q"
    walk = DeviceTextDoc("t")
    walk.apply_batch(TextChangeBatch.from_changes(changes, "t",
                                                  _try_native=False))
    assert doc.text() == walk.text()


# ---------------------------------------------------------------------------
# cross-doc planner parity (INTERNALS §16)
# ---------------------------------------------------------------------------


def _rewrite_obj(changes, obj):
    """The same wire stream retargeted at another object — the cross-doc
    grouping shape (identical planning columns, different obj id)."""
    return [{**c, "ops": [{**op, "obj": obj} for op in c["ops"]]}
            for c in changes]


def _population_state(docs):
    out = {}
    for k, doc in docs.items():
        st = engine_state(doc)
        st["index_rows"] = tuple(r.tobytes() for r in doc.index.rows())
        out[k] = st
    return out


def _run_population(seed, cross, columnar, monkeypatch, batch_index="1",
                    n_docs=6, n_chunks=4):
    """Deliver one randomized stream (out-of-order, dups, premature) to a
    doc population in chunks through the stacked executor — the lane
    shape — under the given planner/index flags; returns final state."""
    from automerge_tpu.engine import stacked as S
    monkeypatch.setenv("AMTPU_CROSS_DOC_PLAN", cross)
    monkeypatch.setenv("AMTPU_COLUMNAR_PLAN", columnar)
    monkeypatch.setenv("AMTPU_BATCH_INDEX", batch_index)
    rng = random.Random(seed * 13 + 5)
    docs = {f"d{i}": DeviceTextDoc(f"d{i}") for i in range(n_docs)}
    # one shared stream for the population + one divergent doc (its own
    # stream: a group of one, exercising the fallback path)
    shared = rand_text_changes(random.Random(seed), n_changes=20, obj="X")
    lone = rand_text_changes(random.Random(seed + 77), n_changes=12,
                             obj="X")
    cuts = sorted(rng.sample(range(1, len(shared)), n_chunks - 1))
    chunks = [shared[a:b] for a, b in
              zip([0] + cuts, cuts + [len(shared)])]
    lone_cuts = [len(lone) * (i + 1) // n_chunks for i in range(n_chunks)]
    lone_chunks = [lone[a:b] for a, b in
                   zip([0] + lone_cuts[:-1], lone_cuts)]
    for chunk, lchunk in zip(chunks, lone_chunks):
        items = [(doc, _rewrite_obj(chunk, k))
                 for k, doc in docs.items() if k != "d0"]
        if lchunk:
            items.append((docs["d0"], _rewrite_obj(lchunk, "d0")))
        st = S.apply_stacked(items)
        if not st:
            for doc, changes in items:
                doc.apply_changes(changes)
        else:
            S.assert_round_budget(st)
    return _population_state(docs)


@pytest.mark.parametrize("seed", range(4))
def test_cross_doc_planner_parity(seed, monkeypatch):
    """Committed state of a whole doc population is byte-identical with
    the cross-doc planner on vs off, under BOTH AMTPU_COLUMNAR_PLAN
    values and both index structures, over out-of-order/dup/premature
    chunked deliveries (the randomized parity bar of ISSUE 12)."""
    ref = _run_population(seed, "0", "1", monkeypatch)
    for cross, columnar, bidx in (("1", "1", "1"), ("1", "1", "0"),
                                  ("1", "0", "1"), ("0", "0", "1")):
        got = _run_population(seed, cross, columnar, monkeypatch,
                              batch_index=bidx)
        assert got == ref, (cross, columnar, bidx)


def test_cross_doc_planner_shares_and_stays_identical(monkeypatch):
    """The uniform-population shape actually SHARES (schedules, run
    detection, rank seeds — the stats prove the pass ran once), and the
    shared plan commits the same bytes as the per-doc planner."""
    from automerge_tpu.engine import stacked as S
    monkeypatch.setenv("AMTPU_COLUMNAR_PLAN", "1")

    def build(cross):
        monkeypatch.setenv("AMTPU_CROSS_DOC_PLAN", cross)
        docs = {f"t{i}": DeviceTextDoc(f"t{i}") for i in range(8)}
        stats = []
        for rnd in range(3):
            base = 1 + rnd * 8
            key = "_head" if rnd == 0 else f"a:{base - 1}"
            ops = []
            k = key
            for j in range(8):
                ops.append({"action": "ins", "obj": "X", "key": k,
                            "elem": base + j})
                ops.append({"action": "set", "obj": "X",
                            "key": f"a:{base + j}",
                            "value": chr(97 + (base + j) % 26)})
                k = f"a:{base + j}"
            chunk = [{"actor": "a", "seq": rnd + 1, "deps": {},
                      "ops": ops}]
            items = [(doc, _rewrite_obj(chunk, kk))
                     for kk, doc in docs.items()]
            st = S.apply_stacked(items)
            assert st, "population fell off the stacked path"
            S.assert_round_budget(st)
            stats.append(st)
        return docs, stats

    docs_on, stats_on = build("1")
    docs_off, _ = build("0")
    cd = stats_on[-1]["cross_doc"]
    assert cd["groups"] == 1 and cd["docs"] == 8
    assert cd["sched_shared"] == 7 and cd["sched_templated"] == 1
    assert cd["rank_seeded"] == 8
    # one bulk index merge per doc per round, never per range
    assert stats_on[-1]["index_merges"] == stats_on[-1]["text_plans"] == 8
    for k in docs_on:
        assert engine_state(docs_on[k]) == engine_state(docs_off[k])

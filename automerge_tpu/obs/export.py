"""Chrome trace-event JSON export (Perfetto-loadable) + schema validator.

The flight recorder's tuples map onto the Trace Event Format's complete
("X") and instant ("i") events:

- span  (dur >= 0) -> {"ph": "X", "name", "cat", "ts", "dur", "pid",
                       "tid", "args"}
- event (dur == -1)-> {"ph": "i", "name", "cat", "ts", "s": "t", ...}

Timestamps are microseconds relative to the recorder's session origin, so
a trace opens at t=0 in https://ui.perfetto.dev regardless of process
uptime. Thread names ride along as metadata ("M") events when known.

`validate_chrome_trace` is the ONE schema check shared by
tests/test_obs.py and the CI trace-smoke step: every span must carry
category/ts/dur, the trace must be non-empty, and (when the trace came
from `bench.py --trace`) every pipeline-ring span must nest inside a
`bench/stream` span on the timeline — the structural guarantee that ring
work is attributable to its stream.

Lineage flow events (INTERNALS §18.5): ``lineage``-category hop events
carry ``{actor, seq, site}`` args; the exporter stitches every sampled
change's hops into ONE Chrome flow — a start ("s") at the first hop,
steps ("t") at each intermediate hop, a finish ("f") at the last —
whose ``id`` is the change's deterministic sample hash.  Loading the
trace in https://ui.perfetto.dev draws one change's journey across
actors/threads as a single connected arrow chain.  Flow pairing (every
started flow finishes, monotone timestamps) is part of the validator's
schema; ``require_flows`` additionally demands at least one flow (the
CI lineage smoke's contract).
"""

from __future__ import annotations

import json
from typing import Optional

from .recorder import ARGS, CAT, DUR, NAME, TID, TS


def _flow_id(actor: str, seq) -> int:
    """Deterministic flow id for one change: THE sampler's content hash
    (`lineage.sample_key`), truncated to 48 bits — traces from two
    replicas of the same run stitch on identical flow ids by
    construction, and a sampler-keying change can never silently
    diverge from the exporter."""
    from .lineage import sample_key
    return sample_key(actor, seq) >> 16


def lineage_flow_events(records, t0_ns: int, pid: int = 1) -> list:
    """Flow events stitching ``lineage``-category hop records into one
    timeline per sampled change (>= 2 hops; a single-hop chain has no
    edge to draw)."""
    chains: dict = {}
    for r in records:
        if r[CAT] != "lineage" or not r[ARGS]:
            continue
        actor, seq = r[ARGS].get("actor"), r[ARGS].get("seq")
        if actor is None or seq is None:
            continue
        chains.setdefault((actor, seq), []).append(r)
    out = []
    for (actor, seq), hops in sorted(chains.items()):
        if len(hops) < 2:
            continue
        hops.sort(key=lambda r: r[TS])
        fid = _flow_id(actor, seq)
        name = f"change {actor}:{seq}"
        for i, r in enumerate(hops):
            ph = "s" if i == 0 else ("f" if i == len(hops) - 1 else "t")
            ev = {"ph": ph, "id": fid, "name": name, "cat": "lineage",
                  "ts": (r[TS] - t0_ns) / 1000.0, "pid": pid,
                  "tid": r[TID]}
            if ph == "f":
                ev["bp"] = "e"
            out.append(ev)
    return out


def to_chrome_trace(records, t0_ns: Optional[int] = None,
                    pid: int = 1) -> dict:
    """Records -> Chrome trace-event JSON object."""
    if t0_ns is None:
        t0_ns = min((r[TS] for r in records), default=0)
    events = []
    tids = set()
    for r in records:
        ts_us = (r[TS] - t0_ns) / 1000.0
        tids.add(r[TID])
        ev = {"name": r[NAME], "cat": r[CAT], "ts": ts_us,
              "pid": pid, "tid": r[TID]}
        if r[ARGS]:
            ev["args"] = dict(r[ARGS])
        if r[DUR] >= 0:
            ev["ph"] = "X"
            ev["dur"] = r[DUR] / 1000.0
        else:
            ev["ph"] = "i"
            ev["s"] = "t"
        events.append(ev)
    events += lineage_flow_events(records, t0_ns, pid)
    # device-truth counter tracks (INTERNALS §19): compile totals and
    # device-resident bytes as "C"-phase samples on the same timeline —
    # Perfetto draws them as counter lanes under the span tracks
    from .device_truth import REGISTRY as _dt_registry
    events += _dt_registry.counter_events(t0_ns, pid)
    meta = [{"ph": "M", "name": "process_name", "pid": pid, "ts": 0,
             "args": {"name": "automerge_tpu"}}]
    meta += [{"ph": "M", "name": "thread_name", "pid": pid, "tid": t,
              "ts": 0, "args": {"name": f"thread-{t}"}}
             for t in sorted(tids)]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def write_trace(path: str, records, t0_ns: Optional[int] = None) -> str:
    with open(path, "w") as fh:
        json.dump(to_chrome_trace(records, t0_ns), fh)
    return path


class TraceValidationError(ValueError):
    """The emitted trace JSON violates the INTERNALS §11 schema."""


def validate_chrome_trace(obj, require_stream_nesting: bool = False,
                          require_flows: bool = False) -> dict:
    """Validate a trace JSON object (or a path to one). Raises
    :class:`TraceValidationError`; returns summary counts on success.

    Checks (the CI smoke's contract, ISSUE 6 + ISSUE 14):
    - the trace holds at least one non-metadata event (an empty trace
      FAILS — a --trace run that recorded nothing is a wiring bug);
    - every "X" span carries name/cat/ts/dur with dur >= 0;
    - every "i" instant carries name/cat/ts;
    - every "C" counter sample carries name/cat/ts plus a numeric
      args value (the device-truth counter tracks, INTERNALS §19);
    - flow events ("s"/"t"/"f") PAIR UP: every flow id with a start has
      exactly one finish, steps/finishes never appear without a start,
      and each flow's timestamps are monotone — a dangling flow is a
      stitching bug, not a rendering quirk;
    - with `require_stream_nesting` (bench traces): every `ring`-category
      span's [ts, ts+dur] interval lies inside some `bench`/`stream`
      span's interval (thread-agnostic containment — the ring's worker
      thread is a different tid by design);
    - with `require_flows` (the lineage smoke): at least one complete
      flow must be present.
    """
    if isinstance(obj, (str, bytes)):
        with open(obj) as fh:
            obj = json.load(fh)
    if not isinstance(obj, dict) or not isinstance(
            obj.get("traceEvents"), list):
        raise TraceValidationError("trace must be an object with a "
                                   "traceEvents list")
    spans, instants, streams, rings = [], [], [], []
    counters: list = []
    flows: dict = {}    # id -> {"s": [...], "t": [...], "f": [...]}
    for ev in obj["traceEvents"]:
        ph = ev.get("ph")
        if ph == "M":
            continue
        for fld in ("name", "cat", "ts"):
            if fld not in ev:
                raise TraceValidationError(
                    f"event missing `{fld}`: {ev!r}")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                raise TraceValidationError(
                    f"span without a valid `dur`: {ev!r}")
            spans.append(ev)
            if ev["cat"] == "bench" and ev["name"] == "stream":
                streams.append((ev["ts"], ev["ts"] + dur))
            elif ev["cat"] == "ring":
                rings.append(ev)
        elif ph == "i":
            instants.append(ev)
        elif ph == "C":
            vals = ev.get("args")
            if not isinstance(vals, dict) or not vals or any(
                    not isinstance(v, (int, float)) for v in vals.values()):
                raise TraceValidationError(
                    f"counter sample without numeric args: {ev!r}")
            counters.append(ev)
        elif ph in ("s", "t", "f"):
            if "id" not in ev:
                raise TraceValidationError(f"flow event without an "
                                           f"`id`: {ev!r}")
            flows.setdefault(ev["id"], {"s": [], "t": [], "f": []}
                             )[ph].append(ev["ts"])
        else:
            raise TraceValidationError(f"unsupported phase {ph!r}: {ev!r}")
    if not spans and not instants:
        raise TraceValidationError("empty trace: no spans or events "
                                   "recorded")
    for fid, parts in flows.items():
        if len(parts["s"]) != 1 or len(parts["f"]) != 1:
            raise TraceValidationError(
                f"flow {fid} does not pair up: {len(parts['s'])} starts, "
                f"{len(parts['f'])} finishes")
        lo, hi = parts["s"][0], parts["f"][0]
        if hi < lo or any(not lo <= t <= hi for t in parts["t"]):
            raise TraceValidationError(
                f"flow {fid} has non-monotone step timestamps")
    if require_flows and not flows:
        raise TraceValidationError("no lineage flow events recorded (a "
                                   "lineage smoke that stitched nothing "
                                   "is a wiring bug)")
    if require_stream_nesting:
        if not streams:
            raise TraceValidationError("no bench/stream spans to nest "
                                       "ring spans under")
        # microsecond float rounding at the edges gets a 1 us grace
        for ev in rings:
            lo, hi = ev["ts"], ev["ts"] + ev["dur"]
            if not any(a - 1 <= lo and hi <= b + 1 for a, b in streams):
                raise TraceValidationError(
                    "ring span does not nest inside any bench/stream "
                    f"span: {ev!r}")
    return {"n_spans": len(spans), "n_events": len(instants),
            "n_streams": len(streams), "n_ring_spans": len(rings),
            "n_flows": len(flows), "n_counter_samples": len(counters)}

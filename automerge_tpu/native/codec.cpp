// Native wire-format decoder for columnar text-change batches.
//
// The reference keeps its whole runtime in JavaScript (no native tier —
// SURVEY.md §0); this framework's runtime tier is native where it pays:
// decoding JSON change lists (the sync wire format, INTERNALS.md:150-324 in
// the reference) into the struct-of-arrays columns the device engine
// consumes (engine/columnar.py:TextChangeBatch). The Python decoder loops
// per op (~1us/op); this decoder is a single-pass recursive-descent parse
// into preallocated columns (measured 484 ns/op, 3.5x the Python
// decoder - JSON lexing dominates both; docs/MEASUREMENTS.md).
//
// Scope: ins/set/del/inc ops on ONE list/text object, with single-char
// string values or integer values. Anything else (nested objects, rich
// values, unknown fields that matter) sets `unsupported`, and the Python
// caller falls back to the reference decoder for the whole batch —
// correctness never depends on this fast path.
//
// Build: g++ -O2 -shared -fPIC codec.cpp -o libamtpu_codec.so (driven by
// automerge_tpu/native/__init__.py, cached; ctypes binding, no pybind11).

#include <algorithm>
#include <climits>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>
#include <unordered_map>

namespace {

struct Parser {
    const char* p;
    const char* end;
    bool ok = true;
    std::string err;

    explicit Parser(const char* s, size_t n) : p(s), end(s + n) {}

    void fail(const std::string& m) {
        if (ok) { ok = false; err = m; }
    }
    void ws() { while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p; }
    bool eat(char c) {
        ws();
        if (p < end && *p == c) { ++p; return true; }
        return false;
    }
    bool expect(char c) {
        if (!eat(c)) fail(std::string("expected '") + c + "'");
        return ok;
    }
    bool peek(char c) { ws(); return p < end && *p == c; }

    // JSON string -> UTF-8 bytes (handles escapes incl. \uXXXX pairs)
    bool str(std::string& out) {
        out.clear();
        if (!expect('"')) return false;
        while (p < end && *p != '"') {
            char c = *p++;
            if (c != '\\') { out.push_back(c); continue; }
            if (p >= end) { fail("bad escape"); return false; }
            char e = *p++;
            switch (e) {
                case '"': out.push_back('"'); break;
                case '\\': out.push_back('\\'); break;
                case '/': out.push_back('/'); break;
                case 'b': out.push_back('\b'); break;
                case 'f': out.push_back('\f'); break;
                case 'n': out.push_back('\n'); break;
                case 'r': out.push_back('\r'); break;
                case 't': out.push_back('\t'); break;
                case 'u': {
                    if (end - p < 4) { fail("bad \\u"); return false; }
                    auto hex4 = [&]() {
                        unsigned v = 0;
                        for (int i = 0; i < 4; i++) {
                            char h = *p++;
                            v <<= 4;
                            if (h >= '0' && h <= '9') v |= h - '0';
                            else if (h >= 'a' && h <= 'f') v |= h - 'a' + 10;
                            else if (h >= 'A' && h <= 'F') v |= h - 'A' + 10;
                            else { fail("bad hex"); return 0u; }
                        }
                        return v;
                    };
                    unsigned cp = hex4();
                    if (!ok) return false;
                    if (cp >= 0xD800 && cp <= 0xDBFF) {  // surrogate pair
                        if (end - p < 6 || p[0] != '\\' || p[1] != 'u') {
                            fail("lone surrogate"); return false;
                        }
                        p += 2;
                        unsigned lo = hex4();
                        if (!ok) return false;
                        cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
                    }
                    // encode UTF-8
                    if (cp < 0x80) out.push_back((char)cp);
                    else if (cp < 0x800) {
                        out.push_back((char)(0xC0 | (cp >> 6)));
                        out.push_back((char)(0x80 | (cp & 0x3F)));
                    } else if (cp < 0x10000) {
                        out.push_back((char)(0xE0 | (cp >> 12)));
                        out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
                        out.push_back((char)(0x80 | (cp & 0x3F)));
                    } else {
                        out.push_back((char)(0xF0 | (cp >> 18)));
                        out.push_back((char)(0x80 | ((cp >> 12) & 0x3F)));
                        out.push_back((char)(0x80 | ((cp >> 6) & 0x3F)));
                        out.push_back((char)(0x80 | (cp & 0x3F)));
                    }
                    break;
                }
                default: fail("bad escape"); return false;
            }
        }
        return expect('"');
    }

    bool integer(long long& out) {
        ws();
        bool neg = false;
        if (p < end && *p == '-') { neg = true; ++p; }
        if (p >= end || *p < '0' || *p > '9') { fail("expected int"); return false; }
        long long v = 0;
        while (p < end && *p >= '0' && *p <= '9') {
            if (v > (LLONG_MAX - 9) / 10) {
                fail("int out of range");  // would wrap -> python fallback
                return false;
            }
            v = v * 10 + (*p++ - '0');
        }
        if (p < end && (*p == '.' || *p == 'e' || *p == 'E')) {
            fail("float value");  // unsupported -> python fallback
            return false;
        }
        out = neg ? -v : v;
        return true;
    }

    // skip any JSON value (for unknown fields)
    bool skip() {
        ws();
        if (p >= end) { fail("eof"); return false; }
        char c = *p;
        if (c == '"') { std::string s; return str(s); }
        if (c == '{') {
            ++p;
            if (eat('}')) return true;
            do {
                std::string k;
                if (!str(k) || !expect(':') || !skip()) return false;
            } while (eat(','));
            return expect('}');
        }
        if (c == '[') {
            ++p;
            if (eat(']')) return true;
            do { if (!skip()) return false; } while (eat(','));
            return expect(']');
        }
        if (!strncmp(p, "true", 4)) { p += 4; return true; }
        if (!strncmp(p, "false", 5)) { p += 5; return true; }
        if (!strncmp(p, "null", 4)) { p += 4; return true; }
        long long n;
        // tolerate floats when skipping
        if (*p == '-' || (*p >= '0' && *p <= '9')) {
            while (p < end && (*p == '-' || *p == '+' || *p == '.' ||
                               *p == 'e' || *p == 'E' ||
                               (*p >= '0' && *p <= '9'))) ++p;
            return true;
        }
        (void)n;
        fail("bad value");
        return false;
    }
};

constexpr int8_t KIND_INS = 0, KIND_SET = 1, KIND_DEL = 2, KIND_INC = 3;
constexpr int32_t HEAD_PARENT = -1;

struct Batch {
    bool unsupported = false;
    std::string err;
    std::string err_obj;                   // object id ops must target
    std::string scratch1, scratch2, scratch3, scratch4;  // join buffers
    // per change
    std::vector<std::string> actors;
    std::vector<int32_t> seqs;
    std::vector<std::string> deps_json;    // raw slices, decoded in python
    std::vector<std::string> messages;     // "" = none
    std::vector<uint8_t> has_message;
    // per op
    std::vector<int32_t> op_change;
    std::vector<int8_t> op_kind;
    std::vector<int32_t> op_ta, op_tc, op_pa, op_pc;
    std::vector<int64_t> op_value;
    // batch actor interning
    std::vector<std::string> actor_table;
    std::unordered_map<std::string, int32_t> actor_rank;

    int32_t intern(const std::string& a) {
        auto it = actor_rank.find(a);
        if (it != actor_rank.end()) return it->second;
        int32_t r = (int32_t)actor_table.size();
        actor_table.push_back(a);
        actor_rank.emplace(a, r);
        return r;
    }
};

// "actor:ctr" -> (rank, ctr); false if malformed
bool parse_elem_id(Batch& b, const std::string& id, int32_t& a, int32_t& c) {
    size_t pos = id.rfind(':');
    if (pos == std::string::npos || pos + 1 >= id.size()) return false;
    if (id.find('\n') != std::string::npos) return false;  // join-safe ids only
    long long ctr = 0;
    for (size_t i = pos + 1; i < id.size(); i++) {
        if (id[i] < '0' || id[i] > '9') return false;
        ctr = ctr * 10 + (id[i] - '0');
        if (ctr > INT32_MAX) return false;  // python fallback, no truncation
    }
    a = b.intern(id.substr(0, pos));
    c = (int32_t)ctr;
    return true;
}

// single-char UTF-8 string -> codepoint, or -1
int64_t single_codepoint(const std::string& s) {
    if (s.empty()) return -1;
    unsigned char c0 = s[0];
    size_t need = c0 < 0x80 ? 1 : (c0 >> 5) == 6 ? 2 : (c0 >> 4) == 14 ? 3
                  : (c0 >> 3) == 30 ? 4 : 0;
    if (need == 0 || s.size() != need) return -1;
    if (need == 1) return c0;
    uint32_t cp = c0 & (0x7F >> need);
    for (size_t i = 1; i < need; i++) {
        if ((s[i] & 0xC0) != 0x80) return -1;
        cp = (cp << 6) | (s[i] & 0x3F);
    }
    return cp;
}

bool parse_op(Parser& ps, Batch& b, const std::string& obj_id,
              int32_t change_row) {
    if (!ps.expect('{')) return false;
    std::string action, obj, key, value_str;
    long long elem = -1, value_int = 0;
    bool have_value_str = false, have_value_int = false, value_other = false;
    bool have_datatype = false;
    if (!ps.peek('}')) do {
        std::string k;
        if (!ps.str(k) || !ps.expect(':')) return false;
        if (k == "action") { if (!ps.str(action)) return false; }
        else if (k == "obj") { if (!ps.str(obj)) return false; }
        else if (k == "key") { if (!ps.str(key)) return false; }
        else if (k == "elem") { if (!ps.integer(elem)) return false; }
        else if (k == "value") {
            ps.ws();
            if (ps.peek('"')) { have_value_str = ps.str(value_str); if (!have_value_str) return false; }
            else if (ps.p < ps.end && (*ps.p == '-' || (*ps.p >= '0' && *ps.p <= '9'))) {
                if (!ps.integer(value_int)) { value_other = true; ps.ok = true; if (!ps.skip()) return false; }
                else have_value_int = true;
            } else { value_other = true; if (!ps.skip()) return false; }
        }
        else if (k == "datatype") { have_datatype = true; if (!ps.skip()) return false; }
        else { if (!ps.skip()) return false; }
    } while (ps.eat(','));
    if (!ps.expect('}')) return false;

    if (obj != obj_id) { b.unsupported = true; b.err = "op targets other object"; return true; }
    b.op_change.push_back(change_row);
    if (action == "ins") {
        if (elem < 0 || elem > INT32_MAX) {
            // missing 'elem' field (stays -1) or out of int32 range: defer
            // to the python decoder rather than emit a corrupt packed key
            b.unsupported = true;
            b.err = elem < 0 ? "ins without elem" : "elem out of range";
        }
        b.op_kind.push_back(KIND_INS);
        b.op_ta.push_back(-2);  // filled by caller: the change's actor
        b.op_tc.push_back(elem < 0 || elem > INT32_MAX ? 0 : (int32_t)elem);
        if (key == "_head") { b.op_pa.push_back(HEAD_PARENT); b.op_pc.push_back(0); }
        else {
            int32_t a = HEAD_PARENT, c = 0;
            if (!parse_elem_id(b, key, a, c)) {
                // keep columns aligned: the post-parse fixup loop walks all
                // columns of this change even on the unsupported path
                b.unsupported = true; b.err = "bad elemId";
            }
            b.op_pa.push_back(a); b.op_pc.push_back(c);
        }
        b.op_value.push_back(0);
    } else if (action == "set" || action == "del" || action == "inc") {
        b.op_kind.push_back(action == "set" ? KIND_SET : action == "del" ? KIND_DEL : KIND_INC);
        int32_t a = 0, c = 0;
        if (!parse_elem_id(b, key, a, c)) {
            b.unsupported = true; b.err = "bad elemId";  // columns stay aligned
            a = 0; c = 0;
        }
        b.op_ta.push_back(a); b.op_tc.push_back(c);
        b.op_pa.push_back(HEAD_PARENT); b.op_pc.push_back(0);
        if (action == "set") {
            if (have_datatype || value_other || have_value_int) {
                // pooled / rich values -> python decoder
                b.unsupported = true; b.err = "rich value";
                b.op_value.push_back(0);
            } else if (have_value_str) {
                int64_t cp = single_codepoint(value_str);
                if (cp < 0) { b.unsupported = true; b.err = "multi-char value"; }
                b.op_value.push_back(cp < 0 ? 0 : cp);
            } else { b.unsupported = true; b.err = "missing value"; b.op_value.push_back(0); }
        } else if (action == "inc") {
            b.op_value.push_back(have_value_int ? value_int : 0);
            if (!have_value_int) { b.unsupported = true; b.err = "inc without int"; }
        } else b.op_value.push_back(0);
    } else {
        b.unsupported = true; b.err = "unsupported action: " + action;
        // keep columns aligned
        b.op_kind.push_back(KIND_DEL);
        b.op_ta.push_back(0); b.op_tc.push_back(0);
        b.op_pa.push_back(HEAD_PARENT); b.op_pc.push_back(0);
        b.op_value.push_back(0);
    }
    return true;
}

bool parse_change(Parser& ps, Batch& b) {
    if (!ps.expect('{')) return false;
    int32_t row = (int32_t)b.actors.size();
    b.actors.emplace_back();
    b.seqs.push_back(0);
    b.deps_json.emplace_back("{}");
    b.messages.emplace_back();
    b.has_message.push_back(0);
    size_t ops_from = b.op_kind.size();
    // the python decoder raises on changes missing these fields; the
    // native tier must fall back, never default them (a seq-0 change
    // would queue forever in causal admission)
    bool saw_actor = false, saw_seq = false, saw_ops = false;
    if (!ps.peek('}')) do {
        std::string k;
        if (!ps.str(k) || !ps.expect(':')) return false;
        if (k == "actor") {
            saw_actor = true;
            if (!ps.str(b.actors[row])) return false;
            // actor ids travel '\n'-joined to python; exotic ids fall back
            if (b.actors[row].find('\n') != std::string::npos) {
                b.unsupported = true; b.err = "newline in actor id";
            }
        }
        else if (k == "seq") {
            saw_seq = true;
            long long s; if (!ps.integer(s)) return false;
            if (s < 0 || s > INT32_MAX) { b.unsupported = true; b.err = "seq out of range"; s = 0; }
            b.seqs[row] = (int32_t)s;
        }
        else if (k == "deps") {
            // deps is a flat {actor: seq} map; re-serialize compactly (the
            // python side json-decodes each line, so no raw input slices —
            // pretty-printed payloads must round-trip too)
            if (!ps.expect('{')) return false;
            std::string& out = b.deps_json[row];
            out = "{";
            if (!ps.peek('}')) {
                bool first = true;
                do {
                    std::string dk;
                    long long dv;
                    if (!ps.str(dk) || !ps.expect(':')) return false;
                    if (!ps.integer(dv)) { b.unsupported = true; b.err = "non-int dep"; return false; }
                    if (!first) out.push_back(',');
                    first = false;
                    out.push_back('"');
                    for (char ch : dk) {  // JSON-escape the actor id
                        if (ch == '"' || ch == '\\') { out.push_back('\\'); out.push_back(ch); }
                        else if ((unsigned char)ch < 0x20) {
                            char buf[8];
                            snprintf(buf, sizeof buf, "\\u%04x", ch);
                            out += buf;
                        } else out.push_back(ch);
                    }
                    out += "\":" + std::to_string(dv);
                } while (ps.eat(','));
            }
            if (!ps.expect('}')) return false;
            out.push_back('}');
        }
        else if (k == "message") {
            ps.ws();
            if (ps.peek('"')) {
                if (!ps.str(b.messages[row])) return false;
                b.has_message[row] = 1;
                if (b.messages[row].find('\x1f') != std::string::npos) {
                    b.unsupported = true; b.err = "separator in message";
                }
            }
            else {
                // null means absent (matches python's None); any other
                // non-string value the python path PRESERVES, so the
                // native tier must not silently drop it
                if (!ps.peek('n')) {
                    b.unsupported = true; b.err = "non-string message";
                }
                if (!ps.skip()) return false;
            }
        }
        else if (k == "ops") {
            saw_ops = true;
            if (!ps.expect('[')) return false;
            if (!ps.eat(']')) {
                do { if (!parse_op(ps, b, b.err_obj, row)) return false; } while (ps.eat(','));
                if (!ps.expect(']')) return false;
            }
        }
        else { if (!ps.skip()) return false; }
    } while (ps.eat(','));
    if (!ps.expect('}')) return false;
    if (!saw_actor || !saw_seq || !saw_ops) {
        b.unsupported = true; b.err = "change missing actor/seq/ops";
    }
    // ins target actor = the change's own actor
    int32_t rank = b.intern(b.actors[row]);
    for (size_t i = ops_from; i < b.op_kind.size(); i++)
        if (b.op_ta[i] == -2) b.op_ta[i] = rank;
    return true;
}

struct Handle {
    Batch b;
    std::string obj_id;
};

}  // namespace

extern "C" {

void* amtpu_parse(const char* json, long json_len, const char* obj_id) {
    auto* h = new Handle();
    h->obj_id = obj_id;
    h->b.err_obj = obj_id;
    Parser ps(json, (size_t)json_len);
    if (!ps.expect('[')) { h->b.unsupported = true; h->b.err = ps.err; return h; }
    if (!ps.eat(']')) {
        do {
            if (!parse_change(ps, h->b)) {
                h->b.unsupported = true;
                h->b.err = ps.err.empty() ? "parse error" : ps.err;
                return h;
            }
        } while (ps.eat(','));
        if (!ps.expect(']')) { h->b.unsupported = true; h->b.err = ps.err; }
    }
    return h;
}

int amtpu_unsupported(void* hv) { return ((Handle*)hv)->b.unsupported ? 1 : 0; }

const char* amtpu_error(void* hv) { return ((Handle*)hv)->b.err.c_str(); }

long amtpu_n_changes(void* hv) { return (long)((Handle*)hv)->b.actors.size(); }
long amtpu_n_ops(void* hv) { return (long)((Handle*)hv)->b.op_kind.size(); }
long amtpu_n_actors(void* hv) { return (long)((Handle*)hv)->b.actor_table.size(); }

void amtpu_fill_ops(void* hv, int32_t* op_change, int8_t* op_kind,
                    int32_t* ta, int32_t* tc, int32_t* pa, int32_t* pc,
                    int64_t* value) {
    Batch& b = ((Handle*)hv)->b;
    size_t n = b.op_kind.size();
    memcpy(op_change, b.op_change.data(), n * 4);
    memcpy(op_kind, b.op_kind.data(), n);
    memcpy(ta, b.op_ta.data(), n * 4);
    memcpy(tc, b.op_tc.data(), n * 4);
    memcpy(pa, b.op_pa.data(), n * 4);
    memcpy(pc, b.op_pc.data(), n * 4);
    memcpy(value, b.op_value.data(), n * 8);
}

void amtpu_fill_seqs(void* hv, int32_t* seqs) {
    Batch& b = ((Handle*)hv)->b;
    memcpy(seqs, b.seqs.data(), b.seqs.size() * 4);
}

// '\n'-joined string tables (actors, actor_table, deps json, messages)
static void join(const std::vector<std::string>& v, std::string& out) {
    out.clear();
    for (size_t i = 0; i < v.size(); i++) {
        if (i) out.push_back('\n');
        out += v[i];
    }
}

const char* amtpu_actors(void* hv) {
    auto* h = (Handle*)hv;
    join(h->b.actors, h->b.scratch1);
    return h->b.scratch1.c_str();
}
const char* amtpu_actor_table(void* hv) {
    auto* h = (Handle*)hv;
    join(h->b.actor_table, h->b.scratch2);
    return h->b.scratch2.c_str();
}
const char* amtpu_deps(void* hv) {
    auto* h = (Handle*)hv;
    join(h->b.deps_json, h->b.scratch3);
    return h->b.scratch3.c_str();
}
const char* amtpu_messages(void* hv) {
    auto* h = (Handle*)hv;
    // messages may contain '\n'; join with '\x1f' (unit separator)
    h->b.scratch4.clear();
    for (size_t i = 0; i < h->b.messages.size(); i++) {
        if (i) h->b.scratch4.push_back('\x1f');
        h->b.scratch4.push_back(h->b.has_message[i] ? '1' : '0');
        h->b.scratch4 += h->b.messages[i];
    }
    return h->b.scratch4.c_str();
}

void amtpu_free(void* hv) { delete (Handle*)hv; }

}  // extern "C"

// ---------------------------------------------------------------------------
// Typing-run detection over columnar op batches: the single-pass native
// form of engine/runs.py:detect_runs (same predicate, op by op). Python
// numpy needs ~8 vectorized passes over the columns; this walks them once.
// ---------------------------------------------------------------------------

struct RunPlan {
    std::vector<int64_t> hpos, run_len, head_slot, rpos, res_new_slot;
    std::vector<int32_t> blob;
    int64_t n_ins = 0;
    bool blob_lt_128 = true, blob_lt_256 = true;
};

// ---------------------------------------------------------------------------
// Parallel run detection. The greedy scan carries only (a) whether the scan
// position is even with respect to pair consumption — i.e. whether a pair
// crossing the chunk boundary consumed its first op — and (b) whether the
// immediately preceding pair ended at pos-2 (run contiguity). Chunks are
// therefore simulated speculatively for the two possible entry ALIGNMENTS
// (boundary op not consumed / consumed by a boundary-crossing pair), with
// contiguity resolved by construction: the sim assumes the "a pair may have
// ended at start-2" basis, and pairs continuing that entry run accumulate in
// `lead_len` instead of minting a head. The serial stitch then either merges
// the lead into the previous chunk's last run (entry was contiguous) or
// mints the head at the chunk start (it was not). Head slots / residual
// slots are stored chunk-local and rebased by the stitched global INS count.
// ---------------------------------------------------------------------------

struct SimOut {
    std::vector<int64_t> hpos, run_len, head_ins;  // heads; local ins before
    std::vector<int64_t> rpos, res_ins;  // residuals; local ins after, or -1
    std::vector<int32_t> blob;
    int64_t lead_len = 0;   // pairs continuing the PREVIOUS chunk's run
    int64_t ins_count = 0;  // INS ops consumed in this chunk
    int exit_state = 0;     // next chunk entry: 0 aligned/non-contig,
                            // 1 aligned/contig, 2 misaligned (consumed)
    bool blob_lt_128 = true, blob_lt_256 = true;
};

static void simulate_chunk(
    int64_t start, int64_t end, int64_t n, const int8_t* kind,
    const int32_t* ta, const int32_t* tc, const int32_t* pa,
    const int32_t* pc, const int64_t* val, const int32_t* row,
    SimOut& o) {
    constexpr int8_t INS = 0, SET = 1;
    constexpr int64_t NO_PAIR = INT64_MIN;  // can never equal i-2
    if (end > start) {
        o.blob.reserve((end - start) / 2 + 1);  // avoid regrow copies of
        o.hpos.reserve(1024);                   // the per-pair vector
        o.run_len.reserve(1024);
        o.head_ins.reserve(1024);
    }
    int64_t prev_pair = start - 2;  // entry basis: a pair MAY have ended
                                    // at start-2 (stitch resolves truth)
    // NOTE: a block-precomputed predicate-mask variant was measured
    // SLOWER here (the short-circuiting scalar compares run once per
    // PAIR, i.e. half the ops, while masks must be computed for every
    // op); the win on this path is -O3 -march=x86-64-v3 codegen, not
    // manual restructuring.
    int64_t i = start;
    while (i < end) {
        bool pair = (kind[i] == INS && i + 1 < n && kind[i + 1] == SET
                     && row[i + 1] == row[i] && ta[i + 1] == ta[i]
                     && tc[i + 1] == tc[i] && val[i + 1] >= 0
                     && val[i + 1] < (1LL << 31));
        if (pair) {
            bool cont = (prev_pair == i - 2 && prev_pair >= 0
                         && row[i] == row[i - 2]
                         && ta[i] == ta[i - 2] && tc[i] == tc[i - 2] + 1
                         && pa[i] == ta[i - 2] && pc[i] == tc[i - 2]);
            if (cont && o.hpos.empty() && o.rpos.empty()) {
                o.lead_len++;  // unbroken cont prefix from `start`
            } else if (cont) {
                o.run_len.back()++;
            } else {
                o.hpos.push_back(i);
                o.run_len.push_back(1);
                o.head_ins.push_back(o.ins_count);
            }
            int64_t v = val[i + 1];
            o.blob.push_back((int32_t)v);
            if (v >= 128) o.blob_lt_128 = false;
            if (v >= 256) o.blob_lt_256 = false;
            o.ins_count++;
            prev_pair = i;
            i += 2;
        } else {
            o.rpos.push_back(i);
            if (kind[i] == INS) {
                o.ins_count++;
                o.res_ins.push_back(o.ins_count);
            } else {
                o.res_ins.push_back(-1);
            }
            prev_pair = NO_PAIR;
            i += 1;
        }
    }
    if (i == end) {
        o.exit_state = (prev_pair == end - 2 && prev_pair >= 0) ? 1 : 0;
    } else {
        o.exit_state = 2;  // the pair at end-1 consumed op `end`
    }
}

extern "C" {

void* amtpu_detect_runs(
    int64_t n, const int8_t* kind, const int32_t* ta, const int32_t* tc,
    const int32_t* pa, const int32_t* pc, const int64_t* val,
    const int32_t* row, int64_t base_elems) {
    auto* p = new RunPlan();

    constexpr int64_t MIN_CHUNK = 1 << 19;  // thread fan-out threshold
    int64_t hw = (int64_t)std::thread::hardware_concurrency();
    // test/tuning hook: AMTPU_DETECT_THREADS forces the fan-out width so
    // the speculative stitch is exercisable on low-core machines
    if (const char* env_t = getenv("AMTPU_DETECT_THREADS")) {
        long forced = atol(env_t);
        if (forced > 0) hw = forced;
    }
    int64_t T = std::min(hw > 0 ? hw : 1, (n + MIN_CHUNK - 1) / MIN_CHUNK);
    T = std::min<int64_t>(T, 32);

    if (T <= 1) {
        // serial: single chunk, entry aligned and non-contiguous (a lead
        // cannot form: prev_pair = -2 fails the >= 0 guard)
        SimOut s;
        simulate_chunk(0, n, n, kind, ta, tc, pa, pc, val, row, s);
        p->hpos = std::move(s.hpos);
        p->run_len = std::move(s.run_len);
        p->head_slot.resize(p->hpos.size());
        for (size_t j = 0; j < p->hpos.size(); ++j)
            p->head_slot[j] = base_elems + s.head_ins[j] + 1;
        p->rpos = std::move(s.rpos);
        p->res_new_slot.resize(p->rpos.size());
        for (size_t j = 0; j < p->rpos.size(); ++j)
            p->res_new_slot[j] =
                s.res_ins[j] >= 0 ? base_elems + s.res_ins[j] : -1;
        p->blob = std::move(s.blob);
        p->n_ins = s.ins_count;
        p->blob_lt_128 = s.blob_lt_128;
        p->blob_lt_256 = s.blob_lt_256;
        return p;
    }

    std::vector<int64_t> cuts(T + 1);
    for (int64_t k = 0; k <= T; ++k) cuts[k] = n * k / T;
    // two sims per chunk: entry aligned at cuts[k], entry misaligned at
    // cuts[k]+1 (chunk 0 only aligned)
    std::vector<SimOut> A(T), M(T);
    std::vector<std::thread> threads;
    threads.reserve(2 * T - 1);  // one thread per SIM (not per chunk):
    for (int64_t k = 0; k < T; ++k) {  // keeps the critical path ~n/T
        threads.emplace_back([&, k] {  // instead of 2n/T
            simulate_chunk(cuts[k], cuts[k + 1], n, kind, ta, tc, pa, pc,
                           val, row, A[k]);
        });
        if (k > 0)
            threads.emplace_back([&, k] {
                simulate_chunk(cuts[k] + 1, cuts[k + 1], n, kind, ta, tc,
                               pa, pc, val, row, M[k]);
            });
    }
    for (auto& t : threads) t.join();

    // serial stitch: resolve each chunk's entry state, rebase slots
    int state = 0;
    int64_t ins_base = 0;
    for (int64_t k = 0; k < T; ++k) {
        SimOut& s = (state == 2) ? M[k] : A[k];
        if (s.lead_len) {
            if (state == 0) {
                // entry was NOT contiguous: the lead is its own run
                // headed at the chunk's first op (local ins count 0;
                // state 0 implies the aligned sim, so the first op is
                // at cuts[k])
                p->hpos.push_back(cuts[k]);
                p->run_len.push_back(s.lead_len);
                p->head_slot.push_back(base_elems + ins_base + 1);
            } else {
                p->run_len.back() += s.lead_len;
            }
        }
        p->hpos.insert(p->hpos.end(), s.hpos.begin(), s.hpos.end());
        p->run_len.insert(p->run_len.end(), s.run_len.begin(),
                          s.run_len.end());
        for (int64_t h : s.head_ins)
            p->head_slot.push_back(base_elems + ins_base + h + 1);
        p->rpos.insert(p->rpos.end(), s.rpos.begin(), s.rpos.end());
        for (int64_t r : s.res_ins)
            p->res_new_slot.push_back(
                r >= 0 ? base_elems + ins_base + r : -1);
        p->blob.insert(p->blob.end(), s.blob.begin(), s.blob.end());
        p->blob_lt_128 = p->blob_lt_128 && s.blob_lt_128;
        p->blob_lt_256 = p->blob_lt_256 && s.blob_lt_256;
        ins_base += s.ins_count;
        state = s.exit_state;
    }
    p->n_ins = ins_base;
    return p;
}

int64_t amtpu_plan_n_runs(void* pv) { return (int64_t)((RunPlan*)pv)->hpos.size(); }
int64_t amtpu_plan_n_pairs(void* pv) { return (int64_t)((RunPlan*)pv)->blob.size(); }
int64_t amtpu_plan_n_res(void* pv) { return (int64_t)((RunPlan*)pv)->rpos.size(); }
int64_t amtpu_plan_n_ins(void* pv) { return ((RunPlan*)pv)->n_ins; }
int amtpu_plan_blob_lt(void* pv, int bound) {
    auto* p = (RunPlan*)pv;
    return bound == 128 ? p->blob_lt_128 : p->blob_lt_256;
}

void amtpu_plan_fill(void* pv, int64_t* hpos, int64_t* run_len,
                     int64_t* head_slot, int64_t* rpos,
                     int64_t* res_new_slot, int32_t* blob) {
    auto* p = (RunPlan*)pv;
    memcpy(hpos, p->hpos.data(), p->hpos.size() * 8);
    memcpy(run_len, p->run_len.data(), p->run_len.size() * 8);
    memcpy(head_slot, p->head_slot.data(), p->head_slot.size() * 8);
    memcpy(rpos, p->rpos.data(), p->rpos.size() * 8);
    memcpy(res_new_slot, p->res_new_slot.data(), p->res_new_slot.size() * 8);
    memcpy(blob, p->blob.data(), p->blob.size() * 4);
}

void amtpu_plan_free(void* pv) { delete (RunPlan*)pv; }

}  // extern "C"

"""Unified tracing & metrics tier (INTERNALS §11).

One structured observability surface threaded through every hot layer —
host planning, the pipeline ring, device dispatch accounting, the
resilience tier, and the checkpoint writer — replacing nothing: the
existing stats dicts (`doc.dispatch_stats`, `PipelinedIngestor.stats`,
`ResilientChannel.stats`, ...) keep their shapes and are FED by the same
instrumentation points that emit here.

Contract for instrumented call sites (the hot-path discipline):

    from automerge_tpu import obs
    ...
    t0 = obs.now() if obs.ENABLED else 0
    ... the work ...
    if obs.ENABLED:
        obs.span("plan", "prepare_batch", t0,
                 args={"doc": self.obj_id, "n_ops": batch.n_ops})

``obs.ENABLED`` is a module attribute: when tracing is off, the whole
emit path is ONE module-dict lookup and a falsy branch — no call, no
allocation, no lock (the overhead bound is asserted in
tests/test_obs.py). Everything behind the flag goes to a bounded,
lock-striped ring-buffer flight recorder (`obs.recorder.FlightRecorder`)
whose newest records always survive and whose counters are exact across
wraparound.

Enable via ``AMTPU_TRACE=1`` in the environment, `obs.enable()`, or the
scoped ``with obs.tracing(): ...``. Export with `obs.write_trace(path)`
(Chrome trace-event JSON — load at https://ui.perfetto.dev) and read
aggregates with `obs.metrics_snapshot()`.

Category taxonomy (full schema in docs/INTERNALS.md §11):

  plan    host planning: prepare_batch / admission / wire decode
  commit  commit_prepared (args carry n_rounds + dispatch/sync delta)
  device  dispatch/sync accounting (labeled kernel counters), waits
  ring    PipelinedIngestor slot lifecycle (plan/commit spans,
          fallback/serial/abort events, gen + slot tags)
  pull    text materialization pulls (mode + byte counts)
  chan    ResilientChannel (retransmit / dup_drop / window_drop /
          backpressure / dead ...)
  chaos   ChaosLink fault injections (drop / dup / reorder / delay ...)
  quar    quarantine admits / evictions (incl. tenant-attributed
          evict_pressure + dead-peer evict_peer) / releases
  sync    hub snapshot bootstrap (snapshot_capture / serve_cached —
          the join-storm coalescing ratio)
  svc     service tier: tick spans, shed / defer / suspect / evict /
          join / rejoin / protocol_error events (INTERNALS §13)
  ckpt    checkpoint writer (grab spans, conflicts, degrades)
  bench   harness-side regions (stream reps, explicit device waits)
  lineage per-change provenance hops (obs/lineage.py, INTERNALS §18):
          origin / chan/send / chan/retransmit / hub/flush / svc/admit
          / svc/defer / svc/shed / quar/park / quar/release / quar/pen
          / plan/stacked / commit / ckpt/adopt — emitted here only when
          BOTH tracing and lineage sampling are on; the ledger itself
          is independent of the trace ring
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Optional

from .recorder import (  # noqa: F401  (re-exported for consumers/tests)
    ARGS, CAT, DUR, EVENT_DUR, NAME, TID, TS, FlightRecorder,
    span_seconds, span_totals,
)
from .telemetry import Telemetry  # noqa: F401  (re-exported)

#: THE fast-path gate. Instrumented call sites read this module attribute
#: directly (`if obs.ENABLED:`) so a disabled process pays one dict
#: lookup per site and nothing else. Mutated only by enable()/disable().
ENABLED = False

_recorder: Optional[FlightRecorder] = None
_telemetry: Optional[Telemetry] = None

now = time.perf_counter_ns   # monotonic ns — the span clock


def enabled() -> bool:
    return ENABLED


def recorder() -> Optional[FlightRecorder]:
    """The live FlightRecorder (None when tracing never enabled)."""
    return _recorder


def telemetry() -> Optional[Telemetry]:
    """The live rolling-telemetry store (None when tracing never
    enabled). Created and cleared in lockstep with the recorder; fed at
    emit time by span()/event()/counter(), so its aggregates stay exact
    across trace-ring wraparound (INTERNALS §14)."""
    return _telemetry


def enable(capacity: Optional[int] = None) -> FlightRecorder:
    """Turn tracing on (idempotent). A recorder (and its telemetry
    sibling) is created on first enable and retained across disable()
    so late readers can still export; pass `capacity` (records per
    stripe) to size a fresh pair."""
    global ENABLED, _recorder, _telemetry
    if _recorder is None or capacity is not None:
        _recorder = FlightRecorder(capacity)
        _telemetry = Telemetry()
    elif _telemetry is None:
        _telemetry = Telemetry()
    ENABLED = True
    return _recorder


def disable():
    global ENABLED
    ENABLED = False


@contextmanager
def tracing(capacity: Optional[int] = None):
    """Scoped enable: tracing on inside the block, restored (not force-
    disabled) on exit — nesting under a process-wide AMTPU_TRACE=1 keeps
    the outer session running. Yields the recorder."""
    was = ENABLED
    rec = enable(capacity)
    try:
        yield rec
    finally:
        if not was:
            disable()


# ---------------------------------------------------------------------------
# emit side — call ONLY behind an `if obs.ENABLED:` check
# ---------------------------------------------------------------------------


def span(cat: str, name: str, t0_ns: int, args: Optional[dict] = None,
         t1_ns: Optional[int] = None):
    """Record a completed span started at `t0_ns` (from `obs.now()`).
    A zero `t0_ns` (tracing was off when the region started) is dropped —
    a half-observed region must not fabricate a duration."""
    rec = _recorder
    if rec is None or not t0_ns:
        return
    end = t1_ns if t1_ns is not None else time.perf_counter_ns()
    dur = max(0, end - t0_ns)
    rec.emit((t0_ns, dur, cat, name, threading.get_ident(), args))
    tel = _telemetry
    if tel is not None:
        tel.observe_span(cat, name, dur, ts_ns=t0_ns)


def event(cat: str, name: str, args: Optional[dict] = None, n: int = 1):
    """Record an instant event AND bump its wrap-proof counter."""
    rec = _recorder
    if rec is None:
        return
    ts = time.perf_counter_ns()
    rec.emit((ts, EVENT_DUR, cat, name, threading.get_ident(), args))
    rec.bump((cat, name), n)
    tel = _telemetry
    if tel is not None:
        tel.observe_count(cat, name, n, ts_ns=ts)


def counter(cat: str, name: str, n: int = 1):
    """Bump a counter without a ring record (per-dispatch call sites:
    exact totals, no ring pressure)."""
    rec = _recorder
    if rec is not None:
        rec.bump((cat, name), n)
        tel = _telemetry
        if tel is not None:
            tel.observe_count(cat, name, n)


@contextmanager
def span_ctx(cat: str, name: str, args: Optional[dict] = None):
    """Span context manager for NON-hot call sites (bench, soak, tests).
    Hot paths use the explicit now()/span() pair behind the flag."""
    t0 = now() if ENABLED else 0
    try:
        yield
    finally:
        if ENABLED and t0:
            span(cat, name, t0, args)


# ---------------------------------------------------------------------------
# read side
# ---------------------------------------------------------------------------


def snapshot(since_ns: int = 0) -> list:
    """All retained records (see recorder.snapshot); [] when never
    enabled."""
    return [] if _recorder is None else _recorder.snapshot(since_ns)


def metrics_snapshot(since_ns: int = 0) -> dict:
    """Aggregate view of the session: exact counters (wrap-proof) plus
    per-(cat, name) span aggregates.

        {"counters": {"chaos.drop": 12, ...},
         "spans": {"plan.prepare_batch": {"count", "total_ns",
                                          "min_ns", "max_ns"}, ...},
         "emitted": <total records ever>, "retained": <in ring now>}

    Span aggregates come from the telemetry store (fed at emit time),
    so they stay EXACT after trace-ring wraparound — the ISSUE 9 bug
    class. A `since_ns` query falls back to the retained ring records
    (windowed queries belong to `telemetry().windows()`); the ring view
    is also always available directly via `span_totals(snapshot())`.
    """
    if _recorder is None:
        out = {"counters": {}, "spans": {}, "emitted": 0, "retained": 0}
        _merge_device_truth(out)
        return out
    if since_ns == 0 and _telemetry is not None:
        spans = {f"{c}.{n}": dict(agg) for (c, n), agg
                 in sorted(_telemetry.span_aggregates().items())}
    else:
        spans = {f"{c}.{n}": agg for (c, n), agg
                 in sorted(span_totals(_recorder.snapshot(since_ns))
                           .items())}
    out = {
        "counters": {f"{c}.{n}": v
                     for (c, n), v in sorted(_recorder.counters().items())},
        "spans": spans,
        "emitted": _recorder.n_emitted,
        "retained": _recorder.n_retained,
    }
    _merge_device_truth(out)
    return out


def _merge_device_truth(out: dict):
    """Attach the always-on device-truth aggregates (compile registry,
    footprint gauges, persistent-compile-cache state; INTERNALS §19)
    when the session touched a device — independent of the trace ring,
    like the lineage ledger."""
    from . import device_truth
    reg = device_truth.REGISTRY
    if reg.compiles_total or reg.peak_bytes or any(
            h.calls for h in reg._kernels.values()):
        out["device_truth"] = device_truth.summary()


def clear():
    if _recorder is not None:
        _recorder.clear()
    if _telemetry is not None:
        _telemetry.clear()


def write_trace(path: str, since_ns: int = 0) -> str:
    """Dump the retained records as Chrome trace-event JSON (Perfetto-
    loadable); returns `path`. See obs/export.py for the schema."""
    from .export import write_trace as _write
    return _write(path, snapshot(since_ns),
                  t0_ns=None if _recorder is None else _recorder.t0_ns)


# honor AMTPU_TRACE=1 at import: `AMTPU_TRACE=1 python bench.py --trace`
# needs no code path to remember to call enable() before the first span
if os.environ.get("AMTPU_TRACE", "0") not in ("", "0"):
    enable()

# the change-lineage tier (its own module flag + AMTPU_LINEAGE_RATE env
# bootstrap); imported last so `obs` is fully initialized when lineage's
# emit path reaches back for the trace-ring flag
from . import lineage  # noqa: E402,F401

# the device-truth tier (its own always-on module flag; INTERNALS §19):
# imported for the same reason — metrics_snapshot and write_trace reach
# into it, and ops/ingest.py re-binds its kernels through it at import
from . import device_truth  # noqa: E402,F401

"""Shared helpers for the BASELINE.md benchmark configs.

Each config prints one JSON line {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no numbers (BASELINE.md), so vs_baseline is reported
against the driver's north-star rate where one is defined (configs tied to
the 100M ops/s target) and as 0.0/absent otherwise.
"""

import json
import os
import subprocess
import sys
import time

RESULTS: list = []  # every emit() of the run, for the per-round record file


def preflight_device(timeout_s: int = 150) -> bool:
    """True iff jax can actually reach a device. When the remote TPU
    tunnel is down, the axon plugin hangs backend init indefinitely —
    probe in a subprocess so benchmark entry points fail FAST with a
    clear message instead of eating the caller's whole time budget.
    AMTPU_SKIP_PREFLIGHT=1 skips the probe (a parent already probed;
    each probe pays a full backend init, seconds on a remote tunnel)."""
    if os.environ.get("AMTPU_SKIP_PREFLIGHT") == "1":
        return True
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s)
        return out.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def setup_jax_cache():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    os.makedirs(os.path.join(root, ".jax_cache"), exist_ok=True)
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(root, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def timed(fn, warmups: int = 1, reps: int = 2) -> float:
    """Best wall time over `reps` runs after `warmups` compile passes."""
    for _ in range(warmups):
        fn()
    return min(timed_once(fn) for _ in range(reps))


def timed_once(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def _platform() -> str:
    """The platform every config in this process actually ran on — recorded
    in each result row so a CPU-fallback record can never masquerade as a
    chip measurement."""
    import jax
    return jax.devices()[0].platform


def emit(metric: str, value: float, unit: str, vs_baseline: float = 0.0,
         **extra):
    # platform is stamped LAST so no extra kwarg can override provenance
    rec = {"metric": metric, "value": round(value, 2), "unit": unit,
           "vs_baseline": round(vs_baseline, 4), **extra,
           "platform": _platform()}
    RESULTS.append(rec)
    print(json.dumps(rec), flush=True)


def write_record(path: str):
    """One JSON line per emitted config result (BENCH_CONFIGS_r<NN>.json)."""
    with open(path, "w") as fh:
        for rec in RESULTS:
            fh.write(json.dumps(rec) + "\n")

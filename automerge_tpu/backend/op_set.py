"""Op-set reconciliation engine (host oracle path).

This is the CRDT heart of the framework: causal-order gating, vector-clock
concurrency partitioning, LWW-with-conflicts register resolution, counter
folding, and RGA list ordering. It is the semantic counterpart of the
reference's ``backend/op_set.js`` (/root/reference/backend/op_set.js:1-573)
and of the backend-state spec in /root/reference/INTERNALS.md:477-543, but the
state design is different: instead of persistent Immutable.js maps, the engine
keeps ONE mutable index per document lineage plus an append-only command log;
divergent branches fork by deterministic replay (see ``facade.py``). That keeps
the forward path allocation-free-ish and gives the columnar device engine a
flat view to ingest.

Wire formats (changes, ops, patches, diffs) are plain dicts with the exact
key names of the reference protocol (INTERNALS.md:150-475), so fixtures and
peers are interchangeable with the JS implementation.
"""

from __future__ import annotations

from typing import Any, Optional

from .._common import ROOT_ID, make_elem_id, parse_elem_id, transitive_deps
from .skip_list import SkipList

_MAKE_ACTIONS = ("makeMap", "makeList", "makeText", "makeTable")
_ASSIGN_ACTIONS = ("set", "del", "link", "inc")


class ObjRec:
    """Per-object index: the counterpart of byObject[objectId] (INTERNALS.md:495-520)."""

    __slots__ = ("init", "keys", "inbound", "insertion", "following", "max_elem", "elem_ids")

    def __init__(self, init_op=None, is_sequence=False):
        self.init = init_op                  # the make* op, or None for the root map
        self.keys: dict[str, list] = {}      # key -> ops (LWW winner first, desc by actor)
        self.inbound: list = []              # link ops whose value is this object
        self.insertion: dict[str, dict] = {} # elemId -> ins op (lists/text only)
        self.following: dict[str, list] = {} # elemId/_head -> ins ops referencing it
        self.max_elem = 0
        self.elem_ids: Optional[SkipList] = SkipList() if is_sequence else None

    @property
    def obj_type(self) -> Optional[str]:
        return self.init["action"] if self.init else None


class OpSetIndex:
    """Mutable reconciliation state for one document lineage."""

    def __init__(self):
        self.states: dict[str, list] = {}    # actor -> [{'change':…, 'allDeps':…}]
        self.history: list = []              # applied changes, in application order
        self.queue: list = []                # causally not-yet-ready changes
        self.by_object: dict[str, ObjRec] = {ROOT_ID: ObjRec()}
        self.clock: dict[str, int] = {}
        self.deps: dict[str, int] = {}
        self.undo_pos = 0
        self.undo_stack: list = []           # list of op-lists
        self.redo_stack: list = []
        self.undo_local: Optional[list] = None  # capture buffer while a local change applies
        self.commands: list = []             # append-only log for fork-by-replay

    # ------------------------------------------------------------------
    # concurrency / causality
    # ------------------------------------------------------------------

    def is_concurrent(self, op1: dict, op2: dict) -> bool:
        """Neither op happened-before the other (op_set.js:7-16)."""
        actor1, seq1 = op1.get("actor"), op1.get("seq")
        actor2, seq2 = op2.get("actor"), op2.get("seq")
        if not actor1 or not actor2 or not seq1 or not seq2:
            return False
        clock1 = self.states[actor1][seq1 - 1]["allDeps"]
        clock2 = self.states[actor2][seq2 - 1]["allDeps"]
        return clock1.get(actor2, 0) < seq2 and clock2.get(actor1, 0) < seq1

    def causally_ready(self, change: dict) -> bool:
        deps = dict(change["deps"])
        deps[change["actor"]] = change["seq"] - 1
        return all(self.clock.get(a, 0) >= s for a, s in deps.items())

    def transitive_deps(self, base_deps: dict) -> dict:
        """Full vector clock implied by `base_deps` (op_set.js:29-37)."""
        return transitive_deps(self.states, base_deps)

    # ------------------------------------------------------------------
    # object-tree navigation
    # ------------------------------------------------------------------

    def get_path(self, object_id: str):
        """Root-to-object path of keys/indexes, None if unreachable (op_set.js:43-60)."""
        path = []
        while object_id != ROOT_ID:
            rec = self.by_object.get(object_id)
            if rec is None or not rec.inbound:
                return None
            ref = rec.inbound[0]
            object_id = ref["obj"]
            parent = self.by_object[object_id]
            if parent.obj_type in ("makeList", "makeText"):
                index = parent.elem_ids.index_of(ref["key"])
                if index < 0:
                    return None
                path.insert(0, index)
            else:
                path.insert(0, ref["key"])
        return path

    def get_field_ops(self, object_id: str, key: str) -> list:
        rec = self.by_object.get(object_id)
        if rec is None:
            return []
        return rec.keys.get(key, [])

    # ------------------------------------------------------------------
    # op application
    # ------------------------------------------------------------------

    def _apply_make(self, op: dict):
        object_id = op["obj"]
        if object_id in self.by_object:
            raise ValueError(f"Duplicate creation of object {object_id}")
        action = op["action"]
        if action == "makeMap":
            obj_type = "map"
        elif action == "makeTable":
            obj_type = "table"
        else:
            obj_type = "text" if action == "makeText" else "list"
        self.by_object[object_id] = ObjRec(op, is_sequence=obj_type in ("list", "text"))
        return [{"action": "create", "obj": object_id, "type": obj_type}]

    def _apply_insert(self, op: dict):
        object_id, elem = op["obj"], op["elem"]
        elem_id = make_elem_id(op["actor"], elem)
        rec = self.by_object.get(object_id)
        if rec is None:
            raise ValueError(f"Modification of unknown object {object_id}")
        if elem_id in rec.insertion:
            raise ValueError(f"Duplicate list element ID {elem_id}")
        obj_type = "text" if rec.obj_type == "makeText" else "list"
        rec.max_elem = max(elem, rec.max_elem)
        rec.following.setdefault(op["key"], []).append(op)
        rec.insertion[elem_id] = op
        return [{
            "obj": object_id, "type": obj_type, "action": "maxElem",
            "value": rec.max_elem, "path": self.get_path(object_id),
        }]

    @staticmethod
    def _get_conflicts(ops: list) -> list:
        conflicts = []
        for op in ops[1:]:
            conflict = {"actor": op["actor"], "value": op["value"]}
            if op["action"] == "link":
                conflict["link"] = True
            if op.get("datatype"):
                conflict["datatype"] = op["datatype"]
            conflicts.append(conflict)
        return conflicts

    def _patch_list(self, object_id: str, index: int, elem_id: str, action: str, ops):
        rec = self.by_object[object_id]
        obj_type = "text" if rec.obj_type == "makeText" else "list"
        first_op = ops[0] if ops else None
        value = first_op["value"] if first_op else None
        edit = {"action": action, "type": obj_type, "obj": object_id,
                "index": index, "path": self.get_path(object_id)}
        if first_op and first_op["action"] == "link":
            edit["link"] = True
            value = {"obj": first_op["value"]}

        if action == "insert":
            rec.elem_ids.insert_index(index, first_op["key"], value)
            edit["elemId"] = elem_id
            edit["value"] = first_op["value"]
            if first_op.get("datatype"):
                edit["datatype"] = first_op["datatype"]
        elif action == "set":
            rec.elem_ids.set_value(first_op["key"], value)
            edit["value"] = first_op["value"]
            if first_op.get("datatype"):
                edit["datatype"] = first_op["datatype"]
        elif action == "remove":
            rec.elem_ids.remove_index(index)
        else:
            raise ValueError(f"Unknown action type: {action}")

        if ops and len(ops) > 1:
            edit["conflicts"] = self._get_conflicts(ops)
        return [edit]

    def _update_list_element(self, object_id: str, elem_id: str):
        ops = self.get_field_ops(object_id, elem_id)
        rec = self.by_object[object_id]
        index = rec.elem_ids.index_of(elem_id)

        if index >= 0:
            if not ops:
                return self._patch_list(object_id, index, elem_id, "remove", None)
            return self._patch_list(object_id, index, elem_id, "set", ops)

        if not ops:
            return []  # deleting a non-existent element = no-op

        # Find the closest visible predecessor (op_set.js:159-169); the miss
        # path walks the RGA tree — the device engine replaces this with a
        # batched rank recomputation.
        prev_id = elem_id
        while True:
            index = -1
            prev_id = self.get_previous(object_id, prev_id)
            if prev_id is None:
                break
            index = rec.elem_ids.index_of(prev_id)
            if index >= 0:
                break
        return self._patch_list(object_id, index + 1, elem_id, "insert", ops)

    def _update_map_key(self, object_id: str, obj_type: str, key: str):
        ops = self.get_field_ops(object_id, key)
        edit = {"action": "", "type": obj_type, "obj": object_id, "key": key,
                "path": self.get_path(object_id)}
        if not ops:
            edit["action"] = "remove"
        else:
            first_op = ops[0]
            edit["action"] = "set"
            edit["value"] = first_op["value"]
            if first_op["action"] == "link":
                edit["link"] = True
            if first_op.get("datatype"):
                edit["datatype"] = first_op["datatype"]
            if len(ops) > 1:
                edit["conflicts"] = self._get_conflicts(ops)
        return [edit]

    def _apply_assign(self, op: dict, top_level: bool):
        """Process a set/del/link/inc op (op_set.js:196-257).

        Concurrency partition: ops causally before `op` are overwritten; truly
        concurrent ops survive as conflicts. The multi-value register is kept
        sorted descending by actor id — element 0 is the LWW winner.
        """
        object_id = op["obj"]
        rec = self.by_object.get(object_id)
        if rec is None:
            raise ValueError(f"Modification of unknown object {object_id}")
        obj_type = rec.obj_type

        if self.undo_local is not None and top_level:
            if op["action"] == "inc":
                undo_ops = [{"action": "inc", "obj": object_id, "key": op["key"],
                             "value": -op["value"]}]
            else:
                undo_ops = [
                    {k: ref[k] for k in ("action", "obj", "key", "value", "datatype") if k in ref}
                    for ref in rec.keys.get(op["key"], [])
                ]
            if not undo_ops:
                undo_ops = [{"action": "del", "obj": object_id, "key": op["key"]}]
            self.undo_local.extend(undo_ops)

        ops = rec.keys.get(op["key"], [])

        if op["action"] == "inc":
            overwritten = []
            remaining = []
            for other in ops:
                if (other["action"] == "set" and isinstance(other.get("value"), (int, float))
                        and not isinstance(other.get("value"), bool)
                        and other.get("datatype") == "counter"
                        and not self.is_concurrent(other, op)):
                    updated = dict(other)
                    updated["value"] = other["value"] + op["value"]
                    remaining.append(updated)
                else:
                    remaining.append(other)
        else:
            overwritten = [other for other in ops if not self.is_concurrent(other, op)]
            remaining = [other for other in ops if self.is_concurrent(other, op)]

        if op["action"] in ("set", "link"):
            # AT MOST ONE op per actor per register. Two same-actor ops can
            # only coexist transiently when one change assigns a key twice
            # (undo/redo re-minting a conflict set does exactly this); the
            # later op of the change supersedes its predecessor. Keeping
            # both and relying on sort order is ORDER-DEPENDENT: a full
            # reverse after a stable ascending sort flips the same-actor
            # pair on every later application that re-sorts the register,
            # so peers that applied different interleavings materialize
            # different winners from identical change sets (found by
            # scripts/soak.py, general profile seed 6; the reference's
            # sortBy(actor).reverse() has the same latent flip).
            superseded = [o for o in remaining if o["actor"] == op["actor"]]
            overwritten = overwritten + superseded
            remaining = [o for o in remaining if o["actor"] != op["actor"]]

        # Overwritten links drop out of the child's inbound index.
        for prior in overwritten:
            if prior["action"] == "link":
                child = self.by_object.get(prior["value"])
                if child is not None and prior in child.inbound:
                    child.inbound.remove(prior)
        if op["action"] == "link":
            self.by_object[op["value"]].inbound.append(op)
        if op["action"] in ("set", "link"):
            remaining = remaining + [op]
        # descending by actor id — keys are now unique per actor, so the
        # sort is total and application-order-independent
        remaining = sorted(remaining, key=lambda o: o["actor"])[::-1]
        rec.keys[op["key"]] = remaining

        if object_id == ROOT_ID or obj_type == "makeMap":
            return self._update_map_key(object_id, "map", op["key"])
        if obj_type == "makeTable":
            return self._update_map_key(object_id, "table", op["key"])
        if obj_type in ("makeList", "makeText"):
            return self._update_list_element(object_id, op["key"])
        raise ValueError(f"Unknown operation type {obj_type}")

    @staticmethod
    def _simplify_diffs(diffs: list) -> list:
        """Drop redundant maxElem diffs (op_set.js:260-281)."""
        max_elems: dict[str, int] = {}
        result = []
        for diff in reversed(diffs):
            obj, action = diff["obj"], diff["action"]
            if action == "maxElem":
                if obj not in max_elems or max_elems[obj] < diff["value"]:
                    max_elems[obj] = diff["value"]
                    result.append(diff)
            elif action == "insert":
                counter = parse_elem_id(diff["elemId"])[1]
                if obj not in max_elems or max_elems[obj] < counter:
                    max_elems[obj] = counter
                result.append(diff)
            else:
                result.append(diff)
        result.reverse()
        return result

    def _apply_ops(self, ops: list) -> list:
        all_diffs = []
        new_objects = set()
        for op in ops:
            action = op["action"]
            if action in _MAKE_ACTIONS:
                new_objects.add(op["obj"])
                diffs = self._apply_make(op)
            elif action == "ins":
                diffs = self._apply_insert(op)
            elif action in _ASSIGN_ACTIONS:
                diffs = self._apply_assign(op, op["obj"] not in new_objects)
            else:
                raise ValueError(f"Unknown operation type {action}")
            all_diffs.extend(diffs)
        return self._simplify_diffs(all_diffs)

    def _apply_change(self, change: dict) -> list:
        actor, seq = change["actor"], change["seq"]
        prior = self.states.get(actor, [])
        if seq <= len(prior):
            if prior[seq - 1]["change"] != change:
                raise RuntimeError(f"Inconsistent reuse of sequence number {seq} by {actor}")
            return []  # idempotent duplicate

        base_deps = dict(change["deps"])
        base_deps[actor] = seq - 1
        all_deps = self.transitive_deps(base_deps)
        self.states.setdefault(actor, []).append({"change": change, "allDeps": all_deps})

        ops = [{**op, "actor": actor, "seq": seq} for op in change["ops"]]
        diffs = self._apply_ops(ops)

        # New direct-dependency frontier: drop anything now transitively covered.
        new_deps = {a: s for a, s in self.deps.items() if s > all_deps.get(a, 0)}
        new_deps[actor] = seq
        self.deps = new_deps
        self.clock[actor] = seq
        self.history.append(change)
        return diffs

    def _apply_queued_ops(self) -> list:
        """Fixpoint drain of causally-ready queued changes (op_set.js:329-345)."""
        diffs = []
        while True:
            not_ready = []
            for change in self.queue:
                if self.causally_ready(change):
                    diffs.extend(self._apply_change(change))
                else:
                    not_ready.append(change)
            if len(not_ready) == len(self.queue):
                return diffs
            self.queue = not_ready

    def _push_undo_history(self):
        self.undo_stack = self.undo_stack[: self.undo_pos] + [self.undo_local]
        self.undo_pos += 1
        self.redo_stack = []
        self.undo_local = None

    def add_change(self, change: dict, undoable: bool) -> list:
        self.queue.append(change)
        if undoable:
            self.undo_local = []
            diffs = self._apply_queued_ops()
            self._push_undo_history()
            return diffs
        return self._apply_queued_ops()

    # ------------------------------------------------------------------
    # queries
    # ------------------------------------------------------------------

    def get_missing_changes(self, have_deps: dict, clock_bound: Optional[dict] = None) -> list:
        """All changes not covered by `have_deps` (op_set.js:388-395).

        `clock_bound` restricts the view to a historical snapshot of this
        lineage (states lists are append-only, so a clock fully determines a
        past state's visible change-set).
        """
        all_deps = self.transitive_deps(have_deps)
        changes = []
        for actor, states in self.states.items():
            upper = len(states) if clock_bound is None else min(len(states), clock_bound.get(actor, 0))
            for entry in states[all_deps.get(actor, 0): upper]:
                changes.append(entry["change"])
        return changes

    def get_changes_for_actor(self, for_actor: str, after_seq: int = 0,
                              clock_bound: Optional[dict] = None) -> list:
        states = self.states.get(for_actor, [])
        upper = len(states) if clock_bound is None else min(len(states), clock_bound.get(for_actor, 0))
        return [entry["change"] for entry in states[after_seq:upper]]

    @staticmethod
    def missing_deps_of_queue(queue, clock: dict) -> dict:
        missing: dict[str, int] = {}
        for change in queue:
            deps = dict(change["deps"])
            deps[change["actor"]] = change["seq"] - 1
            for dep_actor, dep_seq in deps.items():
                if clock.get(dep_actor, 0) < dep_seq:
                    missing[dep_actor] = max(dep_seq, missing.get(dep_actor, 0))
        return missing

    def get_object_fields(self, object_id: str) -> list:
        rec = self.by_object[object_id]
        return [key for key, ops in rec.keys.items() if ops]

    def get_object_conflicts(self, object_id: str, get_value) -> dict:
        rec = self.by_object[object_id]
        conflicts = {}
        for key, ops in rec.keys.items():
            if len(ops) > 1:
                conflicts[key] = {op["actor"]: get_value(op) for op in ops[1:]}
        return conflicts

    def list_length(self, object_id: str) -> int:
        return len(self.by_object[object_id].elem_ids)

    # ------------------------------------------------------------------
    # RGA ordering (tree walk; the device path replaces this with a sort +
    # pointer-doubling linearization)
    # ------------------------------------------------------------------

    def _get_parent(self, object_id: str, key: str):
        if key == "_head":
            return None
        insertion = self.by_object[object_id].insertion.get(key)
        if insertion is None:
            raise TypeError(f"Missing index entry for list element {key}")
        return insertion["key"]

    def insertions_after(self, object_id: str, parent_id, child_id=None) -> list:
        child_key = None
        if child_id:
            actor_id, counter = parse_elem_id(child_id)
            child_key = (counter, actor_id)
        ops = self.by_object[object_id].following.get(parent_id, [])
        entries = [op for op in ops if op["action"] == "ins"]
        if child_key is not None:
            entries = [op for op in entries if (op["elem"], op["actor"]) < child_key]
        entries.sort(key=lambda op: (op["elem"], op["actor"]), reverse=True)
        return [make_elem_id(op["actor"], op["elem"]) for op in entries]

    def get_next(self, object_id: str, key: str):
        children = self.insertions_after(object_id, key)
        if children:
            return children[0]
        while True:
            ancestor = self._get_parent(object_id, key)
            if ancestor is None:
                return None
            siblings = self.insertions_after(object_id, ancestor, key)
            if siblings:
                return siblings[0]
            key = ancestor

    def get_previous(self, object_id: str, key: str):
        parent_id = self._get_parent(object_id, key)
        children = self.insertions_after(object_id, parent_id)
        if children and children[0] == key:
            return None if parent_id == "_head" else parent_id

        prev_id = None
        for child in children:
            if child == key:
                break
            prev_id = child
        while True:
            grandchildren = self.insertions_after(object_id, prev_id)
            if not grandchildren:
                return prev_id
            prev_id = grandchildren[-1]

    def list_iterator(self, list_id: str, get_value):
        """Yield {'elemId', 'index'?, 'value'?, 'conflicts'?} in RGA order."""
        elem, index = "_head", -1
        while True:
            elem = self.get_next(list_id, elem)
            if elem is None:
                return
            item = {"elemId": elem}
            ops = self.get_field_ops(list_id, elem)
            if ops:
                index += 1
                item["index"] = index
                item["value"] = get_value(ops[0])
                item["conflicts"] = None
                if len(ops) > 1:
                    item["conflicts"] = {op["actor"]: get_value(op) for op in ops[1:]}
            yield item

    # ------------------------------------------------------------------
    # undo / redo (backend/index.js:258-316)
    # ------------------------------------------------------------------

    def do_undo(self, request: dict) -> list:
        if self.undo_pos < 1 or not self.undo_stack[self.undo_pos - 1:self.undo_pos]:
            raise ValueError("Cannot undo: there is nothing to be undone")
        undo_ops = self.undo_stack[self.undo_pos - 1]
        change = {"actor": request["actor"], "seq": request["seq"],
                  "deps": request.get("deps", {}), "message": request.get("message"),
                  "ops": undo_ops}

        redo_ops = []
        for op in undo_ops:
            if op["action"] not in _ASSIGN_ACTIONS:
                raise ValueError(f"Unexpected operation type in undo history: {op}")
            field_ops = self.get_field_ops(op["obj"], op["key"])
            if op["action"] == "inc":
                redo_ops.append({"action": "inc", "obj": op["obj"], "key": op["key"],
                                 "value": -op["value"]})
            elif not field_ops:
                redo_ops.append({"action": "del", "obj": op["obj"], "key": op["key"]})
            else:
                for field_op in field_ops:
                    redo_ops.append({k: v for k, v in field_op.items()
                                     if k not in ("actor", "seq")})

        self.undo_pos -= 1
        self.redo_stack = self.redo_stack + [redo_ops]
        return self.add_change(change, False)

    def do_redo(self, request: dict) -> list:
        if not self.redo_stack:
            raise ValueError("Cannot redo: the last change was not an undo")
        redo_ops = self.redo_stack[-1]
        change = {"actor": request["actor"], "seq": request["seq"],
                  "deps": request.get("deps", {}), "message": request.get("message"),
                  "ops": redo_ops}
        self.undo_pos += 1
        self.redo_stack = self.redo_stack[:-1]
        return self.add_change(change, False)

    # ------------------------------------------------------------------
    # fork-by-replay (replaces Immutable.js structural sharing)
    # ------------------------------------------------------------------

    def record(self, command):
        self.commands.append(command)

    def fork(self, version: int) -> "OpSetIndex":
        fresh = OpSetIndex()
        for command in self.commands[:version]:
            fresh._replay(command)
        fresh.commands = list(self.commands[:version])
        return fresh

    def _replay(self, command):
        kind = command[0]
        if kind == "apply":
            _, changes, undoable = command
            for change in changes:
                self.add_change(change, undoable)
        elif kind == "undo":
            self.do_undo(command[1])
        elif kind == "redo":
            self.do_redo(command[1])
        else:  # pragma: no cover
            raise ValueError(f"Unknown command {kind}")

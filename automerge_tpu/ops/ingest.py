"""Device-side batch ingestion for the columnar text/list engine.

The reference applies ops one at a time (`applyOps`/`applyInsert`/
`applyAssign`, /root/reference/backend/op_set.js:63-283), with an
order-statistic skip list for elemId<->index queries. Here one causally-ready
*round* of changes — often millions of ops — updates the device tables in at
most two jitted XLA programs, all int32/int8/bool (the TPU emulates int64;
int64 sorts/searches run emulated, severalfold slower - design
assumption, docs/MEASUREMENTS.md):

- **expand_runs**: the bulk path. Typing runs (ins+set chains with
  consecutive counters) arrive as ~20-byte descriptors plus a value blob;
  the kernel expands them into element-table rows with one cummax (run-of-
  element) and a handful of scatters — O(elements) at HBM bandwidth, no
  sort, no searchsorted. Host<->device traffic is bytes-per-run, not
  bytes-per-op.
- **apply_residual**: everything irregular (bare inserts, dels, incs,
  assigns to old elements, pooled values). References are pre-resolved to
  slot numbers on the host (engine/host_index.py), so the kernel is pure
  scatters: place inserts, run the LWW register fast path, and flag the
  genuinely contended registers into a `slow` mask the host resolves
  against its conflict/value-pool state — exactly the reference's
  applyAssign semantics, partitioned so the device does the common case.

`materialize_text` turns the tables into list positions + visible values via
the chain-condensed RGA linearization (see ops/linearize.py).

All shapes are static; callers bucket sizes with `bucket()` so XLA retraces
rarely.

**Buffer donation (the streaming tier, INTERNALS §9).** The commit-path
kernels that *replace* the document tables (`expand_runs*_packed`,
`apply_residual_packed`, `merge_and_materialize_dense*`,
`break_chains_packed`, `scatter_registers_packed`) each have a
`*_donated` twin jitted with ``donate_argnums`` over the table operands:
XLA may then write outputs in place of the inputs, so a K-deep pipeline
ring's steady-state device allocation is flat (one table set + staged
inputs) instead of accumulating K generations of dead tables until the
allocator catches up. Donation is a caller CONTRACT, not a hint the
engine can ignore: a donated input buffer is dead after the call, so the
engine only selects the donated twins when the document has opted in
(``CausalDeviceDoc.donate_buffers`` — the checkpoint writer's zero-copy
grab holds raw table references and is incompatible; see
checkpoint/engine_codec.grab).
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .._common import KIND_DEL, KIND_INC, KIND_INS, KIND_SET  # noqa: F401


def bucket(n: int, minimum: int = 256) -> int:
    """Half-octave size buckets (2^k and 3·2^(k-1)): <=25% padding waste."""
    cap = minimum
    while cap < n:
        cap = cap * 3 // 2 if (cap & (cap - 1)) == 0 else (cap // 3) * 4
    return cap


# the 9 element-table operands every commit-path kernel leads with
_TABLE_ARGNUMS = tuple(range(9))
_REG_ARGNUMS = tuple(range(5))      # the 5 register tables

_DONATION = None
_DONATION_FILTERED = False


def donation_enabled() -> bool:
    """Whether the *_donated kernel twins are usable on this backend.

    Donation is an aliasing optimization; results are identical either
    way, but backends that cannot alias emit a per-compile warning which
    this gate suppresses once. ``AMTPU_DONATE=0/1`` forces the answer
    (tests force 1 on cpu to exercise the donated code path); the
    default is on for every non-cpu backend — exactly the platforms
    where steady-state HBM headroom matters."""
    global _DONATION, _DONATION_FILTERED
    if _DONATION is None:
        v = os.environ.get("AMTPU_DONATE", "")
        if v in ("0", "1"):
            _DONATION = v == "1"
        else:
            _DONATION = jax.default_backend() != "cpu"
    if _DONATION and not _DONATION_FILTERED:
        # registered ONCE: this sits on the per-committed-round hot path,
        # and filterwarnings() invalidates the process-wide warning cache
        # on every call
        _DONATION_FILTERED = True
        import warnings
        # backends that cannot alias a particular donated operand
        # (shape-growing rounds; cpu) warn per compile — donation is
        # best-effort there by design
        warnings.filterwarnings("ignore", message=".*onated buffer.*")
    return _DONATION


def buffers_consumed(arrays) -> bool:
    """True iff any of `arrays` was consumed by a donated call — the
    poison-or-recover decision after a raising donated commit (a
    trace/compile failure consumes nothing and must stay retryable)."""
    return any(getattr(a, "is_deleted", lambda: False)() for a in arrays)


def _jit_pair(fn, donate_argnums, static_argnames=()):
    """(plain, donated) jit twins of one kernel implementation."""
    kw = {"static_argnames": static_argnames} if static_argnames else {}
    return (jax.jit(fn, **kw),
            jax.jit(fn, donate_argnums=donate_argnums, **kw))


def _ext(a, fill, out_cap):
    C = a.shape[0]
    if C >= out_cap:
        return a
    return jnp.concatenate([a, jnp.full(out_cap - C, fill, a.dtype)])


@partial(jax.jit, static_argnames=("out_cap",))
def expand_runs(
    # document tables, capacity C
    parent, ctr, actor, value, has_value, win_actor, win_seq, win_counter,
    chain,
    # run descriptors, capacity R (padding: len=0, elem_base=N sentinel)
    run_head_slot, run_parent_slot, run_ctr0, run_actor, run_win_actor,
    run_win_seq, run_elem_base, run_has_value,
    # value blob in run-element order, capacity N
    blob,
    n_run_elems,                  # scalar i32: live prefix of the blob
    *, out_cap: int,
):
    """Expand run descriptors into element-table rows (see module docstring).

    Element j of run r lands at slot run_head_slot[r]+j with parent
    slot-1 (or run_parent_slot for j=0), counter run_ctr0[r]+j, and — when
    run_has_value[r] — an LWW register won by the run's change. Interior
    elements start with their chain bit set (they are their predecessor's
    only — hence Lamport-max — child at insert time; `break_chains` clears
    bits as concurrent children arrive)."""
    R = run_head_slot.shape[0]
    N = blob.shape[0]

    # GATHER-FREE, like `expand_runs_dense`: every per-element column —
    # including the target SLOT itself — is piecewise affine over runs
    # (constant or +1 per element, resetting at run starts), so instead
    # of `table[run_of]` gathers the columns come from one (6, N)
    # boundary-delta cumsum; the only O(N)-indexed operation left is the
    # final single stacked (C, 9) scatter (shared index vector across
    # all nine columns — scatter cost is per-INDEX, so one pass instead
    # of nine is a ~3.4x measured win at residual-round shapes;
    # docs/MEASUREMENTS.md streaming-tier entry).
    run_len_prev = run_elem_base - jnp.concatenate(
        [jnp.zeros(1, run_elem_base.dtype), run_elem_base[:-1]])
    prev = lambda a: jnp.concatenate([jnp.zeros(1, a.dtype), a[:-1]])
    first = jnp.arange(R, dtype=jnp.int32) == 0
    # +1-per-element columns: reset to (ctr0, head_slot) at run starts
    d_ctr = jnp.where(first, run_ctr0,
                      run_ctr0 - (prev(run_ctr0) + run_len_prev - 1))
    d_slot = jnp.where(first, run_head_slot,
                       run_head_slot
                       - (prev(run_head_slot) + run_len_prev - 1))
    # piecewise-constant columns: value deltas at run starts
    wa_v = jnp.where(run_has_value, run_win_actor, -1)
    ws_v = jnp.where(run_has_value, run_win_seq, 0)
    has_v = run_has_value.astype(jnp.int32)
    d_actor = jnp.where(first, run_actor, run_actor - prev(run_actor))
    d_wa = jnp.where(first, wa_v, wa_v - prev(wa_v))
    d_ws = jnp.where(first, ws_v, ws_v - prev(ws_v))
    d_has = jnp.where(first, has_v, has_v - prev(has_v))

    deltas = jnp.ones((6, N), jnp.int32)
    deltas = deltas.at[2:].set(0)
    deltas = deltas.at[:, run_elem_base].set(
        jnp.stack([d_ctr, d_slot, d_actor, d_wa, d_ws, d_has]),
        mode="drop")                      # padding runs: elem_base == N
    cols = jnp.cumsum(deltas, axis=1)
    ctr_col, slot_col = cols[0], cols[1]

    j = jnp.arange(N, dtype=jnp.int32)
    live = j < n_run_elems
    is_start = jnp.zeros(N, bool).at[run_elem_base].set(True, mode="drop")
    tgt = jnp.where(live, slot_col, out_cap)    # OOB sentinel drops padding
    # parent: slot-1 everywhere except run heads (R-sized scatter)
    parent_col = (slot_col - 1).at[run_elem_base].set(
        run_parent_slot, mode="drop")
    has_col = (cols[5] > 0) & live

    return _scatter_rows_9(
        (parent, ctr, actor, value, has_value, win_actor, win_seq,
         win_counter, chain),
        tgt,
        (parent_col, ctr_col, cols[2], blob.astype(jnp.int32), has_col,
         jnp.where(has_col, cols[3], -1), jnp.where(has_col, cols[4], 0),
         jnp.zeros(N, jnp.int32), live & ~is_start),
        out_cap)


def _scatter_rows_9(tables, idx, updates, out_cap: int):
    """Write 9 aligned element-table rows at `idx` as ONE (C, 9) scatter
    (shared index vector; OOB `idx` drops). `tables` / `updates` follow
    the canonical column order (parent, ctr, actor, value, has_value,
    win_actor, win_seq, win_counter, chain); bool columns are carried as
    int32 and cast back on the way out."""
    parent, ctr, actor, value, has_value, win_actor, win_seq, \
        win_counter, chain = tables
    tbl = jnp.stack([
        _ext(parent, 0, out_cap), _ext(ctr, 0, out_cap),
        _ext(actor, 0, out_cap), _ext(value, 0, out_cap),
        _ext(has_value, False, out_cap).astype(jnp.int32),
        _ext(win_actor, -1, out_cap), _ext(win_seq, 0, out_cap),
        _ext(win_counter, False, out_cap).astype(jnp.int32),
        _ext(chain, False, out_cap).astype(jnp.int32)], axis=1)
    upd = jnp.stack([u.astype(jnp.int32) for u in updates], axis=1)
    out = tbl.at[idx].set(upd, mode="drop")
    return (out[:, 0], out[:, 1], out[:, 2], out[:, 3],
            out[:, 4].astype(bool), out[:, 5], out[:, 6],
            out[:, 7].astype(bool), out[:, 8].astype(bool))


@partial(jax.jit, static_argnames=("out_cap",))
def expand_runs_dense(
    parent, ctr, actor, value, has_value, win_actor, win_seq, win_counter,
    chain,
    run_head_slot, run_parent_slot, run_ctr0, run_actor, run_win_actor,
    run_win_seq, run_elem_base, run_has_value,
    blob, n_run_elems, base_slot,
    *, out_cap: int,
):
    """`expand_runs` for the common case where the round mints no residual
    inserts, so the new elements occupy one contiguous slot window
    [base_slot, base_slot + n_run_elems). The element columns are computed
    densely in run-element space and written with dynamic_update_slice —
    contiguous stores instead of 9 scatters. Caller guarantees
    base_slot + N <= out_cap (N = padded blob length).

    GATHER-FREE: every column is piecewise affine over runs (constant, or
    +1 per element), so instead of `table[run_of]` gathers — ~140M elem/s
    on v5e, they dominated the merge at bench scale — each column is a
    run-boundary delta scatter (R elements) + a shared prefix sum: one
    (5, N) cumsum and a handful of R-sized ops, all at vector throughput.
    Slots past n_run_elems inside the padded window receive run-tail
    garbage exactly as before (they are beyond n_elems until a later round
    dus-overwrites them)."""
    R = run_head_slot.shape[0]
    N = blob.shape[0]

    # per-run deltas against the previous run's final element value
    run_len_prev = run_elem_base - jnp.concatenate(
        [jnp.zeros(1, run_elem_base.dtype), run_elem_base[:-1]])
    prev = lambda a: jnp.concatenate([jnp.zeros(1, a.dtype), a[:-1]])
    first = jnp.arange(R, dtype=jnp.int32) == 0
    # ctr column: +1 per element, resets to run_ctr0 at run starts
    # (cum[eb_r] = cum[eb_r - 1] + d_ctr[r] must equal run_ctr0[r], with
    # cum[eb_r - 1] = ctr0[r-1] + len_{r-1} - 1)
    d_ctr = jnp.where(first, run_ctr0,
                      run_ctr0 - (prev(run_ctr0) + run_len_prev - 1))
    # piecewise-constant columns: value deltas at run starts
    wa_v = jnp.where(run_has_value, run_win_actor, -1)
    ws_v = jnp.where(run_has_value, run_win_seq, 0)
    has_v = run_has_value.astype(jnp.int32)
    d_actor = jnp.where(first, run_actor, run_actor - prev(run_actor))
    d_wa = jnp.where(first, wa_v, wa_v - prev(wa_v))
    d_ws = jnp.where(first, ws_v, ws_v - prev(ws_v))
    d_has = jnp.where(first, has_v, has_v - prev(has_v))

    # one boundary scatter per column family + one shared (5, N) prefix sum
    # (padding runs have elem_base == N: OOB, dropped)
    deltas = jnp.ones((5, N), jnp.int32)
    deltas = deltas.at[1:].set(0)
    deltas = deltas.at[:, run_elem_base].set(
        jnp.stack([d_ctr, d_actor, d_wa, d_ws, d_has]), mode="drop")
    cols = jnp.cumsum(deltas, axis=1)

    j = jnp.arange(N, dtype=jnp.int32)
    live = j < n_run_elems
    is_start = jnp.zeros(N, bool).at[run_elem_base].set(True, mode="drop")
    # parent: slot-1 everywhere except run heads (R-sized scatter)
    parent_col = (base_slot - 1) + j
    parent_col = parent_col.at[run_elem_base].set(
        run_parent_slot, mode="drop")
    has_col = (cols[4] > 0) & live

    def dus(table, col, fill):
        return jax.lax.dynamic_update_slice(
            _ext(table, fill, out_cap), col.astype(table.dtype), (base_slot,))

    return (dus(parent, parent_col, 0),
            dus(ctr, cols[0], 0),
            dus(actor, cols[1], 0),
            dus(value, blob, 0),
            dus(has_value, has_col, False),
            dus(win_actor, jnp.where(has_col, cols[2], -1), -1),
            dus(win_seq, jnp.where(has_col, cols[3], 0), 0),
            dus(win_counter, jnp.zeros(N, bool), False),
            dus(chain, live & ~is_start, False))


# Packed-descriptor row layout for expand_runs*_packed: one (9, R) int32
# host->device transfer replaces eight separate array transfers (each costs
# a tunnel/PCIe round trip of latency; on the remote-attached chip used for
# benchmarking, per-transfer overhead dominates the payload). The META row
# carries the round's scalars ([n_run_elems, base_slot, n_runs], rest 0) so
# commit-time dispatch uploads NOTHING host->device.
DESC_HEAD_SLOT, DESC_PARENT_SLOT, DESC_CTR0, DESC_ACTOR, DESC_WIN_ACTOR, \
    DESC_WIN_SEQ, DESC_ELEM_BASE, DESC_HAS_VALUE, DESC_META = range(9)
META_N_ELEMS, META_BASE_SLOT, META_N_RUNS = range(3)

# Residual-op packed layout for apply_residual_packed: one (8, M) int32.
RES_KIND, RES_SLOT, RES_NEW_SLOT, RES_CTR, RES_ACTOR, RES_VALUE, \
    RES_WIN_ACTOR, RES_WIN_SEQ = range(8)


def _unpack_desc(desc):
    return (desc[DESC_HEAD_SLOT], desc[DESC_PARENT_SLOT], desc[DESC_CTR0],
            desc[DESC_ACTOR], desc[DESC_WIN_ACTOR], desc[DESC_WIN_SEQ],
            desc[DESC_ELEM_BASE], desc[DESC_HAS_VALUE].astype(bool))


def _expand_runs_packed(
    parent, ctr, actor, value, has_value, win_actor, win_seq, win_counter,
    chain, desc, blob, *, out_cap: int,
):
    """`expand_runs` taking the run descriptors as one packed (9, R) int32
    matrix (row layout: DESC_*, scalars in the META row). Single h2d
    transfer + single dispatch, no commit-time scalar uploads."""
    return expand_runs(
        parent, ctr, actor, value, has_value, win_actor, win_seq,
        win_counter, chain, *_unpack_desc(desc), blob,
        desc[DESC_META, META_N_ELEMS], out_cap=out_cap)


expand_runs_packed, expand_runs_packed_donated = _jit_pair(
    _expand_runs_packed, _TABLE_ARGNUMS, ("out_cap",))


def _expand_runs_dense_packed(
    parent, ctr, actor, value, has_value, win_actor, win_seq, win_counter,
    chain, desc, blob, *, out_cap: int,
):
    """`expand_runs_dense` + fused `break_chains`, packed descriptors.

    The dense path's chain breaks touch exactly the run heads' parents,
    whose (slot, ctr, actor) already sit in the descriptor matrix — so the
    whole common-case merge round is ONE descriptor transfer, ONE value-blob
    transfer, and ONE device program."""
    (head_slot, parent_slot, ctr0, ractor, rwa, rws, elem_base,
     has) = _unpack_desc(desc)
    n_run_elems = desc[DESC_META, META_N_ELEMS]
    base_slot = desc[DESC_META, META_BASE_SLOT]
    n_runs = desc[DESC_META, META_N_RUNS]
    tables = expand_runs_dense(
        parent, ctr, actor, value, has_value, win_actor, win_seq,
        win_counter, chain, head_slot, parent_slot, ctr0, ractor, rwa, rws,
        elem_base, has, blob, n_run_elems, base_slot, out_cap=out_cap)
    R = desc.shape[1]
    live = jnp.arange(R, dtype=jnp.int32) < n_runs
    chain_n = _break_chains_core(
        tables[8], tables[0], tables[1], tables[2],
        jnp.where(live, parent_slot, 0), jnp.where(live, ctr0, -1),
        jnp.where(live, ractor, -1))
    return tables[:8] + (chain_n,)


expand_runs_dense_packed, expand_runs_dense_packed_donated = _jit_pair(
    _expand_runs_dense_packed, _TABLE_ARGNUMS, ("out_cap",))


def _break_chains_core(chain, parent, ctr, actor, p_slots, h_ctr, h_actor):
    """Clear the chain bit of slot p+1 for every touched parent p whose new
    child Lamport-exceeds (ctr, actor) of p+1.

    This is the incremental form of the reference's `insertionsAfter`
    ordering (/root/reference/backend/op_set.js:440-454): slot p+1 heads its
    own segment once it is no longer p's Lamport-maximal child. Breaks are
    sticky — Lamport maxima only grow — so bits never need re-setting.
    R-sized work per round instead of a full O(C) census per materialize."""
    C = chain.shape[0]
    q = jnp.clip(p_slots + 1, 0, C - 1)
    cq = ctr[q]
    aq = actor[q]
    brk = (p_slots >= 1) & ((h_ctr > cq) | ((h_ctr == cq) & (h_actor > aq)))
    return chain.at[jnp.where(brk, q, C)].set(False, mode="drop")


break_chains = jax.jit(_break_chains_core)


def _break_chains_packed(chain, parent, ctr, actor, touch):
    """`break_chains` with the (p_slot, ctr, actor) touch rows packed as one
    (3, T) int32 transfer."""
    return _break_chains_core(chain, parent, ctr, actor,
                              touch[0], touch[1], touch[2])


break_chains_packed, break_chains_packed_donated = _jit_pair(
    _break_chains_packed, (0,))     # only `chain` is replaced


def _apply_residual_packed(
    parent, ctr, actor, value, has_value, win_actor, win_seq, win_counter,
    chain, res, conflict_slots, *, out_cap: int,
):
    """`apply_residual` taking the residual op columns as one packed
    (8, M) int32 matrix (row layout: RES_*)."""
    return apply_residual(
        parent, ctr, actor, value, has_value, win_actor, win_seq,
        win_counter, chain,
        res[RES_KIND].astype(jnp.int8), res[RES_SLOT], res[RES_NEW_SLOT],
        res[RES_CTR], res[RES_ACTOR], res[RES_VALUE], res[RES_WIN_ACTOR],
        res[RES_WIN_SEQ], conflict_slots, out_cap=out_cap)


apply_residual_packed, apply_residual_packed_donated = _jit_pair(
    _apply_residual_packed, _TABLE_ARGNUMS, ("out_cap",))


def _apply_mixed_round(
    parent, ctr, actor, value, has_value, win_actor, win_seq, win_counter,
    chain, desc, blob, res, conflict_slots, touch,
    *, out_cap: int, expand_kind: str, with_res: bool, with_touch: bool,
):
    """One device program for a whole MIXED round: run expansion
    (dense or sparse, per `expand_kind`), residual placement + register
    fast path, and chain breaks, composed by static flags. The commit of
    any round — dense, sparse, residual-bearing or not — is therefore
    ONE dispatch, and XLA fuses the phases' elementwise work (the
    per-phase (C, 9) stack/unstack round trips of the split programs
    disappear). Unused operands ride as tiny dummies (static flags cut
    the dead branches at trace time). Returns the 9 tables, plus
    `slow_info` when `with_res`."""
    tables = (parent, ctr, actor, value, has_value, win_actor, win_seq,
              win_counter, chain)
    if expand_kind == "dense":
        tables = _expand_runs_dense_packed(*tables, desc, blob,
                                           out_cap=out_cap)
    elif expand_kind == "sparse":
        tables = _expand_runs_packed(*tables, desc, blob, out_cap=out_cap)
    slow_info = None
    if with_res:
        out = _apply_residual_packed(*tables, res, conflict_slots,
                                     out_cap=out_cap)
        tables, slow_info = out[:9], out[9]
    if with_touch:
        tables = tables[:8] + (_break_chains_packed(
            tables[8], tables[0], tables[1], tables[2], touch),)
    return tables + ((slow_info,) if with_res else ())


apply_mixed_round, apply_mixed_round_donated = _jit_pair(
    _apply_mixed_round, _TABLE_ARGNUMS,
    ("out_cap", "expand_kind", "with_res", "with_touch"))

_DUMMY_I32 = None


def _dummy_i32():
    """Shared tiny placeholder for unused traced operands of
    apply_mixed_round (static flags dead-code them; a fresh upload per
    call would still pay a transfer)."""
    global _DUMMY_I32
    if _DUMMY_I32 is None:
        _DUMMY_I32 = jnp.zeros((1, 1), jnp.int32)
    return _DUMMY_I32


@partial(jax.jit, static_argnames=("out_cap",))
def apply_residual(
    # document tables (post expand_runs), capacity C == out_cap
    parent, ctr, actor, value, has_value, win_actor, win_seq, win_counter,
    chain,
    # residual op columns, capacity M (padding: kind=-1, slots=out_cap)
    op_kind,        # int8
    op_slot,        # ins: resolved parent slot (0 = head); assigns: target slot
    op_new_slot,    # ins: assigned element slot; else out_cap
    op_ctr, op_actor,             # ins: minted elemId (global actor rank)
    op_value,                     # int32 (negatives = host value-pool refs)
    op_win_actor, op_win_seq,     # the op's change (global rank, seq)
    conflict_slots,               # [K] slots with host-held conflicts (pad C)
    *, out_cap: int,
):
    """Place irregular inserts and run the LWW register fast path.

    Returns the updated tables + the packed (7, M) `slow_info` matrix (see
    `_register_fast_path`): ops needing host resolution — multi-writer
    rounds, occupied registers, dels, incs, pooled values — plus their
    register state, in op order, as one device->host transfer."""
    M = op_kind.shape[0]
    kind = op_kind.astype(jnp.int32)
    is_ins = kind == KIND_INS
    is_assign = (kind == KIND_SET) | (kind == KIND_DEL) | (kind == KIND_INC)

    ins_idx = jnp.where(is_ins, op_new_slot, out_cap)
    zeros = jnp.zeros(M, jnp.int32)
    # one stacked scatter for the insert placement (see _scatter_rows_9)
    (parent_n, ctr_n, actor_n, value_n, has_n, wa_n, ws_n, wc_n,
     chain_n) = _scatter_rows_9(
        (parent, ctr, actor, value, has_value, win_actor, win_seq,
         win_counter, chain),
        ins_idx,
        (op_slot, op_ctr, op_actor, zeros, zeros,
         jnp.full(M, -1, jnp.int32), zeros, zeros, zeros),
        out_cap)

    (value_n, has_n, wa_n, ws_n, wc_n, slow_info) = _register_fast_path(
        value_n, has_n, wa_n, ws_n, wc_n, kind, is_assign, op_slot,
        op_value, op_win_actor, op_win_seq, conflict_slots, out_cap)
    return (parent_n, ctr_n, actor_n, value_n, has_n, wa_n, ws_n, wc_n,
            chain_n, slow_info)


def _register_fast_path(value_n, has_n, wa_n, ws_n, wc_n, kind, is_assign,
                        op_slot, op_value, op_win_actor, op_win_seq,
                        conflict_slots, out_cap):
    """Shared LWW register resolution (text elements and map keys).

    Fast = a single plain inline set in this round targeting either an
    empty register or the op's own actor's earlier write (always causally
    covered). Everything else -> `slow` for host resolution.

    Returns the updated tables plus `slow_info`, a single packed (7, M)
    int32 array [slow, tslot, reg_value, reg_has, reg_win_actor,
    reg_win_seq, reg_win_counter]: everything the host slow path needs in
    ONE device->host transfer (device round trips dominate small rounds —
    the remote-tunnel RTT is ~10^2 ms)."""
    tslot = jnp.where(is_assign, op_slot, out_cap)
    tclip = jnp.clip(tslot, 0, out_cap - 1)
    counts = jnp.zeros(out_cap + 1, jnp.int32).at[
        jnp.clip(tslot, 0, out_cap)].add(is_assign.astype(jnp.int32))
    cmask = jnp.zeros(out_cap + 1, bool).at[
        jnp.clip(conflict_slots, 0, out_cap)].set(True)
    empty = ~has_n[tclip] & (wa_n[tclip] < 0)
    self_over = (~wc_n[tclip] & (wa_n[tclip] == op_win_actor)
                 & (ws_n[tclip] < op_win_seq))
    fast = (is_assign & (kind == KIND_SET)
            & (counts[tclip] == 1) & (empty | self_over)
            & ~cmask[tclip] & (op_value >= 0))
    f_idx = jnp.where(fast, tslot, out_cap)
    # one stacked (C, 5) scatter over the register columns (shared index
    # vector — same per-index-overhead argument as _scatter_rows_9)
    M = f_idx.shape[0]
    regs = jnp.stack([value_n, has_n.astype(jnp.int32), wa_n, ws_n,
                      wc_n.astype(jnp.int32)], axis=1)
    upd = jnp.stack([op_value, jnp.ones(M, jnp.int32), op_win_actor,
                     op_win_seq, jnp.zeros(M, jnp.int32)], axis=1)
    regs = regs.at[f_idx].set(upd, mode="drop")
    value_n, has_n, wa_n, ws_n, wc_n = (
        regs[:, 0], regs[:, 1].astype(bool), regs[:, 2], regs[:, 3],
        regs[:, 4].astype(bool))

    slow = is_assign & ~fast
    # register state at each slow op's slot, post fast-path/insert writes
    # (a slot is never both fast- and slow-targeted: counts==1 gates fast)
    slow_info = jnp.stack([
        slow.astype(jnp.int32), tslot,
        value_n[tclip], has_n[tclip].astype(jnp.int32),
        wa_n[tclip], ws_n[tclip], wc_n[tclip].astype(jnp.int32)])
    return value_n, has_n, wa_n, ws_n, wc_n, slow_info


def _apply_map_round(
    # register tables, capacity K
    value, has_value, win_actor, win_seq, win_counter,
    # op columns, capacity M (padding: kind=-1, slot=out_cap)
    op_kind, op_slot, op_value, op_win_actor, op_win_seq,
    conflict_slots,
    *, out_cap: int,
):
    """One causally-ready round of map ops (set/del/inc on interned keys).

    The map analogue of `apply_residual` without inserts: key registers are
    dense slots, the LWW fast path handles single uncontended inline-int
    sets, and everything else (dels, incs, pooled values, multi-writer
    rounds, occupied registers) lands in the `slow` mask for host
    resolution — the reference's `applyAssign` partitioned the same way
    (/root/reference/backend/op_set.js:196-258, map branch)."""
    kind = op_kind.astype(jnp.int32)
    is_assign = (kind == KIND_SET) | (kind == KIND_DEL) | (kind == KIND_INC)

    value_n = _ext(value, 0, out_cap)
    has_n = _ext(has_value, False, out_cap)
    wa_n = _ext(win_actor, -1, out_cap)
    ws_n = _ext(win_seq, 0, out_cap)
    wc_n = _ext(win_counter, False, out_cap)
    return _register_fast_path(
        value_n, has_n, wa_n, ws_n, wc_n, kind, is_assign, op_slot,
        op_value, op_win_actor, op_win_seq, conflict_slots, out_cap)


apply_map_round = jax.jit(_apply_map_round, static_argnames=("out_cap",))


def _merge_and_materialize_dense(
    parent, ctr, actor, value, has_value, win_actor, win_seq, win_counter,
    chain, desc, blob, *, out_cap: int, S: int, as_u8: bool, L: int,
):
    """The common-case merge round END TO END in one device program:
    `expand_runs_dense_packed` (with fused chain breaks) followed by the
    codes-only materialization. One launch instead of two — launch/flush
    overhead is a measurable slice of the commit path on remote-attached
    chips, and XLA can overlap the phases' elementwise work.

    Returns the 9 updated tables + (codes, scalars). n_elems for the
    materialization comes from the descriptor META row (base_slot +
    n_run_elems - 1), so the call uploads nothing."""
    tables = expand_runs_dense_packed(
        parent, ctr, actor, value, has_value, win_actor, win_seq,
        win_counter, chain, desc, blob, out_cap=out_cap)
    n_elems = (desc[DESC_META, META_BASE_SLOT]
               + desc[DESC_META, META_N_ELEMS] - 1)
    cols = _slice_live((tables[0], tables[1], tables[2], tables[3],
                        tables[4], tables[8]), L)
    codes, scalars = _materialize_core(*cols, n_elems, S, with_pos=False,
                                       as_u8=as_u8)
    return tables + (codes, scalars)


merge_and_materialize_dense, merge_and_materialize_dense_donated = _jit_pair(
    _merge_and_materialize_dense, _TABLE_ARGNUMS,
    ("out_cap", "S", "as_u8", "L"))


@jax.jit
def remap_ranks(win_actor, remap):
    """Re-rank the winner-actor column after an interning order change."""
    hi = remap.shape[0] - 1
    return jnp.where(win_actor >= 0, remap[jnp.clip(win_actor, 0, hi)],
                     win_actor)


def _linearize_segments(parent, attach_off, ctr, actor, weight, valid):
    """Condensed-tree linearization (see ops/linearize.py for the
    derivation): per-parent children sort descending by (attach, ctr, actor),
    successor chain by pointer doubling, weighted list ranking."""
    import math
    n = parent.shape[0]
    steps = max(1, math.ceil(math.log2(max(2, n))))
    idx = jnp.arange(n, dtype=jnp.int32)
    is_seg = valid & (idx != 0)
    big = jnp.int32(n + 1)

    sort_parent = jnp.where(is_seg, parent, big)
    neg_off = jnp.where(is_seg, -attach_off, big)
    neg_ctr = jnp.where(is_seg, -ctr, big)
    neg_actor = jnp.where(is_seg, -actor, big)
    p_s, _, _, _, idx_s = jax.lax.sort(
        (sort_parent, neg_off, neg_ctr, neg_actor, idx), num_keys=4)

    in_group = p_s < big
    same_next = jnp.concatenate(
        [(p_s[1:] == p_s[:-1]) & in_group[1:], jnp.zeros(1, bool)])
    next_in_sorted = jnp.concatenate([idx_s[1:], jnp.full(1, -1, idx_s.dtype)])
    next_sib = jnp.full((n,), -1, jnp.int32)
    next_sib = next_sib.at[idx_s].set(jnp.where(same_next, next_in_sorted, -1))

    group_start = jnp.concatenate(
        [jnp.ones(1, bool), p_s[1:] != p_s[:-1]]) & in_group
    first_child = jnp.full((n,), -1, jnp.int32)
    first_child = first_child.at[jnp.where(group_start, p_s, big - 1)].set(
        jnp.where(group_start, idx_s, -1), mode="drop")

    has_next = next_sib >= 0
    safe_parent = jnp.where(is_seg, parent, 0)
    anc = jnp.where(has_next | (idx == 0), idx, safe_parent)
    anc = jax.lax.fori_loop(0, steps, lambda _, a: a[a], anc)

    succ = jnp.where(first_child >= 0, first_child, next_sib[anc])

    end = jnp.int32(n)
    nxt = jnp.where(succ >= 0, succ, end)
    nxt = jnp.where(is_seg | (idx == 0), nxt, idx)
    nxt = jnp.concatenate([nxt, jnp.full(1, end, jnp.int32)])
    dist = jnp.where(is_seg, weight, 0).astype(jnp.int32)
    dist = jnp.concatenate([dist, jnp.zeros(1, jnp.int32)])

    def rank_step(_, carry):
        d, nx = carry
        return d + d[nx], nx[nx]

    dist, nxt = jax.lax.fori_loop(0, steps + 1, rank_step, (dist, nxt))
    start = dist[0] - dist[:n]
    return jnp.where(is_seg, start, jnp.where(idx == 0, 0, big))


def _materialize_core(parent, ctr, actor, value, has_value, chain, n_elems,
                      S, with_pos, as_u8):
    """RGA positions + visible compaction from the maintained chain bits.

    Segments (maximal chain runs, contiguous in slot space) compact into S
    nodes (S is a static bucket >= n_segs+1, estimated by the host), the
    condensed tree linearizes in O(S log S), and element position = segment
    start + offset. Visible ranks come from one visibility prefix-sum in
    slot order plus a per-segment base computed in segment space — the
    device-native replacement for the reference skip list's index queries
    (/root/reference/backend/skip_list.js:260-305).
    """
    C = parent.shape[0]
    idx = jnp.arange(C, dtype=jnp.int32)
    is_elem = (idx >= 1) & (idx <= n_elems)
    seg_start = is_elem & ~chain
    vis = has_value & is_elem
    # one fused (2, C) prefix sum: segment ranks + inclusive visible counts
    two = jnp.cumsum(jnp.stack([seg_start.astype(jnp.int32),
                                vis.astype(jnp.int32)]), axis=1)
    rank_incl, cumvis = two[0], two[1]                   # node id per slot
    n_segs = rank_incl[-1]

    # head slot of segment k: rank_incl is non-decreasing and jumps to k at
    # the k-th segment start, so a binary search replaces the C-sized
    # scatter (scatter cost is per-INDEX: ~190M/s over all C slots on v5e;
    # this is S*log C gathers)
    sidx = jnp.arange(S, dtype=jnp.int32)
    heads = jnp.searchsorted(rank_incl, sidx, side="left").astype(jnp.int32)
    heads = jnp.clip(heads, 0, C - 1)

    # segment ranks are assigned in slot order, so heads is sorted by slot
    # and each segment's size is the gap to the next head
    valid = sidx <= n_segs
    live_seg = valid & (sidx >= 1)
    next_head = jnp.where((sidx + 1 <= n_segs) & (sidx + 1 < S),
                          heads[jnp.clip(sidx + 1, 0, S - 1)], n_elems + 1)

    p_slot = parent[heads]
    node_parent = rank_incl[p_slot]
    # attach offset of a parent slot inside its own segment, S-sized:
    # seg_head[p] == heads[rank_incl[p]]
    attach = p_slot - heads[jnp.clip(node_parent, 0, S - 1)]
    nctr = ctr[heads]
    nactor = actor[heads]
    weight = jnp.where(live_seg, next_head - heads, 0)
    starts = _linearize_segments(node_parent, attach, nctr, nactor, weight, valid)

    # visible ranking, segment-space: rank = (visible in segments placed
    # earlier) + (visible before me inside my segment)
    n_vis = cumvis[C - 1]
    head_pre = cumvis[heads] - vis[heads].astype(jnp.int32)
    last = jnp.clip(next_head - 1, 0, C - 1)
    seg_vis = jnp.where(live_seg, cumvis[last] - head_pre, 0)

    big = jnp.int32(C + 2)
    order_key = jnp.where(live_seg, starts, big)
    _, perm = jax.lax.sort((order_key, sidx), num_keys=1)
    sv_perm = seg_vis[perm]
    base_perm = jnp.cumsum(sv_perm) - sv_perm            # exclusive, by pos
    rank_base = jnp.zeros(S, jnp.int32).at[perm].set(base_perm)
    seg_base = rank_base - head_pre                      # one combined table

    # expand S-space tables to slot space GATHER-FREE: `rank_incl` is
    # non-decreasing, so table[rank_incl] is piecewise constant with jumps
    # at segment heads — scatter per-segment deltas at head slots (S-sized)
    # and prefix-sum, instead of a C-sized gather (~140M elem/s on v5e vs
    # vector-rate cumsum). Segment k covers slots [heads[k], heads[k+1]);
    # slots before heads[1] (the head slot 0) read 0, and are never visible.
    def expand_S(table):
        prev = jnp.concatenate([jnp.zeros(1, table.dtype), table[:-1]])
        d = jnp.where(sidx == 1, table, table - prev)
        tgt = jnp.where(live_seg, heads, C)
        return jnp.zeros(C, table.dtype).at[tgt].set(d, mode="drop")

    if with_pos:
        d3 = jnp.stack([expand_S(seg_base), expand_S(starts),
                        expand_S(heads)])
        exp = jnp.cumsum(d3, axis=1)
        sb_exp, starts_exp, seg_head_exp = exp[0], exp[1], exp[2]
    else:
        sb_exp = jnp.cumsum(expand_S(seg_base))
        starts_exp = seg_head_exp = None
    vis_rank = sb_exp + cumvis - vis.astype(jnp.int32)

    if as_u8:
        # known-7-bit documents scatter 1-byte codes: 4x less HBM traffic
        # on the scatter AND 4x less device->host transfer
        codes = jnp.zeros(C, jnp.uint8).at[
            jnp.where(vis, vis_rank, C)].set(
            value.astype(jnp.uint8), mode="drop")
    else:
        codes = jnp.full(C, -1, value.dtype).at[
            jnp.where(vis, vis_rank, C)].set(value, mode="drop")
    scalars = jnp.stack([n_vis, n_segs])   # one packed scalar fetch

    if with_pos:
        pos = jnp.where(is_elem, starts_exp + (idx - seg_head_exp),
                        jnp.where(idx == 0, -1, C + 1))
        return pos, codes, scalars
    return codes, scalars


# Odd 32-bit mixing constants (Knuth golden-ratio / murmur3) for the
# plan-consistency hashes. The per-element mix must be NONLINEAR before the
# sum reduce: a purely multiplicative hash is linear, so any divergence that
# preserves the plain sum (e.g. heads {3,5} vs {2,6}) also preserves
# sum(K*h). The xorshift stages break that cancellation.
# engine/segments.SegmentMirror.{head_checksum,aux_checksum} are the numpy
# twins of `_mix32` — both run the identical uint32-wrapping pipeline.
HASH_K1 = np.uint32(2654435761)   # 0x9E3779B1
HASH_K2 = np.uint32(2246822519)   # 0x85EBCA77
HASH_K3 = np.uint32(3266489917)   # 0xC2B2AE3D
HASH_K4 = np.uint32(668265263)    # 0x27D4EB2F


def _mix32(x):
    """murmur3-fmix-style nonlinear 32-bit mix (device); uint32 wrapping."""
    x = x.astype(jnp.uint32) * HASH_K1
    x = x ^ (x >> 15)
    x = x * HASH_K2
    x = x ^ (x >> 13)
    return x


def mix32_np(x: np.ndarray) -> np.ndarray:
    """Host twin of `_mix32` — identical uint32 pipeline in numpy."""
    x = x.astype(np.uint32) * HASH_K1
    x = x ^ (x >> np.uint32(15))
    x = x * HASH_K2
    x = x ^ (x >> np.uint32(13))
    return x


def _materialize_core_planned(parent, ctr, actor, value, has_value, chain,
                              n_elems, segplan, S, with_pos, as_u8):
    """Materialization with HOST-PLANNED segment structure.

    `segplan` is the (4, S) int32 matrix from
    engine/segments.SegmentMirror.plan(): [head slots, position->segment
    permutation, segment starts, meta(n_segs)]. The host already knows the
    chain/segment structure it staged (every head is a planned run head,
    residual insert, or chain break), so the structural S-stage of
    `_materialize_core` — the 4-key sort, the pointer-doubling
    linearization, and the head searchsorted — disappears from the device
    program. What remains is inherently data-dependent: the visibility
    prefix sum, the S->slot expansion sum, and the codes scatter.

    Trust but verify: the kernel re-derives, from the REAL chain bits, the
    segment count plus TWO int32-wrapping mixing hashes — one over the head
    slots themselves, one over the heads' (parent slot, ctr, actor) columns,
    which fully determine the linearization order — and returns them in the
    scalars. The engine compares them against the mirror at its scalar sync
    and self-heals through the self-contained kernel on mismatch
    (engine/text_doc.DeviceTextDoc._scalars). Multiplicative mixing (Knuth/
    murmur odd constants) makes a divergence that preserves count AND both
    hashes implausible — a plain count+sum check would pass head-set swaps
    like {3,5} vs {2,6}."""
    C = value.shape[0]
    idx = jnp.arange(C, dtype=jnp.int32)
    is_elem = (idx >= 1) & (idx <= n_elems)
    vis = has_value & is_elem
    cumvis = jnp.cumsum(vis.astype(jnp.int32))
    n_vis = cumvis[C - 1]

    heads_raw = segplan[0]
    heads = jnp.clip(heads_raw, 0, C - 1)
    perm = segplan[1]
    n_segs = segplan[3, 0]
    sidx = jnp.arange(S, dtype=jnp.int32)
    live_seg = (sidx >= 1) & (sidx <= n_segs)

    next_head = jnp.where((sidx + 1 <= n_segs) & (sidx + 1 < S),
                          heads_raw[jnp.clip(sidx + 1, 0, S - 1)],
                          n_elems + 1)
    head_pre = cumvis[heads] - vis[heads].astype(jnp.int32)
    last = jnp.clip(next_head - 1, 0, C - 1)
    seg_vis = jnp.where(live_seg, cumvis[last] - head_pre, 0)

    sv_perm = seg_vis[perm]
    base_perm = jnp.cumsum(sv_perm) - sv_perm          # exclusive, by pos
    rank_base = jnp.zeros(S, jnp.int32).at[perm].set(base_perm)
    seg_base = rank_base - head_pre

    def expand_S(table):
        prev = jnp.concatenate([jnp.zeros(1, table.dtype), table[:-1]])
        d = jnp.where(sidx == 1, table, table - prev)
        tgt = jnp.where(live_seg, heads, C)
        return jnp.zeros(C, table.dtype).at[tgt].set(d, mode="drop")

    if with_pos:
        starts = segplan[2]
        d3 = jnp.stack([expand_S(seg_base), expand_S(starts),
                        expand_S(heads)])
        exp = jnp.cumsum(d3, axis=1)
        sb_exp, starts_exp, seg_head_exp = exp[0], exp[1], exp[2]
    else:
        sb_exp = jnp.cumsum(expand_S(seg_base))
        starts_exp = seg_head_exp = None
    vis_rank = sb_exp + cumvis - vis.astype(jnp.int32)

    if as_u8:
        codes = jnp.zeros(C, jnp.uint8).at[
            jnp.where(vis, vis_rank, C)].set(
            value.astype(jnp.uint8), mode="drop")
    else:
        codes = jnp.full(C, -1, value.dtype).at[
            jnp.where(vis, vis_rank, C)].set(value, mode="drop")

    # plan-consistency scalars from the real chain bits: cheap reduces with
    # a NONLINEAR per-element mix (uint32, wraps deterministically), so
    # divergences cannot cancel in the sum
    seg_start = is_elem & ~chain
    n_segs_dev = jnp.sum(seg_start.astype(jnp.int32))
    head_hash_dev = jax.lax.bitcast_convert_type(
        jnp.sum(jnp.where(seg_start, _mix32(idx), jnp.uint32(0))),
        jnp.int32)
    aux_key = (parent.astype(jnp.uint32) * HASH_K2
               + ctr.astype(jnp.uint32) * HASH_K3
               + actor.astype(jnp.uint32) * HASH_K4)
    aux_hash_dev = jax.lax.bitcast_convert_type(
        jnp.sum(jnp.where(seg_start, _mix32(aux_key + idx.astype(jnp.uint32)),
                          jnp.uint32(0))),
        jnp.int32)
    scalars = jnp.stack([n_vis, n_segs, n_segs_dev, head_hash_dev,
                         aux_hash_dev])

    if with_pos:
        pos = jnp.where(is_elem, starts_exp + (idx - seg_head_exp),
                        jnp.where(idx == 0, -1, C + 1))
        return pos, codes, scalars
    return codes, scalars


@partial(jax.jit, static_argnames=("S", "as_u8", "L"))
def materialize_text_planned(parent, ctr, actor, value, has_value, chain,
                             n_elems, segplan,
                             *, S: int, as_u8: bool = False, L: int = None):
    """`materialize_text` with host-planned segment structure (see
    `_materialize_core_planned`). parent/ctr/actor feed only the
    plan-consistency hash reduces, not the linearization."""
    cols = _slice_live((parent, ctr, actor, value, has_value, chain), L)
    return _materialize_core_planned(*cols, n_elems, segplan, S,
                                     with_pos=True, as_u8=as_u8)


@partial(jax.jit, static_argnames=("S", "as_u8", "L"))
def materialize_codes_planned(parent, ctr, actor, value, has_value, chain,
                              n_elems, segplan,
                              *, S: int, as_u8: bool = False, L: int = None):
    """`materialize_codes` with host-planned segment structure."""
    cols = _slice_live((parent, ctr, actor, value, has_value, chain), L)
    return _materialize_core_planned(*cols, n_elems, segplan, S,
                                     with_pos=False, as_u8=as_u8)


def _merge_and_materialize_dense_planned(
    parent, ctr, actor, value, has_value, win_actor, win_seq, win_counter,
    chain, desc, blob, segplan, *, out_cap: int, S: int, as_u8: bool, L: int,
):
    """`merge_and_materialize_dense` with the materialization's segment
    structure staged from the host plan: the whole common-case merge round
    is ONE device program containing no sort and no pointer doubling."""
    tables = expand_runs_dense_packed(
        parent, ctr, actor, value, has_value, win_actor, win_seq,
        win_counter, chain, desc, blob, out_cap=out_cap)
    n_elems = (desc[DESC_META, META_BASE_SLOT]
               + desc[DESC_META, META_N_ELEMS] - 1)
    cols = _slice_live((tables[0], tables[1], tables[2], tables[3],
                        tables[4], tables[8]), L)
    codes, scalars = _materialize_core_planned(
        *cols, n_elems, segplan, S, with_pos=False, as_u8=as_u8)
    return tables + (codes, scalars)


(merge_and_materialize_dense_planned,
 merge_and_materialize_dense_planned_donated) = _jit_pair(
    _merge_and_materialize_dense_planned, _TABLE_ARGNUMS,
    ("out_cap", "S", "as_u8", "L"))


def _slice_live(cols, L):
    """Restrict the element columns to the live-window bucket `L` (static):
    table capacity can exceed the live prefix by up to 50%, and every pass
    in the materialize kernel scales with operand length."""
    if L is None or L >= cols[0].shape[0]:
        return cols
    return tuple(c[:L] for c in cols)


@partial(jax.jit, static_argnames=("S", "L"))
def segment_visible_counts(has_value, n_elems, segplan,
                           *, S: int, L: int = None):
    """Per-segment VISIBLE character counts — the dirty-span descriptor
    feed for the incremental text pull (engine/text_doc.DeviceTextDoc
    `_text_incremental`).

    The host mirror knows the segment structure exactly (heads, order,
    positions: engine/segments.SegmentMirror) but visibility is data the
    device owns, so an incremental pull fetches this one S-sized row —
    tens of KB — instead of the whole O(doc) codes buffer, and the host
    derives every changed span's [visible start, length) from it. Same
    seg_vis formulation as `_materialize_core_planned`; `segplan` is the
    mirror's packed plan (row 0 = head slots, row 3 meta[0] = n_segs)."""
    hv = _slice_live((has_value,), L)[0]
    C = hv.shape[0]
    idx = jnp.arange(C, dtype=jnp.int32)
    vis = hv & (idx >= 1) & (idx <= n_elems)
    cumvis = jnp.cumsum(vis.astype(jnp.int32))
    heads_raw = segplan[0]
    n_segs = segplan[3, 0]
    sidx = jnp.arange(S, dtype=jnp.int32)
    live_seg = (sidx >= 1) & (sidx <= n_segs)
    heads = jnp.clip(heads_raw, 0, C - 1)
    next_head = jnp.where((sidx + 1 <= n_segs) & (sidx + 1 < S),
                          heads_raw[jnp.clip(sidx + 1, 0, S - 1)],
                          n_elems + 1)
    head_pre = cumvis[heads] - vis[heads].astype(jnp.int32)
    last = jnp.clip(next_head - 1, 0, C - 1)
    return jnp.where(live_seg, cumvis[last] - head_pre, 0)


@partial(jax.jit, static_argnames=("S", "as_u8", "L"))
def materialize_text(parent, ctr, actor, value, has_value, chain, n_elems,
                     *, S: int, as_u8: bool = False, L: int = None):
    """Full materialization: (pos, codes, [n_vis, n_segs]). `pos` includes
    tombstones (head = -1, padding > n); `codes` is visible values scattered
    into list order (uint8 when `as_u8` — the host tracks 7-bit-ness). The
    host retries with a bigger S when n_segs+1 > S."""
    cols = _slice_live((parent, ctr, actor, value, has_value, chain), L)
    return _materialize_core(*cols, n_elems, S, with_pos=True, as_u8=as_u8)


@partial(jax.jit, static_argnames=("S", "as_u8", "L"))
def materialize_codes(parent, ctr, actor, value, has_value, chain, n_elems,
                      *, S: int, as_u8: bool = False, L: int = None):
    """Codes-only materialization for `text()`: skips the per-element
    position gather."""
    cols = _slice_live((parent, ctr, actor, value, has_value, chain), L)
    return _materialize_core(*cols, n_elems, S, with_pos=False, as_u8=as_u8)


@jax.jit
def remap_actors(actor, win_actor, remap, n_elems):
    """Re-rank actor ids after interning breaks lexicographic rank order.

    Rare: only when a new actor id sorts before an existing one. The host
    remaps its range index separately (host_index.ElemRangeIndex.remap)."""
    C = actor.shape[0]
    idx = jnp.arange(C, dtype=jnp.int32)
    live = (idx >= 1) & (idx <= n_elems)
    hi = remap.shape[0] - 1
    actor_n = jnp.where(live, remap[jnp.clip(actor, 0, hi)], actor)
    wa_n = jnp.where(win_actor >= 0, remap[jnp.clip(win_actor, 0, hi)],
                     win_actor)
    return actor_n, wa_n


@jax.jit
def pack_rows(*arrays):
    """Stack same-length device arrays into one int32 matrix: the host
    mirror fetch becomes a single device->host transfer (RTT-bound on
    remote-attached chips)."""
    return jnp.stack([a.astype(jnp.int32) for a in arrays])


@jax.jit
def scatter_registers(value, has_value, win_actor, win_seq, win_counter,
                      slots, v, h, wa, ws, wc):
    """Write back host-resolved registers (OOB sentinel slots drop).

    LEGACY per-column upload shape: six separate host arrays, each a
    distinct h2d transfer paying per-transfer link latency. Kept as the
    parity comparator for `scatter_registers_packed`
    (tests/test_dispatch_budget.py) and selectable via
    ``CausalDeviceDoc.packed_residual_writeback = False``."""
    return (value.at[slots].set(v, mode="drop"),
            has_value.at[slots].set(h, mode="drop"),
            win_actor.at[slots].set(wa, mode="drop"),
            win_seq.at[slots].set(ws, mode="drop"),
            win_counter.at[slots].set(wc, mode="drop"))


# Packed-writeback row layout for scatter_registers_packed: one (6, S)
# int32 host->device transfer replaces the six separate arrays above —
# with the packed (7, M) slow_info fetch, the whole host slow-register
# residue costs exactly ONE d2h round trip + ONE h2d upload per round.
WB_SLOT, WB_VALUE, WB_HAS, WB_WIN_ACTOR, WB_WIN_SEQ, WB_WIN_COUNTER = \
    range(6)


def _scatter_registers_packed(value, has_value, win_actor, win_seq,
                              win_counter, wb):
    """`scatter_registers` with the resolved rows packed as one (6, S)
    int32 matrix (row layout: WB_*; padding rows carry an OOB slot)."""
    slots = wb[WB_SLOT]
    return (value.at[slots].set(wb[WB_VALUE], mode="drop"),
            has_value.at[slots].set(wb[WB_HAS].astype(bool), mode="drop"),
            win_actor.at[slots].set(wb[WB_WIN_ACTOR], mode="drop"),
            win_seq.at[slots].set(wb[WB_WIN_SEQ], mode="drop"),
            win_counter.at[slots].set(wb[WB_WIN_COUNTER].astype(bool),
                                      mode="drop"))


scatter_registers_packed, scatter_registers_packed_donated = _jit_pair(
    _scatter_registers_packed, _REG_ARGNUMS)


# ---------------------------------------------------------------------------
# Stacked multi-object rounds (engine/stacked.py; INTERNALS §12)
#
# The nested-document production shape is MANY SMALL objects: a Trellis
# board fans one causal round across ~21 per-object engine docs, and the
# per-(object, round) programs plus their h2d staging dominate the merge
# (docs/MEASUREMENTS.md, cfg4 profile). These kernels execute one causal
# round across EVERY participating object as a constant number of
# programs: per-object tables pad to a common capacity and stack along a
# leading doc axis, and the existing round kernels run under `jax.vmap` —
# the padded-stack shape the DocSet tier already uses for homogeneous
# text docs (engine/doc_set.py), generalized to the mixed map/text
# workload. Padded stacking was chosen over a doc-id column in shared
# flat tables because the run-expansion kernels write one contiguous
# slot window per document (`expand_runs_dense`'s base_slot contract),
# which a doc-id column cannot express without per-doc windows; vmap
# keeps every doc's slot space intact and the kernels unchanged.
# ---------------------------------------------------------------------------

# fill values per table column when padding to the common stacked width
_REG_FILLS = (0, False, -1, 0, False)
_ELEM_FILLS = (0, 0, 0, 0, False, -1, 0, False, False)

# row layout of the packed (D, 5, M) stacked map-op upload
MOP_KIND, MOP_SLOT, MOP_VALUE, MOP_WIN_ACTOR, MOP_WIN_SEQ = range(5)


def _stack_padded(tables, fills, out_cap):
    return tuple(
        jnp.stack([_ext(doc[k], fills[k], out_cap) for doc in tables])
        for k in range(len(fills)))


def _stack_register_tables(tables, remaps, *, out_cap: int):
    """Per-doc register tables -> stacked (D, out_cap) columns.

    `tables` is a tuple of per-doc 5-tuples (value, has_value, win_actor,
    win_seq, win_counter); `remaps` a (D, L) int32 matrix of pending
    actor-rank remaps (identity rows for unaffected docs), folded into
    the gather so a reordering intern costs zero extra programs instead
    of one `remap_ranks` dispatch per document."""
    value, has_value, win_actor, win_seq, win_counter = _stack_padded(
        tables, _REG_FILLS, out_cap)
    hi = remaps.shape[1] - 1
    win_actor = jnp.where(
        win_actor >= 0,
        jnp.take_along_axis(remaps, jnp.clip(win_actor, 0, hi), axis=1),
        win_actor)
    return value, has_value, win_actor, win_seq, win_counter


stack_register_tables = jax.jit(_stack_register_tables,
                                static_argnames=("out_cap",))


def _stack_element_tables(tables, remaps, n_elems, *, out_cap: int):
    """Per-doc element tables -> stacked (D, out_cap) columns with each
    doc's pending actor-rank remap folded in (`remap_actors` semantics
    per row: live slots 1..n_elems re-rank `actor`, any slot re-ranks a
    non-negative `win_actor`)."""
    (parent, ctr, actor, value, has_value, win_actor, win_seq,
     win_counter, chain) = _stack_padded(tables, _ELEM_FILLS, out_cap)
    hi = remaps.shape[1] - 1
    idx = jnp.arange(out_cap, dtype=jnp.int32)[None, :]
    live = (idx >= 1) & (idx <= n_elems[:, None])
    actor = jnp.where(live, jnp.take_along_axis(
        remaps, jnp.clip(actor, 0, hi), axis=1), actor)
    win_actor = jnp.where(win_actor >= 0, jnp.take_along_axis(
        remaps, jnp.clip(win_actor, 0, hi), axis=1), win_actor)
    return (parent, ctr, actor, value, has_value, win_actor, win_seq,
            win_counter, chain)


stack_element_tables = jax.jit(_stack_element_tables,
                               static_argnames=("out_cap",))


@partial(jax.jit, static_argnames=("out_cap",))
def stacked_map_round(value, has_value, win_actor, win_seq, win_counter,
                      ops, conflict_slots, *, out_cap: int):
    """`apply_map_round` vmapped over the doc axis: one program merges
    one causal round of EVERY participating map/table object. `ops`
    carries the whole round's op columns as one (D, 5, M) int32 upload
    (MOP_* rows; padding kind=-1, slot=out_cap), `conflict_slots` one
    (D, K) matrix. Returns the 5 stacked tables + (D, 7, M) slow_info."""
    def one(v, h, wa, ws, wc, o, cs):
        return _apply_map_round(
            v, h, wa, ws, wc, o[MOP_KIND].astype(jnp.int8), o[MOP_SLOT],
            o[MOP_VALUE], o[MOP_WIN_ACTOR], o[MOP_WIN_SEQ], cs,
            out_cap=out_cap)
    return jax.vmap(one)(value, has_value, win_actor, win_seq,
                         win_counter, ops, conflict_slots)


@partial(jax.jit,
         static_argnames=("out_cap", "expand_kind", "with_res",
                          "with_touch"))
def stacked_mixed_round(parent, ctr, actor, value, has_value, win_actor,
                        win_seq, win_counter, chain, desc, blob, res,
                        conflict_slots, touch, *, out_cap: int,
                        expand_kind: str, with_res: bool,
                        with_touch: bool):
    """`apply_mixed_round` vmapped over the doc axis: one program for one
    causal round of every text/list object sharing the group's static
    shape flags. Stacked operands: desc (D, 9, R), blob (D, N), res
    (D, 8, M), conflict_slots (D, K), touch (D, 3, T). Inactive docs
    ride with padding rows — their dense write window lands past their
    live region, exactly the DocSet convention (engine/doc_set.py)."""
    fn = partial(_apply_mixed_round, out_cap=out_cap,
                 expand_kind=expand_kind, with_res=with_res,
                 with_touch=with_touch)
    return jax.vmap(fn)(parent, ctr, actor, value, has_value, win_actor,
                        win_seq, win_counter, chain, desc, blob, res,
                        conflict_slots, touch)


@jax.jit
def stacked_scatter_registers(value, has_value, win_actor, win_seq,
                              win_counter, wb):
    """`scatter_registers_packed` vmapped over the doc axis: every doc's
    host-resolved slow-register writeback lands as ONE (D, 6, S) upload
    + one program (padding rows carry an OOB slot and drop)."""
    return jax.vmap(_scatter_registers_packed)(
        value, has_value, win_actor, win_seq, win_counter, wb)


@jax.jit
def stacked_pack_rows(*tables):
    """vmapped `pack_rows`: stacked (D, cap) columns -> one (D, K, cap)
    int32 matrix, so ONE d2h fetch re-seeds every participating doc's
    host mirror after a stacked apply."""
    return jnp.stack([t.astype(jnp.int32) for t in tables], axis=1)


@jax.jit
def unstack_rows(cols):
    """Split stacked (D, cap) columns back into per-doc row tuples — one
    program with D x K outputs, so re-binding every doc's tables after a
    stacked apply costs one dispatch, not one slice per (doc, table)."""
    D = cols[0].shape[0]
    return tuple(tuple(c[d] for c in cols) for d in range(D))


# ---------------------------------------------------------------------------
# Device-truth registry (obs/device_truth.py; INTERNALS §19)
#
# Every kernel the engine DISPATCHES (the module attributes the labeled
# `_count_dispatch` sites launch) is re-bound to an instrumented handle:
# one ~60 ns cache-size probe per launch detects compile events (wall
# time + shape signature + default device), and the registry lazily
# captures XLA cost/memory analysis once per compiled executable. The
# building-block kernels that only ever run INSIDE fused programs
# (expand_runs*, break_chains*, apply_residual*) are deliberately NOT
# wrapped — they never launch on their own from the engine, and wrapping
# them would record phantom compile events during the fused kernels'
# traces. Call sites are unchanged: the handles ARE the module
# attributes everyone already imports.
# ---------------------------------------------------------------------------

from ..obs import device_truth as _device_truth  # noqa: E402

apply_mixed_round, apply_mixed_round_donated = \
    _device_truth.instrument_pair(
        (apply_mixed_round, apply_mixed_round_donated), "apply_mixed_round")
apply_map_round = _device_truth.instrument(apply_map_round,
                                           "apply_map_round")
merge_and_materialize_dense, merge_and_materialize_dense_donated = \
    _device_truth.instrument_pair(
        (merge_and_materialize_dense, merge_and_materialize_dense_donated),
        "merge_and_materialize_dense")
(merge_and_materialize_dense_planned,
 merge_and_materialize_dense_planned_donated) = \
    _device_truth.instrument_pair(
        (merge_and_materialize_dense_planned,
         merge_and_materialize_dense_planned_donated),
        "merge_and_materialize_dense_planned")
scatter_registers = _device_truth.instrument(scatter_registers,
                                             "scatter_registers")
scatter_registers_packed, scatter_registers_packed_donated = \
    _device_truth.instrument_pair(
        (scatter_registers_packed, scatter_registers_packed_donated),
        "scatter_registers_packed")
pack_rows = _device_truth.instrument(pack_rows, "pack_rows")
remap_ranks = _device_truth.instrument(remap_ranks, "remap_ranks")
remap_actors = _device_truth.instrument(remap_actors, "remap_actors")
materialize_text = _device_truth.instrument(materialize_text,
                                            "materialize_text")
materialize_codes = _device_truth.instrument(materialize_codes,
                                             "materialize_codes")
materialize_text_planned = _device_truth.instrument(
    materialize_text_planned, "materialize_text_planned")
materialize_codes_planned = _device_truth.instrument(
    materialize_codes_planned, "materialize_codes_planned")
segment_visible_counts = _device_truth.instrument(
    segment_visible_counts, "segment_visible_counts")
stack_register_tables = _device_truth.instrument(
    stack_register_tables, "stack_register_tables")
stack_element_tables = _device_truth.instrument(
    stack_element_tables, "stack_element_tables")
stacked_map_round = _device_truth.instrument(stacked_map_round,
                                             "stacked_map_round")
stacked_mixed_round = _device_truth.instrument(stacked_mixed_round,
                                               "stacked_mixed_round")
stacked_scatter_registers = _device_truth.instrument(
    stacked_scatter_registers, "stacked_scatter_registers")
stacked_pack_rows = _device_truth.instrument(stacked_pack_rows,
                                             "stacked_pack_rows")
unstack_rows = _device_truth.instrument(unstack_rows, "unstack_rows")

"""Native C++ wire codec vs the Python decoder: identical columnar batches.

The native tier is optional — tests skip when no toolchain is available —
but when it builds, every in-scope payload must decode bit-identically to
`TextChangeBatch.from_changes`, and out-of-scope payloads must fall back.
"""

import json

import numpy as np
import pytest

from automerge_tpu.engine import DeviceTextDoc, TextChangeBatch
from automerge_tpu import native


def typing_change(actor, seq, text, start=1, after="_head", deps=None,
                  obj="t", message=None):
    ops = []
    key = after
    for i, c in enumerate(text):
        ops += [{"action": "ins", "obj": obj, "key": key, "elem": start + i},
                {"action": "set", "obj": obj, "key": f"{actor}:{start+i}",
                 "value": c}]
        key = f"{actor}:{start+i}"
    ch = {"actor": actor, "seq": seq, "deps": deps or {}, "ops": ops}
    if message is not None:
        ch["message"] = message
    return ch


def assert_batches_equal(a: TextChangeBatch, b: TextChangeBatch):
    assert a.actors == b.actors
    assert a.actor_table == b.actor_table
    assert a.deps == b.deps
    assert a.messages == b.messages
    np.testing.assert_array_equal(a.seqs, b.seqs)
    for f in ("op_change", "op_kind", "op_target_actor", "op_target_ctr",
              "op_parent_actor", "op_parent_ctr", "op_value"):
        np.testing.assert_array_equal(getattr(a, f), getattr(b, f), f)


needs_native = pytest.mark.skipif(not native.available(),
                                  reason="native toolchain unavailable")


@needs_native
def test_parity_typing():
    changes = [typing_change("alice", 1, "hello world", message="hi\nthere"),
               typing_change("bob", 1, "né±漢🎉", start=1,
                             deps={"alice": 1}),
               {"actor": "bob", "seq": 2, "deps": {}, "ops": [
                   {"action": "del", "obj": "t", "key": "alice:2"},
                   {"action": "ins", "obj": "t", "key": "bob:1", "elem": 99},
                   {"action": "set", "obj": "t", "key": "bob:99",
                    "value": "é"}]}]
    payload = json.dumps(changes)
    fast = native.decode_text_changes(payload, "t")
    assert fast is not None
    slow = TextChangeBatch.from_changes(changes, "t")
    assert_batches_equal(fast, slow)


@needs_native
def test_engine_accepts_native_batch():
    changes = [typing_change("w", 1, "native!")]
    batch = TextChangeBatch.from_json(json.dumps(changes), "t")
    doc = DeviceTextDoc("t").apply_batch(batch)
    assert doc.text() == "native!"


@needs_native
def test_out_of_scope_falls_back():
    # rich (multi-char) value -> native returns None, from_json still works
    changes = [{"actor": "a", "seq": 1, "deps": {}, "ops": [
        {"action": "ins", "obj": "t", "key": "_head", "elem": 1},
        {"action": "set", "obj": "t", "key": "a:1", "value": "multi-char"}]}]
    assert native.decode_text_changes(json.dumps(changes), "t") is None
    batch = TextChangeBatch.from_json(json.dumps(changes), "t")
    assert batch.value_pool[0]["value"] == "multi-char"


@needs_native
def test_escapes_and_unicode():
    changes = [{"actor": "aé", "seq": 1, "deps": {}, "ops": [
        {"action": "ins", "obj": "t", "key": "_head", "elem": 1},
        {"action": "set", "obj": "t", "key": "aé:1",
         "value": "🎉"}]}]  # surrogate-pair emoji
    payload = json.dumps(changes)
    fast = native.decode_text_changes(payload, "t")
    slow = TextChangeBatch.from_changes(json.loads(payload), "t")
    assert fast is not None
    assert_batches_equal(fast, slow)


@needs_native
def test_pretty_printed_payload():
    """Whitespace/indentation in the wire JSON must not break decoding."""
    changes = [typing_change("alice", 1, "hi"),
               typing_change("bob", 1, "yo", deps={"alice": 1})]
    pretty = json.dumps(changes, indent=2)
    fast = native.decode_text_changes(pretty, "t")
    slow = TextChangeBatch.from_changes(changes, "t")
    assert fast is not None
    assert_batches_equal(fast, slow)


@needs_native
def test_newline_actor_falls_back():
    changes = [{"actor": "a\nb", "seq": 1, "deps": {}, "ops": []}]
    assert native.decode_text_changes(json.dumps(changes), "t") is None
    assert TextChangeBatch.from_json(json.dumps(changes), "t").actors == ["a\nb"]


@needs_native
def test_decode_speed_sanity():
    """The native decoder should beat the Python loop comfortably."""
    import time
    changes = [typing_change(f"actor-{a}", 1, "x" * 500)
               for a in range(20)]
    payload = json.dumps(changes)
    t0 = time.perf_counter()
    fast = native.decode_text_changes(payload, "t")
    t_native = time.perf_counter() - t0
    t0 = time.perf_counter()
    slow = TextChangeBatch.from_changes(json.loads(payload), "t")
    t_python = time.perf_counter() - t0
    assert_batches_equal(fast, slow)
    assert t_native < t_python  # typically 20-100x

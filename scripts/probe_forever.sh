#!/bin/bash
# Keep probing the TPU tunnel for the whole round. Launch DETACHED
# (setsid nohup) so the harness's 600 s background-task cap can't kill it:
#
#   setsid nohup bash scripts/probe_forever.sh > /tmp/probe_forever.log 2>&1 &
#
# Each iteration delegates to probe_loop.sh (which holds the single-client
# chip lock while probing and auto-launches chip_session.sh on success).
# chip_session.log is append-only across rounds, so completion/failure
# markers are counted RELATIVE TO LAUNCH — a marker from a previous round
# must not stop this round's probing. The loop stops when, since launch:
#   - a chip session COMPLETED (endless relaunching would hold the chip), or
#   - a session failed its on-chip smoke (deterministic test failure:
#     relaunching the identical doomed session would hold the chip forever;
#     a human/agent must look at the log first).
# A session that dies mid-run from a tunnel drop leaves neither marker and
# is retried.
REPO="$(cd "$(dirname "$0")/.." && pwd)"
LOG="$REPO/scripts/chip_session.log"
DONE_MARK="=== chip session done"
FAIL_MARK="on-chip smoke FAILED"

count() {  # occurrences of $1 in the session log (0 if no log yet)
  if [ -f "$LOG" ]; then grep -c "$1" "$LOG" || true; else echo 0; fi
}
done0=$(count "$DONE_MARK")
fail0=$(count "$FAIL_MARK")

while true; do
  if [ "$(count "$DONE_MARK")" -gt "$done0" ]; then
    echo "chip session completed; probe_forever exiting ($(date +%H:%M:%S))"
    exit 0
  fi
  if [ "$(count "$FAIL_MARK")" -gt "$fail0" ]; then
    echo "on-chip smoke FAILED (deterministic); not relaunching — inspect $LOG ($(date +%H:%M:%S))"
    exit 4
  fi
  bash "$REPO/scripts/probe_loop.sh"
  sleep 45
done

"""Mesh-sharded batched merge on the 8-device virtual CPU mesh."""

import numpy as np
import pytest


from automerge_tpu.parallel.mesh import example_doc_tables as doc_tables


def typing_run(actor, seq, deps, text, ctr0, parent):
    """A change typing `text` as one ins+set run (engine wire format)."""
    ops = []
    for i, ch in enumerate(text):
        c = ctr0 + i
        key = "_head" if (i == 0 and parent == "_head") else (
            parent if i == 0 else f"{actor}:{c - 1}")
        ops.append({"action": "ins", "obj": "t", "key": key, "elem": c})
        ops.append({"action": "set", "obj": "t", "key": f"{actor}:{c}",
                    "value": chr(97 + (i + ctr0) % 26)})
    return {"actor": actor, "seq": seq, "deps": deps, "ops": ops}


def reference_order(parent, ctr, actor, valid, visible, values):
    """Sequential RGA materialization for one doc (host shadow model)."""
    n = len(parent)
    children = {i: [] for i in range(n)}
    for i in range(1, n):
        if valid[i]:
            children[parent[i]].append(i)
    for lst in children.values():
        lst.sort(key=lambda i: (ctr[i], actor[i]), reverse=True)
    out = []

    def dfs(i):
        for c in children[i]:
            if visible[c]:
                out.append(values[c])
            dfs(c)
    dfs(0)
    return out


def test_batched_merge_matches_shadow_model():
    from automerge_tpu.parallel import batched_merge_step
    tables = doc_tables(6, 32, seed=1)
    pos, out, n_vis = batched_merge_step(*[np.asarray(t) for t in tables])
    out = np.asarray(out)
    for d in range(6):
        expected = reference_order(*[t[d] for t in tables])
        got = [v for v in out[d] if v >= 0]
        assert got == expected, f"doc {d}"
        assert int(n_vis[d]) == len(expected)


def test_sharded_merge_on_virtual_mesh():
    import jax
    from automerge_tpu.parallel import make_mesh, sharded_merge_step, batched_merge_step
    if len(jax.devices()) < 2:
        pytest.skip("needs multiple devices")
    mesh = make_mesh()
    n_docs = mesh.shape["doc"] * 2
    cap = mesh.shape["elem"] * 16
    tables = doc_tables(n_docs, cap, seed=2)
    pos_s, out_s, nvis_s = sharded_merge_step(mesh, *tables)
    pos_b, out_b, nvis_b = batched_merge_step(*[np.asarray(t) for t in tables])
    assert np.array_equal(np.asarray(pos_s), np.asarray(pos_b))
    assert np.array_equal(np.asarray(out_s), np.asarray(out_b))
    assert np.array_equal(np.asarray(nvis_s), np.asarray(nvis_b))
    # outputs actually live sharded across the mesh
    assert len(out_s.sharding.device_set) == len(jax.devices())


def test_one_document_larger_than_a_shard():
    """A SINGLE document whose element table spans every elem shard many
    times over (cap = 64x the per-device shard would be at 8 devices):
    sharded == unsharded, and the outputs stay distributed."""
    import jax
    from automerge_tpu.parallel import (batched_merge_step, make_mesh,
                                        sharded_merge_step)
    if len(jax.devices()) < 2:
        pytest.skip("needs multiple devices")
    n_dev = len(jax.devices())
    mesh = make_mesh(doc_axis=1)          # ALL devices on the elem axis
    assert mesh.shape["elem"] == n_dev
    cap = n_dev * 512                      # per-device shard = 512 elements
    tables = doc_tables(1, cap, seed=7)
    pos_s, out_s, nvis_s = sharded_merge_step(mesh, *tables)
    pos_b, out_b, nvis_b = batched_merge_step(*[np.asarray(t) for t in tables])
    assert np.array_equal(np.asarray(pos_s), np.asarray(pos_b))
    assert np.array_equal(np.asarray(out_s), np.asarray(out_b))
    assert int(nvis_s[0]) == int(nvis_b[0])
    assert len(out_s.sharding.device_set) == n_dev
    # the big intermediates' shardings: the element axis is genuinely split
    assert out_s.sharding.shard_shape(out_s.shape)[1] == cap // n_dev


def test_sharded_engine_merge_exceeding_shard():
    """The REAL engine path (DeviceTextDocSet sharded tables) with one
    document whose elements exceed a single device's shard: text output
    equals the single-doc engine's."""
    import jax
    from automerge_tpu.engine import DeviceTextDoc, DeviceTextDocSet
    from automerge_tpu.engine.columnar import TextChangeBatch
    if len(jax.devices()) < 2:
        pytest.skip("needs multiple devices")

    n_dev = len(jax.devices())
    base_len = n_dev * 96                  # >> one shard at capacity 1024/8
    changes = [typing_run("base", 1, {}, "a" * base_len, 1, "_head"),
               typing_run("alice", 1, {"base": 1}, "HELLO", 10_000, "base:5"),
               typing_run("bob", 1, {"base": 1}, "WORLD", 20_000, "base:5")]

    single = DeviceTextDoc("t")
    for ch in changes:
        single.apply_changes([ch])

    from automerge_tpu.parallel import make_mesh
    mesh = make_mesh(doc_axis=1)          # all devices shard the elem axis
    ds = DeviceTextDocSet(["t"], capacity=2048, mesh=mesh)
    batch = TextChangeBatch.from_changes(changes, "t")
    ds.apply_batches({"t": batch})
    assert ds.texts()["t"] == single.text()


def test_sharded_planned_materialize_matches_engine():
    """Elem-sharded codes-only materialization with HOST-PLANNED segment
    structure: no sort in the compiled program (see SHARDING_r3.md audit);
    output must equal the single-device engine text, on a document spanning
    every shard."""
    import jax
    import numpy as np
    from automerge_tpu.engine import DeviceTextDoc
    from automerge_tpu.ops.ingest import bucket
    from automerge_tpu.parallel import make_mesh, sharded_planned_materialize
    if len(jax.devices()) < 2:
        pytest.skip("needs multiple devices")

    n_dev = len(jax.devices())
    doc = DeviceTextDoc("t", capacity=n_dev * 256)
    doc.apply_changes([typing_run("base", 1, {}, "x" * (n_dev * 128), 1,
                                  "_head")])
    doc.apply_changes([
        typing_run("alice", 1, {"base": 1}, "HELLO", 10_000, "base:7"),
        typing_run("bob", 1, {"base": 1}, "WORLD", 20_000, "base:7"),
        {"actor": "carol", "seq": 1, "deps": {"base": 1}, "ops": [
            {"action": "del", "obj": "t", "key": "base:2"}]},
    ])
    expected = doc.text()
    assert doc.seg_mirror is not None

    mesh = make_mesh(doc_axis=1)
    S = bucket(doc.seg_mirror.n_segs + 2, 64)
    segplan = doc.seg_mirror.plan(S, doc.n_elems)
    dev = doc._ensure_dev()
    codes, scalars = sharded_planned_materialize(
        mesh, dev["parent"], dev["ctr"], dev["actor"],
        dev["value"], dev["has_value"], dev["chain"],
        doc.n_elems, segplan, S=S)
    scal = np.asarray(scalars)
    assert int(scal[1]) == int(scal[2]) == doc.seg_mirror.n_segs
    assert int(scal[3]) == doc.seg_mirror.head_checksum()
    assert int(scal[4]) == doc.seg_mirror.aux_checksum()
    n_vis = int(scal[0])
    got = "".join(chr(v) for v in np.asarray(codes)[:n_vis])
    assert got == expected
    assert len(codes.sharding.device_set) == n_dev

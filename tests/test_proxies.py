"""Proxy-layer semantics inside change blocks.

Counterpart of the reference's proxy conformance suite
(/root/reference/test/proxies_test.js): the reference pins JS Array/Object
semantics on its ES Proxy layer; these pin the Python dict/list protocols on
ours — reads, slices, mutators, errors, and read-your-writes behavior.
"""

import pytest

import automerge_tpu as am


def change(doc, cb):
    return am.change(doc, cb)


@pytest.fixture
def listdoc():
    return change(am.init("actor-1"),
                  lambda d: d.__setitem__("xs", [10, 20, 30, 40]))


class TestMapProxy:
    def test_read_write_styles(self):
        def cb(d):
            d["a"] = 1
            d.b = 2
            assert d["b"] == 2 and d.a == 1
            assert d.get("missing", "dflt") == "dflt"
        doc = change(am.init(), cb)
        assert am.to_json(doc) == {"a": 1, "b": 2}

    def test_keys_values_items_iteration(self):
        seen = {}

        def cb(d):
            d.update({"x": 1, "y": 2})
            seen["keys"] = sorted(d.keys())
            seen["values"] = sorted(d.values())
            seen["items"] = sorted(d.items())
            seen["iter"] = sorted(iter(d))
            seen["len"] = len(d)
            seen["contains"] = "x" in d and "z" not in d
        change(am.init(), cb)
        assert seen == {"keys": ["x", "y"], "values": [1, 2],
                        "items": [("x", 1), ("y", 2)], "iter": ["x", "y"],
                        "len": 2, "contains": True}

    def test_delete_missing_key_raises(self):
        doc = change(am.init(), lambda d: d.__setitem__("a", 1))
        with pytest.raises(KeyError):
            change(doc, lambda d: d.__delitem__("nope"))

    def test_delattr(self):
        doc = change(am.init(), lambda d: d.update({"a": 1, "b": 2}))
        doc = change(doc, lambda d: delattr(d, "a"))
        assert am.to_json(doc) == {"b": 2}

    def test_nested_proxy_identity_and_equality(self):
        def cb(d):
            d["m"] = {"k": [1, 2]}
            assert d["m"] == {"k": [1, 2]}
            assert d["m"]["k"] == [1, 2]
        change(am.init(), cb)


class TestListProxy:
    def test_slice_reads(self, listdoc):
        seen = {}

        def cb(d):
            xs = d["xs"]
            seen["mid"] = xs[1:3]
            seen["neg"] = xs[-2:]
            seen["step"] = xs[::2]
            seen["rev"] = xs[::-1]
        change(listdoc, cb)
        assert seen == {"mid": [20, 30], "neg": [30, 40],
                        "step": [10, 30], "rev": [40, 30, 20, 10]}

    def test_slice_delete(self, listdoc):
        doc = change(listdoc, lambda d: d["xs"].__delitem__(slice(1, 3)))
        assert am.to_json(doc) == {"xs": [10, 40]}

    def test_slice_assignment_rejected(self, listdoc):
        with pytest.raises(TypeError, match="splice"):
            change(listdoc, lambda d: d["xs"].__setitem__(slice(0, 1), [9]))

    def test_stepped_slice_delete_rejected(self, listdoc):
        with pytest.raises(TypeError, match="stepped"):
            change(listdoc, lambda d: d["xs"].__delitem__(slice(0, 4, 2)))

    def test_pop_remove_index_count(self, listdoc):
        seen = {}

        def cb(d):
            xs = d["xs"]
            seen["pop"] = xs.pop()
            seen["pop0"] = xs.pop(0)
            xs.append(20)
            seen["index"] = xs.index(20)
            seen["count"] = xs.count(20)
            xs.remove(20)
            seen["after_remove"] = xs.to_list()
        doc = change(listdoc, cb)
        assert seen == {"pop": 40, "pop0": 10, "index": 0, "count": 2,
                        "after_remove": [30, 20]}
        assert am.to_json(doc) == {"xs": [30, 20]}

    def test_remove_missing_raises(self, listdoc):
        with pytest.raises(ValueError):
            change(listdoc, lambda d: d["xs"].remove(999))

    def test_index_missing_raises(self, listdoc):
        with pytest.raises(ValueError):
            change(listdoc, lambda d: d["xs"].index(999))

    def test_pop_empty_raises(self):
        doc = change(am.init(), lambda d: d.__setitem__("xs", []))
        with pytest.raises(IndexError):
            change(doc, lambda d: d["xs"].pop())

    def test_splice(self, listdoc):
        doc = change(listdoc,
                     lambda d: d["xs"].splice(1, 2, [99, 98, 97]))
        assert am.to_json(doc) == {"xs": [10, 99, 98, 97, 40]}

    def test_read_your_writes_within_block(self, listdoc):
        seen = {}

        def cb(d):
            xs = d["xs"]
            xs[0] = 11
            seen["updated"] = xs[0]
            xs.insert(0, 5)
            seen["len"] = len(xs)
            seen["contains"] = 5 in xs
        change(listdoc, cb)
        assert seen == {"updated": 11, "len": 5, "contains": True}

    def test_nested_list_of_maps_mutation(self):
        doc = change(am.init(), lambda d: d.__setitem__(
            "rows", [{"n": 1}, {"n": 2}]))

        def cb(d):
            for row in d["rows"]:
                row["n"] = row["n"] * 10
        doc = change(doc, cb)
        assert am.to_json(doc) == {"rows": [{"n": 10}, {"n": 20}]}

    def test_out_of_range_read_raises(self, listdoc):
        with pytest.raises(IndexError):
            change(listdoc, lambda d: d["xs"][99])

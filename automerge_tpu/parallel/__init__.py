from .mesh import (batched_merge_step, make_mesh,  # noqa: F401
                   sharded_merge_step, sharded_planned_materialize)

"""One region's endpoint of an inter-region replication link.

A :class:`RegionLink` carries the unchanged ``{docId, clock, changes?}``
sync protocol between two regions' room hubs over a WAN-profile chaos
transport, and owns everything the distance implies:

- a :class:`~automerge_tpu.resilience.channel.ResilientChannel` for
  exactly-once delivery, with a TIGHT retransmit budget so a vanished
  peer region is declared dead in bounded rounds (dead-link detection);
- the typed degradation ladder (INTERNALS §20.3): ``ok`` →
  ``lagged`` (pending cross-region group tokens above threshold) →
  ``partitioned`` (channel dead; outbound traffic buffers, bounded) →
  ``healing`` (probe answered; channel revived into a fresh epoch,
  hub peers re-attached, buffers drained) → ``ok``.  Every transition
  is counted here and evented on the owning service's black-box ring.
- the reconnect protocol: raw ``probe``/``hello`` control frames that
  BYPASS the channel (a dead channel can't carry its own resurrection),
  carrying the revived channel epoch so both ends agree which frames
  are stale history (``ResilientChannel.revive`` semantics).

Buffering during a partition is two-tier, because the two message
classes fail differently: clock-only advertisements dedup into a dict
keyed ``(room, docId)`` — the LAST advert wins and is never dropped,
since a lost advert is a room the remote might never learn about —
while payload-bearing envelopes fill a bounded drop-oldest deque
(counted).  Dropped payloads are safe: the heal-time hub peer
re-attachment re-advertises every doc, and advertisement IS a clock
reveal, so the delta recomputes from truth rather than from history.
"""

from __future__ import annotations

from .. import obs
from ..obs import lineage
from ..resilience.channel import ResilientChannel
from ..resilience.errors import PeerDeadError, ProtocolError

#: The degradation ladder's rungs, mildest first.
OK = "ok"
LAGGED = "lagged"
PARTITIONED = "partitioned"
HEALING = "healing"
LADDER = (OK, LAGGED, PARTITIONED, HEALING)

#: Raw control frames that bypass the reliability channel.
CONTROL_KINDS = ("probe", "probe_ack", "hello", "hello_ack")


class RegionLink:
    """This region's endpoint toward ONE remote region."""

    __slots__ = ("region", "remote", "label", "chan", "out", "state",
                 "lag_threshold", "probe_every", "max_buffer",
                 "_probe_countdown", "_buf_adverts", "_buf_data",
                 "_last_reveal", "stats", "transitions")

    def __init__(self, region, remote: str, *, seed: int = 0,
                 lag_threshold: int = 32, probe_every: int = 4,
                 max_buffer: int = 512, max_retries: int = 6,
                 base_rto: int = 2, max_rto: int = 16):
        self.region = region
        self.remote = remote
        #: directed label — `fed/ship` and `fed/buffer` lineage hops and
        #: the ladder events carry it, so a stuck chain's postmortem
        #: names WHICH region link it is parked on
        self.label = f"{region.name}->{remote}"
        self.out = None               # outbound ChaosLink (wired later)
        self.state = OK
        self.lag_threshold = lag_threshold
        self.probe_every = probe_every
        self.max_buffer = max_buffer
        self._probe_countdown = probe_every
        self._buf_adverts: dict = {}  # (room, docId) -> (room, msg)
        self._buf_data: list = []     # bounded, drop-oldest
        #: last GENUINE clock the remote stated per (room, docId) — what
        #: heal re-injects after the hub-peer wipe. The hub's believed
        #: clocks advance OPTIMISTICALLY at send time and frames can die
        #: in the partition buffer, so believed state is not safe to
        #: carry across a heal; the remote's own clock statements are.
        self._last_reveal: dict = {}
        self.stats = {"shipped": 0, "delivered": 0, "buffered": 0,
                      "buffer_dropped": 0, "probes": 0, "hellos": 0,
                      "reconnects": 0, "protocol_errors": 0}
        self.transitions: dict = {}
        self.chan = ResilientChannel(
            self._send_env, self._deliver, seed=seed,
            base_rto=base_rto, max_rto=max_rto, max_retries=max_retries,
            on_dead=self._on_chan_dead, label=f"fed:{self.label}")

    # -- wiring ---------------------------------------------------------

    def attach_transport(self, chaos_link):
        """Install the outbound chaos edge (its `deliver` must be the
        REMOTE link's :meth:`on_raw`)."""
        self.out = chaos_link

    def _send_env(self, env):
        self.out.send(env)

    def _send_ctl(self, frame: dict):
        # raw, un-sequenced, best-effort: control frames repeat until
        # answered, so chaos loss only delays the ladder, never wedges it
        self.out.send(frame)

    # -- ladder ---------------------------------------------------------

    def _to(self, state: str, **why):
        if state == self.state:
            return
        key = f"{self.state}->{state}"
        self.transitions[key] = self.transitions.get(key, 0) + 1
        self.state = state
        self.region.svc._note("fed_state", link=self.label, to=state,
                              **why)
        if obs.enabled():
            obs.event("fed", "state",
                      {"link": self.label, "to": state, **why})

    def _on_chan_dead(self, _chan):
        self._to(PARTITIONED, reason="channel_dead")
        self._probe_countdown = 0      # probe on the very next pump

    def lag(self) -> int:
        """Cross-region replication lag in GROUP TOKENS: envelopes
        carrying an ordering token the remote has not durably received —
        un-acked in the channel window plus partition-buffered.  Reaches
        exactly zero at quiescence (a minted-head comparison would not:
        mints the encode path declined to ship are wasted, not owed)."""
        pend = sum(1 for p in self.chan.pending_payloads()
                   if isinstance(p, dict) and p.get("gtok"))
        return pend + len(self._buf_data)

    # -- outbound (the hub's send_msg for peer `region:<remote>`) -------

    def ship(self, room_id: str, msg: dict):
        if self.state in (PARTITIONED, HEALING):
            return self._buffer(room_id, msg)
        env = self._envelope(room_id, msg)
        if lineage.ENABLED:
            for actor, seq in lineage.payload_keys(msg):
                lineage.hop(actor, seq, "fed/ship", site=self.label)
        try:
            self.chan.send(env)
            self.stats["shipped"] += 1
        except PeerDeadError:
            # raced the death declaration; the on_dead hook already
            # moved the ladder — keep the message
            self._buffer(room_id, msg)

    def _envelope(self, room_id: str, msg: dict) -> dict:
        env = {"fed": "msg", "room": room_id, "msg": msg}
        gtok = None
        wire = msg.get("wire")
        if wire is not None:
            # the frame manifest already carries the token minted at
            # encode time (one mint per (doc, clock) group); mirror it
            # on the envelope so the receiver observes in O(1), no decode
            gtok = getattr(wire, "group", None)
        if gtok is None and (msg.get("changes") or msg.get("wire")
                             or msg.get("checkpoint")):
            gtok = self.region.clock.mint(room_id)
        if gtok:
            env["gtok"] = list(gtok)
        return env

    def _buffer(self, room_id: str, msg: dict):
        self.stats["buffered"] += 1
        if not (msg.get("changes") or msg.get("wire")
                or msg.get("checkpoint")):
            # clock-only advert: last-wins dedup, NEVER dropped (a lost
            # advert could be a room the remote never learns about)
            self._buf_adverts[(room_id, msg["docId"])] = (room_id, msg)
            return
        if lineage.ENABLED:
            for actor, seq in lineage.payload_keys(msg):
                lineage.hop(actor, seq, "fed/buffer", site=self.label)
        if len(self._buf_data) >= self.max_buffer:
            self._buf_data.pop(0)
            self.stats["buffer_dropped"] += 1
        self._buf_data.append((room_id, msg))

    # -- inbound --------------------------------------------------------

    def on_raw(self, obj):
        """The transport delivery point: raw control frames (no channel
        ``kind``) dispatch to the reconnect protocol; everything else is
        a channel envelope."""
        if isinstance(obj, dict) and "kind" not in obj \
                and obj.get("fed") in CONTROL_KINDS:
            return self._control(obj)
        try:
            self.chan.on_wire(obj)
        except ProtocolError:
            self.stats["protocol_errors"] += 1

    def _deliver(self, payload):
        # exactly-once, in-order release from the channel
        if not isinstance(payload, dict) or payload.get("fed") != "msg":
            self.stats["protocol_errors"] += 1
            return
        room_id, msg = payload.get("room"), payload.get("msg")
        gtok = payload.get("gtok")
        if gtok:
            origin, g_room, tok = gtok
            self.region.clock.observe(g_room, origin, tok)
        if isinstance(msg, dict) and isinstance(msg.get("clock"), dict):
            self._last_reveal[(room_id, msg.get("docId"))] = \
                dict(msg["clock"])
        if lineage.ENABLED:
            for actor, seq in lineage.payload_keys(msg):
                lineage.hop(actor, seq, "fed/recv",
                            site=f"{self.remote}->{self.region.name}")
        self.stats["delivered"] += 1
        self.region._deliver_msg(self.remote, room_id, msg)

    # -- reconnect protocol ---------------------------------------------

    def _control(self, frame: dict):
        kind = frame["fed"]
        if kind == "probe":
            self._send_ctl({"fed": "probe_ack", "n": frame.get("n", 0)})
        elif kind == "probe_ack":
            if self.state == PARTITIONED:
                # the remote answered: revive into a fresh epoch and
                # offer it; stale pre-partition frames (either way) now
                # fail the epoch gate instead of corrupting the window
                self.chan.revive()
                # a new epoch may mean a new remote INCARNATION (killed
                # and rejoined empty): every pre-revive reveal is void —
                # a stale clock can claim state the fresh peer does not
                # hold, which would withhold its bootstrap delta forever
                self._last_reveal.clear()
                self.stats["reconnects"] += 1
                self._to(HEALING, reason="probe_answered")
                self._send_ctl({"fed": "hello",
                                "epoch": self.chan.epoch})
        elif kind == "hello":
            self.stats["hellos"] += 1
            revived = self._align(frame.get("epoch", 0))
            self._send_ctl({"fed": "hello_ack",
                            "epoch": self.chan.epoch})
            self._heal(force=revived)
        elif kind == "hello_ack":
            revived = self._align(frame.get("epoch", 0))
            self._heal(force=revived)

    def _align(self, peer_epoch: int) -> bool:
        """Adopt the remote's offered epoch: revive if this side is dead
        or behind, and accept their frames from `peer_epoch` on.
        Idempotent — a chaos-duplicated hello must not re-revive.
        Returns True when it DID revive (the send window was cleared, so
        the caller must run the heal re-advertisement even if this
        side's ladder never left ``ok`` — an asymmetric partition kills
        only the direction with traffic)."""
        ch = self.chan
        revived = False
        if ch.dead or ch.epoch < peer_epoch:
            ch.revive()
            self._last_reveal.clear()   # pre-revive reveals are void
            revived = True
            if ch.epoch < peer_epoch:
                ch.epoch = peer_epoch
        if ch._peer_epoch < peer_epoch:
            ch._peer_epoch = peer_epoch
            ch._recv_high = 0
            ch._recv_buf.clear()
        return revived

    def _heal(self, force: bool = False):
        """Both ends agreed on fresh epochs: re-attach the hub peers
        (re-advertisement recomputes every delta from truth — including
        snapshot bootstrap for a region that lost everything) and drain
        the partition buffers."""
        if self.state == OK and not force:
            return
        if self.state != HEALING:
            self._to(HEALING, reason="hello")
        adverts = list(self._buf_adverts.values())
        data = list(self._buf_data)
        self._buf_adverts.clear()
        self._buf_data.clear()
        self._to(OK, reason="healed")
        self.region._reattach_peer(self.remote)
        for room_id, msg in adverts + data:
            self.ship(room_id, msg)

    # -- driving --------------------------------------------------------

    def pump(self) -> int:
        """One round: move the outbound chaos edge, run the channel's
        retransmit timers, probe while partitioned, update the lag rung."""
        n = self.out.pump() if self.out is not None else 0
        if not self.chan.dead:
            self.chan.tick()
        if self.state == PARTITIONED:
            self._probe_countdown -= 1
            if self._probe_countdown <= 0:
                self._probe_countdown = self.probe_every
                self.stats["probes"] += 1
                self._send_ctl({"fed": "probe", "n": self.stats["probes"]})
        elif self.state == HEALING:
            # control frames ride the RAW edge (no retransmit channel):
            # a chaos-dropped hello/hello_ack must not strand the
            # handshake — keep re-offering our epoch until the heal
            # completes (idempotent: _align dedups a duplicate hello)
            self._probe_countdown -= 1
            if self._probe_countdown <= 0:
                self._probe_countdown = self.probe_every
                self.stats["hellos"] += 1
                self._send_ctl({"fed": "hello", "epoch": self.chan.epoch})
        elif self.state in (OK, LAGGED):
            lag = self.lag()
            if self.state == OK and lag > self.lag_threshold:
                self._to(LAGGED, lag=lag)
            elif self.state == LAGGED and lag <= self.lag_threshold:
                self._to(OK, lag=lag)
        return n

    def idle(self) -> bool:
        return (self.state == OK and self.chan.idle
                and not self._buf_adverts and not self._buf_data
                and (self.out is None or self.out.idle))

    def describe(self) -> dict:
        ch = self.chan.stats
        return {"remote": self.remote, "state": self.state,
                "lag_tokens": self.lag(),
                "buffered_adverts": len(self._buf_adverts),
                "buffered_data": len(self._buf_data),
                "transitions": dict(self.transitions),
                "stats": dict(self.stats),
                "channel": {"dead": ch["dead"], "epoch": self.chan.epoch,
                            "revives": ch["revives"],
                            "sent": ch["sent"],
                            "retransmits": ch["retransmits"],
                            "stale_epoch_dropped":
                                ch["stale_epoch_dropped"],
                            "stale_acks": ch["stale_acks"]}}

"""Apply backend diffs to the materialized document tree.

Counterpart of /root/reference/frontend/apply_patch.js: structural sharing via
an `updated` overlay over the previous `cache`, child->parent `inbound` index
maintenance (single-parent invariant), and parent re-linking up to the root.

Consecutive list/text insert diffs at adjacent indexes — and removes at the
same index — are applied as ONE slice splice (the reference's optimization,
apply_patch.js:332-384): a K-insert patch into an N-element document costs
O(N + K) list work instead of K separate O(N) `list.insert` shifts, which
turns bulk loads (load/merge of big Text docs) from quadratic to linear.
A single-element run degenerates to exactly the element-wise operation, so
there is one code path; ``apply_diffs(..., splice_batch=False)`` keeps the
element-wise path reachable for the A/B benchmark (benchmarks/run_all.py).
"""

from __future__ import annotations

from .._common import ROOT_ID, parse_elem_id
from .types import (Counter, ListDoc, MapDoc, Table, Text, instantiate_table,
                    instantiate_text, timestamp_to_datetime)


def get_value(diff: dict, cache: dict, updated: dict):
    """Reconstruct the value a diff assigns (apply_patch.js:10-25)."""
    if diff.get("link"):
        child = updated.get(diff["value"])
        return child if child is not None else cache[diff["value"]]
    datatype = diff.get("datatype")
    if datatype == "timestamp":
        return timestamp_to_datetime(diff["value"])
    if datatype == "counter":
        return Counter(diff["value"])
    if datatype is not None:
        raise TypeError(f"Unknown datatype: {datatype}")
    return diff["value"]


def _is_doc_object(value) -> bool:
    return isinstance(value, (MapDoc, ListDoc, Table, Text)) and value._object_id


def _child_references(obj, key) -> dict:
    """Object IDs referenced at `key` (value + conflicts) (apply_patch.js:32-41)."""
    refs = {}
    if isinstance(obj, ListDoc):
        conflicts = (obj._conflicts[key] or {}) if 0 <= key < len(obj._conflicts) else {}
        value = obj[key] if 0 <= key < len(obj) else None
    else:
        conflicts = obj._conflicts.get(key) or {}
        value = dict.get(obj, key)
    for child in [value, *conflicts.values()]:
        if _is_doc_object(child):
            refs[child._object_id] = True
    return refs


class InboundIndex(dict):
    """child object id -> parent object id, plus (``key_of``) the STABLE
    key the child sits at under that parent when one exists.

    The key record is what lets ``update_parent_objects`` relink an
    updated child into its parent by direct key access instead of
    scanning every entry of the parent — under a 100k-key root map, the
    full scan made ONE nested one-key change cost ~70 ms (1M dict probes
    per change). List children record no key (indices shift under
    splices; lists keep the scan), so ``key_of`` may lack entries — the
    relink falls back to the scan whenever a needed key is missing, and
    plain dicts (older callers, tests) behave exactly as before."""

    __slots__ = ("key_of",)

    def __init__(self, *args):
        super().__init__(*args)
        self.key_of: dict = {}

    def copy_index(self) -> "InboundIndex":
        new = InboundIndex(self)
        new.key_of = dict(self.key_of)
        return new


def copy_inbound(inbound: dict) -> dict:
    """Per-change copy preserving the key index when present."""
    if isinstance(inbound, InboundIndex):
        return inbound.copy_index()
    return dict(inbound)


_NO_KEY = object()   # sentinel: "linked at an unstable/unknown key"


def _update_inbound(object_id: str, refs_before: dict, refs_after: dict,
                    inbound: dict, key=_NO_KEY):
    key_of = getattr(inbound, "key_of", None)
    for ref in refs_before:
        if ref not in refs_after:
            inbound.pop(ref, None)
            if key_of is not None:
                key_of.pop(ref, None)
    for ref in refs_after:
        if inbound.get(ref) is not None and inbound[ref] != object_id:
            raise ValueError(f"Object {ref} has multiple parents")
        if ref not in inbound:
            inbound[ref] = object_id
        if key_of is not None:
            if key is _NO_KEY:
                key_of.pop(ref, None)
            else:
                key_of[ref] = key


def _clone_map_object(original, object_id: str) -> MapDoc:
    if original is not None and original._object_id != object_id:
        raise ValueError(f"cloneMapObject ID mismatch: {original._object_id} != {object_id}")
    obj = MapDoc(original or {}, object_id=object_id)
    obj._conflicts = {k: dict(v) for k, v in (original._conflicts if original else {}).items()}
    return obj


def _update_map_object(diff: dict, cache: dict, updated: dict, inbound: dict):
    object_id = diff["obj"]
    if object_id not in updated:
        updated[object_id] = _clone_map_object(cache.get(object_id), object_id)
    obj = updated[object_id]
    conflicts = obj._conflicts
    refs_before, refs_after = {}, {}

    action = diff["action"]
    if action == "create":
        pass
    elif action == "set":
        refs_before = _child_references(obj, diff["key"])
        dict.__setitem__(obj, diff["key"], get_value(diff, cache, updated))
        if diff.get("conflicts"):
            conflicts[diff["key"]] = {
                c["actor"]: get_value(c, cache, updated) for c in diff["conflicts"]
            }
        else:
            conflicts.pop(diff["key"], None)
        refs_after = _child_references(obj, diff["key"])
    elif action == "remove":
        refs_before = _child_references(obj, diff["key"])
        if dict.__contains__(obj, diff["key"]):
            dict.__delitem__(obj, diff["key"])
        conflicts.pop(diff["key"], None)
    else:
        raise ValueError(f"Unknown action type: {action}")

    _update_inbound(object_id, refs_before, refs_after, inbound,
                    key=diff.get("key", _NO_KEY))   # create has no key


def _parent_map_targeted(object_id: str, cache: dict, updated: dict,
                         child_ids: list, key_of: dict):
    """Relink ONLY the updated children, each at its recorded key —
    O(children) instead of O(parent size). Semantics identical to
    `_parent_map_object`: a key is rewritten only when its current value
    (or a conflict value at it) still references the stale child."""
    if object_id not in updated:
        updated[object_id] = _clone_map_object(cache.get(object_id), object_id)
    obj = updated[object_id]
    for child_id in child_ids:
        key = key_of[child_id]
        new_child = updated[child_id]
        value = dict.get(obj, key)
        if _is_doc_object(value) and value._object_id == child_id:
            dict.__setitem__(obj, key, new_child)
        conflicts = obj._conflicts.get(key)
        if conflicts:
            for actor_id, cvalue in list(conflicts.items()):
                if _is_doc_object(cvalue) and cvalue._object_id == child_id:
                    conflicts[actor_id] = new_child


def _parent_map_object(object_id: str, cache: dict, updated: dict):
    if object_id not in updated:
        updated[object_id] = _clone_map_object(cache.get(object_id), object_id)
    obj = updated[object_id]
    for key in list(obj.keys()):
        value = dict.get(obj, key)
        if _is_doc_object(value) and value._object_id in updated:
            dict.__setitem__(obj, key, updated[value._object_id])
        conflicts = obj._conflicts.get(key)
        if conflicts:
            for actor_id, cvalue in list(conflicts.items()):
                if _is_doc_object(cvalue) and cvalue._object_id in updated:
                    conflicts[actor_id] = updated[cvalue._object_id]


def _update_table_object(diff: dict, cache: dict, updated: dict, inbound: dict):
    object_id = diff["obj"]
    if object_id not in updated:
        cached = cache.get(object_id)
        updated[object_id] = cached._clone() if cached else instantiate_table(object_id)
    table = updated[object_id]
    refs_before, refs_after = {}, {}

    action = diff["action"]
    if action == "create":
        pass
    elif action == "set":
        previous = table.by_id(diff["key"])
        if _is_doc_object(previous):
            refs_before[previous._object_id] = True
        if diff.get("link"):
            child = updated.get(diff["value"])
            table._set(diff["key"], child if child is not None else cache[diff["value"]])
            refs_after[diff["value"]] = True
        else:
            table._set(diff["key"], diff["value"])
    elif action == "remove":
        previous = table.by_id(diff["key"])
        if _is_doc_object(previous):
            refs_before[previous._object_id] = True
        table.remove(diff["key"])
    else:
        raise ValueError(f"Unknown action type: {action}")

    _update_inbound(object_id, refs_before, refs_after, inbound)


def _parent_table_object(object_id: str, cache: dict, updated: dict):
    if object_id not in updated:
        updated[object_id] = cache[object_id]._clone()
    table = updated[object_id]
    for key in list(table.entries.keys()):
        value = table.by_id(key)
        if _is_doc_object(value) and value._object_id in updated:
            table._set(key, updated[value._object_id])


def _clone_list_object(original, object_id: str) -> ListDoc:
    if original is not None and original._object_id != object_id:
        raise ValueError(f"cloneListObject ID mismatch: {original._object_id} != {object_id}")
    lst = ListDoc(original or [], object_id=object_id)
    lst._conflicts = list(original._conflicts) if original is not None else []
    lst._elem_ids = list(original._elem_ids) if original is not None else []
    lst._max_elem = original._max_elem if original is not None else 0
    return lst


def _update_list_object(diff: dict, cache: dict, updated: dict, inbound: dict):
    object_id = diff["obj"]
    if object_id not in updated:
        updated[object_id] = _clone_list_object(cache.get(object_id), object_id)
    lst = updated[object_id]
    conflicts, elem_ids = lst._conflicts, lst._elem_ids

    value, conflict = None, None
    action = diff["action"]
    if action in ("insert", "set"):
        value = get_value(diff, cache, updated)
        if diff.get("conflicts"):
            conflict = {c["actor"]: get_value(c, cache, updated) for c in diff["conflicts"]}

    refs_before, refs_after = {}, {}
    if action == "create":
        pass
    elif action == "insert":
        lst._max_elem = max(lst._max_elem, parse_elem_id(diff["elemId"])[1])
        list.insert(lst, diff["index"], value)
        conflicts.insert(diff["index"], conflict)
        elem_ids.insert(diff["index"], diff["elemId"])
        refs_after = _child_references(lst, diff["index"])
    elif action == "set":
        refs_before = _child_references(lst, diff["index"])
        list.__setitem__(lst, diff["index"], value)
        conflicts[diff["index"]] = conflict
        refs_after = _child_references(lst, diff["index"])
    elif action == "remove":
        refs_before = _child_references(lst, diff["index"])
        list.__delitem__(lst, diff["index"])
        del conflicts[diff["index"]]
        del elem_ids[diff["index"]]
    elif action == "maxElem":
        lst._max_elem = max(lst._max_elem, diff["value"])
    else:
        raise ValueError(f"Unknown action type: {action}")

    _update_inbound(object_id, refs_before, refs_after, inbound)


def _splice_list_insert(run: list, cache: dict, updated: dict, inbound: dict):
    """One slice assignment for a run of adjacent-index list inserts."""
    object_id = run[0]["obj"]
    if object_id not in updated:
        updated[object_id] = _clone_list_object(cache.get(object_id), object_id)
    lst = updated[object_id]
    idx = run[0]["index"]

    values, confls, eids = [], [], []
    max_elem = lst._max_elem
    refs_after = {}
    for diff in run:
        value = get_value(diff, cache, updated)
        conflict = None
        if diff.get("conflicts"):
            conflict = {c["actor"]: get_value(c, cache, updated)
                        for c in diff["conflicts"]}
        values.append(value)
        confls.append(conflict)
        eids.append(diff["elemId"])
        max_elem = max(max_elem, parse_elem_id(diff["elemId"])[1])
        for child in (value, *(conflict or {}).values()):
            if _is_doc_object(child):
                refs_after[child._object_id] = True
    lst._max_elem = max_elem
    list.__setitem__(lst, slice(idx, idx), values)
    lst._conflicts[idx:idx] = confls
    lst._elem_ids[idx:idx] = eids
    _update_inbound(object_id, {}, refs_after, inbound)


def _splice_list_remove(run: list, cache: dict, updated: dict, inbound: dict):
    """One slice deletion for a run of same-index list removes."""
    object_id = run[0]["obj"]
    if object_id not in updated:
        updated[object_id] = _clone_list_object(cache.get(object_id), object_id)
    lst = updated[object_id]
    idx, k = run[0]["index"], len(run)
    if idx < 0 or idx + k > len(lst):
        # slice deletion would silently clamp; fail loudly like the
        # element-wise list.__delitem__ does on a malformed diff
        raise IndexError(
            f"list remove range [{idx}, {idx + k}) out of bounds "
            f"for length {len(lst)}")
    refs_before = {}
    for i in range(idx, idx + k):
        refs_before.update(_child_references(lst, i))
    list.__delitem__(lst, slice(idx, idx + k))
    del lst._conflicts[idx: idx + k]
    del lst._elem_ids[idx: idx + k]
    _update_inbound(object_id, refs_before, {}, inbound)


def _parent_list_object(object_id: str, cache: dict, updated: dict):
    if object_id not in updated:
        updated[object_id] = _clone_list_object(cache.get(object_id), object_id)
    lst = updated[object_id]
    for index in range(len(lst)):
        value = list.__getitem__(lst, index)
        if _is_doc_object(value) and value._object_id in updated:
            list.__setitem__(lst, index, updated[value._object_id])
        conflicts = lst._conflicts[index]
        if conflicts:
            for actor_id, cvalue in list(conflicts.items()):
                if _is_doc_object(cvalue) and cvalue._object_id in updated:
                    conflicts[actor_id] = updated[cvalue._object_id]


def _update_text_object(diff: dict, cache: dict, updated: dict):
    object_id = diff["obj"]
    text = _text_target(object_id, cache, updated)

    action = diff["action"]
    if action == "create":
        pass
    elif action == "insert":
        text._max_elem = max(text._max_elem, parse_elem_id(diff["elemId"])[1])
        elem = {"elemId": diff["elemId"], "value": get_value(diff, cache, updated),
                "conflicts": diff.get("conflicts")}
        text.elems.insert(diff["index"], elem)
    elif action == "set":
        text.elems[diff["index"]] = {
            "elemId": text.elems[diff["index"]]["elemId"],
            "value": get_value(diff, cache, updated),
            "conflicts": diff.get("conflicts"),
        }
    elif action == "remove":
        del text.elems[diff["index"]]
    elif action == "maxElem":
        text._max_elem = max(text._max_elem, diff["value"])
    else:
        raise ValueError(f"Unknown action type: {action}")


def _splice_text_insert(run: list, cache: dict, updated: dict):
    """One slice assignment for a run of adjacent-index text inserts.

    Bulk-shaped (a fresh peer's initial sync delivers the whole document
    as one run): the loop body inlines `get_value`'s plain-value case and
    `parse_elem_id`'s counter extraction — at 100k diffs the generic
    helpers were the measured hot path; shapes that carry links,
    datatypes, or malformed elemIds take them unchanged."""
    object_id = run[0]["obj"]
    text = _text_target(object_id, cache, updated)
    idx = run[0]["index"]
    max_elem = text._max_elem
    elems = []
    append = elems.append
    for diff in run:
        elem_id = diff["elemId"]
        _, sep, ctr = elem_id.rpartition(":")
        if sep and ctr.isdigit():
            c = int(ctr)
            if c > max_elem:
                max_elem = c
        else:
            max_elem = max(max_elem, parse_elem_id(elem_id)[1])
        if diff.get("link") or diff.get("datatype"):
            value = get_value(diff, cache, updated)
        else:
            value = diff["value"]
        append({"elemId": elem_id, "value": value,
                "conflicts": diff.get("conflicts")})
    text._max_elem = max_elem
    text.elems[idx:idx] = elems


def _splice_text_remove(run: list, cache: dict, updated: dict):
    object_id = run[0]["obj"]
    text = _text_target(object_id, cache, updated)
    idx, k = run[0]["index"], len(run)
    if idx < 0 or idx + k > len(text.elems):
        raise IndexError(
            f"text remove range [{idx}, {idx + k}) out of bounds "
            f"for length {len(text.elems)}")
    del text.elems[idx: idx + k]


def _text_target(object_id: str, cache: dict, updated: dict):
    if object_id not in updated:
        cached = cache.get(object_id)
        if cached is not None:
            # O(n_chunks) copy-on-write snapshot, NOT an O(n) list copy —
            # this is the per-keystroke frontend cost on large documents
            # (ChunkedElems docstring, types.py)
            updated[object_id] = instantiate_text(
                object_id, cached.elems.copy(), cached._max_elem)
        else:
            updated[object_id] = instantiate_text(object_id, [], 0)
    return updated[object_id]


def update_parent_objects(cache: dict, updated: dict, inbound: dict):
    """Propagate updated children into new parent versions up to the root
    (apply_patch.js:393-414). Map parents relink by recorded key
    (`InboundIndex.key_of`) when every affected child has one; lists and
    tables — and plain-dict inbound callers — keep the full scan."""
    key_of = getattr(inbound, "key_of", None)
    affected = updated
    while affected:
        parents = {}
        for child_id in list(affected.keys()):
            parent_id = inbound.get(child_id)
            if parent_id:
                parents[parent_id] = True
        affected = parents
        if not parents:
            break
        # a freshly-cloned parent starts from the CACHE version, whose
        # entries reference the stale versions of EVERY updated child —
        # group over the whole `updated` map, not just this wave
        children_of: dict = {}
        if key_of is not None:
            for child_id in updated:
                p = inbound.get(child_id)
                if p in parents:
                    children_of.setdefault(p, []).append(child_id)
        for object_id in parents:
            obj = updated.get(object_id)
            if obj is None:
                obj = cache.get(object_id)
            if isinstance(obj, ListDoc):
                _parent_list_object(object_id, cache, updated)
            elif isinstance(obj, Table):
                _parent_table_object(object_id, cache, updated)
            else:
                kids = children_of.get(object_id, [])
                if key_of is not None and kids and \
                        all(k in key_of for k in kids):
                    _parent_map_targeted(object_id, cache, updated, kids,
                                         key_of)
                else:
                    _parent_map_object(object_id, cache, updated)


def _run_end(diffs: list, i: int) -> int:
    """End (exclusive) of the maximal spliceable run starting at diffs[i]:
    same object, same action; inserts at adjacent ascending indexes,
    removes at the same index (how the backend emits a contiguous range —
    each removal shifts the next element down to the same position)."""
    first = diffs[i]
    action, obj, dtype = first["action"], first["obj"], first["type"]
    j = i + 1
    while j < len(diffs):
        d = diffs[j]
        if d["type"] != dtype or d["obj"] != obj or d["action"] != action:
            break
        if action == "insert":
            if d["index"] != diffs[j - 1]["index"] + 1:
                break
        else:  # remove
            if d["index"] != first["index"]:
                break
        j += 1
    return j


def apply_diffs(diffs: list, cache: dict, updated: dict, inbound: dict,
                *, splice_batch: bool = True):
    i, n = 0, len(diffs)
    while i < n:
        diff = diffs[i]
        diff_type = diff["type"]
        if (splice_batch and diff_type in ("list", "text")
                and diff["action"] in ("insert", "remove")):
            j = _run_end(diffs, i)
            run = diffs[i:j]
            if diff_type == "list":
                if diff["action"] == "insert":
                    _splice_list_insert(run, cache, updated, inbound)
                else:
                    _splice_list_remove(run, cache, updated, inbound)
            else:
                if diff["action"] == "insert":
                    _splice_text_insert(run, cache, updated)
                else:
                    _splice_text_remove(run, cache, updated)
            i = j
            continue
        if diff_type == "map":
            _update_map_object(diff, cache, updated, inbound)
        elif diff_type == "table":
            _update_table_object(diff, cache, updated, inbound)
        elif diff_type == "list":
            _update_list_object(diff, cache, updated, inbound)
        elif diff_type == "text":
            _update_text_object(diff, cache, updated)
        else:
            raise TypeError(f"Unknown object type: {diff_type}")
        i += 1


def clone_root_object(root: MapDoc) -> MapDoc:
    if root._object_id != ROOT_ID:
        raise ValueError(f"Not the root object: {root._object_id}")
    return _clone_map_object(root, ROOT_ID)

"""Fused round kernels: one device program per causal round (ISSUE 17).

PR-15's roofline attribution split `device_wait_s` into per-kernel shares
and put `apply_mixed_round`, the stacked mixed/map round programs, and the
scatter paths at the top of the queue. This module collapses that queue:
the per-round program *sequence* (expand -> residual -> chain breaks, then
a separate map-lane program, then per-lane scatters) becomes

  - `fused_mixed_round`   — the solo-doc text round, ONE program with no
    static shape flags: the expand/residual/touch phases of
    `_apply_mixed_round` run unconditionally over padding-convention
    no-ops, so every round of every shape shares one trace per capacity
    bucket instead of one per (expand_kind, with_res, with_touch) cell.
  - `fused_stacked_round` — the megakernel: BOTH stacked lanes (every
    map/table object's round AND every text/list object's round) in one
    dispatch, replacing `stacked_map_round` + one `stacked_mixed_round`
    per shape group.
  - `fused_scatter_registers` — both lanes' host-resolved slow writebacks
    as one dispatch, replacing two `stacked_scatter_registers` launches.

The expansion's (6, N) boundary-delta cumsum — the only multi-pass XLA
reduction left on the commit path — lowers through the mode ladder
(`fused_mode()`): "pallas" runs `ops/scan_pallas.multi_scan` (one VMEM
tile pass, SMEM carries) on TPU, "interpret" runs the same kernel under
the Pallas interpreter so cpu tier-1 exercises the real kernel, "lax"
composes `jnp.cumsum` for backends with no Mosaic at all. Everything else
in the fused bodies is ordinary lax that XLA fuses around the scan.

Parity contract (the PR-5/7 discipline): the XLA program path —
`apply_mixed_round`, `stacked_map_round`, `stacked_mixed_round`,
`stacked_scatter_registers` — stays verbatim behind `AMTPU_FUSED_ROUNDS=0`
as the byte-identical comparator. The fused core reorders NOTHING
observable: run-head chain breaks move from the dense expand into the
uniform expand (sparse plans' touch matrices already cover the same
(parent, ctr, actor) triples, and breaks are sticky Lamport maxima, so
applying them from the descriptor too is idempotent), and padding
conventions (kind=-1 residual rows, slot=out_cap sentinels, p_slot=0
touches) make absent phases exact no-ops.
"""

from __future__ import annotations

import os
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from .ingest import (  # noqa: F401
    DESC_ELEM_BASE, DESC_META, META_BASE_SLOT, META_N_ELEMS, META_N_RUNS,
    MOP_KIND, MOP_SLOT, MOP_VALUE, MOP_WIN_ACTOR, MOP_WIN_SEQ,
    RES_KIND, RES_NEW_SLOT, RES_SLOT,
    _TABLE_ARGNUMS, _apply_map_round, _apply_residual_packed,
    _break_chains_core, _break_chains_packed, _jit_pair,
    _materialize_core, _materialize_core_planned, _scatter_rows_9,
    _scatter_registers_packed, _slice_live, _unpack_desc,
)

_MODES = ("pallas", "interpret", "lax")


def fused_rounds_enabled() -> bool:
    """AMTPU_FUSED_ROUNDS gate, default ON (read per call so tests and
    the A/B harness can flip it per leg)."""
    return os.environ.get("AMTPU_FUSED_ROUNDS", "1") != "0"


def fused_mode() -> str:
    """The scan-lowering rung: AMTPU_FUSED_MODE when explicitly set
    ("pallas" | "interpret" | "lax"), else "pallas" on TPU and "lax"
    elsewhere. "lax" is the default off-chip rung because the Pallas
    interpreter pays a per-tile Python dispatch tax that would slow the
    cpu tier-1 suite; the interpret rung is exercised by the targeted
    parity tests instead."""
    m = os.environ.get("AMTPU_FUSED_MODE", "")
    if m in _MODES:
        return m
    try:
        backend = jax.default_backend()
    except Exception:  # pragma: no cover - backend probe failure
        backend = "cpu"
    return "pallas" if backend == "tpu" else "lax"


def _cumsum_rows(x, mode: str):
    """Row-wise inclusive prefix sum of (K, N) int32 via the mode ladder."""
    if mode == "lax":
        return jnp.cumsum(x, axis=1)
    from .scan_pallas import multi_scan
    return multi_scan(x, interpret=(mode != "pallas"))


def _fused_expand(tables, desc, blob, *, out_cap: int, mode: str):
    """`expand_runs` with the (6, N) column cumsum lowered through the
    mode ladder, plus the dense path's fused run-head chain breaks
    applied uniformly from the descriptor (idempotent for sparse plans —
    their touch matrices carry the same run-head triples)."""
    (run_head_slot, run_parent_slot, run_ctr0, run_actor, run_win_actor,
     run_win_seq, run_elem_base, run_has_value) = _unpack_desc(desc)
    n_run_elems = desc[DESC_META, META_N_ELEMS]
    R = run_head_slot.shape[0]
    N = blob.shape[0]

    run_len_prev = run_elem_base - jnp.concatenate(
        [jnp.zeros(1, run_elem_base.dtype), run_elem_base[:-1]])
    prev = lambda a: jnp.concatenate([jnp.zeros(1, a.dtype), a[:-1]])
    first = jnp.arange(R, dtype=jnp.int32) == 0
    d_ctr = jnp.where(first, run_ctr0,
                      run_ctr0 - (prev(run_ctr0) + run_len_prev - 1))
    d_slot = jnp.where(first, run_head_slot,
                       run_head_slot
                       - (prev(run_head_slot) + run_len_prev - 1))
    wa_v = jnp.where(run_has_value, run_win_actor, -1)
    ws_v = jnp.where(run_has_value, run_win_seq, 0)
    has_v = run_has_value.astype(jnp.int32)
    d_actor = jnp.where(first, run_actor, run_actor - prev(run_actor))
    d_wa = jnp.where(first, wa_v, wa_v - prev(wa_v))
    d_ws = jnp.where(first, ws_v, ws_v - prev(ws_v))
    d_has = jnp.where(first, has_v, has_v - prev(has_v))

    deltas = jnp.ones((6, N), jnp.int32)
    deltas = deltas.at[2:].set(0)
    deltas = deltas.at[:, run_elem_base].set(
        jnp.stack([d_ctr, d_slot, d_actor, d_wa, d_ws, d_has]),
        mode="drop")                      # padding runs: elem_base == N
    cols = _cumsum_rows(deltas, mode)
    ctr_col, slot_col = cols[0], cols[1]

    j = jnp.arange(N, dtype=jnp.int32)
    live = j < n_run_elems
    is_start = jnp.zeros(N, bool).at[run_elem_base].set(True, mode="drop")
    tgt = jnp.where(live, slot_col, out_cap)    # OOB sentinel drops padding
    parent_col = (slot_col - 1).at[run_elem_base].set(
        run_parent_slot, mode="drop")
    has_col = (cols[5] > 0) & live

    tables = _scatter_rows_9(
        tables, tgt,
        (parent_col, ctr_col, cols[2], blob.astype(jnp.int32), has_col,
         jnp.where(has_col, cols[3], -1), jnp.where(has_col, cols[4], 0),
         jnp.zeros(N, jnp.int32), live & ~is_start),
        out_cap)

    n_runs = desc[DESC_META, META_N_RUNS]
    live_r = jnp.arange(R, dtype=jnp.int32) < n_runs
    chain_n = _break_chains_core(
        tables[8], tables[0], tables[1], tables[2],
        jnp.where(live_r, run_parent_slot, 0),
        jnp.where(live_r, run_ctr0, -1),
        jnp.where(live_r, run_actor, -1))
    return tables[:8] + (chain_n,)


def _fused_mixed_core(
    parent, ctr, actor, value, has_value, win_actor, win_seq, win_counter,
    chain, desc, blob, res, conflict_slots, touch,
    *, out_cap: int, mode: str,
):
    """The flag-free mixed round: every phase of `_apply_mixed_round`
    runs unconditionally — absent phases ride padding conventions (a
    runless descriptor expands nothing, kind=-1 residual rows are
    no-ops, p_slot=0 touches break nothing) — so one trace per capacity
    bucket covers every round shape. Returns the 9 tables + slow_info
    (always: callers skip the d2h fetch when the round staged no
    residuals)."""
    tables = (parent, ctr, actor, value, has_value, win_actor, win_seq,
              win_counter, chain)
    tables = _fused_expand(tables, desc, blob, out_cap=out_cap, mode=mode)
    out = _apply_residual_packed(*tables, res, conflict_slots,
                                 out_cap=out_cap)
    tables, slow_info = out[:9], out[9]
    tables = tables[:8] + (_break_chains_packed(
        tables[8], tables[0], tables[1], tables[2], touch),)
    return tables + (slow_info,)


fused_mixed_round, fused_mixed_round_donated = _jit_pair(
    _fused_mixed_core, _TABLE_ARGNUMS, ("out_cap", "mode"))


def _fused_commit_core(
    parent, ctr, actor, value, has_value, win_actor, win_seq, win_counter,
    chain, desc, blob, *, out_cap: int, S: int, as_u8: bool, L: int,
    mode: str,
):
    """The ring-commit megakernel (the PR-17 follow-on): the pipelined
    ingestor's steady-state commit — the common-case dense merge round
    END TO END, expansion (scan lowered through the mode ladder) plus
    the codes-only materialization — as ONE fused-tier program. The XLA
    pair (`merge_and_materialize_dense*`, ops/ingest.py) stays verbatim
    behind AMTPU_FUSED_ROUNDS=0 as the byte-identical comparator."""
    tables = _fused_expand(
        (parent, ctr, actor, value, has_value, win_actor, win_seq,
         win_counter, chain), desc, blob, out_cap=out_cap, mode=mode)
    n_elems = (desc[DESC_META, META_BASE_SLOT]
               + desc[DESC_META, META_N_ELEMS] - 1)
    cols = _slice_live((tables[0], tables[1], tables[2], tables[3],
                        tables[4], tables[8]), L)
    codes, scalars = _materialize_core(*cols, n_elems, S, with_pos=False,
                                       as_u8=as_u8)
    return tables + (codes, scalars)


fused_commit_round, fused_commit_round_donated = _jit_pair(
    _fused_commit_core, _TABLE_ARGNUMS, ("out_cap", "S", "as_u8", "L",
                                         "mode"))


def _fused_commit_planned_core(
    parent, ctr, actor, value, has_value, win_actor, win_seq, win_counter,
    chain, desc, blob, segplan, *, out_cap: int, S: int, as_u8: bool,
    L: int, mode: str,
):
    """`_fused_commit_core` with the materialization's segment structure
    staged from the host plan — no device sort, no pointer doubling;
    the fused-tier twin of `merge_and_materialize_dense_planned`."""
    tables = _fused_expand(
        (parent, ctr, actor, value, has_value, win_actor, win_seq,
         win_counter, chain), desc, blob, out_cap=out_cap, mode=mode)
    n_elems = (desc[DESC_META, META_BASE_SLOT]
               + desc[DESC_META, META_N_ELEMS] - 1)
    cols = _slice_live((tables[0], tables[1], tables[2], tables[3],
                        tables[4], tables[8]), L)
    codes, scalars = _materialize_core_planned(
        *cols, n_elems, segplan, S, with_pos=False, as_u8=as_u8)
    return tables + (codes, scalars)


fused_commit_round_planned, fused_commit_round_planned_donated = _jit_pair(
    _fused_commit_planned_core, _TABLE_ARGNUMS,
    ("out_cap", "S", "as_u8", "L", "mode"))


def _fused_stacked_round(
    # map lane: 5 stacked register tables + (D, 5, M) ops + (D, K) conflicts
    m_value, m_has, m_wa, m_ws, m_wc, m_ops, m_conflict,
    # text lane: 9 stacked element tables + stacked round operands
    parent, ctr, actor, value, has_value, win_actor, win_seq, win_counter,
    chain, desc, blob, res, t_conflict, touch,
    *, map_cap: int, text_cap: int, with_map: bool, with_text: bool,
    mode: str,
):
    """The megakernel: one causal round of EVERY participating object —
    both lanes — as ONE device program. Absent lanes ride `_absent()`
    placeholders (static flags dead-code them). Returns the map lane's
    5 tables + (D, 7, M) slow_info when `with_map`, then the text lane's
    9 tables + (D, 7, M) slow_info when `with_text`."""
    out = ()
    if with_map:
        def one_map(v, h, wa, ws, wc, o, cs):
            return _apply_map_round(
                v, h, wa, ws, wc, o[MOP_KIND].astype(jnp.int8), o[MOP_SLOT],
                o[MOP_VALUE], o[MOP_WIN_ACTOR], o[MOP_WIN_SEQ], cs,
                out_cap=map_cap)
        out += jax.vmap(one_map)(m_value, m_has, m_wa, m_ws, m_wc, m_ops,
                                 m_conflict)
    if with_text:
        fn = partial(_fused_mixed_core, out_cap=text_cap, mode=mode)
        out += jax.vmap(fn)(parent, ctr, actor, value, has_value, win_actor,
                            win_seq, win_counter, chain, desc, blob, res,
                            t_conflict, touch)
    return out


fused_stacked_round = jax.jit(
    _fused_stacked_round,
    static_argnames=("map_cap", "text_cap", "with_map", "with_text",
                     "mode"))


def _fused_scatter_registers(
    m_value, m_has, m_wa, m_ws, m_wc, m_wb,
    t_value, t_has, t_wa, t_ws, t_wc, t_wb,
    *, with_map: bool, with_text: bool,
):
    """Both lanes' host-resolved slow-register writebacks as ONE program
    (two (D, 6, S) uploads, one dispatch) — replaces the per-lane
    `stacked_scatter_registers` launches."""
    out = ()
    if with_map:
        out += jax.vmap(_scatter_registers_packed)(
            m_value, m_has, m_wa, m_ws, m_wc, m_wb)
    if with_text:
        out += jax.vmap(_scatter_registers_packed)(
            t_value, t_has, t_wa, t_ws, t_wc, t_wb)
    return out


fused_scatter_registers = jax.jit(
    _fused_scatter_registers, static_argnames=("with_map", "with_text"))


# --- padding operands -------------------------------------------------------

_ABSENT = None
_DUMMIES: dict = {}


def _absent():
    """Shared placeholder for a dead lane's traced operands of
    `fused_stacked_round` (static flags cut the branches; a fresh upload
    per call would still pay a transfer)."""
    global _ABSENT
    if _ABSENT is None:
        _ABSENT = jnp.zeros((1, 1), jnp.int32)
    return _ABSENT


def round_dummies(out_cap: int):
    """Cached no-op operands for the phases a solo round did not stage:
    (desc, blob, res, conflict_slots, touch). Each follows the padding
    convention its phase treats as absent — a runless descriptor with
    the elem_base sentinel, kind=-1/slot=out_cap residual rows, an
    all-out_cap conflict vector, p_slot=0 touch rows."""
    d = _DUMMIES.get(out_cap)
    if d is None:
        desc = np.zeros((9, 1), np.int32)
        desc[DESC_ELEM_BASE, 0] = 1       # == blob length: padding sentinel
        res = np.zeros((8, 1), np.int32)
        res[RES_KIND] = -1
        res[RES_SLOT] = out_cap
        res[RES_NEW_SLOT] = out_cap
        d = (jnp.asarray(desc), jnp.zeros(1, jnp.int32), jnp.asarray(res),
             jnp.full(1, out_cap, jnp.int32), jnp.zeros((3, 1), jnp.int32))
        _DUMMIES[out_cap] = d
    return d


# --- device-truth registry (obs/device_truth.py; INTERNALS §19/§21) --------
#
# Same discipline as ops/ingest.py: the kernels the engine DISPATCHES are
# re-bound to instrumented handles; the building blocks that only run
# inside them (_fused_expand, _fused_mixed_core, multi_scan) are not.

from ..obs import device_truth as _device_truth  # noqa: E402

fused_mixed_round, fused_mixed_round_donated = \
    _device_truth.instrument_pair(
        (fused_mixed_round, fused_mixed_round_donated), "fused_mixed_round")
fused_commit_round, fused_commit_round_donated = \
    _device_truth.instrument_pair(
        (fused_commit_round, fused_commit_round_donated),
        "fused_commit_round")
fused_commit_round_planned, fused_commit_round_planned_donated = \
    _device_truth.instrument_pair(
        (fused_commit_round_planned, fused_commit_round_planned_donated),
        "fused_commit_round_planned")
fused_stacked_round = _device_truth.instrument(fused_stacked_round,
                                               "fused_stacked_round")
fused_scatter_registers = _device_truth.instrument(
    fused_scatter_registers, "fused_scatter_registers")

"""Batched vector-clock index for multi-peer, multi-doc sync.

The reference diffs one (peer, doc) pair at a time with a per-actor clock
walk (`getMissingChanges`, /root/reference/backend/op_set.js:388-395, driven
per peer by src/connection.js:58-74). Here the whole doc-set's clocks and
every peer's believed clocks intern into dense int64 matrices, so "who needs
what" for N peers x M docs x A actors is ONE numpy comparison — the
framework's device-adjacent answer to SURVEY §5's "trivially vectorizable"
note. Change extraction then touches only the (peer, doc) pairs the
comparison flagged.
"""

from __future__ import annotations

import numpy as np


class _Interner:
    """Key -> dense slot, with slot recycling: a removed key's slot goes
    to a free list and is handed to the next NEW key, so the dense axis
    is bounded by the PEAK live population, not the lifetime total —
    500 add/remove churn cycles on a 3-peer hub cost 3 slots, not 500
    (the churn-storm memory bound)."""

    __slots__ = ("idx", "items", "free")

    def __init__(self):
        self.idx: dict = {}
        self.items: list = []
        self.free: list = []

    def __call__(self, key) -> int:
        i = self.idx.get(key)
        if i is None:
            if self.free:
                i = self.free.pop()
                self.items[i] = key
            else:
                i = len(self.items)
                self.items.append(key)
            self.idx[key] = i
        return i

    def remove(self, key):
        """Free a key's slot for reuse; returns the slot (or None). The
        caller must zero the matrix rows it indexed — the next occupant
        inherits the slot, never the data."""
        i = self.idx.pop(key, None)
        if i is not None:
            self.items[i] = None
            self.free.append(i)
        return i

    def __len__(self):
        return len(self.items)


def _grow(arr: np.ndarray, shape: tuple) -> np.ndarray:
    if arr.shape == shape:
        return arr
    out = np.zeros(shape, arr.dtype)
    if arr.size:
        out[tuple(slice(0, s) for s in arr.shape)] = arr
    return out


class ClockMatrix:
    """Dense (docs x actors) local clocks + (peers x docs x actors) believed
    peer clocks; `pending()` compares them all at once."""

    def __init__(self):
        self._docs = _Interner()
        self._actors = _Interner()
        self._peers = _Interner()
        self._ours = np.zeros((0, 0), np.int64)
        self._theirs = np.zeros((0, 0, 0), np.int64)
        self._active = np.zeros((0, 0), bool)   # (peer, doc) servable pairs

    def _sync_shapes(self):
        d, a, p = len(self._docs), len(self._actors), len(self._peers)
        self._ours = _grow(self._ours, (d, a))
        self._theirs = _grow(self._theirs, (p, d, a))
        self._active = _grow(self._active, (p, d))

    def update_ours(self, doc_id: str, clock: dict):
        di = self._docs(doc_id)
        cols = [self._actors(actor) for actor in clock]
        self._sync_shapes()
        row = self._ours[di]
        for actor, ci in zip(clock, cols):
            if clock[actor] > row[ci]:
                row[ci] = clock[actor]

    def update_theirs(self, peer_id: str, doc_id: str, clock: dict):
        pi = self._peers(peer_id)
        di = self._docs(doc_id)
        cols = [self._actors(actor) for actor in clock]
        self._sync_shapes()
        row = self._theirs[pi, di]
        for actor, ci in zip(clock, cols):
            if clock[actor] > row[ci]:
                row[ci] = clock[actor]

    def known_peer_doc(self, peer_id: str, doc_id: str) -> bool:
        return peer_id in self._peers.idx and doc_id in self._docs.idx

    def our_clock(self, doc_id: str) -> dict:
        di = self._docs.idx.get(doc_id)
        if di is None or di >= self._ours.shape[0]:
            return {}
        row = self._ours[di]
        return {self._actors.items[i]: int(s)
                for i, s in enumerate(row) if s > 0}

    def their_clock(self, peer_id: str, doc_id: str) -> dict:
        if not self.known_peer_doc(peer_id, doc_id):
            return {}
        self._sync_shapes()
        row = self._theirs[self._peers.idx[peer_id], self._docs.idx[doc_id]]
        return {self._actors.items[i]: int(s)
                for i, s in enumerate(row) if s > 0}

    def set_active(self, peer_id: str, doc_id: str, flag: bool = True):
        """Mark a (peer, doc) pair servable: only active pairs can appear
        in `pending()`. Keeps unrevealed/removed pairs out of the
        comparison entirely (otherwise they would be re-flagged forever)."""
        pi = self._peers(peer_id)
        di = self._docs(doc_id)
        self._sync_shapes()
        self._active[pi, di] = flag

    def reset_peer(self, peer_id: str):
        """Forget a peer's believed clocks and deactivate its pairs (it may
        reconnect fresh later; update_theirs is monotone max, so zeroing is
        the only way back)."""
        pi = self._peers.idx.get(peer_id)
        if pi is not None and pi < self._theirs.shape[0]:
            self._theirs[pi] = 0
        if pi is not None and pi < self._active.shape[0]:
            self._active[pi] = False

    def release_peer(self, peer_id: str):
        """reset_peer + recycle the peer's matrix slot (the churn bound:
        add/remove N peers holds the peer axis at the PEAK concurrent
        count — a removed peer costs nothing once released; a same-id
        reconnect interns fresh, possibly into a recycled slot whose rows
        were zeroed here)."""
        self.reset_peer(peer_id)
        self._peers.remove(peer_id)

    @property
    def peer_slots(self) -> int:
        """Width of the dense peer axis (live + recycled-free slots) —
        what the churn-storm regression test bounds."""
        return len(self._peers)

    def has_peer(self, peer_id: str) -> bool:
        """Whether the peer currently occupies a matrix slot (public
        introspection — `release_peer` is what makes this False)."""
        return peer_id in self._peers.idx

    def lag_table(self) -> dict:
        """Replication lag of every interned peer against our local
        clocks, from ONE vectorized comparison (Okapi's cheap causal
        metadata, PAPERS.md): {peer_id: {"ops": total change deficit,
        "docs": {doc_id: deficit}}} counting only ACTIVE (revealed)
        pairs. A deficit is the summed per-actor seq shortfall — the
        number of changes this hub still believes the peer is missing.
        Believed clocks advance optimistically at send time, so this
        term alone covers not-yet-extracted changes; the service tier
        adds the un-acked wire component (INTERNALS §14.2)."""
        self._sync_shapes()
        live = [(i, p) for i, p in enumerate(self._peers.items)
                if p is not None]
        out = {p: {"ops": 0, "docs": {}} for _, p in live}
        if not self._theirs.size or not live:
            return out
        deficit = self._ours[None, :, :] - self._theirs
        np.clip(deficit, 0, None, out=deficit)
        deficit *= self._active[:, :, None]
        per_pair = deficit.sum(axis=2)               # (peers, docs)
        for pi, di in zip(*np.nonzero(per_pair)):
            peer = self._peers.items[pi]
            doc = self._docs.items[di]
            if peer is None or doc is None:
                continue
            n = int(per_pair[pi, di])
            out[peer]["docs"][doc] = n
            out[peer]["ops"] += n
        return out

    def pending(self) -> list:
        """All ACTIVE (peer_id, doc_id) pairs where the peer is missing
        changes: ONE vectorized comparison over every peer, doc, actor."""
        self._sync_shapes()
        if not self._theirs.size:
            return []
        needy = (self._theirs < self._ours[None]).any(axis=2) & self._active
        return [(self._peers.items[p], self._docs.items[d])
                for p, d in zip(*np.nonzero(needy))]

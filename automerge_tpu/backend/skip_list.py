"""Indexable (order-statistic) skip list: visible elemId <-> list index.

Capability counterpart of the reference's immutable skip list
(/root/reference/backend/skip_list.js:1-343): a probabilistic ordered index
mapping element IDs to list positions and back in expected O(log n), with the
same injectable level-randomness determinism hook the reference tests rely on
(skip_list.js:114-117).

Design differs deliberately: the reference builds a persistent
(immutable-on-update) structure because its whole backend state is persistent;
here the backend uses an append-only command log with replay-on-fork (see
``automerge_tpu.backend.facade``), so the index is a plain mutable structure —
cheaper by a constant factor and friendlier to the columnar device encoding
that replaces it on the hot path (segmented prefix scans in the device engine).

Every node stores forward and backward links *with hop widths* at each of its
levels, so both ``key_of(index)`` (position lookup) and ``index_of(key)``
(rank query) run in expected O(log n).
"""

from __future__ import annotations

import random
from typing import Any, Iterator, Optional

_MAX_LEVEL = 32
_HEAD = object()  # sentinel key for the head tower


class _Node:
    __slots__ = ("key", "value", "level", "nxt", "nxt_w", "prv", "prv_w")

    def __init__(self, key, value, level):
        self.key = key
        self.value = value
        self.level = level
        self.nxt = [None] * level      # successor key per level (None = tail)
        self.nxt_w = [1] * level       # element-count distance to successor
        self.prv = [_HEAD] * level     # predecessor key per level
        self.prv_w = [1] * level       # element-count distance from predecessor


class SkipList:
    """Mutable order-statistic skip list keyed by elemId strings."""

    def __init__(self, random_source=None, level_source=None):
        # random_source: () -> float in [0, 1); level_source: iterator of ints
        # (explicit level injection, used by deterministic tests).
        self._random = random_source or random.random
        self._levels = iter(level_source) if level_source is not None else None
        self._head = _Node(_HEAD, None, 1)
        self._head.nxt = [None]
        self._head.nxt_w = [1]
        self._nodes: dict[Any, _Node] = {}
        self._length = 0

    # -- level policy: geometric with p=0.75 of stopping, like the reference
    # (skip_list.js:7-21) --
    def _random_level(self) -> int:
        if self._levels is not None:
            return max(1, next(self._levels))
        level = 1
        while level < _MAX_LEVEL and self._random() >= 0.75:
            level += 1
        return level

    def __len__(self) -> int:
        return self._length

    @property
    def length(self) -> int:
        return self._length

    def __contains__(self, key) -> bool:
        return key in self._nodes

    def _node(self, key) -> _Node:
        if key is _HEAD:
            return self._head
        return self._nodes[key]

    def _predecessors(self, index: int):
        """Per-level predecessors of position `index`, with their positions.

        Returns (preds, pred_pos) lists of length head.level; preds[l] is the
        rightmost node at level l whose position is < index (head pos = -1).
        """
        head_level = self._head.level
        preds = [self._head] * head_level
        pred_pos = [-1] * head_level
        cur, cur_pos = self._head, -1
        for level in range(head_level - 1, -1, -1):
            while cur.nxt[level] is not None and cur_pos + cur.nxt_w[level] < index:
                cur_pos += cur.nxt_w[level]
                cur = self._nodes[cur.nxt[level]]
            preds[level] = cur
            pred_pos[level] = cur_pos
        return preds, pred_pos

    def insert_index(self, index: int, key, value=None) -> "SkipList":
        if not isinstance(index, int) or index < 0 or index > self._length:
            raise IndexError(f"insert index {index} out of bounds for length {self._length}")
        if key in self._nodes:
            raise ValueError(f"duplicate skip list key {key}")
        level = self._random_level()

        # Grow the head tower first so every level has a predecessor.
        while self._head.level < level:
            self._head.nxt.append(None)
            self._head.nxt_w.append(self._length + 1)
            self._head.level += 1

        preds, pred_pos = self._predecessors(index)
        node = _Node(key, value, level)
        self._nodes[key] = node

        for l in range(level):
            pred = preds[l]
            succ_key = pred.nxt[l]
            succ_pos = pred_pos[l] + pred.nxt_w[l]  # position of succ (or length for tail)
            node.nxt[l] = succ_key
            node.nxt_w[l] = succ_pos - index + 1
            node.prv[l] = pred.key
            node.prv_w[l] = index - pred_pos[l]
            pred.nxt[l] = key
            pred.nxt_w[l] = index - pred_pos[l]
            if succ_key is not None:
                succ = self._nodes[succ_key]
                succ.prv[l] = key
                succ.prv_w[l] = succ_pos - index + 1
        for l in range(level, self._head.level):
            preds[l].nxt_w[l] += 1
            succ_key = preds[l].nxt[l]
            if succ_key is not None:
                self._nodes[succ_key].prv_w[l] += 1

        self._length += 1
        return self

    def insert_after(self, pred_key, key, value=None) -> "SkipList":
        """Insert `key` immediately after `pred_key` (None = head)."""
        if pred_key is None:
            return self.insert_index(0, key, value)
        return self.insert_index(self.index_of(pred_key) + 1, key, value)

    def remove_index(self, index: int) -> "SkipList":
        if not isinstance(index, int) or index < 0 or index >= self._length:
            raise IndexError(f"remove index {index} out of bounds for length {self._length}")
        preds, _ = self._predecessors(index)
        target = self._nodes[preds[0].nxt[0]]

        for l in range(target.level):
            pred = preds[l]
            succ_key = target.nxt[l]
            pred.nxt[l] = succ_key
            pred.nxt_w[l] = pred.nxt_w[l] + target.nxt_w[l] - 1
            if succ_key is not None:
                succ = self._nodes[succ_key]
                succ.prv[l] = pred.key
                succ.prv_w[l] = pred.nxt_w[l]
        for l in range(target.level, self._head.level):
            preds[l].nxt_w[l] -= 1
            succ_key = preds[l].nxt[l]
            if succ_key is not None:
                self._nodes[succ_key].prv_w[l] -= 1

        del self._nodes[target.key]
        self._length -= 1
        return self

    def remove_key(self, key) -> "SkipList":
        return self.remove_index(self.index_of(key))

    def index_of(self, key) -> int:
        """Rank of `key` among visible elements, or -1 if absent.

        Walks backward toward the head, always jumping at the current node's
        top level and summing hop widths (the same rank-query strategy as the
        reference's predecessor walk, skip_list.js:124-166).
        """
        node = self._nodes.get(key)
        if node is None:
            return -1
        total = 0
        while node.key is not _HEAD:
            top = node.level - 1
            total += node.prv_w[top]
            node = self._node(node.prv[top])
        return total - 1

    def key_of(self, index: int):
        if not isinstance(index, int) or index < 0 or index >= self._length:
            return None
        cur, cur_pos = self._head, -1
        for level in range(self._head.level - 1, -1, -1):
            while cur.nxt[level] is not None and cur_pos + cur.nxt_w[level] <= index:
                cur_pos += cur.nxt_w[level]
                cur = self._nodes[cur.nxt[level]]
                if cur_pos == index:
                    return cur.key
        return cur.key if cur_pos == index else None

    def get_value(self, key):
        node = self._nodes.get(key)
        return node.value if node else None

    def set_value(self, key, value) -> "SkipList":
        self._nodes[key].value = value
        return self

    def __iter__(self) -> Iterator:
        key = self._head.nxt[0]
        while key is not None:
            node = self._nodes[key]
            yield key
            key = node.nxt[0]

    def items(self):
        key = self._head.nxt[0]
        while key is not None:
            node = self._nodes[key]
            yield key, node.value
            key = node.nxt[0]

"""Checkpoint & compaction tier (automerge_tpu/checkpoint/).

Pins the subsystem's contracts end to end:

- property test over random merge/undo/delete histories: ``load(save(doc))``
  renders byte-for-byte like the oracle backend's document
- checkpoint restore equivalence (document AND re-serialized history),
  delta saves (tail-only payload + tail replay at load)
- integrity: truncated / bit-flipped bundles raise the typed
  ``CheckpointError``; the DocSet bootstrap falls back to full log replay
- async-capture vs sync-capture byte identity; the conflict path degrades
  to a synchronous grab
- engine-level restore equivalence + tail replay (the bench.py seam)
- snapshot-bootstrapped sync, including a corrupt bundle healing through
  the ``noSnapshot`` full-history fallback
"""

import json

import numpy as np
import pytest

import automerge_tpu as am
from automerge_tpu import frontend as Frontend
from automerge_tpu.backend import facade
from automerge_tpu.checkpoint import (
    AsyncCheckpointer, Checkpoint, CheckpointError, capture_engine,
    capture_state, restore_engine, restore_state,
)
from automerge_tpu.resilience import ProtocolError


def canon(doc) -> str:
    return json.dumps(am.to_json(doc), sort_keys=True, default=str)


def oracle_doc(changes):
    """The same history replayed through the pure-host oracle backend."""
    state, _ = facade.apply_changes(facade.init(), changes)
    patch = facade.get_patch(state)
    patch["state"] = state
    return Frontend.apply_patch(Frontend.init({"backend": facade.Backend}),
                                patch)


def random_history_doc(seed: int):
    """A doc grown through seeded random merge/undo/delete interleavings."""
    rng = np.random.default_rng(seed)
    base = am.change(am.init("base"), lambda d: (
        d.__setitem__("t", am.Text("seed")),
        d.__setitem__("m", {"k": 0})))
    changes = am.get_all_changes(base)
    peers = [am.apply_changes(am.init(f"p{i}"), changes) for i in range(3)]
    for _ in range(int(rng.integers(10, 20))):
        i = int(rng.integers(0, len(peers)))
        act = int(rng.integers(0, 6))
        if act == 0:
            k = f"k{int(rng.integers(0, 4))}"
            v = int(rng.integers(-99, 99))
            peers[i] = am.change(peers[i],
                                 lambda d, k=k, v=v: d.__setitem__(k, v))
        elif act == 1:
            def edit(d):
                t = d["t"]
                if len(t) and rng.integers(0, 3) == 0:
                    t.delete_at(int(rng.integers(0, len(t))))
                else:
                    t.insert_at(int(rng.integers(0, len(t) + 1)),
                                chr(97 + int(rng.integers(0, 26))))
            peers[i] = am.change(peers[i], edit)
        elif act == 2 and am.can_undo(peers[i]):
            peers[i] = am.undo(peers[i])
        elif act == 3 and am.can_redo(peers[i]):
            peers[i] = am.redo(peers[i])
        else:
            j = int(rng.integers(0, len(peers)))
            if j != i:
                peers[i] = am.merge(peers[i], peers[j])
    for _ in range(2):
        for i in range(len(peers)):
            for j in range(len(peers)):
                if i != j:
                    peers[i] = am.merge(peers[i], peers[j])
    return peers[0]


@pytest.mark.parametrize("seed", range(6))
def test_save_load_matches_oracle_property(seed):
    doc = random_history_doc(seed)
    back = am.load(am.save(doc))
    odoc = oracle_doc(am.get_all_changes(doc))
    assert canon(back) == canon(odoc) == canon(doc)


@pytest.mark.parametrize("seed", range(4))
def test_checkpoint_restore_equivalence_property(seed):
    doc = random_history_doc(seed)
    ck = am.checkpoint_doc(doc)
    back = am.restore(ck)
    assert canon(back) == canon(doc)
    # history-complete: the restored doc re-serializes byte-for-byte
    assert am.save(back) == am.save(doc)
    # and keeps syncing: diverge both sides, then re-merge
    back = am.change(back, lambda d: d.__setitem__("after", 1))
    doc = am.change(doc, lambda d: d["t"].insert_at(0, "Q"))
    doc = am.merge(doc, back)
    back = am.merge(back, doc)
    assert canon(back) == canon(doc)


def test_restore_drops_undo_history_like_load():
    doc = am.change(am.init("u"), lambda d: d.__setitem__("x", 1))
    assert am.can_undo(doc)
    assert not am.can_undo(am.restore(am.checkpoint_doc(doc)))
    assert not am.can_undo(am.load(am.save(doc)))


def test_delta_save_tail_replay():
    doc = am.change(am.init("alice"),
                    lambda d: d.__setitem__("t", am.Text("hello")))
    for i in range(5):
        doc = am.change(doc, lambda d, i=i: d["t"].insert_at(0, str(i)))
    ck = am.checkpoint_doc(doc)
    tail_start = doc
    for i in range(3):
        doc = am.change(doc, lambda d, i=i: d["t"].insert_at(0, chr(65 + i)))
    delta = am.save(doc, checkpoint=ck)
    payload = json.loads(delta)
    assert payload["format"] == "automerge-tpu-delta-v1"
    assert payload["checkpointId"] == ck.id
    # compaction: only the tail past the frontier rides in the save
    assert len(payload["changes"]) == 3
    assert len(delta) < len(am.save(doc))
    back = am.load(delta, checkpoint=ck)
    assert canon(back) == canon(doc)
    # the frontier state itself round-trips with an empty tail
    empty_delta = am.save(tail_start, checkpoint=ck)
    assert json.loads(empty_delta)["changes"] == []
    assert canon(am.load(empty_delta, checkpoint=ck)) == canon(tail_start)


def test_delta_load_requires_checkpoint():
    doc = am.change(am.init("a"), lambda d: d.__setitem__("x", 1))
    ck = am.checkpoint_doc(doc)
    doc = am.change(doc, lambda d: d.__setitem__("y", 2))
    delta = am.save(doc, checkpoint=ck)
    with pytest.raises(ValueError, match="delta-compacted"):
        am.load(delta)
    # a different checkpoint is rejected by id before any restore work
    other = am.checkpoint_doc(am.change(am.init("b"),
                                        lambda d: d.__setitem__("z", 9)))
    with pytest.raises(CheckpointError, match="wrong base checkpoint"):
        am.load(delta, checkpoint=other)


def test_delta_save_rejects_non_ancestor():
    doc = am.change(am.init("a"), lambda d: d.__setitem__("x", 1))
    ck = am.checkpoint_doc(am.change(doc,
                                     lambda d: d.__setitem__("y", 2)))
    with pytest.raises(ValueError, match="not an ancestor"):
        am.save(doc, checkpoint=ck)   # doc is BEHIND the checkpoint


# ---------------------------------------------------------------------------
# integrity / fallback
# ---------------------------------------------------------------------------

def _doc_with_history():
    doc = am.change(am.init("alice"),
                    lambda d: d.__setitem__("t", am.Text("integrity")))
    doc = am.change(doc, lambda d: d.__setitem__("m", {"k": [1, 2]}))
    doc = am.change(doc, lambda d: d["t"].delete_at(0))
    return doc


def test_truncated_bundle_raises_checkpoint_error():
    ck = am.checkpoint_doc(_doc_with_history())
    for cut in (10, 50, len(ck.data) // 2, len(ck.data) - 3):
        with pytest.raises(CheckpointError):
            restore_state(ck.data[:cut])


def test_bit_flipped_bundle_raises_checkpoint_error():
    ck = am.checkpoint_doc(_doc_with_history())
    n = len(ck.data)
    # flip bytes across the whole bundle: header, manifest, array blobs
    for pos in (2, n // 4, n // 2, (3 * n) // 4, n - 10):
        data = bytearray(ck.data)
        data[pos] ^= 0x40
        with pytest.raises(CheckpointError):
            restore_state(bytes(data))


def test_manifest_bit_flip_raises_checkpoint_error():
    # the manifest region carries clock/conflicts/value-pool state OUTSIDE
    # the array blobs; a flip that keeps the JSON parseable (e.g. a clock
    # digit) must still fail the header hash, never restore silently
    from automerge_tpu.checkpoint import bundle as _bundle
    ck = am.checkpoint_doc(_doc_with_history())
    hdr = len(_bundle.MAGIC) + 8 + 32
    data = bytearray(ck.data)
    pos = ck.data.index(b'"clock"', hdr) + len(b'"clock"') + 12
    data[pos] ^= 0x01   # single-bit change inside the manifest JSON
    with pytest.raises(CheckpointError, match="manifest"):
        restore_state(bytes(data))
    with pytest.raises(CheckpointError):
        Checkpoint(bytes(data)).clock   # peek is hash-verified too


def test_corrupt_bundle_falls_back_to_full_replay():
    from automerge_tpu import DocSet
    doc = _doc_with_history()
    ck = am.checkpoint_doc(doc)
    corrupt = bytearray(ck.data)
    corrupt[len(corrupt) // 2] ^= 0xFF
    ds = DocSet()
    # without a fallback the corruption surfaces typed
    with pytest.raises(CheckpointError):
        ds.bootstrap_doc("doc", bytes(corrupt))
    # with the full log, restore degrades to replay and still lands
    out = ds.bootstrap_doc("doc", bytes(corrupt),
                           fallback_changes=am.get_all_changes(doc))
    assert canon(out) == canon(doc)


# ---------------------------------------------------------------------------
# engine-level (the bench seam)
# ---------------------------------------------------------------------------

def _engine_text_doc(n=400):
    import bench
    from automerge_tpu.engine import DeviceTextDoc
    doc = DeviceTextDoc("t")
    doc.apply_batch(bench.base_batch("t", n))
    doc.apply_batch(bench.merge_batch("t", 6, 50, n, seed=2))
    return doc, n


def test_engine_restore_equivalence_and_tail_replay():
    import bench
    doc, n = _engine_text_doc()
    data = capture_engine(doc)
    d2 = restore_engine(data)
    assert d2.text() == doc.text()
    assert d2.elem_ids() == doc.elem_ids()
    # tail replay lands identically on original and restored
    tail = bench.merge_batch("t", 4, 30, n, seed=7, actor_prefix="tl")
    doc.apply_batch(tail)
    d2.apply_batch(tail)
    assert d2.text() == doc.text()
    assert d2.elem_ids() == doc.elem_ids()
    assert dict(d2.clock) == dict(doc.clock)


def test_engine_restore_preserves_conflict_registers():
    from automerge_tpu.engine import DeviceTextDoc

    def mk(a, key, parent, val, deps):
        return {"actor": a, "seq": 1, "deps": deps, "ops": [
            {"action": "ins", "obj": "t", "key": parent, "elem": 1},
            {"action": "set", "obj": "t", "key": key, "value": val}]}

    doc = DeviceTextDoc("t")
    doc.apply_changes([mk("base", "base:1", "_head", "x", {})])
    # two concurrent writers on the same element -> a stored conflict
    doc.apply_changes([
        {"actor": "a", "seq": 1, "deps": {"base": 1}, "ops": [
            {"action": "set", "obj": "t", "key": "base:1", "value": "A"}]},
        {"actor": "b", "seq": 1, "deps": {"base": 1}, "ops": [
            {"action": "set", "obj": "t", "key": "base:1", "value": "B"}]},
    ])
    assert doc.conflicts_at(0) is not None
    d2 = restore_engine(capture_engine(doc))
    assert d2.text() == doc.text()
    assert d2.conflicts_at(0) == doc.conflicts_at(0)


def test_engine_capture_rejects_queued_changes():
    from automerge_tpu.engine import DeviceTextDoc
    doc = DeviceTextDoc("t")
    doc.apply_changes([{"actor": "a", "seq": 2, "deps": {}, "ops": [
        {"action": "ins", "obj": "t", "key": "_head", "elem": 1},
        {"action": "set", "obj": "t", "key": "a:1", "value": "x"}]}])
    assert doc.queue   # causally premature: parked in the engine queue
    with pytest.raises(CheckpointError, match="queued"):
        capture_engine(doc)


# ---------------------------------------------------------------------------
# async writer
# ---------------------------------------------------------------------------

def test_async_capture_identity_engine_doc():
    doc, _ = _engine_text_doc(200)
    with AsyncCheckpointer() as w:
        h = w.capture_async(doc)
        sync_bytes = AsyncCheckpointer.capture(doc)
        assert h.result(30) == sync_bytes
        assert w.stats["async_captures"] == 1
        assert w.stats["sync_fallbacks"] == 0
    assert restore_engine(sync_bytes).text() == doc.text()


def test_async_capture_identity_backend_state():
    doc = _doc_with_history()
    state = Frontend.get_backend_state(doc)
    with AsyncCheckpointer() as w:
        h = w.capture_async(state)
        assert h.result(30) == capture_state(state)


def test_async_capture_conflict_degrades_to_sync():
    doc, _ = _engine_text_doc(200)
    doc._busy = 1   # simulate a mutation permanently in flight
    with AsyncCheckpointer(max_grab_retries=2) as w:
        h = w.capture_async(doc)
        h._done.wait(30)
        assert w.stats["sync_fallbacks"] == 1
        assert w.stats["grab_conflicts"] == 2
        doc._busy = 0   # commit boundary: the caller owns quiescence now
        data = h.result(30)
    assert data == AsyncCheckpointer.capture(doc)
    assert restore_engine(data).text() == doc.text()


def test_async_capture_during_pipeline_is_consistent_prefix():
    import bench
    from automerge_tpu.engine import DeviceTextDoc, PipelinedIngestor
    n = 3000
    doc = DeviceTextDoc("p")
    doc.apply_batch(bench.base_batch("p", n))
    halves = [bench.merge_batch("p", 10, 50, n, seed=s, actor_prefix=p_)
              for s, p_ in ((1, "a"), (2, "b"))]
    with AsyncCheckpointer() as w:
        with PipelinedIngestor(doc) as pipe:
            pipe.feed(halves[0])
            h = w.capture_async(doc)
            pipe.feed(halves[1])
            pipe.flush()
        restored = restore_engine(h.result(60))
    # the capture is SOME consistent prefix of the ingestion: replaying
    # the full halves on top converges it to the final doc (idempotent
    # dedup absorbs whatever the snapshot already contained)
    restored.apply_batch(halves[0])
    restored.apply_batch(halves[1])
    assert restored.text() == doc.text()


# ---------------------------------------------------------------------------
# api.load envelope validation (satellite)
# ---------------------------------------------------------------------------

def test_load_rejects_non_dict_payload_typed():
    for bad in ("[1]", '"str"', "3", "null"):
        with pytest.raises(ProtocolError):
            am.load(bad)


def test_load_rejects_missing_changes_typed():
    with pytest.raises(ProtocolError):
        am.load('{"format": "automerge-tpu-v1"}')
    with pytest.raises(ProtocolError):
        am.load('{"format": "automerge-tpu-v1", "changes": 5}')


def test_load_unknown_format_still_value_error():
    with pytest.raises(ValueError):
        am.load('{"format": "something-else", "changes": []}')
    # ProtocolError IS a ValueError, so legacy callers keep working
    assert issubclass(ProtocolError, ValueError)


# ---------------------------------------------------------------------------
# snapshot-bootstrapped sync
# ---------------------------------------------------------------------------

def _wire(sa, sb):
    from automerge_tpu import Connection
    qa, qb = [], []
    ca = Connection(sa, qa.append)
    cb = Connection(sb, qb.append)
    ca.open()
    cb.open()
    return ca, cb, qa, qb


def _pump(ca, cb, qa, qb, mutate=None, log=None):
    for _ in range(12):
        moved = False
        while qa:
            msg = json.loads(json.dumps(qa.pop(0)))   # wire round-trip
            if log is not None:
                log.append(msg)
            if mutate is not None:
                msg = mutate(msg)
            cb.receive_msg(msg)
            moved = True
        while qb:
            ca.receive_msg(json.loads(json.dumps(qb.pop(0))))
            moved = True
        if not moved:
            return


def _long_history_doc_set():
    from automerge_tpu import DocSet
    ds = DocSet()
    doc = am.change(am.init("origin"),
                    lambda d: d.__setitem__("t", am.Text("seed")))
    for i in range(20):
        doc = am.change(
            doc, lambda d, i=i: d["t"].insert_at(len(d["t"]),
                                                 chr(97 + i % 26)))
    ds.set_doc("doc", doc)
    return ds


def test_sync_snapshot_bootstrap(monkeypatch):
    from automerge_tpu import DocSet, SyncHub
    monkeypatch.setattr(SyncHub, "snapshot_min_changes", 8)
    sa, sb = _long_history_doc_set(), DocSet()
    ca, cb, qa, qb = _wire(sa, sb)
    log = []
    _pump(ca, cb, qa, qb, log=log)
    assert any("checkpoint" in m for m in log), \
        "joining peer should have been served a checkpoint bundle"
    assert canon(sa.get_doc("doc")) == canon(sb.get_doc("doc"))
    # bidirectional sync keeps working after the bootstrap
    sb.set_doc("doc", am.change(sb.get_doc("doc"),
                                lambda d: d["t"].insert_at(0, "Z")))
    _pump(ca, cb, qa, qb)
    assert canon(sa.get_doc("doc")) == canon(sb.get_doc("doc"))


def test_sync_snapshot_corrupt_falls_back_to_full_history(monkeypatch):
    from automerge_tpu import DocSet, SyncHub
    monkeypatch.setattr(SyncHub, "snapshot_min_changes", 8)
    sa, sb = _long_history_doc_set(), DocSet()
    ca, cb, qa, qb = _wire(sa, sb)
    n_corrupt = [0]

    def corrupt(msg):
        if "checkpoint" in msg:
            n_corrupt[0] += 1
            raw = bytearray(Checkpoint.from_base64(msg["checkpoint"]).data)
            raw[len(raw) // 2] ^= 0xFF   # hash-mismatched bundle
            msg = dict(msg)
            msg["checkpoint"] = Checkpoint(bytes(raw)).to_base64()
        return msg

    _pump(ca, cb, qa, qb, mutate=corrupt)
    assert n_corrupt[0] >= 1
    # the corrupt bundle was rejected and the peer recovered via the
    # noSnapshot full-history fallback — full log replay, same document
    assert canon(sa.get_doc("doc")) == canon(sb.get_doc("doc"))


def test_sync_snapshot_disabled_by_zero_threshold(monkeypatch):
    from automerge_tpu import DocSet, SyncHub
    monkeypatch.setattr(SyncHub, "snapshot_min_changes", 0)
    sa, sb = _long_history_doc_set(), DocSet()
    ca, cb, qa, qb = _wire(sa, sb)
    log = []
    _pump(ca, cb, qa, qb, log=log)
    assert not any("checkpoint" in m for m in log)
    assert canon(sa.get_doc("doc")) == canon(sb.get_doc("doc"))


def test_soak_checkpoint_profile_session():
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import soak
    soak.session_checkpoint(1)


def test_bench_restore_metrics_small_scale():
    import bench
    rec = bench.measure_restore(base_n=4000, tail_actors=4,
                                ops_per_change=40)
    assert rec["restore_full_replay_s"] > 0
    assert rec["restore_snapshot_s"] > 0
    assert rec["restore_bundle_bytes"] > 0
    # no speed assertion at toy scale — the 1M-doc ratio is pinned by the
    # bench record (docs/MEASUREMENTS.md); this pins shape + equivalence


def test_grab_mid_mutation_serves_commit_boundary_snapshot():
    """ISSUE 12: a grab observing a mutation in flight no longer climbs
    the busy-wait/retry ladder — it reads the doc's cached
    commit-boundary snapshot with zero coordination (CaptureConflict is
    kept only for donated buffers / the cold first-grab race)."""
    doc, _ = _engine_text_doc(200)
    bytes0 = AsyncCheckpointer.capture(doc)   # caches the snapshot
    doc._busy = 1                             # a bulk index merge mid-flight
    try:
        with AsyncCheckpointer(max_grab_retries=2) as w:
            h = w.capture_async(doc)
            data = h.result(30)
            assert w.stats["snapshot_serves"] == 1
            assert w.stats["sync_fallbacks"] == 0
            assert w.stats["grab_conflicts"] == 0
    finally:
        doc._busy = 0
    assert data == bytes0                     # the commit-boundary state


def test_grab_racing_bulk_index_merge_is_consistent_prefix():
    """Async grabs racing a thread of real applies (each holding _busy
    across its bulk index merge): every capture restores to SOME
    consistent prefix — replaying the full stream on top converges it to
    the final document byte-for-byte."""
    import threading
    import time

    import bench
    from automerge_tpu.engine import DeviceTextDoc

    n = 2000
    doc = DeviceTextDoc("r")
    base = bench.base_batch("r", n)
    doc.apply_batch(base)
    batches = [bench.merge_batch("r", 8, 40, n, seed=s, actor_prefix=p)
               for s, p in ((1, "a"), (2, "b"), (3, "c"), (4, "d"))]
    captures = []
    # seed the snapshot cache SYNCHRONOUSLY before the mutator starts:
    # an async seed could lose the race and hit the cold-first-grab
    # CaptureConflict path this test deliberately excludes
    seed = AsyncCheckpointer.capture(doc)
    with AsyncCheckpointer() as w:
        handles = []
        done = threading.Event()

        def mutate():
            for b in batches:
                doc.apply_batch(b)
            done.set()

        t = threading.Thread(target=mutate)
        t.start()
        while not done.is_set() and len(handles) < 12:
            handles.append(w.capture_async(doc))
            time.sleep(0.01)
        t.join(60)
        captures = [seed] + [h.result(60) for h in handles]
        assert w.stats["grab_conflicts"] == 0, w.stats
    final = doc.text()
    for data in captures:
        restored = restore_engine(data)
        for b in batches:
            restored.apply_batch(b)
        assert restored.text() == final


def test_snapshot_not_served_for_donation_enabled_doc():
    """Review regression (ISSUE 12): a cached commit-boundary snapshot
    must NOT be served once the doc enters donated-buffer mode — donated
    commits consume the snapshot's table buffers in place, so the busy
    path falls back to CaptureConflict exactly as pre-snapshot."""
    from automerge_tpu.checkpoint.engine_codec import CaptureConflict, grab

    doc, _ = _engine_text_doc(200)
    AsyncCheckpointer.capture(doc)          # caches the snapshot
    doc.donate_buffers = True
    try:
        with pytest.raises(CaptureConflict):
            grab(doc)                       # deferred grab refuses outright
        doc._busy = 1
        with pytest.raises(CaptureConflict):
            grab(doc, inline=True)          # busy + donated: no stale serve
    finally:
        doc._busy = 0
        doc.donate_buffers = False
    # donation off again and quiescent: live grabs resume
    assert grab(doc)["mode"] == "live"

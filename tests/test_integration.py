"""Facade-level integration tests.

Coverage mirrors the reference's integration suite (/root/reference/test/
test.js): init/from, change semantics, nested objects, lists, concurrent use &
convergence (LWW + conflicts, counter merge, add-wins, no interleaving,
same-position ordering by actor), undo/redo, save/load, history, diff, and the
changes API.
"""

import datetime

import pytest

import automerge_tpu as am


def set_(key, value):
    def cb(doc):
        doc[key] = value
    return cb


class TestInit:
    def test_init_empty(self):
        doc = am.init()
        assert am.to_json(doc) == {}

    def test_init_with_actor_id(self):
        doc = am.init("actor-1")
        assert am.get_actor_id(doc) == "actor-1"

    def test_from_initial_state(self):
        doc = am.from_({"birds": ["chaffinch"], "n": 42})
        assert am.to_json(doc) == {"birds": ["chaffinch"], "n": 42}

    def test_uuid_actor_by_default(self):
        doc = am.init()
        assert isinstance(am.get_actor_id(doc), str) and len(am.get_actor_id(doc)) > 8


class TestChange:
    def test_change_returns_new_doc(self):
        d1 = am.init()
        d2 = am.change(d1, set_("bird", "magpie"))
        assert am.to_json(d1) == {}
        assert am.to_json(d2) == {"bird": "magpie"}

    def test_attribute_style(self):
        d1 = am.init()
        d2 = am.change(d1, lambda d: setattr(d, "bird", "magpie"))
        assert d2["bird"] == "magpie"

    def test_noop_change_returns_same_doc(self):
        d1 = am.change(am.init(), set_("bird", "magpie"))
        d2 = am.change(d1, set_("bird", "magpie"))  # same value: no-op
        assert d2 is d1

    def test_noop_callback(self):
        d1 = am.init()
        d2 = am.change(d1, lambda d: None)
        assert d2 is d1

    def test_nested_change_raises(self):
        d1 = am.init()
        with pytest.raises(TypeError):
            am.change(d1, lambda d: am.change(d, set_("x", 1)))

    def test_root_required(self):
        d1 = am.change(am.init(), set_("nested", {}))
        with pytest.raises(TypeError):
            am.change(d1["nested"], set_("x", 1))

    def test_nested_maps(self):
        d = am.change(am.init(), set_("position", {"x": 1, "y": {"z": 2}}))
        assert am.to_json(d) == {"position": {"x": 1, "y": {"z": 2}}}
        assert am.get_object_id(d["position"]) is not None

    def test_delete_key(self):
        d1 = am.change(am.init(), lambda d: d.update({"a": 1, "b": 2}))
        d2 = am.change(d1, lambda d: d.__delitem__("a"))
        assert am.to_json(d2) == {"b": 2}

    def test_read_own_writes_in_block(self):
        seen = {}

        def cb(d):
            d["x"] = 5
            seen["x"] = d["x"]
            d["nested"] = {"a": 1}
            seen["a"] = d["nested"]["a"]
            d["nested"]["b"] = 2
            seen["b"] = d["nested"]["b"]

        am.change(am.init(), cb)
        assert seen == {"x": 5, "a": 1, "b": 2}

    def test_datetime_round_trip(self):
        now = datetime.datetime(2026, 7, 29, 12, 0, tzinfo=datetime.timezone.utc)
        d = am.change(am.init(), set_("now", now))
        assert d["now"] == now

    def test_message_in_history(self):
        d = am.change(am.init(), "hello commit", set_("x", 1))
        assert am.get_history(d)[0].change["message"] == "hello commit"

    def test_assigning_doc_object_raises(self):
        d1 = am.change(am.init(), set_("a", {"x": 1}))

        def cb(d):
            d["b"] = d["a"]
        with pytest.raises(TypeError, match="already belongs"):
            am.change(d1, cb)


class TestLists:
    def test_list_operations(self):
        d1 = am.change(am.init(), set_("birds", ["chaffinch", "goldfinch"]))

        def edit(d):
            birds = d["birds"]
            birds.insert(1, "greenfinch")
            birds.append("bullfinch")
            birds[0] = "wren"
            del birds[3]
        d2 = am.change(d1, edit)
        assert am.to_json(d2) == {"birds": ["wren", "greenfinch", "goldfinch"]}

    def test_list_of_maps(self):
        d = am.change(am.init(), set_("todos", [{"title": "a", "done": False}]))
        d2 = am.change(d, lambda doc: doc["todos"][0].__setitem__("done", True))
        assert am.to_json(d2) == {"todos": [{"title": "a", "done": True}]}

    def test_insert_at_delete_at(self):
        d1 = am.change(am.init(), set_("xs", [1, 2, 3]))
        d2 = am.change(d1, lambda d: d["xs"].insert_at(1, 10, 11).delete_at(3))
        assert am.to_json(d2) == {"xs": [1, 10, 11, 3]}

    def test_negative_index(self):
        d1 = am.change(am.init(), set_("xs", [1, 2, 3]))
        d2 = am.change(d1, lambda d: d["xs"].__setitem__(-1, 30))
        assert am.to_json(d2) == {"xs": [1, 2, 30]}

    def test_out_of_bounds_raises(self):
        d1 = am.change(am.init(), set_("xs", [1]))
        with pytest.raises(IndexError):
            am.change(d1, lambda d: d["xs"].insert_at(5, 9))

    def test_python_insert_clamps_like_list(self):
        # Python list.insert clamps out-of-range indexes; the proxy matches.
        d1 = am.change(am.init(), set_("xs", [1]))
        d2 = am.change(d1, lambda d: d["xs"].insert(99, 2))
        assert am.to_json(d2) == {"xs": [1, 2]}


class TestConcurrentUse:
    def test_concurrent_different_keys(self):
        a = am.change(am.init("actor-a"), set_("a", 1))
        b = am.change(am.init("actor-b"), set_("b", 2))
        merged_ab = am.merge(a, b)
        merged_ba = am.merge(b, a)
        assert am.to_json(merged_ab) == am.to_json(merged_ba) == {"a": 1, "b": 2}

    def test_lww_conflict_same_key(self):
        a = am.change(am.init("actor-1"), set_("bird", "magpie"))
        b = am.change(am.init("actor-2"), set_("bird", "blackbird"))
        ab = am.merge(a, b)
        ba = am.merge(b, a)
        # winner is the highest actor id, deterministically on both sides
        assert ab["bird"] == "blackbird"
        assert ba["bird"] == "blackbird"
        assert am.get_conflicts(ab, "bird") == {"actor-1": "magpie"}
        assert am.get_conflicts(ba, "bird") == {"actor-1": "magpie"}

    def test_conflict_resolved_by_later_write(self):
        a = am.change(am.init("actor-1"), set_("bird", "magpie"))
        b = am.change(am.init("actor-2"), set_("bird", "blackbird"))
        ab = am.merge(a, b)
        resolved = am.change(ab, set_("bird", "robin"))
        assert resolved["bird"] == "robin"
        assert am.get_conflicts(resolved, "bird") is None

    def test_counter_merge_adds(self):
        a = am.change(am.init("actor-1"), set_("n", am.Counter(0)))
        b = am.merge(am.init("actor-2"), a)
        a2 = am.change(a, lambda d: d["n"].increment(3))
        b2 = am.change(b, lambda d: d["n"].increment(4))
        ab = am.merge(a2, b2)
        ba = am.merge(b2, a2)
        assert am.to_json(ab)["n"] == 7
        assert am.to_json(ba)["n"] == 7

    def test_add_wins_on_concurrent_update_and_delete(self):
        base = am.change(am.init("actor-1"), set_("bird", "robin"))
        other = am.merge(am.init("actor-2"), base)
        deleted = am.change(base, lambda d: d.__delitem__("bird"))
        updated = am.change(other, set_("bird", "sparrow"))
        m1 = am.merge(deleted, updated)
        m2 = am.merge(updated, deleted)
        assert am.to_json(m1) == am.to_json(m2) == {"bird": "sparrow"}

    def test_concurrent_list_inserts_no_interleaving(self):
        base = am.change(am.init("actor-1"), set_("log", []))
        other = am.merge(am.init("actor-2"), base)
        a = am.change(base, lambda d: d["log"].extend(["a1", "a2", "a3"]))
        b = am.change(other, lambda d: d["log"].extend(["b1", "b2", "b3"]))
        m1 = am.to_json(am.merge(a, b))["log"]
        m2 = am.to_json(am.merge(b, a))["log"]
        assert m1 == m2
        # each actor's run stays contiguous
        a_pos = [m1.index(x) for x in ("a1", "a2", "a3")]
        b_pos = [m1.index(x) for x in ("b1", "b2", "b3")]
        assert a_pos == sorted(a_pos) and a_pos[2] - a_pos[0] == 2
        assert b_pos == sorted(b_pos) and b_pos[2] - b_pos[0] == 2

    def test_same_position_insert_ordered_by_actor(self):
        base = am.change(am.init("aaaa"), set_("xs", ["x"]))
        other = am.merge(am.init("bbbb"), base)
        a = am.change(base, lambda d: d["xs"].insert(0, "from-a"))
        b = am.change(other, lambda d: d["xs"].insert(0, "from-b"))
        m1 = am.to_json(am.merge(a, b))["xs"]
        m2 = am.to_json(am.merge(b, a))["xs"]
        assert m1 == m2
        # higher actor id comes first (descending Lamport order)
        assert m1 == ["from-b", "from-a", "x"]

    def test_concurrent_nested_object_creation(self):
        a = am.change(am.init("actor-1"), set_("config", {"a": 1}))
        b = am.change(am.init("actor-2"), set_("config", {"b": 2}))
        m = am.merge(a, b)
        # one whole object wins; the other is a conflict
        assert am.to_json(m)["config"] == {"b": 2}
        conflicts = am.get_conflicts(m, "config")
        assert am.to_json(conflicts["actor-1"]) == {"a": 1}

    def test_three_way_convergence(self):
        a = am.change(am.init("a"), set_("x", 1))
        b = am.merge(am.init("b"), a)
        c = am.merge(am.init("c"), a)
        b2 = am.change(b, set_("y", 2))
        c2 = am.change(c, set_("z", 3))
        a2 = am.change(a, set_("x", 10))
        final1 = am.merge(am.merge(a2, b2), c2)
        final2 = am.merge(am.merge(c2, a2), b2)
        assert am.to_json(final1) == am.to_json(final2) == {"x": 10, "y": 2, "z": 3}

    def test_merge_same_actor_raises(self):
        a = am.init("actor-1")
        b = am.init("actor-1")
        with pytest.raises(ValueError, match="itself"):
            am.merge(a, b)


class TestApplyChanges:
    def test_network_style_sync(self):
        a = am.change(am.init("actor-1"), set_("x", 1))
        a2 = am.change(a, set_("y", 2))
        b = am.init("actor-2")
        b2 = am.apply_changes(b, am.get_all_changes(a2))
        assert am.to_json(b2) == {"x": 1, "y": 2}

    def test_incremental_changes(self):
        a1 = am.change(am.init("actor-1"), set_("x", 1))
        b1 = am.apply_changes(am.init("actor-2"), am.get_all_changes(a1))
        a2 = am.change(a1, set_("y", 2))
        delta = am.get_changes(a1, a2)
        assert len(delta) == 1
        b2 = am.apply_changes(b1, delta)
        assert am.to_json(b2) == {"x": 1, "y": 2}

    def test_out_of_order_buffering(self):
        a1 = am.change(am.init("actor-1"), set_("x", 1))
        a2 = am.change(a1, set_("y", 2))
        delta2 = am.get_changes(a1, a2)
        b = am.init("actor-2")
        b1 = am.apply_changes(b, delta2)  # arrives before its dependency
        assert am.to_json(b1) == {}
        assert am.get_missing_deps(b1) == {"actor-1": 1}
        b2 = am.apply_changes(b1, am.get_changes(am.init(), a1))
        assert am.to_json(b2) == {"x": 1, "y": 2}
        assert am.get_missing_deps(b2) == {}

    def test_changes_survive_json_round_trip(self):
        import json
        a = am.change(am.init("actor-1"), set_("items", [{"k": "v"}]))
        changes = json.loads(json.dumps(am.get_all_changes(a)))
        b = am.apply_changes(am.init("actor-2"), changes)
        assert am.to_json(b) == {"items": [{"k": "v"}]}


class TestUndoRedo:
    def test_undo_set(self):
        d1 = am.change(am.init(), set_("x", 1))
        d2 = am.change(d1, set_("x", 2))
        assert am.can_undo(d2)
        d3 = am.undo(d2)
        assert am.to_json(d3) == {"x": 1}
        d4 = am.undo(d3)
        assert am.to_json(d4) == {}

    def test_undo_nothing_raises(self):
        with pytest.raises(ValueError, match="nothing to be undone"):
            am.undo(am.init())

    def test_redo(self):
        d1 = am.change(am.init(), set_("x", 1))
        d2 = am.undo(d1)
        assert am.can_redo(d2)
        d3 = am.redo(d2)
        assert am.to_json(d3) == {"x": 1}
        assert not am.can_redo(d3)

    def test_redo_without_undo_raises(self):
        d1 = am.change(am.init(), set_("x", 1))
        with pytest.raises(ValueError, match="no prior undo"):
            am.redo(d1)

    def test_undo_delete_restores(self):
        d1 = am.change(am.init(), set_("bird", "magpie"))
        d2 = am.change(d1, lambda d: d.__delitem__("bird"))
        d3 = am.undo(d2)
        assert am.to_json(d3) == {"bird": "magpie"}

    def test_undo_counter_increment(self):
        d1 = am.change(am.init(), set_("n", am.Counter(10)))
        d2 = am.change(d1, lambda d: d["n"].increment(5))
        d3 = am.undo(d2)
        assert am.to_json(d3) == {"n": 10}

    def test_new_change_clears_redo_stack(self):
        d1 = am.change(am.init(), set_("x", 1))
        d2 = am.undo(d1)
        d3 = am.change(d2, set_("y", 9))
        assert not am.can_redo(d3)

    def test_undoable_false_excluded_from_undo_history(self):
        d1 = am.change(am.init(), {"undoable": False}, set_("x", 1))
        assert not am.can_undo(d1)


class TestSaveLoad:
    def test_round_trip(self):
        d = am.change(am.init("actor-1"), set_("todos", [{"t": "x", "done": False}]))
        d2 = am.change(d, lambda doc: doc["todos"][0].__setitem__("done", True))
        loaded = am.load(am.save(d2), "actor-2")
        assert am.to_json(loaded) == am.to_json(d2)

    def test_load_preserves_max_elem(self):
        # After delete + reload, new inserts must not reuse element ids.
        d1 = am.change(am.init("actor-1"), set_("xs", ["a", "b"]))
        d2 = am.change(d1, lambda d: d["xs"].delete_at(1))
        loaded = am.load(am.save(d2), "actor-1")
        d3 = am.change(loaded, lambda d: d["xs"].append("c"))
        assert am.to_json(d3) == {"xs": ["a", "c"]}
        # merging back into the original lineage must not collide
        other = am.load(am.save(d2), "actor-2")
        m = am.merge(other, d3)
        assert am.to_json(m) == {"xs": ["a", "c"]}

    def test_save_includes_queued_changes(self):
        a1 = am.change(am.init("actor-1"), set_("x", 1))
        a2 = am.change(a1, set_("y", 2))
        b = am.apply_changes(am.init("actor-2"), am.get_changes(a1, a2))  # missing dep
        restored = am.load(am.save(b), "actor-3")
        assert am.get_missing_deps(restored) == {"actor-1": 1}
        full = am.apply_changes(restored, am.get_changes(am.init(), a1))
        assert am.to_json(full) == {"x": 1, "y": 2}

    def test_bad_format_raises(self):
        with pytest.raises(ValueError, match="format"):
            am.load('{"format": "not-a-doc"}')


class TestHistoryAndDiff:
    def test_history_snapshots(self):
        d1 = am.change(am.init(), set_("x", 1))
        d2 = am.change(d1, set_("y", 2))
        history = am.get_history(d2)
        assert len(history) == 2
        assert am.to_json(history[0].snapshot) == {"x": 1}
        assert am.to_json(history[1].snapshot) == {"x": 1, "y": 2}

    def test_diff(self):
        d1 = am.change(am.init(), set_("x", 1))
        d2 = am.change(d1, set_("y", 2))
        diffs = am.diff(d1, d2)
        assert len(diffs) == 1
        assert diffs[0]["key"] == "y"

    def test_diff_diverged_raises(self):
        d1 = am.change(am.init("actor-1"), set_("x", 1))
        e1 = am.change(am.init("actor-2"), set_("y", 1))
        with pytest.raises(ValueError, match="diverged"):
            am.diff(d1, e1)

    def test_equals(self):
        d1 = am.change(am.init("a1"), set_("x", 1))
        d2 = am.apply_changes(am.init("a2"), am.get_all_changes(d1))
        assert am.equals(am.to_json(d1), am.to_json(d2))
        assert not am.equals(am.to_json(d1), {"x": 2})


class TestFreeze:
    def test_frozen_docs_raise_on_mutation(self):
        d1 = am.change(am.init({"freeze": True}), set_("xs", [1]))
        with pytest.raises(TypeError, match="frozen"):
            d1["direct"] = 1
        with pytest.raises(TypeError, match="frozen"):
            d1["xs"].append(2)

    def test_unfrozen_by_default_but_convention(self):
        d1 = am.change(am.init(), set_("x", 1))
        # default docs are not frozen (same as the reference)
        d1["sneaky"] = 1
        assert d1["sneaky"] == 1


class TestNetZeroMerge:
    def test_merge_applies_net_zero_histories(self):
        """A remote history whose net effect is zero (delete + its undo)
        emits NO net diffs — merge must still apply the changes, or they
        are silently dropped from the returned lineage and a later
        different-order merge diverges (soak seed 400057)."""
        import automerge_tpu as am
        from automerge_tpu import Text
        from automerge_tpu import frontend as Frontend

        base = am.change(am.init("base"),
                         lambda d: d.__setitem__("t", Text("seed")))
        bc = am.get_all_changes(base)
        a = am.apply_changes(am.init("actor-0"), bc)
        b = am.apply_changes(am.init("actor-1"), bc)
        a = am.change(a, lambda d: d.__setitem__("c", 36))
        # b: delete three chars, then undo (restores) -> net-zero
        b = am.change(b, lambda d: [d["t"].delete_at(0) for _ in range(3)])
        b = am.undo(b)
        assert str(b["t"]) == "seed"

        m = am.merge(a, b)
        clock = dict(Frontend.get_backend_state(m).clock)
        assert clock.get("actor-1", 0) == 2, clock   # changes ARE applied
        got = {(c["actor"], c["seq"]) for c in am.get_all_changes(m)}
        assert ("actor-1", 1) in got and ("actor-1", 2) in got

        # and the order-independence that seed 400057 violated
        c0 = am.apply_changes(am.init("obs1"), am.get_all_changes(m))
        m2 = am.merge(b, a)
        c1 = am.apply_changes(am.init("obs2"), am.get_all_changes(m2))
        assert am.to_json(c0) == am.to_json(c1)

    def test_merge_with_nothing_new_returns_same_doc(self):
        import automerge_tpu as am
        a = am.change(am.init("aaaa"), lambda d: d.__setitem__("x", 1))
        b = am.merge(am.init("bbbb"), a)
        # b has nothing a lacks: merge must return the SAME doc object
        assert am.merge(a, b) is a


class TestRedoConflictConvergence:
    def test_redo_of_conflicted_register_converges_all_orders(self):
        """A redo change re-mints the WHOLE conflict set of a register as
        multiple same-actor ops in one change. Keeping both ops and
        breaking ties by list order is application-order-dependent: a
        stable ascending sort followed by a full reverse flips the
        same-actor pair on every later re-sort of the register, so peers
        that merged in different orders materialized different winners
        from IDENTICAL change sets (found by scripts/soak.py general
        profile seed 6). The register now keeps at most one op per actor
        — the later op of the change supersedes its predecessor."""
        import automerge_tpu as am

        base = am.change(am.init("base"), lambda d: d.__setitem__("m", {"k": 0}))
        bc = am.get_all_changes(base)
        a1 = am.apply_changes(am.init("actor-1"), bc)
        a2 = am.apply_changes(am.init("actor-2"), bc)
        a2 = am.change(a2, lambda d: d["m"].__setitem__("k", 32))
        a1 = am.change(a1, lambda d: d["m"].__setitem__("k", 49))
        a1 = am.merge(a1, a2)            # a1 sees the conflict {49, 32}
        a1 = am.undo(a1)                 # seq2: restore pre-conflict value
        a1 = am.redo(a1)                 # seq3: re-mints BOTH 32 and 49
        # a0 wrote concurrently with the undo/redo pair
        subset = [c for c in am.get_all_changes(a1)
                  if (c["actor"], c["seq"]) in
                  {("base", 1), ("actor-1", 1), ("actor-2", 1)}]
        a0 = am.apply_changes(am.init("actor-0"), subset)
        a0 = am.change(a0, lambda d: d["m"].__setitem__("k", 43))

        import itertools
        winners = set()
        for perm in itertools.permutations([a0, a1, a2]):
            m = am.init("observer")
            for p in perm:
                m = am.merge(m, p)
            winners.add(am.to_json(m)["m"]["k"])
        # every application order materializes the same winner: actor-1's
        # redo causally covers 32, and actor-1 > actor-0 on the tiebreak
        assert winners == {49}, winners

"""Geo-distributed federation (ISSUE 16, INTERNALS §20).

The contracts under test:

- **GroupClock** — O(groups) causal metadata: one monotone ordering
  token per (room, origin-region), destination-independent mints,
  idempotent max-merge observation, a dumpable table bounded by groups
  (never peers).
- **Group tokens on the wire** — the ``[origin, room, token]`` triple
  rides the ``AMTPUWIRE1`` manifest (hash-covered, version-tolerant),
  round-trips through encode/decode, and malformed triples are typed
  ``WireFormatError`` rejections.
- **WAN chaos profiles** — named, seeded, ASYMMETRIC per direction;
  the bandwidth cap throttles (holds, never drops) over-budget frames.
- **Partition tolerance** — three regions partitioned and healed
  converge to byte-identical canonical saves AND identical change
  histories, with ZERO residual cross-region lag; the degradation
  ladder walks ok → partitioned → healing → ok with every transition
  counted and evented; local writes are accepted throughout.
- **Reconnect epochs** — heal revives both channel endpoints into a
  fresh epoch (stale pre-partition frames drop instead of replaying
  into the reset window) and hub peer re-attachment recomputes the
  delta from clocks, including snapshot bootstrap for an empty joiner.
- **Observability** — ``amtpu_region_*`` families on the service
  scrape (prom-validator-clean), the federation block in describe(),
  and lineage chains that SPAN regions: fed/ship → fed/recv hops with
  per-hop dwell, and a most-stuck postmortem that names the
  partitioned region link a buffered change is parked on.
"""

import json

import pytest

import automerge_tpu as am
from automerge_tpu.engine.wire_format import (
    WireFormatError, decode, split_outgoing, validate_group_token,
)
from automerge_tpu.federation import (
    FederatedRegion, GroupClock, RegionPlacement, connect_regions,
)
from automerge_tpu.obs import lineage, prom
from automerge_tpu.obs.prom import validate_prom
from automerge_tpu.resilience import WAN_PROFILES, ChaosLink, wan_pair, \
    wan_profile
from automerge_tpu.service import ServiceConfig, SyncService


@pytest.fixture(autouse=True)
def _lineage_off_after():
    was = lineage.ENABLED
    yield
    if not was:
        lineage.disable()
    lineage.clear()


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _mk_fabric(names=("us", "eu", "ap"), profile="cross_region", seed=3,
               **region_kw):
    """Full-mesh fabric: {name: FederatedRegion}, {(a, b): (fwd, rev)}."""
    regions = {n: FederatedRegion(SyncService(ServiceConfig(region=n)),
                                  n, **region_kw) for n in names}
    chaos = {}
    s = seed
    names = list(names)
    for i in range(len(names)):
        for j in range(i + 1, len(names)):
            a, b = names[i], names[j]
            _, _, fwd, rev = connect_regions(
                regions[a], regions[b], profile=profile, seed=s)
            chaos[(a, b)] = (fwd, rev)
            s += 10
    return regions, chaos


def _seed_room(regions, room_id="room0"):
    doc = am.change(am.init(f"{room_id}-origin"),
                    lambda d: d.__setitem__("k", 0))
    base = am.get_all_changes(doc)
    for r in regions.values():
        r.svc.seed_doc(room_id, am.apply_changes(
            am.init(f"srv-{r.name}-{room_id}"), base))


def _pump(regions, n=1):
    for _ in range(n):
        for r in regions.values():
            r.pump()
            r.svc.tick()


def _edit(regions, region, room_id, key, val):
    ds = regions[region].svc.room(room_id).doc_set
    ds.set_doc(room_id, am.change(ds.get_doc(room_id),
                                  lambda d: d.__setitem__(key, val)))


def _settle(regions, max_rounds=800):
    for i in range(max_rounds):
        _pump(regions)
        if i > 5 and all(r.idle() for r in regions.values()):
            return i
    raise AssertionError(
        f"fabric failed to quiesce in {max_rounds} rounds: "
        f"{ {n: r.lag_table() for n, r in regions.items()} }")


def _canonical_save(doc):
    """Replica-independent save bytes: replay the FULL change history
    (deterministically ordered) under one probe actor — byte-identical
    iff the histories are identical."""
    chs = sorted(am.get_all_changes(doc),
                 key=lambda c: (c["actor"], c["seq"]))
    return am.save(am.apply_changes(am.init("canon-probe"), chs))


def _histories(doc):
    return sorted(json.dumps(c, sort_keys=True)
                  for c in am.get_all_changes(doc))


def _assert_converged(regions, room_id="room0"):
    docs = {n: r.svc.room(room_id).doc_set.get_doc(room_id)
            for n, r in regions.items()}
    assert all(d is not None for d in docs.values()), docs
    saves = {n: _canonical_save(d) for n, d in docs.items()}
    assert len(set(saves.values())) == 1, \
        f"saves diverged: { {n: len(s) for n, s in saves.items()} }"
    hists = {n: _histories(d) for n, d in docs.items()}
    ref = next(iter(hists.values()))
    assert all(h == ref for h in hists.values()), "histories diverged"


def _residual_lag(regions):
    return sum(entry["lag_tokens"] for r in regions.values()
               for entry in r.lag_table().values())


# ---------------------------------------------------------------------------
# GroupClock: O(groups) causal metadata
# ---------------------------------------------------------------------------

def test_group_clock_mints_monotone_per_room():
    gc = GroupClock("us")
    assert gc.mint("a") == ["us", "a", 1]
    assert gc.mint("a") == ["us", "a", 2]
    assert gc.mint("b") == ["us", "b", 1]   # independent per room
    assert gc.head("a") == 2 and gc.head("b") == 1
    assert gc.head("never") == 0


def test_group_clock_observe_is_idempotent_max_merge():
    gc = GroupClock("eu")
    assert gc.observe("a", "us", 3) is True
    assert gc.observe("a", "us", 3) is False      # duplicate
    assert gc.observe("a", "us", 1) is False      # stale reorder
    assert gc.observe("a", "us", 7) is True       # gap is fine: max-merge
    assert gc.seen("a", "us") == 7
    assert gc.stats == {"minted": 0, "observed": 2, "stale": 2}


def test_group_clock_state_is_o_groups_not_o_peers():
    gc = GroupClock("hub")
    # 1000 tokens from 2 origins over 3 rooms: table stays 3 x <=3
    for i in range(1000):
        gc.observe(f"room-{i % 3}", ("us", "eu")[i % 2], i + 1)
        gc.mint(f"room-{i % 3}")
    table = gc.table()
    assert len(table) == 3
    assert all(set(v) <= {"us", "eu", "hub"} for v in table.values())


def test_group_clock_rejects_bad_region():
    with pytest.raises(ValueError):
        GroupClock("")


# ---------------------------------------------------------------------------
# group tokens on the AMTPUWIRE1 manifest
# ---------------------------------------------------------------------------

def _changes(n=3):
    doc = am.init("wire-actor")
    for i in range(n):
        doc = am.change(doc, lambda d, i=i: d.__setitem__(f"k{i}", i))
    return am.get_all_changes(doc)


def test_group_token_rides_the_manifest():
    prefix, frame = split_outgoing(_changes(), min_ops=0,
                                   group=["us", "room0", 7])
    assert frame is not None
    assert frame.group == ["us", "room0", 7]      # send-side cache
    batch = decode(frame.data)
    assert batch._group == ["us", "room0", 7]     # decode round-trip
    # token-less frames stay token-less (no default minting at encode)
    _, bare = split_outgoing(_changes(), min_ops=0)
    assert bare.group is None
    assert getattr(decode(bare.data), "_group", None) is None


def test_group_token_validation_is_typed():
    good = ["us", "room0", 1]
    assert validate_group_token(list(good)) == good
    for bad in (["us", "room0"],               # truncated
                ["us", "room0", 0],            # tokens start at 1
                ["us", "room0", True],         # bool is not a token
                ["", "room0", 1],              # empty region
                ["us", "", 1],                 # empty room
                ["us", "room0", 2 ** 63],      # i64 overflow
                "us/room0/1",                  # not a triple
                ["us", "room0", "1"]):         # stringly token
        with pytest.raises(WireFormatError):
            validate_group_token(bad)
    # split_outgoing treats an un-encodable token like any other encode
    # failure: typed rejection inside, graceful dict-wire fallback out
    prefix, frame = split_outgoing(_changes(), min_ops=0,
                                   group=["us", "room0", 0])
    assert frame is None and len(prefix) == 3


# ---------------------------------------------------------------------------
# WAN chaos profiles
# ---------------------------------------------------------------------------

def test_wan_profiles_are_named_and_asymmetric():
    assert set(WAN_PROFILES) == {"wan", "wan_partitioned", "cross_region"}
    for name in WAN_PROFILES:
        fwd, rev = wan_profile(name, "fwd"), wan_profile(name, "rev")
        assert fwd != rev, f"{name} should be asymmetric"
        assert fwd["bandwidth"] > rev["bandwidth"]  # fat egress, thin rtn
    with pytest.raises(KeyError):
        wan_profile("lan")


def test_wan_pair_is_deterministic():
    def run():
        got = []
        fwd, _rev = wan_pair(got.append, lambda m: None,
                             profile="wan", seed=42)
        for i in range(200):
            fwd.send({"i": i})
            fwd.pump()
        fwd.drain(200)
        return got, dict(fwd.stats)
    a_msgs, a_stats = run()
    b_msgs, b_stats = run()
    assert a_msgs == b_msgs and a_stats == b_stats
    assert a_stats["dropped"] > 0 or a_stats["delayed"] > 0


def test_bandwidth_cap_throttles_without_dropping():
    got = []
    link = ChaosLink(got.append, seed=1, bandwidth=64)
    big = {"payload": "x" * 100}
    for _ in range(8):
        link.send(dict(big))
    rounds = 0
    while not link.idle and rounds < 100:
        link.pump()
        rounds += 1
    assert len(got) == 8                      # throttled, never dropped
    assert link.stats["throttled"] > 0
    # each ~100-byte frame alone busts the 64-byte round budget, so the
    # cap serialized delivery to one frame per pump round
    assert rounds >= 8


def test_bandwidth_cap_first_frame_always_passes():
    got = []
    link = ChaosLink(got.append, seed=1, bandwidth=1)  # absurdly thin
    link.send({"payload": "y" * 1000})
    link.pump()
    assert len(got) == 1                      # oversized head-of-line


# ---------------------------------------------------------------------------
# RegionPlacement
# ---------------------------------------------------------------------------

def test_region_placement_deterministic_and_movable():
    p = RegionPlacement(["us", "eu", "ap"])
    q = RegionPlacement(["us", "eu", "ap"])
    rooms = [f"room-{i}" for i in range(30)]
    assert [p.home(r) for r in rooms] == [q.home(r) for r in rooms]
    spread = p.spread(rooms)
    assert sum(spread.values()) == 30 and len(spread) == 3
    victim = rooms[0]
    before, epoch0 = p.home(victim), p.epoch
    target = next(n for n in ("us", "eu", "ap") if n != before)
    p.move(victim, target)
    assert p.home(victim) == target
    assert p.table() == {victim: target}      # explicit override only
    assert p.epoch == epoch0 + 1              # move fence
    p.move(victim, before)                    # back home drops the entry
    assert p.table() == {}


def test_region_placement_rejects_unknowns():
    with pytest.raises(ValueError):
        RegionPlacement([])
    with pytest.raises(ValueError):
        RegionPlacement(["us", "us"])
    with pytest.raises(ValueError):
        RegionPlacement(["us"], overrides={"r": "mars"})
    p = RegionPlacement(["us", "eu"])
    with pytest.raises(ValueError):
        p.move("r", "mars")


# ---------------------------------------------------------------------------
# federation: convergence, partition, heal
# ---------------------------------------------------------------------------

def test_two_regions_converge_over_wan_chaos():
    regions, _ = _mk_fabric(("us", "eu"), seed=7)
    _seed_room(regions)
    _edit(regions, "us", "room0", "from_us", 1)
    _edit(regions, "eu", "room0", "from_eu", 2)
    _settle(regions)
    _assert_converged(regions)
    assert _residual_lag(regions) == 0
    # the ordering tokens actually flowed: eu observed us's mints
    assert regions["eu"].clock.seen("room0", "us") > 0
    assert regions["us"].clock.seen("room0", "eu") > 0


def test_remote_region_can_introduce_a_room():
    regions, _ = _mk_fabric(("us", "eu"), seed=11)
    _pump(regions, 3)
    # a room born in eu AFTER the fabric is up reaches us lazily
    doc = am.change(am.init("late-room"), lambda d: d.__setitem__("v", 9))
    regions["eu"].svc.seed_doc("late", doc)
    _settle(regions)
    got = regions["us"].svc.room("late").doc_set.get_doc("late")
    assert got is not None and am.to_json(got)["v"] == 9


def test_three_region_partition_heal_byte_identical():
    regions, chaos = _mk_fabric(seed=3)
    _seed_room(regions)
    _pump(regions, 30)

    fwd, rev = chaos[("us", "eu")]
    fwd.partition()
    rev.partition()
    # local writes stay accepted in EVERY region mid-partition (ladder
    # rung one), including both sides of the cut
    for k in range(5):
        for n in regions:
            _edit(regions, n, "room0", f"{n}{k}", k)
        _pump(regions, 8)
    _pump(regions, 120)           # retransmit cap + dead declaration
    us_eu = regions["us"].links["eu"]
    eu_us = regions["eu"].links["us"]
    assert us_eu.state == "partitioned" and eu_us.state == "partitioned"
    assert us_eu.transitions.get("ok->partitioned") == 1
    # the cut is OBSERVABLE: link_up 0 on the scrape mid-partition
    page = regions["us"].svc.scrape()
    assert 'amtpu_region_link_up{peer="eu",region="us"} 0' in page
    # and evented on the service black-box ring
    events = [e for e in regions["us"].svc._events
              if e["event"] == "fed_state"]
    assert any(e["to"] == "partitioned" and e["link"] == "us->eu"
               for e in events)

    fwd.heal()
    rev.heal()
    _settle(regions)
    _assert_converged(regions)
    assert _residual_lag(regions) == 0
    # full ladder walked, counted once per rung
    assert us_eu.transitions.get("partitioned->healing") == 1
    assert us_eu.transitions.get("healing->ok") == 1
    # heal revived BOTH endpoints into a fresh epoch
    assert us_eu.chan.stats["revives"] >= 1
    assert eu_us.chan.stats["revives"] >= 1
    assert us_eu.chan.epoch >= 1 and eu_us.chan.epoch >= 1


def test_partition_buffers_are_two_tier_and_bounded():
    regions, chaos = _mk_fabric(("us", "eu"), seed=19, max_buffer=4)
    _seed_room(regions)
    _pump(regions, 30)
    fwd, rev = chaos[("us", "eu")]
    fwd.partition()
    rev.partition()
    # dead-link detection is traffic-driven (an idle cut link owes
    # nothing — same contract as the service health ladder): one edit
    # puts frames in flight, the retransmit cap then declares death
    _edit(regions, "us", "room0", "tripwire", 1)
    _pump(regions, 120)
    link = regions["us"].links["eu"]
    assert link.state == "partitioned"
    for k in range(12):
        _edit(regions, "us", "room0", f"burst{k}", k)
        _pump(regions, 1)
    # payload buffer clamped at the cap, drop-oldest counted; the
    # advert tier dedups by (room, doc) and never exceeds the doc count
    assert len(link._buf_data) <= 4
    assert link.stats["buffer_dropped"] > 0
    assert len(link._buf_adverts) <= 1
    fwd.heal()
    rev.heal()
    _settle(regions)
    # dropped buffer entries are SAFE: heal re-advertises and the delta
    # recomputes from clocks — convergence never depended on the buffer
    _assert_converged(regions)
    assert _residual_lag(regions) == 0


def test_region_killed_and_rejoined_bootstraps_from_snapshot():
    regions, chaos = _mk_fabric(("us", "eu"), seed=23)
    _seed_room(regions)
    regions["us"].svc.room("room0").hub.snapshot_min_changes = 4
    for k in range(8):
        _edit(regions, "us", "room0", f"pre{k}", k)
    _settle(regions)
    _assert_converged(regions)

    # region eu dies: cut the WAN, then rebuild its service from nothing
    fwd, rev = chaos[("us", "eu")]
    fwd.partition()
    rev.partition()
    _edit(regions, "us", "room0", "during_cut", 1)   # traffic -> death
    _pump(regions, 120)
    assert regions["us"].links["eu"].state == "partitioned"
    dead = regions.pop("eu")
    fresh = FederatedRegion(SyncService(ServiceConfig(region="eu")), "eu")
    fresh_link = fresh.link_to("us", seed=77)
    # rewire the chaos edges at the dead region's addresses
    fwd._deliver = fresh_link.on_raw
    fresh_link.attach_transport(rev)
    regions["eu"] = fresh
    fresh.svc.room("room0")               # empty replica, empty clock
    del dead
    fwd.heal()
    rev.heal()
    _settle(regions)
    _assert_converged(regions)
    # the rejoin was served by the checkpoint bootstrap, not a change
    # replay: the fresh region's doc arrived with the full history
    assert len(am.get_all_changes(
        fresh.svc.room("room0").doc_set.get_doc("room0"))) >= 9


# ---------------------------------------------------------------------------
# observability: scrape, describe, lineage across regions
# ---------------------------------------------------------------------------

def test_scrape_exports_region_families_prom_clean():
    regions, _ = _mk_fabric(seed=31)
    _seed_room(regions)
    _edit(regions, "us", "room0", "x", 1)
    _settle(regions)
    page = regions["us"].svc.scrape()
    report = validate_prom(page)
    assert not report.get("errors"), report
    for fam in ("amtpu_region_lag_tokens", "amtpu_region_link_up",
                "amtpu_region_link_state", "amtpu_region_shipped_total",
                "amtpu_region_group_tokens_minted_total"):
        assert fam in page, fam
    assert 'peer="eu"' in page and 'peer="ap"' in page
    assert 'amtpu_region_lag_tokens{peer="eu",region="us"} 0' in page


def test_describe_carries_the_federation_block():
    regions, _ = _mk_fabric(("us", "eu"), seed=37,
                            placement=RegionPlacement(["us", "eu"]))
    _seed_room(regions)
    _edit(regions, "us", "room0", "minted", 1)   # something to ship
    _settle(regions)
    dump = regions["us"].svc.describe()
    json.dumps(dump, default=str)             # postmortem-serializable
    fed = dump["federation"]
    assert fed["region"] == "us"
    assert fed["links"]["eu"]["state"] == "ok"
    assert fed["links"]["eu"]["lag_tokens"] == 0
    assert fed["group_clock"]["minted"] >= 1
    assert "placement_epoch" in fed


def test_lineage_chain_spans_three_regions_with_dwell():
    lineage.enable(rate=1, capacity=2048)
    regions, _ = _mk_fabric(seed=41)
    _seed_room(regions)
    _pump(regions, 20)
    _edit(regions, "us", "room0", "traced", 1)
    _settle(regions)
    _assert_converged(regions)
    led = lineage.ledger()
    spanning = []
    for chain in led.chains():
        stages = [h[0] for h in chain["hops"]]
        # the traced edit originated on us's server replica; seed-doc
        # chains also cross regions but with arbitrary ship directions
        if "fed/ship" in stages and "fed/recv" in stages \
                and chain["actor"].startswith("srv-us"):
            spanning.append(chain)
    assert spanning, "no chain crossed a region boundary"
    best = max(spanning, key=lambda c: len(c["hops"]))
    # ship names the directed link, recv the crossing, commit the
    # region-qualified room replica (ServiceConfig.region)
    ship_sites = {h[1] for h in best["hops"] if h[0] == "fed/ship"}
    recv_sites = {h[1] for h in best["hops"] if h[0] == "fed/recv"}
    # first crossing leaves us; relays (eu re-shipping to ap) may add
    # further directed edges — every site is a directed region pair
    assert ship_sites & {"us->eu", "us->ap"}, ship_sites
    assert recv_sites & {"us->eu", "us->ap"}, recv_sites
    assert all("->" in s for s in ship_sites | recv_sites)
    commit_sites = {h[1] for h in best["hops"] if h[0] == "commit"}
    assert commit_sites & {"svc:eu/room0", "svc:ap/room0"}, commit_sites
    # per-hop dwell: timestamps are monotone, so every consecutive hop
    # pair yields a non-negative dwell (the postmortem renders these)
    ts = [h[2] for h in best["hops"]]
    assert all(b >= a for a, b in zip(ts, ts[1:]))
    # and the ledger aggregated a fed-stage dwell series
    agg = led.telemetry.span_aggregates()
    fed_dwells = [k for k in agg
                  if k[0] == "lineage" and k[1].startswith("dwell:fed/")]
    assert fed_dwells, sorted(agg)


def test_stuck_postmortem_names_the_partitioned_link():
    lineage.enable(rate=1, capacity=2048)
    regions, chaos = _mk_fabric(seed=43)
    _seed_room(regions)
    _pump(regions, 20)
    # cut BOTH of us's links, so a us-born change is visible nowhere
    # remote and its chain parks on a fed/buffer hop
    for pair in (("us", "eu"), ("us", "ap")):
        key = pair if pair in chaos else (pair[1], pair[0])
        for edge in chaos[key]:
            edge.partition()
    _edit(regions, "us", "room0", "tripwire", 1)   # traffic -> death
    _pump(regions, 120)
    assert regions["us"].links["eu"].state == "partitioned"
    assert regions["us"].links["ap"].state == "partitioned"
    _edit(regions, "us", "room0", "wedged", 1)
    _pump(regions, 10)
    dump = regions["us"].svc.describe()
    stuck = dump["lineage"]["stuck"]
    assert stuck, "nothing mid-flight despite a cut fabric"
    # every us-born change is visible nowhere remote, so the top entries
    # are mid-flight; the buffered one's chain ends ON the cut link
    assert stuck[0]["mid_flight"] is True
    buffered = [s for s in stuck if s["stuck_at"] == "fed/buffer"]
    assert buffered, [s["stuck_at"] for s in stuck]
    assert buffered[0]["stuck_site"] in ("us->eu", "us->ap")
    # the hop chain renders per-hop dwell offsets for the operator
    assert all(len(h) >= 3 for h in buffered[0]["hops"])
